"""Config search: rank server-region placements by client-perceived latency.

Reference: fantoch_bote/src/search.rs (Search / SearchInput / RankingParams /
FTMetric) + protocol stats naming from fantoch_bote/src/protocol.rs.  For
every n-region configuration drawn from the candidate set it computes, per
protocol and fault level, the histogram of client-perceived latencies
(clients either at the input regions or colocated with the servers), scores
the configuration by how much Atlas improves over the FPaxos and EPaxos
baselines, and returns configurations sorted by score.

Array-first redesign: instead of the reference's nested per-config loops
over Planet lookups, the candidate regions become one dense RTT matrix
(Planet.latency_matrix) and each config's quorum latencies are numpy
row-sorts over matrix slices.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from fantoch_tpu.core.metrics import Histogram
from fantoch_tpu.core.planet import Planet, Region
from fantoch_tpu.planner.bote import minority, quorum_size

# protocol short names (protocol.rs:12-18); key format "<short><f>[C]"
_SHORT = {"atlas": "a", "epaxos": "e", "fpaxos": "f"}
COLOCATED = "C"


@dataclass(frozen=True)
class RankingParams:
    """Thresholds for counting a config as an improvement
    (search.rs:617-650): minimum decrease (ms) of Atlas mean latency vs
    the FPaxos and EPaxos baselines at the same fault level."""

    min_mean_decrease_vs_fpaxos: int = 15
    min_mean_decrease_vs_epaxos: int = 0
    min_mean_ft_improvement: int = 0
    fault_levels: Tuple[int, ...] = (1, 2)


@dataclass(frozen=True)
class ConfigScore:
    regions: Tuple[Region, ...]
    score: float
    stats: Dict[str, Histogram] = field(compare=False, hash=False, default_factory=dict)


@dataclass(frozen=True)
class Placement:
    """A single-protocol placement picked by :meth:`Search.best_placement`
    — the scenario observatory's config *output* (the expansion manifest
    records both the chosen regions and the objective value)."""

    regions: Tuple[Region, ...]
    objective: str
    value: float


class Search:
    def __init__(
        self,
        planet: Planet,
        candidate_servers: Sequence[Region],
        clients: Optional[Sequence[Region]] = None,
    ):
        self._planet = planet
        self._servers = list(candidate_servers)
        self._clients = list(clients) if clients is not None else list(candidate_servers)
        self._all = self._servers + [
            c for c in self._clients if c not in self._servers
        ]
        self._index = {r: i for i, r in enumerate(self._all)}
        self._matrix = planet.latency_matrix(self._all)

    # --- per-config stats ---

    def compute_stats(
        self,
        config: Sequence[Region],
        colocated: bool = False,
        fault_levels: Tuple[int, ...] = (1, 2),
    ) -> Dict[str, Histogram]:
        """{'a_f1': Histogram, ...} for atlas/fpaxos at each fault level and
        epaxos (minority), clients at input regions or colocated
        (search.rs:262-376 analog)."""
        n = len(config)
        clients = list(config) if colocated else self._clients
        suffix = COLOCATED if colocated else ""
        sidx = np.array([self._index[r] for r in config])
        cidx = np.array([self._index[r] for r in clients])
        # server-to-server distances sorted per row: quorum latencies
        ss = np.sort(self._matrix[np.ix_(sidx, sidx)], axis=1)  # [n, n]
        # client -> closest server (0 if colocated)
        cs = self._matrix[np.ix_(cidx, sidx)]  # [clients, n]
        closest_srv = np.argmin(cs, axis=1)
        to_closest = cs[np.arange(len(cidx)), closest_srv]

        out: Dict[str, Histogram] = {}

        def add(name: str, per_client: np.ndarray) -> None:
            hist = Histogram()
            for v in per_client.tolist():
                hist.increment(int(v))
            out[name + suffix] = hist

        for f in fault_levels:
            if f > minority(n):
                continue
            q_atlas = quorum_size("atlas", n, f)
            add("a_f%d" % f, to_closest + ss[closest_srv, q_atlas - 1])
            q_fp = quorum_size("fpaxos", n, f)
            # fpaxos: best leader placement for these clients
            best = None
            for leader_pos in range(n):
                leader_to_q = ss[leader_pos, q_fp - 1]
                lat = self._matrix[np.ix_(cidx, sidx[leader_pos : leader_pos + 1])][
                    :, 0
                ] + leader_to_q
                mean = lat.mean()
                if best is None or mean < best[0]:
                    best = (mean, lat)
            assert best is not None
            add("f_f%d" % f, best[1])
        q_ep = quorum_size("epaxos", n, minority(n))
        add("e", to_closest + ss[closest_srv, q_ep - 1])
        return out

    # --- single-protocol placement (scenario observatory) ---

    def placement_latencies(
        self,
        config: Sequence[Region],
        protocol: str,
        f: int,
        colocated: bool = False,
    ) -> np.ndarray:
        """Per-client perceived latency (ms) for one protocol on one
        placement: leaderless protocols pay client -> closest server ->
        that server's closest quorum; fpaxos pays client -> best leader
        -> the leader's closest quorum (same math as compute_stats, one
        protocol at a time)."""
        n = len(config)
        clients = list(config) if colocated else self._clients
        sidx = np.array([self._index[r] for r in config])
        cidx = np.array([self._index[r] for r in clients])
        ss = np.sort(self._matrix[np.ix_(sidx, sidx)], axis=1)
        q = quorum_size(protocol, n, f)
        assert q <= n, f"{protocol} quorum {q} exceeds n={n}"
        if protocol == "fpaxos":
            best = None
            for leader_pos in range(n):
                lat = (
                    self._matrix[np.ix_(cidx, sidx[leader_pos : leader_pos + 1])][:, 0]
                    + ss[leader_pos, q - 1]
                )
                mean = lat.mean()
                if best is None or mean < best[0]:
                    best = (mean, lat)
            assert best is not None
            return best[1]
        cs = self._matrix[np.ix_(cidx, sidx)]
        closest_srv = np.argmin(cs, axis=1)
        to_closest = cs[np.arange(len(cidx)), closest_srv]
        return to_closest + ss[closest_srv, q - 1]

    @staticmethod
    def _objective_value(latencies: np.ndarray, objective: str) -> float:
        if objective == "mean":
            return float(latencies.mean())
        if objective == "p95":
            return float(np.percentile(latencies, 95))
        if objective == "p99":
            return float(np.percentile(latencies, 99))
        if objective == "max":
            return float(latencies.max())
        raise ValueError(f"unknown objective {objective!r}")

    def placement_objective(
        self,
        config: Sequence[Region],
        protocol: str,
        f: int,
        objective: str = "mean",
        colocated: bool = False,
    ) -> float:
        return self._objective_value(
            self.placement_latencies(config, protocol, f, colocated=colocated),
            objective,
        )

    def best_placement(
        self,
        protocol: str,
        n: int,
        f: int,
        objective: str = "mean",
        colocated: bool = False,
    ) -> Placement:
        """Exhaustive over n-combinations of the candidate servers,
        minimizing the chosen latency objective.  Deterministic for a
        fixed candidate set: ties break on the sorted region-name tuple,
        never on iteration order of anything unordered."""
        best: Optional[Tuple[float, Tuple[str, ...], Tuple[Region, ...]]] = None
        for combo in itertools.combinations(self._servers, n):
            value = self.placement_objective(
                combo, protocol, f, objective=objective, colocated=colocated
            )
            key = (value, tuple(sorted(r.name for r in combo)))
            if best is None or key < (best[0], best[1]):
                best = (key[0], key[1], tuple(combo))
        assert best is not None, "need at least n candidate servers"
        return Placement(regions=best[2], objective=objective, value=best[0])

    # --- ranked search ---

    def sorted_configs(
        self,
        n: int,
        params: RankingParams = RankingParams(),
        colocated: bool = False,
        top: int = 10,
    ) -> List[ConfigScore]:
        """All n-combinations of the candidate servers, scored by the summed
        mean-latency decrease of Atlas vs the FPaxos and EPaxos baselines
        across ``params.fault_levels`` (search.rs:97-178 ranking); configs
        failing a minimum-decrease threshold at any level are dropped."""
        scored: List[ConfigScore] = []
        for combo in itertools.combinations(self._servers, n):
            stats = self.compute_stats(
                combo, colocated=colocated, fault_levels=params.fault_levels
            )
            suffix = COLOCATED if colocated else ""
            score = 0.0
            ok = True
            for f in params.fault_levels:
                if f > minority(n):
                    continue
                a = stats.get(f"a_f{f}{suffix}")
                fp = stats.get(f"f_f{f}{suffix}")
                ep = stats.get(f"e{suffix}")
                assert a is not None and fp is not None and ep is not None
                dec_fp = fp.mean() - a.mean()
                dec_ep = ep.mean() - a.mean()
                if dec_fp < params.min_mean_decrease_vs_fpaxos:
                    ok = False
                    break
                if dec_ep < params.min_mean_decrease_vs_epaxos:
                    ok = False
                    break
                score += dec_fp + dec_ep
            if ok:
                scored.append(ConfigScore(tuple(combo), score, stats))
        scored.sort(key=lambda c: -c.score)
        return scored[:top]
