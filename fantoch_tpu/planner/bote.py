"""Bote: client-perceived quorum-latency planner.

Reference: fantoch_bote/src/lib.rs:38-186 and protocol.rs:20-35.  Given a
Planet (inter-region RTT matrix), server regions and client regions, it
computes the latency every client would perceive:

  * leaderless protocols — client -> closest server + that server ->
    its closest quorum of ``quorum_size`` (lib.rs:38-58);
  * leader-based protocols — client -> leader + leader -> its closest
    quorum (lib.rs:60-88), with ``best_leader`` ranking all leader
    choices by a Histogram statistic (lib.rs:90-121).

The quorum latency counts the source region itself as the first quorum
member at 0 ms (the planet's sorted-by-distance list starts with self —
lib.rs:152-186 ``nth_closest``).
"""

from __future__ import annotations

from typing import Iterable, List, Sequence, Tuple

from fantoch_tpu.core.metrics import Histogram
from fantoch_tpu.core.planet import Planet, Region


def minority(n: int) -> int:
    return n // 2


def quorum_size(protocol: str, n: int, f: int) -> int:
    """Per-protocol (fast-path) quorum size, matching the Config helpers
    in core/config.py (fantoch_bote/src/protocol.rs:20-35).

    EPaxos ignores the given f: it always tolerates a minority.  Newt's
    fast quorum is minority + f (Config.newt_quorum_sizes, non-tiny);
    Caesar's is 3n//4 + 1 (Config.caesar_quorum_sizes); Basic and
    FPaxos write to a bare majority-of-voters f + 1."""
    if protocol in ("fpaxos", "basic"):
        return f + 1
    if protocol == "epaxos":
        fm = minority(n)
        return fm + (fm + 1) // 2
    if protocol in ("atlas", "newt"):
        return minority(n) + f
    if protocol == "caesar":
        return (3 * n) // 4 + 1
    raise ValueError(f"unknown protocol {protocol}")


class Bote:
    def __init__(self, planet: Planet):
        self._planet = planet

    @staticmethod
    def new(dataset: str = "gcp") -> "Bote":
        return Bote(Planet.new(dataset))

    @property
    def planet(self) -> Planet:
        return self._planet

    def leaderless(
        self,
        servers: Sequence[Region],
        clients: Iterable[Region],
        quorum_size: int,
    ) -> List[Tuple[Region, int]]:
        """Per-client perceived latency for a leaderless protocol."""
        out = []
        for client in clients:
            to_closest, closest = self.nth_closest(1, client, servers)
            closest_to_quorum = self.quorum_latency(closest, servers, quorum_size)
            out.append((client, to_closest + closest_to_quorum))
        return out

    def leader(
        self,
        leader: Region,
        servers: Sequence[Region],
        clients: Iterable[Region],
        quorum_size: int,
    ) -> List[Tuple[Region, int]]:
        """Per-client perceived latency with a fixed leader."""
        leader_to_quorum = self.quorum_latency(leader, servers, quorum_size)
        out = []
        for client in clients:
            to_leader = self._planet.ping_latency(client, leader)
            assert to_leader is not None
            out.append((client, to_leader + leader_to_quorum))
        return out

    def best_leader(
        self,
        servers: Sequence[Region],
        clients: Sequence[Region],
        quorum_size: int,
        sort_by: str = "mean",
    ) -> Tuple[Region, Histogram]:
        """The leader minimizing the chosen latency statistic
        ('mean' | 'cov' | 'mdtm')."""
        best = None
        for leader in servers:
            hist = Histogram()
            for _client, latency in self.leader(leader, servers, clients, quorum_size):
                hist.increment(latency)
            stat = getattr(hist, sort_by)()
            if best is None or stat < best[2]:
                best = (leader, hist, stat)
        assert best is not None, "servers must be non-empty"
        return best[0], best[1]

    def quorum_latency(
        self, from_: Region, regions: Sequence[Region], quorum_size: int
    ) -> int:
        latency, _ = self.nth_closest(quorum_size, from_, regions)
        return latency

    def nth_closest(
        self, nth: int, from_: Region, regions: Sequence[Region]
    ) -> Tuple[int, Region]:
        """nth (1-based) closest of ``regions`` to ``from_``; ``from_``
        itself counts at distance 0 when it is in ``regions``."""
        sorted_all = self._planet.sorted_by_distance(from_)
        assert sorted_all is not None, f"{from_} not in planet"
        allowed = set(regions)
        seen = 0
        for latency, region in sorted_all:
            if region in allowed:
                seen += 1
                if seen == nth:
                    return latency, region
        raise AssertionError(f"fewer than {nth} of {regions} in planet")
