"""Native (C++) host-runtime components, loaded via ctypes.

The reference's runtime is entirely native (Rust); here the TPU device
kernels carry the hot path and this package supplies native host pieces
where Python costs real time: the batch SCC resolver used by offline
replay, stuck-residue finishing and the pending watchdog
(fantoch_tpu/native/tarjan.cpp — the C++ twin of
fantoch_ps/src/executor/graph/tarjan.rs).

Build-on-first-use with ``g++`` (see :func:`load`); everything degrades
to the pure-Python oracle when the toolchain or binary is unavailable, so
the framework stays importable anywhere.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "tarjan.cpp")
_LIB = os.path.join(_DIR, "_fantoch_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _build() -> None:
    # compile to a temp path and atomically rename: a concurrent process
    # must never dlopen a partially written .so
    tmp = f"{_LIB}.{os.getpid()}.tmp"
    subprocess.run(
        ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", tmp, _SRC],
        check=True,
        capture_output=True,
        text=True,
        timeout=120,
    )
    os.replace(tmp, _LIB)


def load() -> Optional[ctypes.CDLL]:
    """The native library, building it on first use; None if unavailable."""
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        try:
            if not os.path.exists(_LIB) or os.path.getmtime(_LIB) < os.path.getmtime(_SRC):
                _build()
            lib = ctypes.CDLL(_LIB)
            fn = lib.fantoch_resolve_sccs
            fn.restype = ctypes.c_int32
            fn.argtypes = [
                ctypes.c_int32,
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
            ]
            _lib = lib
        except Exception as exc:  # noqa: BLE001 — toolchain/binary unavailable
            # one-time diagnostic before latching the permanent fallback to
            # the slow Python oracle: a broken toolchain should be loud
            import warnings

            detail = repr(exc)
            stderr = getattr(exc, "stderr", None)
            if stderr:
                detail += f"; stderr: {str(stderr).strip()[-400:]}"
            warnings.warn(
                f"native resolver unavailable, falling back to the Python "
                f"oracle: {detail}",
                RuntimeWarning,
                stacklevel=2,
            )
            _load_failed = True
    return _lib


def available() -> bool:
    return load() is not None


def resolve_sccs(
    offsets: np.ndarray,  # int32[n + 1] CSR row offsets
    targets: np.ndarray,  # int32[nnz] dep slots; -1 executed/none, -2 missing
    dot_key: np.ndarray,  # int64[n] packed dots (intra-SCC order)
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """(order, scc_size_per_position) for the emittable prefix, or None when
    the native library is unavailable (callers fall back to the Python
    oracle).  Same contract as the host Tarjan oracle: SCCs contiguous and
    dot-sorted, dependencies before dependents, missing-blocked components
    omitted."""
    lib = load()
    if lib is None:
        return None
    n = len(offsets) - 1
    if n == 0:
        return np.empty(0, np.int32), np.empty(0, np.int32)
    offsets = np.ascontiguousarray(offsets, dtype=np.int32)
    targets = np.ascontiguousarray(targets, dtype=np.int32)
    dot_key = np.ascontiguousarray(dot_key, dtype=np.int64)
    out_order = np.empty(n, dtype=np.int32)
    out_size = np.empty(n, dtype=np.int32)
    if len(targets) == 0:
        targets = np.zeros(1, dtype=np.int32)  # ndpointer rejects size-0 reuse
    emitted = lib.fantoch_resolve_sccs(
        n, offsets, targets, dot_key, out_order, out_size
    )
    if emitted < 0:
        raise ValueError("native resolver rejected the input CSR")
    return out_order[:emitted], out_size[:emitted]
