// Native batch SCC resolver — the C++ twin of the host Tarjan oracle.
//
// The reference implements its execution-ordering walk in native code
// (fantoch_ps/src/executor/graph/tarjan.rs:99-319, Rust); the TPU rebuild
// keeps the batched device kernel (fantoch_tpu/ops/graph_resolve.py) as
// the hot path and this C++ resolver as the native host oracle for the
// paths a device kernel does not fit: stuck-residue finishing, offline
// execution-log replay (fantoch_tpu/bin/replay.py) and the pending
// watchdog.  Exact same output contract as the Python oracle
// (fantoch_tpu/executor/graph/tarjan.py):
//
//   * members of one SCC are contiguous in the output and sorted by dot;
//   * an SCC follows every SCC it depends on (reverse-topological pop
//     order of Tarjan on the dependency orientation);
//   * vertices reaching a MISSING dependency (dep == -2) are not emitted.
//
// Input: CSR adjacency over batch slots.  dep targets are slot indices,
// -1 = executed/none (pruned), -2 = missing (blocks the component).
//
// Build: fantoch_tpu/native/__init__.py (g++ -O3 -shared, atomic rename),
// loaded via ctypes — no pybind11 dependency.

#include <algorithm>
#include <cstdint>
#include <vector>

namespace {

constexpr int32_t kTerminal = -1;
constexpr int32_t kMissing = -2;

struct Frame {
    int32_t v;
    int32_t edge;  // next edge offset to visit
};

}  // namespace

extern "C" {

// Returns the number of emitted (ordered) vertices, or -1 on bad input.
//   n            — batch size
//   offsets      — int32[n + 1] CSR row offsets into targets
//   targets      — int32[offsets[n]] dep slots (or kTerminal / kMissing)
//   dot_key      — int64[n] packed (source << 32 | sequence), intra-SCC order
//   out_order    — int32[n] emitted execution order (slot indices)
//   out_scc_size — int32[n] SCC size per emitted *position* (repeated for
//                  each member; callers derive CHAIN_SIZE metrics from the
//                  leader positions where a new SCC starts)
int32_t fantoch_resolve_sccs(int32_t n, const int32_t* offsets,
                             const int32_t* targets, const int64_t* dot_key,
                             int32_t* out_order, int32_t* out_scc_size) {
    if (n < 0) return -1;
    // Tarjan bookkeeping
    std::vector<int32_t> index(n, -1);   // discovery id, -1 = unvisited
    std::vector<int32_t> low(n, 0);
    std::vector<char> on_stack(n, 0);
    std::vector<char> blocked(n, 0);     // reaches a missing dependency
    std::vector<int32_t> stack;          // tarjan component stack
    std::vector<Frame> dfs;              // explicit DFS stack
    std::vector<std::vector<int32_t>> sccs;
    int32_t next_id = 0;

    stack.reserve(64);
    dfs.reserve(64);

    for (int32_t root = 0; root < n; ++root) {
        if (index[root] != -1) continue;
        dfs.push_back({root, offsets[root]});
        index[root] = low[root] = next_id++;
        on_stack[root] = 1;
        stack.push_back(root);

        while (!dfs.empty()) {
            Frame& f = dfs.back();
            const int32_t v = f.v;
            if (f.edge < offsets[v + 1]) {
                const int32_t w = targets[f.edge++];
                if (w == kTerminal) continue;
                if (w == kMissing) {
                    blocked[v] = 1;
                    continue;
                }
                if (w < 0 || w >= n) return -1;
                if (index[w] == -1) {
                    index[w] = low[w] = next_id++;
                    on_stack[w] = 1;
                    stack.push_back(w);
                    dfs.push_back({w, offsets[w]});
                } else if (on_stack[w]) {
                    low[v] = std::min(low[v], index[w]);
                } else if (blocked[w]) {
                    // finished component that reaches a missing dep:
                    // poisoning propagates to every dependent
                    blocked[v] = 1;
                }
            } else {
                dfs.pop_back();
                if (!dfs.empty()) {
                    const int32_t parent = dfs.back().v;
                    low[parent] = std::min(low[parent], low[v]);
                    if (blocked[v]) blocked[parent] = 1;
                }
                if (low[v] == index[v]) {
                    // pop the SCC; blocked-ness is shared by all members
                    // (they reach each other), so one flag decides
                    std::vector<int32_t> scc;
                    char scc_blocked = 0;
                    int32_t w;
                    do {
                        w = stack.back();
                        stack.pop_back();
                        on_stack[w] = 0;
                        scc_blocked |= blocked[w];
                        scc.push_back(w);
                    } while (w != v);
                    if (scc_blocked) {
                        for (int32_t m : scc) blocked[m] = 1;
                    } else {
                        std::sort(scc.begin(), scc.end(),
                                  [&](int32_t a, int32_t b) {
                                      return dot_key[a] < dot_key[b];
                                  });
                        sccs.push_back(std::move(scc));
                    }
                }
            }
        }
    }

    // Tarjan pops SCCs in reverse topological order of the condensation
    // *along the dependency orientation*: a component is only rooted after
    // all components it depends on have been popped, so pop order itself
    // is a valid execution order.
    int32_t pos = 0;
    for (const auto& scc : sccs) {
        const int32_t size = static_cast<int32_t>(scc.size());
        for (int32_t m : scc) {
            out_order[pos] = m;
            out_scc_size[pos] = size;
            ++pos;
        }
    }
    return pos;
}

}  // extern "C"
