"""Server side of the real runner: one process = a TCP mesh endpoint plus
worker / executor / client-session asyncio tasks.

Reference: fantoch/src/run/task/{process,executor,client}.rs and
fantoch/src/run/mod.rs:105-445.  Same architecture, asyncio-idiomatic:

* a peer listener accepts inbound connections; a reader task per inbound
  connection routes messages to workers by ``Protocol.message_index``
  (process.rs:292-326);
* outbound connections are opened to every peer (connect_to_all,
  process.rs:21-111) with a writer task per peer draining a send queue;
* ``workers`` protocol tasks pull tagged items from their own queue —
  submits, peer messages, periodic events, executed notifications — call
  into the (shared, cooperatively-scheduled) protocol state machine and
  drain its outputs (the hot ``process_task`` select loop,
  process.rs:467-678);
* ``executors`` executor clones route execution infos by key hash
  (executor.rs:14-120) and push per-key results to the client sessions
  that own each client id;
* client sessions perform the ClientHi handshake, assign dots for
  leaderless protocols (AtomicDotGen, client.rs:221-223), aggregate
  per-key results into CommandResults and stream them back.

Intra-process parallelism note: the reference guards shared protocol state
with Sequential/Atomic/Locked structure variants; here worker tasks share
one protocol object under cooperative scheduling (handlers never await), so
every variant's semantics collapse to the sequential one — the real
parallelism axis on TPU is the batched device step, not threads.
"""

from __future__ import annotations

import asyncio
from typing import Any, Dict, List, Optional, Set, Tuple

from fantoch_tpu.core.config import Config
from fantoch_tpu.core.ids import AtomicIdGen, ClientId, ProcessId, ShardId
from fantoch_tpu.core.timing import RunTime
from fantoch_tpu.errors import PeerLostError, QuorumLostError
from fantoch_tpu.observability.tracer import edge_dot
from fantoch_tpu.executor.aggregate import AggregatePending
from fantoch_tpu.executor.base import ExecutorResult
from fantoch_tpu.protocol.base import Protocol, ToForward, ToSend
from fantoch_tpu.run.links import (
    ACK_EVERY,
    KIND_ACK,
    KIND_DATA,
    LinkState,
    PeerLinks,
    ReconnectPolicy,
)
from fantoch_tpu.run.backpressure import (
    DEFAULT_QUEUE_CAPACITY,
    DEFAULT_UNACKED_CAP,
)
from fantoch_tpu.run.prelude import (
    ClientHi,
    ClientHiAck,
    DigestKeyReply,
    DigestKeyRequest,
    Overloaded,
    PingReply,
    PingReq,
    POEExecutor,
    POEProtocol,
    ProcessHi,
    Register,
    Submit,
    ToClient,
    ToPool,
    Unregister,
    WarnQueue,
)
from fantoch_tpu.run.ingest import (
    AdaptiveIngestBatcher,
    requested_ingest_deadline_ms,
    resolve_ingest_target,
)
from fantoch_tpu.run.routing import worker_dot_index_shift
from fantoch_tpu.run.rw import Rw, connect_with_retry, deserialize, serialize
from fantoch_tpu.utils import key_hash, logger

Address = Tuple[str, int]


def _peek_is_submit(queue: "asyncio.Queue") -> bool:
    """True when the queue's head item is a submit, without dequeuing.
    Peeks CPython's asyncio.Queue internals behind a guard: if the
    implementation detail ever changes we degrade to per-command submits
    (correct, just unbatched) instead of crashing the worker."""
    inner = getattr(queue, "_queue", None)
    if inner is None or not queue.qsize():
        return False
    try:
        return inner[0][0] == "submit"
    except (IndexError, KeyError, TypeError):
        return False


def _info_commit_dots(info: Any) -> List[Any]:
    """The commit dots a logged execution info carries (WAL replay uses
    them to advance the restored committed horizon).  Per-command infos
    expose ``.dot``; the array batches carry dot columns; dotless infos
    (detached votes, requests, slot infos) contribute none."""
    from fantoch_tpu.core.ids import Dot

    dot = getattr(info, "dot", None)
    if isinstance(dot, Dot):
        return [dot]
    dot_src = getattr(info, "dot_src", None)
    dot_seq = getattr(info, "dot_seq", None)
    if dot_src is not None and dot_seq is not None:
        return [
            Dot(int(source), int(sequence))
            for source, sequence in zip(dot_src, dot_seq)
        ]
    return []


def executor_index(info: Any, size: int) -> Optional[int]:
    """Executor routing: by key hash when the info names a key
    (fantoch/src/executor/mod.rs:161-166), else executor 0.  A ``key``
    attribute that is not a string (GraphAddBatch carries the whole key
    *array*) is not a routing key — batches go to the main executor."""
    key = getattr(info, "key", None)
    if isinstance(key, str):
        return key_hash(key) % size
    return 0


class _StampingQueue(WarnQueue):
    """Queue whose items carry their entry time — the delay line's source
    (delay.rs timestamps messages on entry, :6-39).  Inherits the
    warn-on-depth overload signal and the bounded watermark gate
    (delayed links back up first)."""

    def __init__(
        self,
        name: str,
        loop: asyncio.AbstractEventLoop,
        capacity: Optional[int] = None,
    ):
        super().__init__(name, capacity=capacity)
        self._stamp_loop = loop

    def put_nowait(self, item: Any) -> None:  # type: ignore[override]
        super().put_nowait((self._stamp_loop.time(), item))


class _ClientSession:
    """Server side of one client connection (client.rs:79-260)."""

    def __init__(self, runtime: "ProcessRuntime", rw: Rw):
        self.runtime = runtime
        self.rw = rw
        # buffer_early: on a non-target shard the server-side forward can
        # execute the command before this connection's Register arrives
        self.pending = AggregatePending(
            runtime.process.id, runtime.process.shard_id, buffer_early=True
        )
        self.client_ids: List[ClientId] = []
        self._flush_needed = asyncio.Event()

    def deliver(self, result: ExecutorResult) -> None:
        self._emit(self.pending.add_executor_result(result))

    def _shed(self, rifl, depth: int, limit: int) -> None:
        """Admission control: reject a submission with a typed Overloaded
        reply + retry-after hint instead of queueing past the bound —
        warn-then-shed where the reference warn-then-blocks (chan.rs:
        36-58); blocking is the *reader pause* below, reserved for depths
        between the admission limit and the hard queue capacity."""
        runtime = self.runtime
        runtime.shed_submissions += 1
        retry_after = runtime.config.overload_retry_after_ms * max(
            1, depth // max(1, limit)
        )
        from fantoch_tpu.run.backpressure import log_per_doubling

        if log_per_doubling(runtime.shed_submissions):
            logger.warning(
                "p%s: shedding submission %s (edge depth %d >= admission "
                "limit %d; retry after %dms; %d sheds total)",
                runtime.process.id, rifl, depth, limit, retry_after,
                runtime.shed_submissions,
            )
        self.rw.write(Overloaded(rifl, retry_after, depth, limit))
        self._flush_needed.set()

    def _emit(self, cmd_result) -> None:
        if cmd_result is not None:
            self.runtime.replied += 1
            tracer = self.runtime.tracer
            if tracer.enabled:
                # the send half of the coordinator->client hop: with the
                # client's own `reply` span event this brackets the
                # return network flight (critpath's reply_net split)
                tracer.edge(
                    "s", "Reply", self.runtime.process.id, 0, 0,
                    rifl=cmd_result.rifl,
                )
            self.rw.write(ToClient(cmd_result))
            self._flush_needed.set()  # single per-session flusher picks it up

    async def _flush_loop(self) -> None:
        while True:
            await self._flush_needed.wait()
            self._flush_needed.clear()
            try:
                await self.rw.flush()
            except (ConnectionError, OSError):
                return  # session torn down by run()'s recv seeing EOF

    async def run(self) -> None:
        hi = await self.rw.recv()
        if hi is None:
            return  # client vanished before the handshake
        assert isinstance(hi, ClientHi)
        self.client_ids = hi.client_ids
        for client_id in self.client_ids:
            self.runtime.client_sessions[client_id] = self
        flusher = None
        try:
            # ack AFTER registration: the client holds submissions until
            # every shard acks, so a partial can never arrive before its
            # session is routable (the ClientHi-vs-execution race)
            await self.rw.send(ClientHiAck())
            flusher = self.runtime.spawn(self._flush_loop())
            while True:
                msg = await self.rw.recv()
                if msg is None:
                    break
                if isinstance(msg, Register):
                    # non-target shard of a multi-shard command: start
                    # result aggregation for our part, but do not submit
                    # (the target shard's MForwardSubmit drives our
                    # protocol instance)
                    self.pending.wait_for(msg.cmd)
                    self._emit(self.pending.drain_early(msg.cmd.rifl))
                    continue
                if isinstance(msg, Unregister):
                    # the client deadline-shed a multi-shard command the
                    # target shard never admitted: drop our aggregation
                    # entry or it leaks for the session's life
                    self.pending.cancel(msg.rifl)
                    continue
                assert isinstance(msg, Submit)
                cmd = msg.cmd
                self.runtime.submitted += 1
                tracer = self.runtime.tracer
                if tracer.enabled:
                    # ingress edge: the recv half of the client->server
                    # hop — splits submit->payload into network flight
                    # vs coordinator ingest queue in the critpath report
                    tracer.edge(
                        "r", "Submit", 0, self.runtime.process.id, 0,
                        rifl=cmd.rifl,
                    )
                limit = self.runtime.config.admission_limit
                if limit is not None:
                    depth = self.runtime.admission_depth()
                    if depth >= limit:
                        # shed BEFORE wait_for: a rejected command must
                        # leave no aggregation state (the retry re-runs
                        # the full submit path)
                        self._shed(cmd.rifl, depth, limit)
                        continue
                self.pending.wait_for(cmd)
                self._emit(self.pending.drain_early(cmd.rifl))
                dot = (
                    self.runtime.next_dot()
                    if self.runtime.protocol_cls.leaderless()
                    else None
                )
                index = (
                    worker_dot_index_shift(dot)
                    if dot is not None
                    else (0, 0)  # leader-based: submit handled by any worker
                )
                self.runtime.workers.forward(index, ("submit", dot, cmd))
                if self.runtime.workers.gated:
                    # cooperative backpressure at the client edge: stop
                    # reading this client's socket until the worker pool
                    # drains below its low watermark — the client's TCP
                    # stream stalls instead of our heap growing
                    self.runtime.backpressure_pauses += 1
                    await self.runtime.workers.wait_for_credit()
        except (ConnectionError, OSError) as exc:
            # a lost client is the client's problem, not the cluster's:
            # unregister and keep serving everyone else
            logger.warning(
                "client session %s lost mid-run: %r", self.client_ids, exc
            )
        finally:
            if flusher is not None:
                flusher.cancel()
            for client_id in self.client_ids:
                self.runtime.client_sessions.pop(client_id, None)


class ProcessRuntime:
    def __init__(
        self,
        protocol_cls: type,
        process_id: ProcessId,
        shard_id: ShardId,
        config: Config,
        listen_addr: Address,
        client_addr: Address,
        peers: Dict[ProcessId, Address],
        sorted_processes: List[Tuple[ProcessId, ShardId]],
        workers: int = 1,
        executors: int = 1,
        multiplexing: int = 1,
        peer_delays: Optional[Dict[ProcessId, int]] = None,
        ping_sort: bool = False,
        metrics_file: Optional[str] = None,
        metrics_interval_ms: int = 5000,
        execution_log: Optional[str] = None,
        tracer_show_interval_ms: Optional[int] = None,
        reconnect_policy: Optional[ReconnectPolicy] = None,
        send_timeout_s: float = 30.0,
        heartbeat_interval_s: Optional[float] = 1.0,
        heartbeat_misses: int = 8,
        trace_file: Optional[str] = None,
        wal_dir: Optional[str] = None,
        wal_snapshot_interval_ms: int = 2000,
        telemetry_file: Optional[str] = None,
        metrics_port: Optional[int] = None,
        flight_dir: Optional[str] = None,
    ):
        self.protocol_cls = protocol_cls
        self.config = config
        self.listen_addr = listen_addr
        self.client_addr = client_addr
        self.peers = peers
        self.sorted_processes = sorted_processes
        self.time = RunTime()

        self.process: Protocol
        self.process, self.periodic_events = protocol_cls.new(process_id, shard_id, config)
        # sanity: non-parallel components can't be split across tasks
        # (run/mod.rs:191-209)
        if not protocol_cls.parallel():
            workers = 1
        if not protocol_cls.Executor.parallel():
            executors = 1
        # multi-shard graph executors answer peer-shard dependency requests
        # on the secondary executor (executor.rs:242-262): fail fast here
        # rather than hang when a GraphRequest cannot be routed
        if config.shard_count > 1 and hasattr(protocol_cls.Executor, "executor_index_of"):
            assert executors >= 2, (
                "shard_count > 1 needs executors >= 2 (main + secondary "
                "request-serving executor)"
            )
        # overload-control plane (run/backpressure.py): every run-layer
        # queue is bounded with a watermark credit gate (None in the
        # config = the built-in default; an explicit 0 = legacy
        # unbounded warn-only queues), socket readers pause on closed
        # gates, and the client edge sheds past Config.admission_limit
        self.queue_capacity: Optional[int] = (
            DEFAULT_QUEUE_CAPACITY
            if config.queue_capacity is None
            else (config.queue_capacity or None)
        )
        self.link_unacked_cap = (
            DEFAULT_UNACKED_CAP
            if config.link_unacked_cap is None
            else config.link_unacked_cap
        )
        self.shed_submissions = 0
        self.backpressure_pauses = 0
        # consistency-audit plane (core/audit.py): per-key chained
        # execution digests live in the executors' KVStores when
        # Config.execution_digests is on; the heartbeat piggybacks
        # summaries so replicas cross-audit each other online
        self.digest_checks = 0
        self.digest_mismatches = 0
        self.workers = ToPool("workers", workers, capacity=self.queue_capacity)
        self.executor_pool = ToPool(
            "executors", executors, capacity=self.queue_capacity
        )
        if executors > 1:
            # batched array commit seams (Newt's TableVotesArrays) span
            # keys, but a multi-executor pool routes infos per key — fall
            # back to per-command infos so key ownership stays intact
            set_commit_arrays = getattr(self.process, "set_commit_arrays", None)
            if set_commit_arrays is not None:
                set_commit_arrays(False)
        self.executors = [
            protocol_cls.Executor(process_id, shard_id, config) for _ in range(executors)
        ]
        for index, executor in enumerate(self.executors):
            executor.set_executor_index(index)
        # restart plane (run/wal.py): durable command log + snapshots.
        # Recovery runs HERE — before executor state sharing and tracer
        # wiring — so everything downstream operates on restored objects.
        self.wal = None
        self.incarnation = 0
        self._recovered = False
        self._dot_lease = 0
        self._lease_gap_dots: List[Any] = []
        self._wal_snapshot_interval_ms = wal_snapshot_interval_ms
        if wal_dir is not None:
            from fantoch_tpu.run.wal import Wal, resolve_wal_sync

            self.wal = Wal(wal_dir, sync=resolve_wal_sync(config.wal_sync))
            self._recover_from_wal()
        # secondary request-serving executors share the primary's vertex
        # index (the reference's SharedMap across clones, index.rs:19-22):
        # peer-shard requests must be answerable from *pending* vertices or
        # cross-shard dependency cycles deadlock
        share = getattr(type(self.executors[0]), "share_state_from", None)
        if share is not None:
            for executor in self.executors[1:]:
                executor.share_state_from(self.executors[0])
        # accelerator fault tolerance: arm the pool's device planes
        # (deadline/shadow knobs travel in the config; FANTOCH_DEVICE_FAULT
        # env specs rehearse deterministic failures on a live rig) and
        # dump the flight ring on every failover.  After a WAL restore
        # this re-attaches the live handles the pickled planes dropped.
        self._arm_device_faults()
        self.dot_gen = AtomicIdGen(process_id)
        if self._dot_lease:
            # never re-issue a pre-crash sequence (the WAL dot lease)
            self.dot_gen.resume_after(self._dot_lease)
        self.client_sessions: Dict[ClientId, _ClientSession] = {}
        assert multiplexing >= 1
        self.multiplexing = multiplexing
        self._peer_writers: Dict[ProcessId, PeerLinks] = {}
        # crash tolerance (run/links.py): reconnect schedule, per-send
        # timeout, heartbeat failure detector, quorum-aware degradation
        self.reconnect_policy = reconnect_policy or ReconnectPolicy()
        self.send_timeout_s = send_timeout_s
        self.heartbeat_interval_s = heartbeat_interval_s
        self.heartbeat_misses = heartbeat_misses
        self.dead_peers: Set[ProcessId] = set()
        # failure detector state: last loop-time any frame arrived from a
        # peer (readers update it; the heartbeat task judges silence)
        self._last_heard: Dict[ProcessId, float] = {}
        self._shard_of: Dict[ProcessId, ShardId] = dict(sorted_processes)
        # receiver-side dedup state, keyed (peer, link) so it survives
        # reconnects of the underlying TCP connection
        self._link_recv_seq: Dict[Tuple[ProcessId, int], int] = {}
        # last seen WAL incarnation per peer: a bumped incarnation means
        # the peer RESTARTED (fresh seq space) and its dedup state resets;
        # same-life reconnects keep it (run/wal.py)
        self._peer_incarnations: Dict[ProcessId, int] = {}
        # live peer-connection rws -> peer id, for the chaos hook
        self._chaos_rws: Dict[Rw, ProcessId] = {}
        # per-connection artificial delay in ms (delay.rs:6-39): outbound
        # frames to these peers pass through a FIFO delay line
        self.peer_delays = peer_delays or {}
        # latency-sort peers at startup via in-band ping (ping.rs:13-78)
        self.ping_sort = ping_sort
        self._ping_waiters: Dict[int, asyncio.Future] = {}
        self._ping_nonce = 0
        # observability (metrics_logger.rs / execution_logger.rs / tracer.rs)
        self.metrics_file = metrics_file
        self.metrics_interval_ms = metrics_interval_ms
        # live telemetry plane (observability/timeseries.py): ONE periodic
        # writer covers both the windowed series and the legacy pickle
        # snapshot, on ONE cadence — Config.telemetry_interval_ms when
        # set, else the metrics_interval_ms argument
        self.telemetry_interval_ms = (
            config.telemetry_interval_ms
            if config.telemetry_interval_ms is not None
            else metrics_interval_ms
        )
        self.telemetry = None
        if telemetry_file is not None:
            from fantoch_tpu.observability.timeseries import SeriesWriter

            self.telemetry = SeriesWriter(
                telemetry_file, self.time, window_ms=self.telemetry_interval_ms
            )
        # Prometheus-text exposition endpoint + on-demand profile trigger
        # (observability/exposition.py); started in start()
        self.metrics_port = metrics_port
        self.metrics_server = None
        # client-edge throughput tallies: submissions seen (pre-shed) and
        # command results streamed back — the submit/reply rate series
        self.submitted = 0
        self.replied = 0
        self.tracer_show_interval_ms = tracer_show_interval_ms
        self.execution_logger = None
        if execution_log is not None:
            from fantoch_tpu.run.observe import ExecutionLogger

            self.execution_logger = ExecutionLogger(execution_log)
        # per-runtime prof registry (utils/prof.py): installed into the
        # context before tasks spawn, so several runtimes sharing one
        # Python process (the localhost harness) never blend histograms
        from fantoch_tpu.core.metrics import Metrics as _Metrics

        self.prof_registry = _Metrics()
        # per-dot lifecycle tracing (fantoch_tpu/observability): wall-clock
        # spans into this runtime's own JSONL log
        from fantoch_tpu.observability.tracer import NOOP_TRACER, Tracer

        self.tracer = NOOP_TRACER
        if trace_file is not None and config.trace_sample_rate > 0:
            self.tracer = Tracer(
                self.time, trace_file, config.trace_sample_rate, clock="wall"
            )
        # message-edge sequence for cross-process span stitching: one
        # monotone counter per sender, carried as POEProtocol.edge so the
        # receiver's recv event pairs with our send event.  Offset by the
        # WAL incarnation so a restarted life's seqs never collide with
        # the previous life's edges still present in PEERS' trace logs
        # (our own log truncates on reopen; theirs does not)
        self._edge_seq = self.incarnation << 32
        # per-peer wall-clock offsets from heartbeat RTT brackets — the
        # correlator's skew table (run/links.ClockOffsetEstimator)
        from fantoch_tpu.run.links import ClockOffsetEstimator

        self._clock_offsets = ClockOffsetEstimator()
        # failure flight recorder (observability/recorder.py): a bounded
        # ring of UNSAMPLED events teed off the same tracer seam, dumped
        # as flight_p<pid>.json on fatal failures / WAL-restart boots /
        # SIGUSR1 — every failure ships its own black box
        self.flight = None
        self.flight_dir = flight_dir
        if config.flight_recorder:
            from fantoch_tpu.observability.exposition import profile_output_dir
            from fantoch_tpu.observability.recorder import FlightRecorder

            if self.flight_dir is None:
                self.flight_dir = profile_output_dir(
                    trace_file, telemetry_file, metrics_file
                )
            self.flight = FlightRecorder(
                self.time, pid=process_id, inner=self.tracer
            )
            self.tracer = self.flight
        self.process.set_tracer(self.tracer)
        for executor in self.executors:
            executor.set_tracer(self.tracer)
        self._tasks: Set[asyncio.Task] = set()
        self._servers: List[asyncio.base_events.Server] = []
        self._connected = asyncio.Event()
        # set during stop()/_teardown(): reconnect loops and the failure
        # detector must stand down — a peer vanishing because the operator
        # is shutting the cluster down is not a fault (and a cancellation
        # surfacing as wait_for's TimeoutError inside the writer must not
        # resurrect the task into a reconnect loop)
        self._stopping = False
        # first task failure; .failed is awaited by harnesses so a crashed
        # worker tears the cluster down loudly instead of stalling it
        self.failure: Optional[BaseException] = None
        self.failed = asyncio.Event()

    # --- restart plane (run/wal.py) ---

    def _recover_from_wal(self) -> None:
        """Boot-time restart: load the latest snapshot, replay the log
        tail into the executors, resume the dot lease, and bump the
        incarnation.  ``start()`` triggers the rejoin sync (MSync
        catch-up past our horizon) once the mesh is connected."""
        state = self.wal.recover()
        self.incarnation = self.wal.incarnation
        self._dot_lease = state.dot_lease
        snap = state.snapshot
        replayed = 0
        if snap is not None:
            self.process = self.protocol_cls.restore(snap["protocol"])
            blobs = snap["executors"]
            assert len(blobs) == len(self.executors), (
                "executor pool size changed across restart"
            )
            from fantoch_tpu.executor.base import Executor as _Executor

            self.executors = [_Executor.restore(blob) for blob in blobs]
            for index, executor in enumerate(self.executors):
                executor.set_executor_index(index)
            if self.executor_pool.size > 1:
                # re-apply the per-key-pool arrays opt-out to the
                # restored protocol instance
                set_commit_arrays = getattr(self.process, "set_commit_arrays", None)
                if set_commit_arrays is not None:
                    set_commit_arrays(False)
            # infos queued but unconsumed at snapshot time ride the
            # snapshot (they predate the log position the tail starts at)
            for info in snap.get("queued_infos", ()):
                self._replay_info(info)
                replayed += 1
        for kind, payload in state.tail:
            if kind == "info":
                self._replay_info(payload)
                replayed += 1
        # fold every replayed commit dot into the restored protocol's
        # committed clock: the rejoin horizon (MSync) must cover the
        # tail, or peers would re-stream commits whose effects the
        # executor replay already applied — a second application would
        # execute them twice (exactly-once across restart)
        tail_dots = sorted(
            {
                dot
                for _kind, payload in state.tail
                if _kind == "info"
                for dot in _info_commit_dots(payload)
            }
            | {
                dot
                for payload in ((snap or {}).get("queued_infos", ()))
                for dot in _info_commit_dots(payload)
            }
        )
        if tail_dots:
            self.process.note_durable_commits(tail_dots)
        # slot-ordered protocols (FPaxos): the replayed infos carry slots,
        # not dots — fold them so the rejoin MSlotSync floor covers the
        # tail (re-streaming would execute the slots twice)
        tail_slot_records: Dict[int, Any] = {}
        for payload in (snap or {}).get("queued_infos", ()):
            if hasattr(payload, "slot"):
                tail_slot_records[payload.slot] = payload.cmd
        for _kind, payload in state.tail:
            if _kind == "info" and hasattr(payload, "slot"):
                tail_slot_records[payload.slot] = payload.cmd
        if tail_slot_records:
            self.process.note_durable_chosen(sorted(tail_slot_records.items()))
        # the dot lease's unissued remainder: [last-committed-own-seq+1,
        # lease] sequences may never be issued again, and GC stability
        # is a meet of CONTIGUOUS frontiers — an unfilled gap would
        # freeze the whole mesh's stable frontier for this source
        # forever.  Rejoin nudges the hole dots into recovery consensus
        # (they commit as noops where nobody ever saw them; in-flight
        # ones resolve to their real value), restoring contiguity.
        self._lease_gap_dots = self._compute_lease_gap()
        self.wal_replayed_infos = replayed
        self._recovered = snap is not None or bool(state.tail)
        if self._recovered:
            logger.warning(
                "p%s: recovered from WAL (incarnation %d, snapshot=%s, "
                "%d replayed commit infos); rejoin sync runs after connect",
                self.process.id,
                self.incarnation,
                snap is not None,
                replayed,
            )

    def _compute_lease_gap(self) -> List[Any]:
        """Own-source dots at or below the recovered lease that are not
        in the committed clock: never-issued remainder of the last lease
        batch plus pre-crash in-flight dots.  Bounded by
        DOT_LEASE_BATCH + the in-flight window."""
        if not self._dot_lease:
            return []
        clock = getattr(self.process, "_gc_track", None)
        if (
            clock is None
            or not hasattr(clock, "my_clock")  # slot-watermark GC (FPaxos)
            or self.config.shard_count != 1
        ):
            return []
        from fantoch_tpu.core.ids import Dot

        me = self.process.id
        mine = clock.my_clock().get(me)
        return [
            Dot(me, sequence)
            for sequence in range(1, self._dot_lease + 1)
            if mine is None or not mine.contains(sequence)
        ]

    def _replay_info(self, info: Any) -> None:
        """Re-feed one logged commit info into its executor.  Results are
        discarded — their client sessions died with the previous life
        (clients reconnect and the rifl-dedup seams make re-submission
        exactly-once); KVStore effects are deterministic re-applies in
        the original order, so the store converges to the crash state."""
        executor = self.executors[self._executor_position(info)]
        executor.handle_batch([info], self.time)
        for _result in executor.to_clients_iter():
            pass
        for _out in executor.to_executors_iter():
            pass

    def _write_wal_snapshot(self) -> None:
        """One crash-consistent snapshot: protocol + executors + the
        infos currently queued toward the executor pool (logged before
        the snapshot's position but not yet applied — without them the
        tail would skip their effects).  Runs between task steps on the
        cooperative loop, so the capture is atomic w.r.t. handlers."""
        queued: List[Any] = []
        for position in range(self.executor_pool.size):
            inner = getattr(self.executor_pool.queue(position), "_queue", None)
            if inner:
                queued.extend(inner)
        self.wal.save_snapshot(
            {
                "protocol": self.process.snapshot(),
                "executors": [executor.snapshot() for executor in self.executors],
                "queued_infos": queued,
                "dot_lease": self._dot_lease,
            }
        )

    async def _wal_task(self) -> None:
        """Periodic WAL tick: fsync appends (the ``interval`` policy's
        loss bound) and take rotation-bounded snapshots so restart is
        snapshot + a short tail, and the log stays finite."""
        loop = asyncio.get_running_loop()
        snap_interval = self._wal_snapshot_interval_ms / 1000
        tick = min(1.0, snap_interval)
        last_snapshot = loop.time()
        while True:
            await asyncio.sleep(tick)
            self.wal.sync()
            if loop.time() - last_snapshot >= snap_interval:
                self._write_wal_snapshot()
                last_snapshot = loop.time()

    def next_dot(self):
        """Dot allocation with the WAL lease: the generator's high
        watermark is persisted (fsync'd regardless of policy) in
        DOT_LEASE_BATCH strides ahead of use, so a restarted process can
        never re-issue a live sequence."""
        dot = self.dot_gen.next_id()
        if self.wal is not None and dot.sequence > self._dot_lease:
            from fantoch_tpu.run.wal import DOT_LEASE_BATCH

            self._dot_lease = dot.sequence + DOT_LEASE_BATCH
            self.wal.append_lease(self._dot_lease)
        return dot

    # --- lifecycle ---

    def spawn(self, coro) -> asyncio.Task:
        task = asyncio.ensure_future(coro)
        task.add_done_callback(self._on_task_done)
        self._tasks.add(task)
        return task

    def _on_task_done(self, task: asyncio.Task) -> None:
        # a dead worker/reader/executor silently stalls the whole process
        # (the reference logs and exits the task, process.rs:320-325); make
        # failures loud: record the exception and actively tear down.
        # (Raising here would only reach the loop exception handler.)
        # Connection-level failures are NOT fatal anymore: writer tasks
        # reconnect with backoff and surface PeerLostError through the
        # quorum check (_declare_peer_lost) instead of escaping here.
        self._tasks.discard(task)
        if task.cancelled():
            return
        exc = task.exception()
        if exc is not None:
            logger.error("runner task crashed: %r", exc)
            self._fail(exc)

    def _arm_device_faults(self) -> None:
        """Wire the accelerator fault plane into every device plane the
        executor pool drives: re-apply the config knobs (per-dispatch
        deadline, shadow-check rate), install any ``FANTOCH_DEVICE_FAULT``
        env-spec injector (sim/device_faults.py — the live rehearsal of
        the sim nemesis), and attach a failure listener that dumps the
        flight ring.  A failover is NOT fatal: the plane keeps serving
        bit-for-bit from its host twin and cuts back after rebuild — the
        dump is the black box, not a teardown."""
        from fantoch_tpu.sim.device_faults import install_env_faults

        planes = [
            plane
            for executor in self.executors
            for plane in executor.device_planes()
        ]
        if not planes:
            return
        pid = self.process.id
        for plane in planes:
            plane.configure_faults(self.config, process_id=pid)

        def record(plane_name, kind, dispatch, detail):
            logger.warning(
                "p%s: injected device fault %s on %s plane at dispatch %d (%s)",
                pid, kind, plane_name, dispatch, detail,
            )

        install_env_faults(planes, process_id=pid, record=record)

        def on_failure(plane, exc):
            logger.warning(
                "p%s: %s plane failed over (%r); serving from host twin",
                pid, plane.plane_name, exc,
            )
            self._dump_flight(
                f"device-failover: {plane.plane_name}: {type(exc).__name__}",
                suffix=f"_{plane.plane_name}",
            )

        for plane in planes:
            plane.attach_failure_listener(on_failure)

    def _fail(self, exc: BaseException) -> None:
        """Record the first fatal failure and tear the runtime down.
        The flight recorder dumps FIRST — the ring's recent unsampled
        events are the black box that explains the typed failure
        (DivergenceError, StalledExecutionError, QuorumLostError, ...)."""
        if self.failure is None:
            self.failure = exc
            self.failed.set()
            self._dump_flight(f"{type(exc).__name__}: {exc}")
        self._teardown()

    def _dump_flight(self, reason: str, suffix: str = "") -> Optional[str]:
        """Write the flight ring (no-op without a recorder); dump
        failures must never mask the failure being recorded."""
        if self.flight is None:
            return None
        path = f"{self.flight_dir}/flight_p{self.process.id}{suffix}.json"
        try:
            self.flight.dump(path, reason)
        except OSError as exc:
            logger.error("flight dump to %s failed: %r", path, exc)
            return None
        logger.warning(
            "p%s: flight recorder dumped %d event(s) to %s (%s)",
            self.process.id, len(self.flight.events()), path, reason,
        )
        return path

    async def _boot_flight_dump(self) -> None:
        """WAL-restart boot trigger: give the rejoin exchange one
        snapshot interval to land in the ring, then dump the new life's
        replay/rejoin black box (its own file — a later failure dump
        must not overwrite the boot record)."""
        await asyncio.sleep(
            min(1.0, self._wal_snapshot_interval_ms / 1000)
        )
        self._dump_flight(
            f"wal-restart-boot (incarnation {self.incarnation})",
            suffix="_boot",
        )

    def _teardown(self) -> None:
        self._stopping = True
        for task in list(self._tasks):
            task.cancel()
        for server in self._servers:
            server.close()

    async def start(self) -> None:
        """Listen, connect to all peers, then start worker/executor loops."""
        # scope the prof registry to this runtime BEFORE any task spawns:
        # every spawned task snapshots the context and records here (when
        # start() runs as its own task — the harness pattern — the caller's
        # context is untouched)
        from fantoch_tpu.utils import prof

        prof.set_registry(self.prof_registry)
        # count XLA recompiles for the metrics snapshot when any device
        # plane can compile (the hook is process-global and idempotent)
        if (
            self.config.device_table_plane
            or self.config.device_pred_plane
            or self.config.device_graph_plane
            or self.config.batched_graph_executor
            or self.config.batched_table_executor
            or self.config.batched_pred_executor
        ):
            from fantoch_tpu.core.compile_cache import ensure_compile_cache
            from fantoch_tpu.observability.device import subscribe_recompiles

            subscribe_recompiles()
            # persistent compile cache before the first dispatch:
            # restarted processes reload programs from disk instead of
            # re-paying the compile wall
            ensure_compile_cache(self.config, obs_dir=self._obs_dir())
        peer_server = await asyncio.start_server(self._on_peer, *self.listen_addr)
        client_server = await asyncio.start_server(self._on_client, *self.client_addr)
        self._servers = [peer_server, client_server]

        # connect to every peer — `multiplexing` reliable links each,
        # retrying while they boot (process.rs:71-111).  The links object
        # is only registered once its first connection is up: the reader
        # task's wait-guard keys on _peer_writers membership, and an empty
        # links would crash its random pick
        for peer_id, addr in self.peers.items():
            links = PeerLinks()
            for index in range(self.multiplexing):
                rw = await connect_with_retry(addr)
                await rw.send(
                    ProcessHi(
                        self.process.id, self.process.shard_id, index,
                        self.incarnation,
                    )
                )
                link = LinkState(
                    peer_id, addr, index, rw,
                    unacked_cap=self.link_unacked_cap,
                )
                self._chaos_rws[rw] = peer_id
                delay_ms = self.peer_delays.get(peer_id)
                if delay_ms:
                    # FIFO delay line between the enqueue side and the
                    # writer (delay.rs:6-39): frames leave `delay_ms` after
                    # entering, so entry times are stamped at put (a burst
                    # still leaves one delay later, not serialized at one
                    # frame per delay)
                    queue = _StampingQueue(
                        f"delay->p{peer_id}[{index}]",
                        asyncio.get_running_loop(),
                        capacity=self.queue_capacity,
                    )
                    delayed: asyncio.Queue = WarnQueue(
                        f"writer->p{peer_id}[{index}]",
                        capacity=self.queue_capacity,
                    )
                    self.spawn(self._delay_task(queue, delayed, delay_ms))
                    link.queue = delayed
                else:
                    queue = WarnQueue(
                        f"writer->p{peer_id}[{index}]",
                        capacity=self.queue_capacity,
                    )
                    link.queue = queue
                link.writer_task = self.spawn(self._peer_writer_task(link))
                self.spawn(self._ack_reader_task(link, rw))
                links.queues.append(queue)
                links.links.append(link)
                self._peer_writers[peer_id] = links

        if self.ping_sort:
            self.sorted_processes = await self._ping_sorted_processes()
        connect_ok, self.closest_shard_process = self.process.discover(
            self.sorted_processes
        )
        assert connect_ok, "discover must succeed with a full process list"

        for position in range(self.workers.size):
            self.spawn(self._worker_task(position))
        for position in range(self.executor_pool.size):
            self.spawn(self._executor_task(position))
        for event, interval_ms in self.periodic_events:
            self.spawn(self._periodic_task(event, interval_ms))
        interval = self.config.executor_executed_notification_interval_ms
        if interval is not None:
            self.spawn(self._executed_notification_task(interval))
        cleanup = self.config.executor_cleanup_interval_ms
        if cleanup is not None and self.config.shard_count > 1:
            self.spawn(self._executor_cleanup_task(cleanup))
        if self.heartbeat_interval_s is not None and self.peers:
            self.spawn(self._heartbeat_task())
        if self.metrics_file is not None or self.telemetry is not None:
            # one telemetry writer, one cadence: the windowed series and
            # the legacy pickle snapshot share the periodic task
            self.spawn(self._telemetry_task())
        if self.metrics_port is not None:
            from fantoch_tpu.observability.exposition import MetricsServer

            self.metrics_server = MetricsServer(
                self.telemetry_sample,
                self.metrics_port,
                labels={"pid": str(self.process.id)},
                profile_dir=self._obs_dir(),
            )
            await self.metrics_server.start()
            self.metrics_port = self.metrics_server.port
        if self.execution_logger is not None:
            self.spawn(self._execution_log_flush_task())
        if self.tracer.enabled:
            self.spawn(self._trace_flush_task())
        if self.tracer_show_interval_ms is not None:
            # the span-subscriber analog: enabling the tracer installs
            # latency spans over the hot paths automatically
            # (fantoch_prof/src/lib.rs:78-136 via utils/prof.py)
            from fantoch_tpu.utils import prof

            prof.auto_instrument()
            self.spawn(self._tracer_task())
        if self.wal is not None:
            self.spawn(self._wal_task())
        if self._recovered:
            # rejoin: now that the mesh is connected, broadcast MSync so
            # live peers stream the commits we missed while down
            self.workers.forward_to(0, ("rejoin", None))
            if self.flight is not None:
                self.spawn(self._boot_flight_dump())
        self._connected.set()

    async def stop(self) -> None:
        self._stopping = True
        tasks = list(self._tasks)
        self._teardown()
        # bounded re-cancel: asyncio.wait_for can swallow a cancellation
        # (inner future completes in the cancel's tick), leaving a task
        # parked with no cancel pending — re-cancel instead of hanging
        for _round in range(3):
            if not tasks:
                break
            _done, pending = await asyncio.wait(tasks, timeout=5)
            if not pending:
                break
            for task in pending:
                task.cancel()
            tasks = list(pending)
        if self.metrics_server is not None:
            await self.metrics_server.stop()
        if self.execution_logger is not None:
            self.execution_logger.close()
        if self.metrics_file is not None or self.telemetry is not None:
            # final window + snapshot so short runs always leave one behind
            self._emit_telemetry()
        if self.telemetry is not None:
            self.telemetry.close()
        if self.wal is not None:
            # flush, no final snapshot: every recovery is crash-shaped
            # (last periodic snapshot + tail), so the restart path the
            # tests exercise is the one production would take
            self.wal.close()
        self.tracer.close()

    # --- connection handlers ---

    async def _on_peer(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        rw = Rw(reader, writer)
        hi = await rw.recv()
        if hi is None:
            return  # dialer gave up (e.g. crashed mid-handshake)
        assert isinstance(hi, ProcessHi), f"unexpected handshake {hi}"
        incarnation = getattr(hi, "incarnation", 0)
        known = self._peer_incarnations.get(hi.process_id)
        if known is not None and incarnation != known:
            # the peer RESTARTED: its links number frames from 1 again —
            # reset per-link dedup or every new frame would be swallowed
            # as a duplicate of the previous life
            for key in list(self._link_recv_seq):
                if key[0] == hi.process_id:
                    self._link_recv_seq[key] = 0
            logger.warning(
                "p%s: peer p%s handshake with new incarnation %d "
                "(was %d): link dedup reset",
                self.process.id, hi.process_id, incarnation, known,
            )
        self._peer_incarnations[hi.process_id] = incarnation
        if hi.process_id in self.dead_peers:
            self._declare_peer_up(hi.process_id)
        self._chaos_rws[rw] = hi.process_id
        self.spawn(
            self._reader_task(
                hi.process_id, hi.shard_id, rw, (hi.process_id, getattr(hi, "link", 0))
            )
        )

    async def _on_client(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        await self._connected.wait()
        session = _ClientSession(self, Rw(reader, writer))
        self.spawn(session.run())

    # --- tasks ---

    async def _reader_task(
        self,
        from_: ProcessId,
        from_shard: ShardId,
        rw: Rw,
        dedup_key: Tuple[ProcessId, int],
    ) -> None:
        """Route peer messages to workers by message index, and peer
        executor infos (cross-shard dependency traffic) to the executor
        pool (process.rs:292-326).

        Frames arrive sequence-numbered (run/links.py): after a sender
        reconnect it resends its unacked window, so frames at or below the
        last seen sequence are dropped here (exactly-once delivery across
        connection loss); the ack written back — immediately on connect,
        then every ACK_EVERY frames — trims the sender's window."""
        last_seq = self._link_recv_seq.setdefault(dedup_key, 0)
        try:
            await self._reader_loop(from_, from_shard, rw, dedup_key, last_seq)
        finally:
            # drop the chaos-hook registration with the connection, or a
            # flapping link accumulates one dead Rw per reconnect
            self._chaos_rws.pop(rw, None)

    async def _reader_loop(
        self,
        from_: ProcessId,
        from_shard: ShardId,
        rw: Rw,
        dedup_key: Tuple[ProcessId, int],
        last_seq: int,
    ) -> None:
        try:
            rw.write_link_frame(KIND_ACK, last_seq, b"")
            await rw.flush()
        except (ConnectionError, OSError):
            return
        received = 0
        loop = asyncio.get_running_loop()
        while True:
            frame = await rw.recv_link_frame()
            if frame is None:
                return
            self._last_heard[from_] = loop.time()
            if from_ in self.dead_peers:
                # frames from a peer we declared dead: it is back (wrong
                # call, or it restarted and reconnected) — revive it
                self._declare_peer_up(from_)
            kind, seq, payload = frame
            if kind != KIND_DATA:
                continue
            if seq <= self._link_recv_seq[dedup_key]:
                continue  # duplicate from a reconnect resend
            self._link_recv_seq[dedup_key] = seq
            received += 1
            if received % ACK_EVERY == 0:
                try:
                    rw.write_link_frame(KIND_ACK, seq, b"")
                    await rw.flush()
                except (ConnectionError, OSError):
                    return
            msg = deserialize(payload)
            if isinstance(msg, PingReq):
                # our outbound writer to this peer may still be connecting
                # (pings fly during start); wait for it rather than crash
                while from_ not in self._peer_writers:
                    await asyncio.sleep(0.01)
                t_send = getattr(msg, "t_send_us", None)
                self._peer_writers[from_].put_nowait(
                    serialize(
                        PingReply(
                            msg.nonce,
                            req_t_send_us=t_send,
                            t_reply_us=(
                                self.time.micros() if t_send is not None else None
                            ),
                        )
                    )
                )
                digest = getattr(msg, "digest", None)
                if digest is not None:
                    self._check_peer_digest(from_, digest)
            elif isinstance(msg, DigestKeyRequest):
                while from_ not in self._peer_writers:
                    await asyncio.sleep(0.01)
                self._peer_writers[from_].put_nowait(
                    serialize(DigestKeyReply(msg.key, self._digest_entries(msg.key)))
                )
            elif isinstance(msg, DigestKeyReply):
                self._resolve_divergence(from_, msg.key, msg.entries)
            elif isinstance(msg, PingReply):
                waiter = self._ping_waiters.pop(msg.nonce, None)
                if waiter is not None and not waiter.done():
                    waiter.set_result(None)
                # clock-offset bracket: fold the echoed stamps into the
                # per-peer estimate; an improved (lower-RTT) sample rides
                # the trace so the correlator sees the best-known skew
                req_t = getattr(msg, "req_t_send_us", None)
                if req_t is not None and msg.t_reply_us is not None:
                    improved = self._clock_offsets.sample(
                        from_, req_t, msg.t_reply_us, self.time.micros()
                    )
                    if improved is not None and self.tracer.enabled:
                        rtt, off = improved
                        self.tracer.offset(self.process.id, from_, off, rtt)
            elif isinstance(msg, POEExecutor):
                position = self._executor_position(msg.info)
                self.executor_pool.forward_to(position, msg.info)
            else:
                assert isinstance(msg, POEProtocol)
                edge_seq = getattr(msg, "edge", None)
                if edge_seq is not None and self.tracer.enabled:
                    # the recv half of a stitched message edge: pairs
                    # with the sender's (src, seq) send event
                    dot = edge_dot(msg.msg)
                    if dot is not None:
                        self.tracer.edge(
                            "r", type(msg.msg).__name__, from_,
                            self.process.id, edge_seq, dot=dot,
                        )
                index = self.protocol_cls.message_index(msg.msg)
                self.workers.forward(index, ("msg", from_, from_shard, msg.msg))
            if self.workers.gated or self.executor_pool.gated:
                # cooperative backpressure: a downstream queue crossed its
                # high watermark — stop draining this peer's socket until
                # it falls below the low one.  The pause propagates to
                # the sending peer via TCP flow control (its writer task
                # blocks on flush), which is how pressure crosses process
                # boundaries without unbounded buffering on either side
                self.backpressure_pauses += 1
                await self.workers.wait_for_credit()
                await self.executor_pool.wait_for_credit()

    @staticmethod
    async def _delay_task(
        source: "_StampingQueue", sink: asyncio.Queue, delay_ms: int
    ) -> None:
        """FIFO delay line (delay.rs:6-39): each frame is released
        ``delay_ms`` after it *entered* the queue (entry time stamped by
        the _StampingQueue at put), preserving order.  The delay task is
        an asynchronous producer, so it CAN honor the sink's credit gate:
        a backed-up writer pauses the line instead of growing the sink."""
        loop = asyncio.get_running_loop()
        while True:
            entered, frame = await source.get()
            remaining = entered + delay_ms / 1000 - loop.time()
            if remaining > 0:
                await asyncio.sleep(remaining)
            sink.put_nowait(frame)
            if getattr(sink, "gated", False):
                await sink.wait_for_credit()

    async def _ping_sorted_processes(self) -> List[Tuple[ProcessId, ShardId]]:
        """Latency-sort same-shard peers by measured RTT (ping.rs:13-78,
        sort_by_distance :144); self always leads at 0ms, other-shard
        entries keep their closest-process role."""
        shard_peers = [
            (pid, s) for pid, s in self.sorted_processes
            if s == self.process.shard_id and pid != self.process.id
        ]
        # peers are probed concurrently: total ping time ~= samples RTTs of
        # the slowest peer, not the sum over peers
        measured = await asyncio.gather(
            *(self._ping_peer(pid) for pid, _s in shard_peers)
        )
        rtts: Dict[ProcessId, float] = {
            pid: rtt for (pid, _s), rtt in zip(shard_peers, measured)
        }
        ordered = sorted(shard_peers, key=lambda e: rtts[e[0]])
        others = [
            (pid, s) for pid, s in self.sorted_processes
            if s != self.process.shard_id
        ]
        return [(self.process.id, self.process.shard_id)] + ordered + others

    async def _ping_peer(
        self, peer_id: ProcessId, samples: int = 3, timeout: float = 10.0
    ) -> float:
        """Median RTT to a peer over the live connection, ms."""
        loop = asyncio.get_running_loop()
        times = []
        for _ in range(samples):
            self._ping_nonce += 1
            nonce = self._ping_nonce
            fut: asyncio.Future = loop.create_future()
            self._ping_waiters[nonce] = fut
            t0 = loop.time()
            self._peer_writers[peer_id].put_nowait(serialize(PingReq(nonce)))
            try:
                await asyncio.wait_for(fut, timeout=timeout)
            finally:
                self._ping_waiters.pop(nonce, None)
            times.append((loop.time() - t0) * 1000)
        times.sort()
        return times[len(times) // 2]

    async def _peer_writer_task(self, link: LinkState) -> None:
        """Drains pre-serialized frames onto one reliable peer link
        (serialization happens at enqueue time: a message may also be
        self-delivered, and the local handler can mutate it in place
        before this task would run).

        Crash tolerance: every data frame is sequence-numbered and kept in
        the link's unacked window until the peer acks it; a send error or
        per-send timeout triggers reconnect-with-backoff-and-jitter, after
        which the window is resent (the peer's reader dedups by seq).
        When the reconnect budget is exhausted the peer goes through the
        quorum check instead of tearing the whole process down."""
        queue = link.queue
        # the _stopping check also reaps a cancellation that wait_for
        # swallowed (inner future completed in the same tick the cancel
        # landed — asyncio returns the result and loses the cancel); the
        # task must still exit promptly or stop()'s gather hangs on it
        while not link.dead and not self._stopping:
            rw = link.rw
            try:
                if link.resend:
                    for seq, frame in link.unacked:
                        rw.write_link_frame(KIND_DATA, seq, frame)
                    link.resend = False
                    await asyncio.wait_for(rw.flush(), self.send_timeout_s)
                    continue
                frame = await queue.get()
                rw.write_link_frame(KIND_DATA, link.next_seq(), frame)
                link.note_sent(link.seq, frame)
                # batch whatever accumulated while writing (flush
                # coalescing, process.rs:329-385)
                while not queue.empty():
                    frame = queue.get_nowait()
                    rw.write_link_frame(KIND_DATA, link.next_seq(), frame)
                    link.note_sent(link.seq, frame)
                await asyncio.wait_for(rw.flush(), self.send_timeout_s)
                if link.over_unacked_cap():
                    # the peer reads frames (TCP accepts them) but never
                    # acks: a live-but-wedged consumer.  Buffering more
                    # resend state only converts its overload into our
                    # OOM — declare the peer lost through the existing
                    # typed path (quorum check decides degrade vs fail)
                    self._declare_peer_lost(
                        link.peer_id,
                        PeerLostError(
                            link.peer_id,
                            0,
                            BufferError(
                                f"unacked resend window overflow "
                                f"({len(link.unacked)} > {link.unacked_cap})"
                            ),
                        ),
                    )
                    return
            except (ConnectionError, OSError, asyncio.TimeoutError):
                # NB: a cancellation hitting inside wait_for can surface
                # as TimeoutError (the classic asyncio footgun) — the
                # _stopping check keeps a shutdown from resurrecting this
                # task into a reconnect loop that outlives stop()
                if link.dead or self._stopping:
                    return
                try:
                    await self._reconnect_link(link)
                except PeerLostError as exc:
                    self._declare_peer_lost(link.peer_id, exc)
                    return

    async def _ack_reader_task(self, link: LinkState, rw: Rw) -> None:
        """Reads ack frames the peer's reader writes back on our outbound
        connection, trimming the link's resend window.  Ends silently on
        EOF — the writer owns reconnects (one per connection incarnation;
        a reconnect spawns a fresh one on the new rw)."""
        loop = asyncio.get_running_loop()
        try:
            while True:
                frame = await rw.recv_link_frame()
                if frame is None:
                    return
                self._last_heard[link.peer_id] = loop.time()
                kind, seq, _payload = frame
                if kind == KIND_ACK:
                    link.ack(seq)
        finally:
            # dead connection: only the live rw should stay registered for
            # the chaos hook (the writer re-registers on reconnect)
            if rw is not link.rw:
                self._chaos_rws.pop(rw, None)

    async def _reconnect_link(self, link: LinkState) -> None:
        """Re-dial one peer link with exponential backoff + full jitter;
        raises PeerLostError once the policy's attempts are exhausted."""
        link.rw.abort()
        last: Optional[BaseException] = None
        attempts = 0
        for delay in self.reconnect_policy.delays():
            if link.dead or self._stopping:
                raise PeerLostError(link.peer_id, attempts, last)
            attempts += 1
            await asyncio.sleep(delay)
            try:
                rw = await asyncio.wait_for(
                    connect_with_retry(link.addr, attempts=1),
                    self.send_timeout_s,
                )
                await rw.send(
                    ProcessHi(
                        self.process.id, self.process.shard_id, link.index,
                        self.incarnation,
                    )
                )
            except (ConnectionError, OSError, asyncio.TimeoutError) as exc:
                last = exc
                continue
            self._chaos_rws.pop(link.rw, None)
            self._chaos_rws[rw] = link.peer_id
            link.rw = rw
            link.resend = True
            self.spawn(self._ack_reader_task(link, rw))
            logger.warning(
                "p%s: reconnected link %d to p%s after %d attempt(s), "
                "resending %d unacked frame(s)",
                self.process.id,
                link.index,
                link.peer_id,
                attempts,
                len(link.unacked),
            )
            return
        raise PeerLostError(link.peer_id, attempts, last)

    async def _heartbeat_task(self) -> None:
        """Peer failure detector: every interval, ping each peer (so even
        an idle link generates traffic whose replies refresh
        ``_last_heard``), and declare a peer lost only after
        ``heartbeat_misses`` intervals of *total silence* — no frame of
        any kind heard from it.  Judging silence rather than ping RTTs
        keeps a congested-but-alive cluster (many processes sharing one
        cooperative loop or core) from false-positive amputations; a
        wedged or unreachable peer still trips the quorum check
        (ping.rs:13-78 machinery, promoted from boot-time sort to a
        liveness monitor)."""
        loop = asyncio.get_running_loop()
        silence_window = self.heartbeat_interval_s * self.heartbeat_misses
        for peer_id in self.peers:
            self._last_heard.setdefault(peer_id, loop.time())
        while True:
            await asyncio.sleep(self.heartbeat_interval_s)
            if self._stopping:
                return
            # divergence detection rides the heartbeat: piggyback our
            # per-key digest summary so every peer cross-audits us at
            # detector cadence (serialized once per tick, not per peer)
            digest = (
                self._digest_summary()
                if self.config.execution_digests
                else None
            )
            for peer_id in self.peers:
                if peer_id in self.dead_peers:
                    continue
                # fire-and-forget probe: any reply (or any other frame)
                # refreshes _last_heard via the reader.  The send stamp
                # turns each probe into a clock-offset bracket (the
                # reply echoes it plus the replier's clock)
                self._ping_nonce += 1
                self._peer_writers[peer_id].put_nowait(
                    serialize(
                        PingReq(
                            self._ping_nonce, digest,
                            t_send_us=self.time.micros(),
                        )
                    )
                )
                silent_for = loop.time() - self._last_heard[peer_id]
                if silent_for > silence_window:
                    self._declare_peer_lost(
                        peer_id,
                        PeerLostError(
                            peer_id,
                            self.heartbeat_misses,
                            TimeoutError(f"silent for {silent_for:.1f}s"),
                        ),
                    )

    # --- online divergence detection (core/audit.py digests) ---

    def _digest_summary(self) -> Optional[Dict[str, Any]]:
        """Merged per-key (count, chain digest) summary across the
        executor pool (executors own disjoint key sets); None when
        digests are off or nothing executed yet."""
        merged: Dict[str, Any] = {}
        for executor in self.executors:
            digest = executor.digest()
            if digest is not None:
                digest.merge_summary_into(merged)
        return merged or None

    def _digest_entries(self, key: str):
        for executor in self.executors:
            digest = executor.digest()
            if digest is not None:
                entries = digest.entries(key)
                if entries:
                    return entries
        return []

    def _check_peer_digest(self, peer_id: ProcessId, summary: Dict[str, Any]) -> None:
        """Verify a peer's heartbeat digest summary against our chains:
        for every key where we reach the peer's write count, our digest
        at that position must match (a hash chain authenticates the whole
        prefix).  On mismatch, request the peer's full chain so the
        DivergenceError can name the FIRST diverging write."""
        self.digest_checks += 1
        mismatched = []
        for executor in self.executors:
            digest = executor.digest()
            if digest is not None:
                mismatched.extend(digest.mismatched_keys(summary))
        for key in mismatched:
            self.digest_mismatches += 1
            logger.error(
                "p%s: execution digest mismatch with p%s on key %r — "
                "requesting its chain to locate the fork",
                self.process.id, peer_id, key,
            )
            self._peer_writers[peer_id].put_nowait(
                serialize(DigestKeyRequest(key))
            )

    def _resolve_divergence(self, peer_id: ProcessId, key: str, entries) -> None:
        """A peer answered our drill-down with its full chain: find the
        first diverging write and fail with the typed error.  A clean
        prefix means the mismatch healed (e.g. we advanced past a stale
        summary) — nothing to report then."""
        from fantoch_tpu.core.audit import DigestEntry, ExecutionDigest
        from fantoch_tpu.core.ids import Rifl
        from fantoch_tpu.errors import DivergenceError

        theirs = [DigestEntry(*entry) for entry in entries]
        divergence = ExecutionDigest.first_divergence(
            self._digest_entries(key), theirs
        )
        if divergence is None:
            return
        position, mine, other = divergence
        mine_rifl = Rifl(mine.src, mine.seq) if mine is not None else None
        theirs_rifl = Rifl(other.src, other.seq) if other is not None else None
        # name the diverging command's dot when the audit commit log can
        # resolve it (Config.audit_log_commits)
        dot = None
        log = self.process.audit_commit_log()
        if log is not None:
            dot = next(
                (
                    ident
                    for ident, (rifl, _value) in log.items()
                    if rifl == mine_rifl
                ),
                None,
            )
        self._fail(
            DivergenceError(
                key, position, mine_rifl, theirs_rifl,
                self.process.id, peer_id, dot=dot,
            )
        )

    def _declare_peer_lost(self, peer_id: ProcessId, cause: BaseException) -> None:
        """Graceful degradation: a lost peer stops the cluster only when
        the survivors can no longer form a quorum (alive < n - f); above
        that the runtime keeps serving and drops frames to the dead peer."""
        if peer_id in self.dead_peers or self._stopping:
            return
        self.dead_peers.add(peer_id)
        links = self._peer_writers.get(peer_id)
        if links is not None:
            links.mark_dead()
        my_shard = self.process.shard_id
        same_shard = [
            pid for pid in self.peers if self._shard_of.get(pid) == my_shard
        ]
        alive = 1 + sum(1 for pid in same_shard if pid not in self.dead_peers)
        needed = self.config.n - self.config.f
        if alive < needed:
            self._fail(QuorumLostError(alive, needed, self.dead_peers))
        else:
            logger.warning(
                "p%s: peer p%s lost (%r); degrading gracefully with "
                "%d/%d same-shard processes alive (quorum needs %d)",
                self.process.id,
                peer_id,
                cause,
                alive,
                self.config.n,
                needed,
            )
            # tell the protocol (worker 0 owns leadership state): FPaxos
            # uses this to elect a new leader without waiting out its own
            # protocol-level silence timeout
            self.workers.forward_to(0, ("peer_down", peer_id))

    def _declare_peer_up(self, peer_id: ProcessId) -> None:
        """The detector hook symmetric to ``_declare_peer_lost``: a peer
        we declared dead is demonstrably reachable again (a frame
        arrived, or it re-handshook after a restart).  Frames flow to it
        again, its writer tasks respawn (reconnecting and resending the
        unacked window), and the protocol hears ``on_peer_up`` so
        recovery-ring / pending-forward targets stop routing around it."""
        if peer_id not in self.dead_peers or self._stopping:
            return
        self.dead_peers.discard(peer_id)
        links = self._peer_writers.get(peer_id)
        if links is not None:
            links.mark_alive()
            for link in links.links:
                # a writer parked on queue.get() at declare-lost time
                # never observed dead=True and would wake into a second
                # life alongside the revival writer, interleaving one
                # seq window across two tasks — retire it first
                if link.writer_task is not None and not link.writer_task.done():
                    link.writer_task.cancel()
                # reconnect BEFORE resuming the writer: the old rw was
                # locally aborted, and asyncio silently discards writes
                # to a closed transport (flush does not raise), so a
                # writer resumed on it would drop frames forever
                link.writer_task = self.spawn(self._revive_link(link))
        self._last_heard[peer_id] = asyncio.get_event_loop().time()
        logger.warning(
            "p%s: peer p%s is back (%d/%d same-shard processes alive)",
            self.process.id,
            peer_id,
            1 + sum(
                1
                for pid in self.peers
                if self._shard_of.get(pid) == self.process.shard_id
                and pid not in self.dead_peers
            ),
            self.config.n,
        )
        self.workers.forward_to(0, ("peer_up", peer_id))

    async def _revive_link(self, link: LinkState) -> None:
        """Revival path: dial the returned peer fresh (resending the
        unacked window), then resume the writer task on the new rw."""
        try:
            await self._reconnect_link(link)
        except PeerLostError as exc:
            self._declare_peer_lost(link.peer_id, exc)
            return
        await self._peer_writer_task(link)

    def inject_link_failure(self, peer_id: Optional[ProcessId] = None) -> int:
        """Chaos hook for tests: hard-kill the live peer-link sockets (all
        of them, or only those to/from ``peer_id``), simulating the
        network dropping connections while every process stays up.
        Returns the number of aborted connections."""
        count = 0
        for rw, rw_peer in list(self._chaos_rws.items()):
            if peer_id is not None and rw_peer != peer_id:
                continue
            rw.abort()
            self._chaos_rws.pop(rw, None)
            count += 1
        return count

    async def _worker_task(self, position: int) -> None:
        queue = self.workers.queue(position)
        process = self.process
        # protocols with a batched submit seam (Newt's kernel-batched clock
        # proposals) take runs of queued submits in one call
        submit_batch = getattr(process, "submit_batch", None)
        while True:
            item = await queue.get()
            kind = item[0]
            if kind == "msg":
                _, from_, from_shard, msg = item
                process.handle(from_, from_shard, msg, self.time)
            elif kind == "submit":
                _, dot, cmd = item
                if submit_batch is not None:
                    # drain the run of consecutive submits queued behind us
                    pairs = [(dot, cmd)]
                    while _peek_is_submit(queue):
                        _, d2, c2 = queue.get_nowait()
                        pairs.append((d2, c2))
                    submit_batch(pairs, self.time)
                    if self.tracer.enabled:
                        # ingest = the worker handing the command to the
                        # protocol; no batching gate on this runner's
                        # submit edge yet, so payload->ingest is ~0 (the
                        # canonical chain stays complete either way).
                        # Stamped AFTER submit: the protocol's payload
                        # stamp runs inside it, and payload <= ingest
                        # must hold on the wall clock
                        for _d, c in pairs:
                            self.tracer.span(
                                "ingest", c.rifl, pid=self.process.id
                            )
                else:
                    process.submit(dot, cmd, self.time)
                    if self.tracer.enabled:
                        self.tracer.span(
                            "ingest", cmd.rifl, pid=self.process.id
                        )
            elif kind == "event":
                process.handle_event(item[1], self.time)
            elif kind == "executed":
                process.handle_executed(item[1], self.time)
            elif kind == "peer_down":
                process.on_peer_down(item[1], self.time)
            elif kind == "peer_up":
                process.on_peer_up(item[1], self.time)
            elif kind == "rejoin":
                process.rejoin(self.time)
                if self._lease_gap_dots:
                    # lease-gap healing: recovery commits the hole dots
                    # (noops where never issued) so the mesh's contiguous
                    # committed frontier for this source does not freeze
                    process.nudge_recovery(self._lease_gap_dots, self.time)
            else:
                raise AssertionError(f"unknown worker item {item}")
            self._drain_protocol()

    def _drain_protocol(self) -> None:
        """Ship protocol outputs (the send_to_processes_and_executors analog,
        process.rs:580-654)."""
        process = self.process
        tracer = self.tracer
        for action in process.to_processes_iter():
            if isinstance(action, ToSend):
                # serialize once, NOW: the self-delivered copy is handled by
                # a worker that may mutate the message in place (e.g. Newt
                # strips MCommit votes), so peers must get bytes captured
                # before any local handling.  When the message's dot is
                # trace-sampled, each peer frame instead carries its own
                # edge sequence (one send event per hop, paired with the
                # receiver's recv event) — per-target serialization, same
                # capture-before-local-handling discipline
                e_dot = None
                if tracer.enabled:
                    e_dot = edge_dot(action.msg)
                    if e_dot is not None and not tracer.sample(e_dot):
                        e_dot = None
                seq = None
                mtype = None
                if e_dot is not None:
                    # ONE edge seq per broadcast, shared by every target
                    # (the hop key is (src, seq, dst) — dst disambiguates)
                    # so the frame still serializes exactly once
                    self._edge_seq += 1
                    seq = self._edge_seq
                    mtype = type(action.msg).__name__
                frame = None
                for target in sorted(action.target):
                    if target != process.id and frame is None:
                        frame = serialize(POEProtocol(action.msg, edge=seq))
                for target in sorted(action.target):
                    if target == process.id:
                        index = self.protocol_cls.message_index(action.msg)
                        self.workers.forward(
                            index, ("msg", process.id, process.shard_id, action.msg)
                        )
                    else:
                        if seq is not None:
                            tracer.edge(
                                "s", mtype, process.id, target, seq,
                                dot=e_dot,
                            )
                        self._peer_writers[target].put_nowait(frame)
            elif isinstance(action, ToForward):
                index = self.protocol_cls.message_index(action.msg)
                self.workers.forward(
                    index, ("msg", process.id, process.shard_id, action.msg)
                )
            else:
                raise AssertionError(f"unknown action {action}")
        for info in process.to_executors_iter():
            if self.wal is not None:
                # durability point: every commit info is logged before it
                # can reach an executor — restart replays exactly the
                # records past the snapshot (append-then-apply order)
                self.wal.append("info", info)
            position = executor_index(info, self.executor_pool.size)
            self.executor_pool.forward_to(position, info)

    def _executor_position(self, info: Any) -> int:
        """Position in the executor pool for an info: the Executor's own
        routing when it defines one (GraphExecutor's main/secondary split,
        executor.rs:242-262), else key/0 routing."""
        index_of = getattr(self.protocol_cls.Executor, "executor_index_of", None)
        if index_of is not None:
            _reserved, index = index_of(info)
            assert index < self.executor_pool.size, (
                f"info {type(info).__name__} routes to executor {index} but the "
                f"pool has {self.executor_pool.size}; multi-shard graph "
                "executors need the main/secondary split (executors >= 2)"
            )
            return index
        pos = executor_index(info, self.executor_pool.size)
        return 0 if pos is None else pos

    def _ship_executor_outputs(self, executor: Any) -> None:
        """Deliver an executor's (shard, info) outputs: same-shard infos go
        to the local pool, cross-shard ones to the closest process of the
        target shard (executor.rs:220-260 fetch_info_to_executors)."""
        for to_shard, xinfo in executor.to_executors_iter():
            if to_shard == self.process.shard_id:
                self.executor_pool.forward_to(self._executor_position(xinfo), xinfo)
            else:
                target = self.closest_shard_process[to_shard]
                self._peer_writers[target].put_nowait(
                    serialize(POEExecutor(xinfo))
                )

    async def _executor_task(self, position: int) -> None:
        queue = self.executor_pool.queue(position)
        executor = self.executors[position]
        # adaptive ingest (run/ingest.py), opt-in: only when a channel
        # requested a positive deadline (Config.ingest_deadline_ms or the
        # env knob) does the drain hold for a fuller batch — unset keeps
        # the legacy drain-whatever-is-queued behavior bit-for-bit
        from time import monotonic

        deadline = requested_ingest_deadline_ms(None, self.config)
        batcher: Optional[AdaptiveIngestBatcher] = None
        if deadline is not None and deadline > 0:
            batcher = AdaptiveIngestBatcher(
                deadline,
                # no device round bound on a host executor drain; 1024
                # caps a hold at the batched-resolver sweet spot
                max_target=1024,
                fixed_target=resolve_ingest_target(None, self.config),
            )
        while True:
            # drain the whole queue: batch-oriented executors (the batched
            # graph resolver) amortize one device round-trip over the drain
            infos = [await queue.get()]
            while True:
                try:
                    infos.append(queue.get_nowait())
                except asyncio.QueueEmpty:
                    break
            if batcher is not None:
                now = monotonic() * 1000.0
                batcher.note_arrivals(now, len(infos))
                seen = len(infos)
                while True:
                    release, wait_ms = batcher.poll(now, len(infos))
                    if release or wait_ms is None:
                        break
                    # hold for the remaining budget, then sweep whatever
                    # landed; a size-target fill releases on the re-poll
                    await asyncio.sleep(wait_ms / 1000.0)
                    while True:
                        try:
                            infos.append(queue.get_nowait())
                        except asyncio.QueueEmpty:
                            break
                    now = monotonic() * 1000.0
                    batcher.note_arrivals(now, len(infos) - seen)
                    seen = len(infos)
                batcher.note_release(now, len(infos))
            if self.execution_logger is not None:
                self.execution_logger.log(infos)
            executor.handle_batch(infos, self.time)
            for result in executor.to_clients_iter():
                session = self.client_sessions.get(result.rifl.source)
                if session is not None:
                    session.deliver(result)
            self._ship_executor_outputs(executor)

    async def _executor_cleanup_task(self, interval_ms: int) -> None:
        """Periodic cleanup tick: retries buffered cross-shard requests on
        the secondary executor (executor.rs:279-293)."""
        while True:
            await asyncio.sleep(interval_ms / 1000)
            for executor in self.executors:
                executor.cleanup(self.time)
                self._ship_executor_outputs(executor)

    def admission_depth(self) -> int:
        """The client edge's congestion signal: the deepest queue across
        the worker and executor pools (the bottleneck queue is what
        grows latency — a sum would hide one wedged consumer behind many
        empty peers)."""
        return max(self.workers.max_depth(), self.executor_pool.max_depth())

    def queue_stats(self) -> Dict[str, Dict[str, float]]:
        """Per-queue depth/high-watermark/pause gauges across every
        run-layer queue this process owns: worker + executor pools, the
        peer-writer queues, and each link's unacked resend window.  The
        snapshot the metrics plane exports (ProcessMetrics.queues) —
        what WarnQueue used to only *log* is now a gauge that survives
        into ``bin/obs.py summarize``."""
        stats: Dict[str, Dict[str, float]] = {}
        stats.update(self.workers.stats())
        stats.update(self.executor_pool.stats())
        for peer_id, links in self._peer_writers.items():
            for queue in links.queues:
                if hasattr(queue, "stats"):
                    stats[queue.name] = queue.stats()
            for link in links.links:
                # with a delay line, links.queues holds the pre-delay
                # stamping queue and link.queue the post-delay writer
                # queue — gauge both (same object without a delay line)
                queue = link.queue
                if queue is not None and hasattr(queue, "stats"):
                    stats[queue.name] = queue.stats()
                stats[f"unacked->p{peer_id}[{link.index}]"] = {
                    "depth": len(link.unacked),
                    "depth_hwm": link.unacked_hwm,
                    "capacity": link.unacked_cap,
                    "pauses": 0,
                    "overflows": 0,
                }
        return stats

    def overload_counters(
        self, stats: Optional[Dict[str, Dict[str, float]]] = None
    ) -> Dict[str, float]:
        """Running totals of the overload-control plane's activity —
        folded into metrics snapshots and (when tracing) the span log.
        Pass a ``queue_stats()`` result to avoid a second walk (and to
        keep one snapshot's ``.queues`` and ``.overload`` views of the
        same instant)."""
        if stats is None:
            stats = self.queue_stats()
        out = {
            "shed_submissions": self.shed_submissions,
            "backpressure_pauses": self.backpressure_pauses,
            "queue_depth_hwm": max(
                (row["depth_hwm"] for row in stats.values()), default=0
            ),
            "queue_depth": max(
                (row["depth"] for row in stats.values()), default=0
            ),
        }
        if self.config.execution_digests:
            # divergence-detection gauges ride the same snapshot/tracer
            # pipeline (bin/obs.py summarize prints the audit line)
            out["digest_checks"] = self.digest_checks
            out["digest_mismatches"] = self.digest_mismatches
            summary = self._digest_summary() or {}
            out["digest_keys"] = len(summary)
        return out

    def _write_metrics_snapshot(self, queues=None, overload=None, device=None) -> None:
        """The legacy crash-consistent pickle snapshot.  ``_emit_telemetry``
        passes its already-collected sources so one tick walks the
        queues/device counters exactly once (and the series window and
        the snapshot's ``.queues``/``.overload`` views agree on the same
        instant); absent args are collected here."""
        from fantoch_tpu.run.observe import ProcessMetrics, write_metrics_snapshot

        if device is None:
            device = self._device_counters()
        if device is not None and self.tracer.enabled:
            # counters ride the trace too, next to the spans of the
            # batches they carried.  jax_recompiles/jax_compile_ms are
            # host-process-global (module tallies in
            # observability/device.py), so they go out unattributed:
            # co-hosted runtimes (the localhost harness) overwrite one
            # (name, pid=None) observation instead of each claiming the
            # same compiles — summing per-pid would n-fold them
            for name, value in sorted(device.items()):
                self.tracer.counter(
                    name, value,
                    pid=(
                        None
                        if name in (
                            "jax_recompiles", "jax_compile_ms",
                            "jax_cache_hits", "jax_cache_misses",
                        )
                        else self.process.id
                    ),
                )
        if queues is None:
            queues = self.queue_stats()
        if overload is None:
            overload = self.overload_counters(queues)
        if self.tracer.enabled:
            # queue-depth gauges + shed/pause tallies ride the span log
            # too (running totals, counters_total last-wins semantics),
            # so `bin/obs.py summarize` shows the overload plane next to
            # the latency breakdown it explains
            for name, value in sorted(overload.items()):
                self.tracer.counter(name, value, pid=self.process.id)
        write_metrics_snapshot(
            self.metrics_file,
            ProcessMetrics(
                [self.process.metrics()],
                [e.metrics() for e in self.executors],
                device,
                queues,
                overload,
            ),
        )

    def _device_counters(self):
        """Fold every executor's device-plane counters (plus the global
        recompile tally) into one per-process dict; None when no device
        plane contributed.  ``jax_recompiles`` is host-process-global
        (``observability/device.py`` module tally): every co-hosted
        runtime's snapshot carries the same total, so readers must not
        sum it across runtimes of one host."""
        from fantoch_tpu.observability.device import (
            cache_hit_count,
            cache_miss_count,
            compile_ms,
            derive_idle_frac,
            merge_counters,
            recompile_count,
        )

        device: Dict[str, float] = {}
        for executor in self.executors:
            merge_counters(device, executor.device_counters())
        if device:
            # dispatch/drain overlap instrument: idle frac from the
            # folded busy/span walls (frac itself never sums)
            derive_idle_frac(device)
            device["jax_recompiles"] = recompile_count()
            device["jax_compile_ms"] = compile_ms()
            device["jax_cache_hits"] = cache_hit_count()
            device["jax_cache_misses"] = cache_miss_count()
            return device
        return None

    def _obs_dir(self) -> str:
        """Directory profiling artifacts land in (one rule for every
        trigger spelling: observability/exposition.profile_output_dir)."""
        from fantoch_tpu.observability.exposition import profile_output_dir

        return profile_output_dir(
            self.telemetry and self.telemetry.path, self.metrics_file
        )

    def telemetry_sample(self, stats=None, overload=None, device=None):
        """One consistent (counters, gauges, histograms) sample — the
        shared source of the windowed series, the legacy snapshot's
        tracer counters, and the ``/metrics`` exposition.  Counter and
        gauge names match the bench/tally keys so a dashboard query and
        a BENCH row key agree.  ``_emit_telemetry`` passes precollected
        sources so one tick never walks them twice; the exposition
        endpoint calls with no args and collects fresh."""
        from fantoch_tpu.core.metrics import Metrics as _Metrics

        counters: Dict[str, float] = {
            "submitted": self.submitted,
            "replied": self.replied,
        }
        if stats is None:
            stats = self.queue_stats()
        # copy: the snapshot writer consumes the same overload dict, and
        # the gauge re-typing below pops keys out of it
        overload = dict(
            self.overload_counters(stats) if overload is None else overload
        )
        gauges: Dict[str, float] = {
            "queue_depth": overload.pop("queue_depth", 0),
            "queue_depth_hwm": overload.pop("queue_depth_hwm", 0),
        }
        if "digest_keys" in overload:
            gauges["digest_keys"] = overload.pop("digest_keys")
        counters.update(overload)
        if device is None:
            device = self._device_counters()
        if device:
            for name, value in device.items():
                if name in ("device_idle_frac", "device_pipeline_depth"):
                    gauges[name] = value
                else:
                    counters[name] = value
        hists: Dict[str, Any] = {}
        executor_metrics = _Metrics()
        for executor in self.executors:
            executor_metrics.merge(executor.metrics())
        for prefix, metrics in (
            ("protocol", self.process.metrics()),
            ("executor", executor_metrics),
        ):
            for kind, value in metrics.aggregated.items():
                counters[f"{prefix}_{getattr(kind, 'value', kind)}"] = value
            for kind, hist in metrics.collected.items():
                hists[f"{prefix}_{getattr(kind, 'value', kind)}"] = hist
        return counters, gauges, hists

    def _emit_telemetry(self) -> None:
        """One telemetry tick: a window line into the series (flushed, so
        a live ``obs watch`` sees it) and — when configured — the legacy
        crash-consistent pickle snapshot, from ONE walk of the queue /
        overload / device sources (so both views describe one instant)."""
        stats = self.queue_stats()
        overload = self.overload_counters(stats)
        device = self._device_counters()
        if self.telemetry is not None:
            counters, gauges, hists = self.telemetry_sample(
                stats, overload, device
            )
            self.telemetry.emit(
                f"p{self.process.id}", counters, gauges, hists
            )
            self.telemetry.flush()
        if self.metrics_file is not None:
            self._write_metrics_snapshot(
                queues=stats, overload=overload, device=device
            )

    async def _telemetry_task(self) -> None:
        """Periodic telemetry cadence (one knob:
        ``Config.telemetry_interval_ms``): windowed series emit + the
        crash-consistent metrics snapshot (metrics_logger.rs:75-87),
        unified on one writer."""
        while True:
            await asyncio.sleep(self.telemetry_interval_ms / 1000)
            self._emit_telemetry()

    async def _execution_log_flush_task(self) -> None:
        """1s execution-log flush (execution_logger.rs:8-29)."""
        while True:
            await asyncio.sleep(1.0)
            self.execution_logger.flush()

    async def _trace_flush_task(self) -> None:
        """Periodic span-log flush: keeps the on-disk JSONL prefix fresh
        (crash consistency — every flushed line is self-contained)."""
        while True:
            await asyncio.sleep(1.0)
            self.tracer.flush()

    async def _tracer_task(self) -> None:
        """Periodic function-latency histogram dump (tracer.rs:16-44).

        The prof registry is scoped to this runtime (utils/prof.py
        contextvar, installed in start() before tasks spawn), so the dump
        owns its samples even when several runtimes share one Python
        process in the localhost harness."""
        from fantoch_tpu.utils import prof

        while True:
            await asyncio.sleep(self.tracer_show_interval_ms / 1000)
            formatted = prof.format_snapshot()
            if formatted:
                logger.info(
                    "tracer (p%s registry):\n%s",
                    self.process.id,
                    formatted,
                )

    async def _periodic_task(self, event: Any, interval_ms: int) -> None:
        while True:
            await asyncio.sleep(interval_ms / 1000)
            index = self.protocol_cls.event_index(event)
            self.workers.forward(index, ("event", event))

    async def _executed_notification_task(self, interval_ms: int) -> None:
        """Collect executed clocks and notify the GC worker
        (executor.rs:295-313)."""
        while True:
            await asyncio.sleep(interval_ms / 1000)
            for executor in self.executors:
                executed = executor.executed(self.time)
                if executed is not None:
                    if self.wal is not None:
                        # the executor emit frontier rides the log too:
                        # a recovered tail shows how far execution got,
                        # next to the commit records that drove it
                        self.wal.append("frontier", executed)
                    self.workers.forward_to(0, ("executed", executed))
