"""Multi-process host scaling for the ordering path: the pool.rs analog
at OS-process granularity.

Reference: fantoch/src/run/pool.rs:115-124 scales one process across 16
worker/16 executor THREADS with Atomic/Locked shared-state variants; this
framework's intra-process parallelism axis is the batch (one core moves
~13-18M cmds/s through the array ordering path, README design notes), so
the multicore unit here is the PROCESS: ``OrderingPool`` spawns N worker
processes, each owning the key buckets ``hash % N == i`` (the same
key-partitioned executor routing as run/routing.py, at process
granularity), and drives each worker's own ``BatchedDependencyGraph``
over array chunks shipped through pipes.  Keys never span workers, so
per-key execution order is exact by construction — the same argument as
the reference's key-partitioned executors (fantoch/src/executor/
mod.rs:161-166) — and aggregate ordering throughput scales with cores.

The pool is deliberately transport-simple (pickled numpy columns over
``multiprocessing`` pipes): the ordering work per chunk is O(batch) with
large constants, so IPC is a few percent at 256k-row chunks.  Workers
force the CPU platform in-Python before touching jax (the TPU-tunnel
interpreter-start hang; see fantoch_tpu/hostenv.py).
"""

from __future__ import annotations

import multiprocessing as mp
from typing import List, Optional, Sequence, Tuple

import numpy as np

from fantoch_tpu.run.ingest import AdaptiveIngestBatcher, plan_ingest_releases


def _worker_main(conn, worker_index: int) -> None:
    """Worker process: owns one key shard's ordering graph."""
    from fantoch_tpu.hostenv import force_cpu_platform

    force_cpu_platform()
    from fantoch_tpu.core import Command, Config, KVOp, Rifl, RunTime
    from fantoch_tpu.executor.graph.batched import BatchedDependencyGraph
    from fantoch_tpu.ops.frontier import pack_dots

    shard = 0
    config = Config(5, 2, batched_graph_executor=True)
    graph = BatchedDependencyGraph(1, shard, config)
    graph.record_order_arrays = True
    clock = RunTime()
    arena: List[Command] = []
    try:
        while True:
            msg = conn.recv()
            kind = msg[0]
            if kind == "stop":
                break
            try:
                if kind == "arena":
                    # the command arena exists at submit time in any
                    # design (bench_integrated_executor's accounting);
                    # build it outside the timed region
                    (_, n) = msg
                    arena = [
                        Command.from_keys(
                            Rifl(1, i + 1), shard, {f"k{i}": (KVOp.put(""),)}
                        )
                        for i in range(n)
                    ]
                    conn.send(("ready", worker_index))
                elif kind == "add":
                    (_, src, seq, key, dep_rows) = msg
                    b = len(src)
                    assert b <= len(arena), (
                        f"arena {len(arena)} < chunk {b}: call prepare() "
                        "with the largest shard size first"
                    )
                    has_dep = dep_rows >= 0
                    dep_idx = np.where(has_dep, dep_rows, 0)
                    dep_dots = np.where(
                        has_dep, pack_dots(src[dep_idx], seq[dep_idx]), -1
                    ).reshape(-1, 1)
                    graph.handle_add_arrays(
                        src, seq, key, dep_dots, arena[:b], clock
                    )
                    graph.resolve_now(clock)
                    order_src, order_seq = graph.take_order_arrays()
                    conn.send(("done", order_src, order_seq))
                else:
                    raise AssertionError(f"unknown pool message {kind!r}")
            except Exception:  # noqa: BLE001 — ship the traceback home
                import traceback

                conn.send(("error", traceback.format_exc(), None))
                raise
    finally:
        conn.close()


class OrderingPool:
    """N key-sharded ordering worker processes behind one front."""

    def __init__(self, workers: int):
        assert workers >= 1
        self.workers = workers
        ctx = mp.get_context("spawn")
        self._conns = []
        self._procs = []
        for i in range(workers):
            parent, child = ctx.Pipe()
            proc = ctx.Process(
                target=_worker_main, args=(child, i), daemon=True
            )
            proc.start()
            child.close()
            self._conns.append(parent)
            self._procs.append(proc)

    def prepare(self, rows_per_worker: int) -> None:
        """Build each worker's command arena (untimed); must cover the
        largest shard any later run will ship."""
        for conn in self._conns:
            conn.send(("arena", rows_per_worker))
        for conn in self._conns:
            msg = conn.recv()
            if msg[0] == "error":
                raise RuntimeError(f"pool worker failed:\n{msg[1]}")
            assert msg[0] == "ready"

    @staticmethod
    def shard_columns(
        key: np.ndarray,
        src: np.ndarray,
        seq: np.ndarray,
        dep_rows: np.ndarray,
        workers: int,
    ) -> List[Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]]:
        """Partition a workload by key bucket and remap the dependency
        row indices into each shard's local numbering (a key's whole
        conflict chain lands in exactly one shard, so every dependency
        stays local)."""
        shard_of = key % workers
        # the sharding is only sound for latest-per-SAME-key dep chains
        # (a key's whole chain lands in one shard); anything else would
        # remap into the wrong shard's numbering — fail loudly instead
        has_any = dep_rows >= 0
        assert (
            key[dep_rows[has_any]] == key[has_any]
        ).all(), "dependency crosses keys: not shardable by key bucket"
        out = []
        # global row -> local row within its shard
        local = np.empty(len(key), dtype=np.int64)
        for w in range(workers):
            rows = np.flatnonzero(shard_of == w)
            local[rows] = np.arange(len(rows))
            dep = dep_rows[rows]
            has = dep >= 0
            remapped = np.where(has, local[np.where(has, dep, 0)], -1)
            out.append(
                (key[rows], src[rows], seq[rows], remapped)
            )
        return out

    def run_shards(self, shards) -> List[Tuple[np.ndarray, np.ndarray]]:
        """Dispatch one pre-sharded workload and wait for every worker's
        (order_src, order_seq); wall time across the call is the
        aggregate ordering latency."""
        self.submit_shards(shards)
        return self.drain_shards()

    def submit_shards(self, shards) -> None:
        """Ship one pre-sharded workload to the workers WITHOUT waiting —
        the dispatch half of the run/pipeline.py dispatch/drain split at
        process granularity.  Each pipe is FIFO, so workloads drain in
        submission order; ``drain_shards`` retires the oldest."""
        assert len(shards) == self.workers
        for conn, (key, src, seq, dep) in zip(self._conns, shards):
            conn.send(("add", src, seq, key, dep))

    def drain_shards(self) -> List[Tuple[np.ndarray, np.ndarray]]:
        """Wait for every worker's (order_src, order_seq) of the oldest
        submitted workload."""
        orders = []
        for conn in self._conns:
            kind, order_src, order_seq = conn.recv()
            if kind == "error":
                raise RuntimeError(f"pool worker failed:\n{order_src}")
            assert kind == "done"
            orders.append((order_src, order_seq))
        return orders

    def run_shards_pipelined(
        self, workloads, depth: int = 1
    ) -> List[List[Tuple[np.ndarray, np.ndarray]]]:
        """Run a sequence of pre-sharded workloads keeping up to
        ``depth`` of them in flight across the worker processes: IPC
        serialization of workload k+1 overlaps the workers' ordering of
        workload k (the serving loop's depth-K overlap applied to the
        host pool).  Results come back in submission order.

        Sends run on a feeder thread: the worker loop is strict
        recv->process->send, so a single-threaded submit-then-drain
        deadlocks as soon as a pickled workload and a pending result
        together exceed the pipe's socket buffer (each side blocked in
        send, neither reading).  With the feeder owning the send
        direction and this thread the recv direction, the main thread is
        always free to drain — each duplex Connection is used by exactly
        one thread per direction, never the same operation concurrently.
        A semaphore caps submitted-but-undrained workloads at
        ``depth + 1`` (depth remain in flight while one drains — the
        PipelineCore convention, so depth=1 really does overlap the IPC
        of workload k+1 with the workers' ordering of workload k); the
        drain loop never blocks on a workload the feeder has not
        confirmed submitting, so a feeder failure raises instead of
        hanging the caller."""
        assert depth >= 1
        import threading

        workloads = list(workloads)
        sem = threading.Semaphore(depth + 1)
        cond = threading.Condition()
        submitted = [0]
        feeder_error: List[BaseException] = []

        def feeder() -> None:
            try:
                for workload in workloads:
                    sem.acquire()
                    self.submit_shards(workload)
                    with cond:
                        submitted[0] += 1
                        cond.notify()
            except BaseException as exc:  # noqa: BLE001 — rethrown below
                with cond:
                    feeder_error.append(exc)
                    cond.notify()

        thread = threading.Thread(target=feeder, daemon=True)
        thread.start()
        results: List[List[Tuple[np.ndarray, np.ndarray]]] = []
        try:
            for i in range(len(workloads)):
                with cond:
                    while submitted[0] <= i and not feeder_error:
                        cond.wait()
                    if submitted[0] <= i:
                        # feeder died before this workload went out: the
                        # workers will never answer it — raise, don't hang
                        raise RuntimeError(
                            "pool feeder failed"
                        ) from feeder_error[0]
                results.append(self.drain_shards())
                sem.release()
        finally:
            thread.join(timeout=60)
        if feeder_error:
            raise RuntimeError("pool feeder failed") from feeder_error[0]
        return results

    def run_shards_adaptive(
        self,
        key: np.ndarray,
        src: np.ndarray,
        seq: np.ndarray,
        dep_rows: np.ndarray,
        arrival_ms: Sequence[float],
        batcher: AdaptiveIngestBatcher,
        depth: int = 1,
    ) -> Tuple[
        List[Tuple[float, int, int]],
        List[List[Tuple[np.ndarray, np.ndarray]]],
    ]:
        """Coalesce an arrival-stamped workload into ingest rounds and
        run them through the pipelined pool: the adaptive batcher's
        size-or-deadline policy (run/ingest.py) replayed offline over the
        sorted ``arrival_ms`` column decides the round boundaries, each
        round is key-sharded and shipped, and up to ``depth`` rounds stay
        in flight.  Returns ``(release plan, per-round orders)`` with the
        plan's half-open ``(release_ms, start, end)`` groups indexing the
        input rows.

        A dependency row that falls in an *earlier* round is dropped
        (-1): each pipe is FIFO, so by the time a round reaches its
        worker every earlier round's rows are already ordered there —
        submission order satisfies the cross-round edge by construction,
        exactly as an earlier dispatch satisfies a dependency in the
        device serving loop."""
        plan = plan_ingest_releases(arrival_ms, batcher)
        workloads = []
        for _release_ms, start, end in plan:
            dep = dep_rows[start:end]
            in_round = dep >= start
            dep = np.where(in_round, dep - start, -1)
            workloads.append(
                self.shard_columns(
                    key[start:end], src[start:end], seq[start:end],
                    dep, self.workers,
                )
            )
        return plan, self.run_shards_pipelined(workloads, depth=depth)

    def close(self) -> None:
        for conn in self._conns:
            try:
                conn.send(("stop",))
            except (BrokenPipeError, OSError):
                pass
        for proc in self._procs:
            proc.join(timeout=30)
            if proc.is_alive():
                proc.terminate()
        for conn in self._conns:
            conn.close()

    def __enter__(self) -> "OrderingPool":
        return self

    def __exit__(self, *exc) -> Optional[bool]:
        self.close()
        return None
