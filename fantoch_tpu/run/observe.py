"""Run-layer observability: metrics snapshots and execution logging/replay.

Reference:
- fantoch/src/run/task/metrics_logger.rs:75-87 — every interval, serialize
  the process's worker + executor metrics to a tmp file and atomically
  rename over the target (crash-consistent snapshots);
- fantoch/src/run/task/execution_logger.rs:8-29 — append every
  ExecutionInfo to a log file for offline debugging;
- fantoch_ps/src/bin/graph_executor_replay.rs:14-38 — replay such a log
  through a fresh executor.

Serialization is pickle (the runner's wire codec); metrics snapshots are
gzip'd like the reference's gzip+bincode.
"""

from __future__ import annotations

import gzip
import os
import pickle
from dataclasses import dataclass
from typing import Any, BinaryIO, Dict, Iterator, List, Optional

from fantoch_tpu.core.config import Config
from fantoch_tpu.core.ids import ProcessId, ShardId
from fantoch_tpu.core.metrics import Metrics
from fantoch_tpu.core.timing import RunTime


@dataclass
class ProcessMetrics:
    """One metrics snapshot: protocol ("workers") + executor metrics
    (metrics_logger.rs:12-30), plus the device-plane counters
    (fantoch_tpu/observability/device.py: dispatch counts, batch
    occupancy, recompiles, kernel wall-ms — no reference counterpart;
    the reference has no device planes).  ``device`` is None on
    planes-off runs and on snapshots written before the field existed
    (``read_metrics_snapshot`` backfills it on read)."""

    workers: List[Metrics]
    executors: List[Metrics]
    device: Optional[Dict[str, float]] = None
    # overload-control plane (run/backpressure.py): per-queue depth /
    # depth-high-watermark / pause / overflow gauges (``queues``) plus
    # the process-level shed/backpressure running totals (``overload``).
    # WarnQueue used to only *log* a falling-behind consumer; these make
    # it a gauge that survives the run.  None on snapshots written
    # before the fields existed (dataclass defaults cover old pickles)
    queues: Optional[Dict[str, Dict[str, float]]] = None
    overload: Optional[Dict[str, float]] = None


def write_metrics_snapshot(path: str, metrics: ProcessMetrics) -> None:
    """Write-tmp-then-rename for crash consistency
    (metrics_logger.rs:75-87)."""
    tmp = path + ".tmp"
    with gzip.open(tmp, "wb") as fh:
        pickle.dump(metrics, fh)
    os.replace(tmp, path)


def write_json_snapshot(path: str, obj) -> None:
    """Crash-consistent JSON snapshot (same tmp+rename discipline as the
    pickle variant); used by the device-serving runtime, whose metrics are
    round/path tallies rather than per-message histograms."""
    import json

    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(obj, fh)
    os.replace(tmp, path)


def read_metrics_snapshot(path: str) -> ProcessMetrics:
    with gzip.open(path, "rb") as fh:
        out = pickle.load(fh)
    assert isinstance(out, ProcessMetrics)
    # snapshots written before the device-counter field existed unpickle
    # without it in __dict__; reads still see None via the dataclass
    # class-attribute default, so no backfill is needed
    return out


class ExecutionLogger:
    """Appends execution infos to a log file (execution_logger.rs:8-29:
    8KB buffering, flush on close; one pickle frame per batch)."""

    def __init__(self, path: str):
        self._fh: BinaryIO = open(path, "wb", buffering=8192)

    def log(self, infos: List[Any]) -> None:
        pickle.dump(infos, self._fh)

    def flush(self) -> None:
        self._fh.flush()

    def close(self) -> None:
        self._fh.close()


def read_execution_log(path: str) -> Iterator[List[Any]]:
    with open(path, "rb") as fh:
        while True:
            try:
                yield pickle.load(fh)
            except EOFError:
                return


def replay_execution_log(
    path: str,
    protocol_cls: type,
    process_id: ProcessId,
    shard_id: ShardId,
    config: Config,
) -> Dict[str, Any]:
    """Replay a log through one fresh executor
    (graph_executor_replay.rs:14-38); returns summary stats.  Replay is
    inherently single-executor: the log already merges every executor
    task's batches in arrival order."""
    executor = protocol_cls.Executor(process_id, shard_id, config)
    executor.set_executor_index(0)
    time = RunTime()
    handled = 0
    results = 0
    for infos in read_execution_log(path):
        handled += len(infos)
        executor.handle_batch(infos, time)
        results += sum(1 for _ in executor.to_clients_iter())
    return {
        "batches_handled": handled,
        "results": results,
        "metrics": executor.metrics(),
    }
