"""Wire messages and worker pools for the real runner.

Reference: fantoch/src/run/prelude.rs (handshakes, client wire protocol,
the POEMessage protocol/executor split) and fantoch/src/run/pool.rs
(``ToPool``: a vector of channels with reserved-index routing).  Channels
are asyncio queues; a pool's ``forward`` resolves a
:data:`fantoch_tpu.run.routing.WorkerIndex` exactly like the reference's
reserved-index arithmetic.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from fantoch_tpu.core.command import Command, CommandResult
from fantoch_tpu.core.ids import ClientId, ProcessId, Rifl, ShardId
from fantoch_tpu.run.backpressure import BoundedQueue
from fantoch_tpu.run.routing import WorkerIndex, resolve_index


class WarnQueue(BoundedQueue):
    """The analog of the reference's bounded channels
    (fantoch/src/run/task/chan.rs:36-58, warn-then-block on full), now
    riding the overload-control plane (run/backpressure.BoundedQueue):
    producers here are synchronous handlers on one cooperative loop, so
    blocking them would deadlock the consumer; instead the queue warns
    (once per doubling, so a runaway queue keeps shouting but doesn't
    spam), tracks depth gauges, and — when bounded — closes a credit
    gate the socket-reader tasks pause on, so pressure propagates
    peer-to-peer via TCP instead of as unbounded heap."""

    def __init__(
        self,
        name: str,
        warn_size: int = 8192,
        capacity: Optional[int] = None,
    ):
        super().__init__(name, capacity=capacity, warn_size=warn_size)


# --- handshakes (prelude.rs:38-50) ---


@dataclass
class ProcessHi:
    """Peer-link handshake.  ``link`` identifies which of the sender's
    ``multiplexing`` links this connection carries: the receiver keys its
    dedup state on (process_id, link) so a reconnected link resumes where
    its predecessor stopped (run/links.py).  ``incarnation`` is the
    sender's WAL boot counter (run/wal.py): a *restarted* process starts
    a fresh sequence space, so the receiver resets its per-link dedup
    when the incarnation changes — same-life reconnects keep it."""

    process_id: ProcessId
    shard_id: ShardId
    link: int = 0
    incarnation: int = 0


@dataclass
class ClientHi:
    client_ids: List[ClientId]


@dataclass
class ClientHiAck:
    """Server -> client: the session is registered for result delivery.
    Clients must not submit before every shard acks — a partial executed
    on a non-target shard before its session registration would be
    unrouteable and silently dropped (the ClientHi-vs-execution race)."""


# --- client wire protocol (prelude.rs:52-69) ---


@dataclass
class Register:
    """Multi-shard registration: a client sends the command to every
    non-target shard it touches so that shard's result aggregation knows
    the rifl (fantoch/src/run/prelude.rs:52, mod.rs:757-764)."""

    cmd: Any


@dataclass
class Unregister:
    """Client -> non-target shard: withdraw a multi-shard command's
    Register (the command was shed past its deadline and will never be
    submitted again).  Without it, each deadline-shed multi-shard command
    would leak one aggregation entry per non-target shard for the life
    of the session — the unbounded-state class the overload plane
    exists to close."""

    rifl: Rifl


@dataclass
class Submit:
    cmd: Command


@dataclass
class ToClient:
    cmd_result: CommandResult


@dataclass
class Overloaded:
    """Server -> client: the submission was shed by admission control
    (the edge queue depth crossed ``Config.admission_limit``) — the wire
    form of :class:`fantoch_tpu.errors.OverloadedError`.  The client
    plane retries with capped exponential backoff floored by
    ``retry_after_ms`` (run/backpressure.Backoff) or sheds the command
    itself once its deadline budget expires.  No reference counterpart:
    the reference's channels block the whole connection instead of
    rejecting a single command."""

    rifl: Rifl
    retry_after_ms: int
    depth: int = 0
    limit: int = 0

    def to_error(self):
        """The typed client-side form of this frame."""
        from fantoch_tpu.errors import OverloadedError

        return OverloadedError(self.depth, self.limit, self.retry_after_ms)


# --- process wire protocol: protocol/executor split (prelude.rs:71-77) ---


@dataclass
class DigestKeyRequest:
    """Divergence drill-down (Config.execution_digests): a peer's
    heartbeat digest summary mismatched ours on ``key`` — send back the
    full hash chain so the FIRST diverging write can be named (the typed
    DivergenceError carries key + position + both commands)."""

    key: str


@dataclass
class DigestKeyReply:
    """One key's full executed-write hash chain:
    [(rifl_src, rifl_seq, digest), ...] (core/audit.DigestEntry rows)."""

    key: str
    entries: List[Any]


@dataclass
class PingReq:
    """Peer RTT probe (the localhost analog of the reference's `ping -c 1`
    shell-out, fantoch/src/run/task/ping.rs:71-78).

    ``digest`` piggybacks the sender's per-key execution-digest summary
    ({key: (write count, chain digest at that count)}) when
    ``Config.execution_digests`` is on: the receiver verifies every key
    where it is at least as far along — replicas cross-audit each other
    on the heartbeat cadence, and a fork surfaces as a typed
    DivergenceError instead of silently serving diverged reads.

    ``t_send_us`` (the sender's wall clock at send) turns the heartbeat
    into a clock-offset probe: the reply echoes it plus the replier's
    own clock, and the sender folds the bracket into its per-peer
    offset estimate (run/links.ClockOffsetEstimator) — what the
    critical-path correlator uses to compare timestamps across
    processes."""

    nonce: int
    digest: Optional[Dict[str, Any]] = None
    t_send_us: Optional[int] = None


@dataclass
class PingReply:
    nonce: int
    # clock-offset echo: the request's send stamp plus the replier's
    # clock at reply time (None on pings that did not carry a stamp)
    req_t_send_us: Optional[int] = None
    t_reply_us: Optional[int] = None


@dataclass
class POEProtocol:
    """A protocol message frame.  ``edge`` carries the sender's
    message-edge sequence number when the dot is trace-sampled
    (observability/tracer.py ``k == "edge"`` events): the receiver
    emits the matching recv edge so the critical-path correlator can
    stitch the hop causally.  None (the overwhelmingly common case)
    costs nothing on the wire beyond the field."""

    msg: Any
    edge: Optional[int] = None


@dataclass
class POEExecutor:
    info: Any


class ToPool:
    """Vector of queues with WorkerIndex routing (pool.rs:11-138).

    ``capacity`` bounds each queue with the watermark credit gate
    (run/backpressure.py): socket readers feeding the pool await
    :meth:`wait_for_credit` between frames, pausing their TCP stream
    while any destination queue sits above its high watermark."""

    def __init__(self, name: str, size: int, capacity: Optional[int] = None):
        self.name = name
        self._queues: List[WarnQueue] = [
            WarnQueue(f"{name}[{i}]", capacity=capacity) for i in range(size)
        ]

    @property
    def size(self) -> int:
        return len(self._queues)

    def queue(self, position: int) -> asyncio.Queue:
        return self._queues[position]

    @property
    def gated(self) -> bool:
        """True while any member queue's credit gate is closed."""
        return any(queue.gated for queue in self._queues)

    async def wait_for_credit(self) -> None:
        """Pause point for reader tasks: returns once every member queue
        is back below its low watermark (consumers share the loop, so
        awaiting here is what drains them)."""
        for queue in self._queues:
            if queue.gated:
                await queue.wait_for_credit()

    def max_depth(self) -> int:
        """The deepest member queue right now — the admission-control
        depth signal (the bottleneck queue, not the sum: one wedged
        worker is what collapses latency)."""
        return max(queue.qsize() for queue in self._queues)

    def stats(self) -> Dict[str, Dict[str, float]]:
        return {queue.name: queue.stats() for queue in self._queues}

    def forward(self, index: WorkerIndex, item: Any) -> None:
        """Route `item` by worker index.

        A None index means broadcast in the reference (each worker owns a
        partition of protocol state, pool.rs:92); here worker tasks share
        one protocol object, so broadcast messages need exactly one
        handling — deliver to queue 0.
        """
        position = resolve_index(index, len(self._queues))
        if position is None:
            position = 0
        self._queues[position].put_nowait(item)

    def forward_to(self, position: int, item: Any) -> None:
        self._queues[position % len(self._queues)].put_nowait(item)

    def broadcast(self, item: Any) -> None:
        for queue in self._queues:
            queue.put_nowait(item)
