"""Wire messages and worker pools for the real runner.

Reference: fantoch/src/run/prelude.rs (handshakes, client wire protocol,
the POEMessage protocol/executor split) and fantoch/src/run/pool.rs
(``ToPool``: a vector of channels with reserved-index routing).  Channels
are asyncio queues; a pool's ``forward`` resolves a
:data:`fantoch_tpu.run.routing.WorkerIndex` exactly like the reference's
reserved-index arithmetic.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass
from typing import Any, List

from fantoch_tpu.core.command import Command, CommandResult
from fantoch_tpu.core.ids import ClientId, ProcessId, ShardId
from fantoch_tpu.run.routing import WorkerIndex, resolve_index
from fantoch_tpu.utils import logger


class WarnQueue(asyncio.Queue):
    """Queue that warns when its depth crosses a threshold — the analog of
    the reference's bounded channels (fantoch/src/run/task/chan.rs:36-58,
    warn-then-block on full).  Producers here are synchronous handlers on
    one cooperative loop, so blocking them would deadlock the consumer;
    instead the overload signal surfaces loudly (once per doubling above
    the threshold, so a runaway queue keeps shouting but doesn't spam)."""

    def __init__(self, name: str, warn_size: int = 8192):
        super().__init__()
        self._warn_name = name
        self._warn_size = warn_size
        self._warn_next = warn_size

    def put_nowait(self, item: Any) -> None:  # type: ignore[override]
        super().put_nowait(item)
        if self.qsize() >= self._warn_next:
            logger.warning(
                "queue %s is full (%d items >= %d): consumer falling behind",
                self._warn_name,
                self.qsize(),
                self._warn_next,
            )
            self._warn_next *= 2

    def get_nowait(self) -> Any:  # type: ignore[override]
        item = super().get_nowait()
        # hysteresis: re-arm only once the queue genuinely drained (half
        # the threshold) — a queue hovering AT the threshold must not warn
        # on every put
        if self.qsize() < self._warn_size // 2:
            self._warn_next = self._warn_size
        return item


# --- handshakes (prelude.rs:38-50) ---


@dataclass
class ProcessHi:
    """Peer-link handshake.  ``link`` identifies which of the sender's
    ``multiplexing`` links this connection carries: the receiver keys its
    dedup state on (process_id, link) so a reconnected link resumes where
    its predecessor stopped (run/links.py).  ``incarnation`` is the
    sender's WAL boot counter (run/wal.py): a *restarted* process starts
    a fresh sequence space, so the receiver resets its per-link dedup
    when the incarnation changes — same-life reconnects keep it."""

    process_id: ProcessId
    shard_id: ShardId
    link: int = 0
    incarnation: int = 0


@dataclass
class ClientHi:
    client_ids: List[ClientId]


@dataclass
class ClientHiAck:
    """Server -> client: the session is registered for result delivery.
    Clients must not submit before every shard acks — a partial executed
    on a non-target shard before its session registration would be
    unrouteable and silently dropped (the ClientHi-vs-execution race)."""


# --- client wire protocol (prelude.rs:52-69) ---


@dataclass
class Register:
    """Multi-shard registration: a client sends the command to every
    non-target shard it touches so that shard's result aggregation knows
    the rifl (fantoch/src/run/prelude.rs:52, mod.rs:757-764)."""

    cmd: Any


@dataclass
class Submit:
    cmd: Command


@dataclass
class ToClient:
    cmd_result: CommandResult


# --- process wire protocol: protocol/executor split (prelude.rs:71-77) ---


@dataclass
class PingReq:
    """Peer RTT probe (the localhost analog of the reference's `ping -c 1`
    shell-out, fantoch/src/run/task/ping.rs:71-78)."""

    nonce: int


@dataclass
class PingReply:
    nonce: int


@dataclass
class POEProtocol:
    msg: Any


@dataclass
class POEExecutor:
    info: Any


class ToPool:
    """Vector of queues with WorkerIndex routing (pool.rs:11-138)."""

    def __init__(self, name: str, size: int):
        self.name = name
        self._queues: List[asyncio.Queue] = [
            WarnQueue(f"{name}[{i}]") for i in range(size)
        ]

    @property
    def size(self) -> int:
        return len(self._queues)

    def queue(self, position: int) -> asyncio.Queue:
        return self._queues[position]

    def forward(self, index: WorkerIndex, item: Any) -> None:
        """Route `item` by worker index.

        A None index means broadcast in the reference (each worker owns a
        partition of protocol state, pool.rs:92); here worker tasks share
        one protocol object, so broadcast messages need exactly one
        handling — deliver to queue 0.
        """
        position = resolve_index(index, len(self._queues))
        if position is None:
            position = 0
        self._queues[position].put_nowait(item)

    def forward_to(self, position: int, item: Any) -> None:
        self._queues[position % len(self._queues)].put_nowait(item)

    def broadcast(self, item: Any) -> None:
        for queue in self._queues:
            queue.put_nowait(item)
