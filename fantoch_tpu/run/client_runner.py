"""Client side of the real runner: closed- and open-loop drivers.

Reference: fantoch/src/run/mod.rs:448-832.  A client task pool shares one
TCP connection per shard; a demux task routes CommandResults back to the
issuing client by rifl source.  Closed-loop clients keep one outstanding
command; open-loop clients submit on a fixed interval regardless of
completions (mod.rs:526-664).
"""

from __future__ import annotations

import asyncio
from typing import Dict, List, Optional, Tuple

from fantoch_tpu.client.client import Client
from fantoch_tpu.client.workload import Workload
from fantoch_tpu.core.ids import ClientId, ShardId
from fantoch_tpu.core.timing import RunTime
from fantoch_tpu.run.prelude import ClientHi, Submit, ToClient
from fantoch_tpu.run.rw import Rw

Address = Tuple[str, int]


async def run_clients(
    client_ids: List[ClientId],
    shard_addresses: Dict[ShardId, Address],
    workload: Workload,
    open_loop_interval_ms: Optional[int] = None,
    status_frequency: Optional[int] = None,
) -> Dict[ClientId, Client]:
    """Drive `client_ids` against the cluster; returns the finished clients
    (latency data inside)."""
    assert len(shard_addresses) == 1, "multi-shard clients arrive with the partial layer"
    (shard_id, addr), = shard_addresses.items()
    reader, writer = await asyncio.open_connection(*addr)
    rw = Rw(reader, writer)
    await rw.send(ClientHi(list(client_ids)))

    time = RunTime()
    clients = {
        client_id: Client(client_id, workload, status_frequency=status_frequency)
        for client_id in client_ids
    }
    for client in clients.values():
        client.connect({shard_id: 0})

    queues: Dict[ClientId, asyncio.Queue] = {cid: asyncio.Queue() for cid in client_ids}

    # sentinel fanned out to every client queue when the demux dies (EOF or
    # error), so the wait loops below fail loudly instead of hanging
    eof_sentinel = object()

    async def demux() -> None:
        try:
            while True:
                msg = await rw.recv()
                if msg is None:
                    return
                assert isinstance(msg, ToClient)
                queues[msg.cmd_result.rifl.source].put_nowait(msg.cmd_result)
        finally:
            for queue in queues.values():
                queue.put_nowait(eof_sentinel)

    demux_task = asyncio.ensure_future(demux())

    async def closed_loop(client: Client) -> None:
        while True:
            nxt = client.next_cmd(time)
            if nxt is None:
                break
            _shard, cmd = nxt
            await rw.send(Submit(cmd))
            cmd_result = await queues[client.id].get()
            if cmd_result is eof_sentinel:
                raise ConnectionError(
                    f"client {client.id}: server connection closed with an "
                    "outstanding command"
                )
            client.handle([cmd_result], time)

    async def open_loop(client: Client) -> None:
        pending = 0
        eof = False

        async def collector() -> None:
            nonlocal pending, eof
            while True:
                cmd_result = await queues[client.id].get()
                if cmd_result is eof_sentinel:
                    eof = True
                    return
                client.handle([cmd_result], time)
                pending -= 1

        collect = asyncio.ensure_future(collector())
        while True:
            nxt = client.next_cmd(time)
            if nxt is None:
                break
            _shard, cmd = nxt
            await rw.send(Submit(cmd))
            pending += 1
            await asyncio.sleep(open_loop_interval_ms / 1000)
        while pending > 0 and not eof:
            await asyncio.sleep(0.01)
        collect.cancel()
        if eof and pending > 0:
            raise ConnectionError(
                f"client {client.id}: server connection closed with "
                f"{pending} outstanding commands"
            )

    driver = open_loop if open_loop_interval_ms is not None else closed_loop
    await asyncio.gather(*(driver(client) for client in clients.values()))
    demux_task.cancel()
    rw.close()
    return clients
