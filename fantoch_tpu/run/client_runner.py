"""Client side of the real runner: closed- and open-loop drivers.

Reference: fantoch/src/run/mod.rs:448-832.  A client task pool shares one
TCP connection per shard; a demux task per connection routes CommandResults
back to the issuing client by rifl source.  Closed-loop clients keep one
outstanding command; open-loop clients submit on a fixed interval
regardless of completions (mod.rs:526-664).

Multi-shard commands: the client Submits to the target shard and Registers
the command with every other shard it touches (mod.rs:757-764); each shard
executes its part and returns one CommandResult, aggregated client-side —
the ShardsPending role of mod.rs:859-917 is played by the per-command
``needed`` counter in the drivers below.
"""

from __future__ import annotations

import asyncio
from typing import Dict, List, Optional, Tuple

from fantoch_tpu.client.client import Client
from fantoch_tpu.client.workload import Workload
from fantoch_tpu.core.command import Command
from fantoch_tpu.core.ids import ClientId, ShardId
from fantoch_tpu.core.timing import RunTime
from fantoch_tpu.observability.tracer import NOOP_TRACER
from fantoch_tpu.run.prelude import ClientHi, ClientHiAck, Register, Submit, ToClient
from fantoch_tpu.run.rw import Rw, connect_with_retry

Address = Tuple[str, int]


async def run_clients(
    client_ids: List[ClientId],
    shard_addresses: Dict[ShardId, Address],
    workload: Workload,
    open_loop_interval_ms: Optional[int] = None,
    status_frequency: Optional[int] = None,
    tracer=NOOP_TRACER,
) -> Dict[ClientId, Client]:
    """Drive `client_ids` against the cluster; returns the finished clients
    (latency data inside)."""
    rws: Dict[ShardId, Rw] = {}
    for shard_id, addr in sorted(shard_addresses.items()):
        rw = await connect_with_retry(addr)
        await rw.send(ClientHi(list(client_ids)))
        rws[shard_id] = rw
    # wait for every shard's registration ack before the first submission:
    # a partial executed on a non-target shard before its session
    # registered would be unrouteable (ClientHi-vs-execution race)
    for shard_id, rw in rws.items():
        ack = await rw.recv()
        assert isinstance(ack, ClientHiAck), f"expected ClientHiAck, got {ack}"


    time = RunTime()
    clients = {
        client_id: Client(client_id, workload, status_frequency=status_frequency)
        for client_id in client_ids
    }
    for client in clients.values():
        client.connect({shard_id: 0 for shard_id in rws})

    queues: Dict[ClientId, asyncio.Queue] = {cid: asyncio.Queue() for cid in client_ids}

    # sentinel fanned out to every client queue when a demux dies (EOF or
    # error), so the wait loops below fail loudly instead of hanging
    eof_sentinel = object()

    async def demux(rw: Rw) -> None:
        try:
            while True:
                msg = await rw.recv()
                if msg is None:
                    return
                assert isinstance(msg, ToClient)
                queues[msg.cmd_result.rifl.source].put_nowait(msg.cmd_result)
        finally:
            for queue in queues.values():
                queue.put_nowait(eof_sentinel)

    demux_tasks = [asyncio.ensure_future(demux(rw)) for rw in rws.values()]

    async def submit(target_shard: ShardId, cmd: Command) -> int:
        """Submit + per-shard registration; returns the number of
        CommandResults to expect (one per shard touched).  All frames are
        written first, then the touched connections flush concurrently —
        no serialized per-shard round-trips on the submit path."""
        touched = []
        for shard_id in cmd.shards():
            if shard_id != target_shard:
                rws[shard_id].write(Register(cmd))
                touched.append(rws[shard_id])
        rws[target_shard].write(Submit(cmd))
        touched.append(rws[target_shard])
        await asyncio.gather(*(rw.flush() for rw in touched))
        return cmd.shard_count

    async def collect(client: Client, needed: int) -> list:
        results = []
        for _ in range(needed):
            cmd_result = await queues[client.id].get()
            if cmd_result is eof_sentinel:
                raise ConnectionError(
                    f"client {client.id}: server connection closed with an "
                    "outstanding command"
                )
            results.append(cmd_result)
        return results

    async def closed_loop(client: Client) -> None:
        while True:
            nxt = client.next_cmd(time)
            if nxt is None:
                break
            target_shard, cmd = nxt
            if tracer.enabled:
                tracer.span("submit", cmd.rifl, cid=client.id)
            needed = await submit(target_shard, cmd)
            results = await collect(client, needed)
            if tracer.enabled:
                tracer.span("reply", cmd.rifl, cid=client.id)
            client.handle(results, time)

    async def open_loop(client: Client) -> None:
        pending = 0
        eof = False
        expect: Dict[object, int] = {}  # rifl -> results still to arrive

        async def collector() -> None:
            nonlocal pending, eof
            buffered: Dict[object, list] = {}
            while True:
                cmd_result = await queues[client.id].get()
                if cmd_result is eof_sentinel:
                    eof = True
                    return
                rifl = cmd_result.rifl
                buffered.setdefault(rifl, []).append(cmd_result)
                if len(buffered[rifl]) == expect[rifl]:
                    if tracer.enabled:
                        tracer.span("reply", rifl, cid=client.id)
                    client.handle(buffered.pop(rifl), time)
                    del expect[rifl]
                    pending -= 1

        collect_task = asyncio.ensure_future(collector())
        while True:
            nxt = client.next_cmd(time)
            if nxt is None:
                break
            target_shard, cmd = nxt
            expect[cmd.rifl] = cmd.shard_count
            if tracer.enabled:
                tracer.span("submit", cmd.rifl, cid=client.id)
            await submit(target_shard, cmd)
            pending += 1
            await asyncio.sleep(open_loop_interval_ms / 1000)
        while pending > 0 and not eof:
            await asyncio.sleep(0.01)
        collect_task.cancel()
        if eof and pending > 0:
            raise ConnectionError(
                f"client {client.id}: server connection closed with "
                f"{pending} outstanding commands"
            )

    driver = open_loop if open_loop_interval_ms is not None else closed_loop
    await asyncio.gather(*(driver(client) for client in clients.values()))
    for task in demux_tasks:
        task.cancel()
    for rw in rws.values():
        rw.close()
    return clients
