"""Client side of the real runner: closed- and open-loop drivers.

Reference: fantoch/src/run/mod.rs:448-832.  A client task pool shares one
TCP connection per shard; a demux task per connection routes CommandResults
back to the issuing client by rifl source.  Closed-loop clients keep one
outstanding command; open-loop clients submit on a pacing schedule
regardless of completions (mod.rs:526-664) — a fixed interval, or seeded
Poisson arrivals at a target per-client rate (the overload plane's load
instrument: closed loops self-throttle and can never push the system past
saturation).

Overload control (run/backpressure.py): a server past its admission limit
replies with a typed ``Overloaded`` frame instead of queueing the
submission.  Both drivers retry with capped exponential backoff + full
jitter, floored by the server's retry-after hint; a per-command
``deadline_ms`` budget bounds the retrying — once it expires the client
*sheds* the command (no latency sample, tallied on the client) rather
than execute it late.

Multi-shard commands: the client Submits to the target shard and Registers
the command with every other shard it touches (mod.rs:757-764); each shard
executes its part and returns one CommandResult, aggregated client-side —
the ShardsPending role of mod.rs:859-917 is played by the per-command
``needed`` counter in the drivers below.  Admission sheds happen at the
target shard *before* protocol submission, so non-target shards never
produce partials for a shed command and the retry re-runs the full path.
"""

from __future__ import annotations

import asyncio
import random
from typing import Dict, List, Optional, Tuple

from fantoch_tpu.client.client import Client
from fantoch_tpu.client.workload import Workload
from fantoch_tpu.core.command import Command
from fantoch_tpu.core.ids import ClientId, ShardId
from fantoch_tpu.core.timing import RunTime
from fantoch_tpu.observability.tracer import NOOP_TRACER
from fantoch_tpu.run.backpressure import Backoff, BoundedQueue, OpenLoopPacer
from fantoch_tpu.run.prelude import (
    ClientHi,
    ClientHiAck,
    Overloaded,
    Register,
    Submit,
    ToClient,
    Unregister,
)
from fantoch_tpu.run.rw import Rw, connect_with_retry

Address = Tuple[str, int]


async def run_clients(
    client_ids: List[ClientId],
    shard_addresses: Dict[ShardId, Address],
    workload: Workload,
    open_loop_interval_ms: Optional[int] = None,
    arrival_rate_per_s: Optional[float] = None,
    arrival_seed: Optional[int] = None,
    deadline_ms: Optional[int] = None,
    raise_on_shed: bool = False,
    status_frequency: Optional[int] = None,
    tracer=NOOP_TRACER,
    telemetry_file: Optional[str] = None,
    telemetry_interval_ms: Optional[int] = None,
) -> Dict[ClientId, Client]:
    """Drive `client_ids` against the cluster; returns the finished clients
    (latency data + overload tallies inside).

    ``open_loop_interval_ms`` / ``arrival_rate_per_s`` select the
    open-loop driver (at most one): fixed-interval pacing, or seeded
    Poisson arrivals at ``arrival_rate_per_s`` *per client*.
    ``deadline_ms`` is the per-command budget across overload retries;
    on expiry the command is shed and tallied — or, with
    ``raise_on_shed``, the typed ``DeadlineExceededError`` (chained to
    the server's ``OverloadedError``) propagates instead, for drivers
    that treat any shed as failure.

    ``telemetry_file`` emits the client plane's windowed series
    (observability/timeseries.py): submit/reply rates, retry/shed
    tallies, and a per-window client-latency histogram (ms) — the
    wall-time twin of the sim runner's ``clients`` source.
    """
    assert open_loop_interval_ms is None or arrival_rate_per_s is None, (
        "pick one open-loop pacing mode: interval or arrival rate"
    )
    rws: Dict[ShardId, Rw] = {}
    for shard_id, addr in sorted(shard_addresses.items()):
        rw = await connect_with_retry(addr)
        await rw.send(ClientHi(list(client_ids)))
        rws[shard_id] = rw
    # wait for every shard's registration ack before the first submission:
    # a partial executed on a non-target shard before its session
    # registered would be unrouteable (ClientHi-vs-execution race)
    for shard_id, rw in rws.items():
        ack = await rw.recv()
        assert isinstance(ack, ClientHiAck), f"expected ClientHiAck, got {ack}"


    time = RunTime()
    clients = {
        client_id: Client(client_id, workload, status_frequency=status_frequency)
        for client_id in client_ids
    }
    for client in clients.values():
        client.connect({shard_id: 0 for shard_id in rws})

    # client-plane telemetry (observability/timeseries.py): windowed
    # submit/reply rates + a per-window latency histogram, one source
    # ("clients") mirroring the sim runner's
    telemetry = None
    telemetry_window_ms = telemetry_interval_ms
    latency_hist = None
    if telemetry_file is not None:
        from fantoch_tpu.core.metrics import Histogram
        from fantoch_tpu.observability.timeseries import (
            DEFAULT_WINDOW_MS,
            SeriesWriter,
        )

        telemetry_window_ms = telemetry_interval_ms or DEFAULT_WINDOW_MS
        telemetry = SeriesWriter(
            telemetry_file, time, window_ms=telemetry_window_ms
        )
        # cumulative latency histogram maintained at O(1) per reply (the
        # observer seam): a window emit snapshots it instead of
        # re-walking every recorded sample — per-tick cost stays flat
        # however long the run gets
        latency_hist = Histogram()
        for client in clients.values():
            client.set_latency_observer(
                lambda latency_us: latency_hist.increment(latency_us // 1000)
            )

    def _emit_telemetry() -> None:
        submitted = retries = sheds = 0
        for client in clients.values():
            submitted += client.issued_commands
            retries += client.overload_retries
            sheds += client.shed_commands
        telemetry.emit(
            "clients",
            {
                "submitted": submitted,
                "replied": latency_hist.count,
                "overload_retries": retries,
                "shed_commands": sheds,
            },
            hists={"latency_ms": latency_hist},
        )
        telemetry.flush()

    async def _telemetry_task() -> None:
        while True:
            await asyncio.sleep(telemetry_window_ms / 1000)
            _emit_telemetry()

    # reply queues ride the bounded/instrumented plane too: the demux is
    # a socket reader, so a client that stops collecting pauses its
    # connection's stream (TCP backpressure) instead of growing the heap
    queues: Dict[ClientId, BoundedQueue] = {
        cid: BoundedQueue(f"client[{cid}]") for cid in client_ids
    }

    # sentinel fanned out to every client queue when a demux dies (EOF or
    # error), so the wait loops below fail loudly instead of hanging
    eof_sentinel = object()

    async def demux(rw: Rw) -> None:
        try:
            while True:
                msg = await rw.recv()
                if msg is None:
                    return
                if isinstance(msg, Overloaded):
                    queues[msg.rifl.source].put_nowait(msg)
                    continue
                assert isinstance(msg, ToClient)
                queue = queues[msg.cmd_result.rifl.source]
                queue.put_nowait(msg.cmd_result)
                if queue.gated:
                    # cooperative backpressure: one client fell behind
                    # collecting — pause this connection's stream until
                    # it drains (head-of-line by design: that IS the TCP
                    # flow-control semantics pressure propagates through)
                    await queue.wait_for_credit()
        finally:
            for queue in queues.values():
                queue.put_nowait(eof_sentinel)

    demux_tasks = [asyncio.ensure_future(demux(rw)) for rw in rws.values()]

    async def submit(
        target_shard: ShardId, cmd: Command, register: bool = True
    ) -> int:
        """Submit + per-shard registration; returns the number of
        CommandResults to expect (one per shard touched).  All frames are
        written first, then the touched connections flush concurrently —
        no serialized per-shard round-trips on the submit path.

        Overload retries pass ``register=False``: the first attempt's
        Registers persist at the non-target shards (a shed happens at
        the target *before* protocol submission, so they are still
        waiting), and re-sending one would RESET the aggregation entry
        (``AggregatePending.wait_for`` replaces it), discarding any
        partials that raced ahead of the retry's Register — a wiped
        partial would hang the client forever."""
        touched = []
        if register:
            for shard_id in cmd.shards():
                if shard_id != target_shard:
                    rws[shard_id].write(Register(cmd))
                    touched.append(rws[shard_id])
        rws[target_shard].write(Submit(cmd))
        touched.append(rws[target_shard])
        await asyncio.gather(*(rw.flush() for rw in touched))
        return cmd.shard_count

    async def unregister(target_shard: ShardId, cmd: Command) -> None:
        """Withdraw a deadline-shed multi-shard command's Registers: the
        non-target shards hold an aggregation entry nothing will ever
        complete (the target shard shed before submission, so they never
        saw — and never will see — any partials)."""
        others = [
            rws[shard_id]
            for shard_id in cmd.shards()
            if shard_id != target_shard
        ]
        for rw in others:
            rw.write(Unregister(cmd.rifl))
        await asyncio.gather(*(rw.flush() for rw in others))

    def _retry_rng(client_id: ClientId) -> random.Random:
        # seeded jitter when the caller wants reproducible schedules;
        # fresh entropy otherwise (live clients must not thunder-herd)
        if arrival_seed is None:
            return random.Random()
        return random.Random(arrival_seed * 7919 + client_id)

    def _deadline_error(rifl, waited_ms: float, msg: Overloaded):
        """The typed deadline-shed error, chained to the server's
        OverloadedError — one construction for both drivers."""
        from fantoch_tpu.errors import DeadlineExceededError

        error = DeadlineExceededError(rifl, waited_ms, deadline_ms)
        error.__cause__ = msg.to_error()
        return error

    async def collect(client: Client, needed: int):
        """Gather one command's outcome: ``("ok", results)`` once all
        ``needed`` per-shard results arrived, or ``("overloaded", msg)``
        when the target shard shed the submission (a shed happens before
        protocol submission, so no partials can precede or follow it)."""
        results: list = []
        while len(results) < needed:
            item = await queues[client.id].get()
            if item is eof_sentinel:
                raise ConnectionError(
                    f"client {client.id}: server connection closed with an "
                    "outstanding command"
                )
            if isinstance(item, Overloaded):
                assert not results, "shed raced a partial result"
                return "overloaded", item
            results.append(item)
        return "ok", results

    async def closed_loop(client: Client) -> None:
        rng = _retry_rng(client.id)
        while True:
            nxt = client.next_cmd(time)
            if nxt is None:
                break
            target_shard, cmd = nxt
            if tracer.enabled:
                tracer.span("submit", cmd.rifl, cid=client.id)
            backoff = Backoff(rng=rng)
            started_ms = time.millis()
            needed = await submit(target_shard, cmd)
            while True:
                kind, payload = await collect(client, needed)
                if kind == "ok":
                    if tracer.enabled:
                        tracer.span("reply", cmd.rifl, cid=client.id)
                    client.handle(payload, time)
                    break
                client.overload_retries += 1
                delay_ms = backoff.next_delay_ms(payload.retry_after_ms)
                waited_ms = time.millis() - started_ms
                if deadline_ms is not None and waited_ms + delay_ms > deadline_ms:
                    # deadline budget exhausted: shed, don't execute late
                    client.shed(cmd.rifl)
                    await unregister(target_shard, cmd)
                    if raise_on_shed:
                        raise _deadline_error(cmd.rifl, waited_ms, payload)
                    break
                await asyncio.sleep(delay_ms / 1000)
                # register=False: the first attempt's Registers persist
                needed = await submit(target_shard, cmd, register=False)

    async def open_loop(client: Client) -> None:
        pending = 0
        eof = False
        expect: Dict[object, int] = {}  # rifl -> results still to arrive
        inflight: Dict[object, Tuple[ShardId, Command]] = {}  # for retries
        started_ms: Dict[object, float] = {}
        backoffs: Dict[object, Backoff] = {}
        retry_tasks: set = set()
        rng = _retry_rng(client.id)
        pacer = OpenLoopPacer(
            interval_ms=open_loop_interval_ms,
            rate_per_s=arrival_rate_per_s,
            seed=(
                None
                if arrival_seed is None
                else arrival_seed * 104729 + client.id
            ),
        )

        def _forget(rifl) -> None:
            nonlocal pending
            del expect[rifl]
            inflight.pop(rifl, None)
            started_ms.pop(rifl, None)
            backoffs.pop(rifl, None)
            pending -= 1

        shed_errors: list = []

        async def resubmit_later(msg: Overloaded) -> None:
            rifl = msg.rifl
            client.overload_retries += 1
            backoff = backoffs.setdefault(rifl, Backoff(rng=rng))
            delay_ms = backoff.next_delay_ms(msg.retry_after_ms)
            waited_ms = time.millis() - started_ms[rifl]
            if deadline_ms is not None and waited_ms + delay_ms > deadline_ms:
                client.shed(rifl)
                target_shard, cmd = inflight[rifl]
                await unregister(target_shard, cmd)
                if raise_on_shed:
                    shed_errors.append(_deadline_error(rifl, waited_ms, msg))
                _forget(rifl)
                return
            await asyncio.sleep(delay_ms / 1000)
            target_shard, cmd = inflight[rifl]
            # register=False: the first attempt's Registers persist
            await submit(target_shard, cmd, register=False)

        async def collector() -> None:
            nonlocal pending, eof
            buffered: Dict[object, list] = {}
            while True:
                item = await queues[client.id].get()
                if item is eof_sentinel:
                    eof = True
                    return
                if isinstance(item, Overloaded):
                    if item.rifl not in expect:
                        continue  # already shed past its deadline
                    task = asyncio.ensure_future(resubmit_later(item))
                    retry_tasks.add(task)
                    task.add_done_callback(retry_tasks.discard)
                    continue
                rifl = item.rifl
                if rifl not in expect:
                    continue  # shed while a retry was in flight
                buffered.setdefault(rifl, []).append(item)
                if len(buffered[rifl]) == expect[rifl]:
                    if tracer.enabled:
                        tracer.span("reply", rifl, cid=client.id)
                    client.handle(buffered.pop(rifl), time)
                    _forget(rifl)

        collect_task = asyncio.ensure_future(collector())
        while not shed_errors:  # fail fast mid-generation on raise_on_shed
            # gap BEFORE each submission (including the first): N clients
            # starting together must not fire a synchronized burst — the
            # same arrival process as the sim's open loop, where the
            # first arrival is itself an exponential gap from t=0
            await asyncio.sleep(pacer.next_gap_s())
            nxt = client.next_cmd(time)
            if nxt is None:
                break
            target_shard, cmd = nxt
            expect[cmd.rifl] = cmd.shard_count
            inflight[cmd.rifl] = (target_shard, cmd)
            started_ms[cmd.rifl] = time.millis()
            if tracer.enabled:
                tracer.span("submit", cmd.rifl, cid=client.id)
            await submit(target_shard, cmd)
            pending += 1
        while pending > 0 and not eof and not shed_errors:
            await asyncio.sleep(0.01)
        for task in list(retry_tasks):
            task.cancel()
        collect_task.cancel()
        if shed_errors:
            raise shed_errors[0]
        if eof and pending > 0:
            raise ConnectionError(
                f"client {client.id}: server connection closed with "
                f"{pending} outstanding commands"
            )

    open_looped = (
        open_loop_interval_ms is not None or arrival_rate_per_s is not None
    )
    driver = open_loop if open_looped else closed_loop
    driver_tasks = [
        asyncio.ensure_future(driver(client)) for client in clients.values()
    ]
    telemetry_task = (
        asyncio.ensure_future(_telemetry_task())
        if telemetry is not None
        else None
    )
    try:
        await asyncio.gather(*driver_tasks)
    finally:
        # raise_on_shed (or any driver failure) must not orphan sibling
        # drivers and the demux tasks on the loop past the raise (cancel
        # is a no-op for tasks that already completed)
        for task in driver_tasks:
            task.cancel()
        for task in demux_tasks:
            task.cancel()
        if telemetry_task is not None:
            telemetry_task.cancel()
        if telemetry is not None:
            # final window so short runs leave at least one behind
            _emit_telemetry()
            telemetry.close()
        for rw in rws.values():
            rw.close()
    return clients
