"""Overload-control primitives: bounded queues, cooperative backpressure,
retry backoff, and open-loop (Poisson) arrival pacing.

The reference bounds every channel and *blocks* producers on full
(fantoch/src/run/task/chan.rs:36-58, warn-then-block) — safe there because
each task owns a thread.  Here every producer is a synchronous handler on
one cooperative asyncio loop, so a blocking put would deadlock the very
consumer that needs to drain the queue.  The plane is therefore
credit-based instead of blocking:

* :class:`BoundedQueue` — an instrumented ``asyncio.Queue`` with a
  high/low watermark gate.  ``put_nowait`` never blocks (synchronous
  handlers stay safe); instead the queue *closes its credit gate* at the
  high watermark and re-opens it once drained below the low one.  The
  tasks that CAN pause — socket reader tasks, whose pause propagates to
  the sender peer-to-peer via TCP flow control — await
  :meth:`BoundedQueue.wait_for_credit` between frames, so pressure flows
  back to the producing process instead of accumulating as unbounded
  heap.  Depth high-watermarks, pause and overflow tallies ride the
  queue for the metrics plane.
* :class:`Backoff` — capped exponential backoff with full jitter for
  clients retrying a shed (:class:`~fantoch_tpu.errors.OverloadedError`)
  submission; honors the server's retry-after hint as a floor.
* :func:`poisson_intervals` / :class:`OpenLoopPacer` — seeded
  open-loop arrival pacing (exponential inter-arrival gaps at a target
  rate): the load instrument that makes overload *measurable*, since a
  closed-loop client pool self-throttles and can never push the system
  past saturation.

Admission control (the warn-then-*shed* half of the plane) lives at the
client-facing edges — ``run/process_runner.py`` sessions and the
``run/device_runner.py`` submit ring — which consult these watermarks and
reply with a typed ``Overloaded`` frame carrying a retry-after hint
instead of queueing past the bound.
"""

from __future__ import annotations

import asyncio
import random
from typing import Any, Dict, Iterator, Optional

from fantoch_tpu.utils import logger

# default high watermark for run-layer queues (the old WarnQueue warn
# threshold: what used to only shout now also gates); low = half of high
DEFAULT_QUEUE_CAPACITY = 8192
# default cap on a live-but-slow peer link's unacked resend window
# (run/links.py): ~512 acked strides of ACK_EVERY=64 frames.  A peer that
# silent-drops this many acks is indistinguishable from a dead one, and
# buffering further only converts its slowness into our OOM
DEFAULT_UNACKED_CAP = 1 << 15


class BoundedQueue(asyncio.Queue):
    """Instrumented queue with a high/low-watermark credit gate.

    ``capacity=None`` keeps the legacy warn-only behavior (unbounded,
    depth gauges still tracked).  With a capacity, ``put_nowait`` still
    never blocks or raises — producers are synchronous handlers on the
    cooperative loop — but the credit gate closes at ``capacity`` and
    re-opens at ``low`` (hysteresis, like the warn re-arm below), and
    puts landing while the gate is closed are tallied as ``overflows``
    (pressure the cooperative pause upstream could not absorb, e.g.
    self-delivered protocol messages).
    """

    def __init__(
        self,
        name: str,
        capacity: Optional[int] = DEFAULT_QUEUE_CAPACITY,
        low: Optional[int] = None,
        warn_size: int = 8192,
    ):
        super().__init__()
        self.name = name
        assert capacity is None or capacity >= 2, capacity
        self.capacity = capacity
        self.low = (
            low if low is not None else (capacity // 2 if capacity else 0)
        )
        self._warn_size = warn_size
        self._warn_next = warn_size
        # gauges for the metrics plane (run/observe.py ProcessMetrics)
        self.depth_hwm = 0
        self.pauses = 0  # times the credit gate closed
        self.overflows = 0  # puts while the gate was already closed
        self._credit = asyncio.Event()
        self._credit.set()

    def put_nowait(self, item: Any) -> None:  # type: ignore[override]
        super().put_nowait(item)
        depth = self.qsize()
        if depth > self.depth_hwm:
            self.depth_hwm = depth
        if self.capacity is not None and depth >= self.capacity:
            if self._credit.is_set():
                self._credit.clear()
                self.pauses += 1
                logger.warning(
                    "queue %s over its high watermark (%d >= %d): "
                    "pausing upstream readers",
                    self.name,
                    depth,
                    self.capacity,
                )
            else:
                self.overflows += 1
        if depth >= self._warn_next:
            logger.warning(
                "queue %s is full (%d items >= %d): consumer falling behind",
                self.name,
                depth,
                self._warn_next,
            )
            self._warn_next *= 2

    def get_nowait(self) -> Any:  # type: ignore[override]
        item = super().get_nowait()
        depth = self.qsize()
        if not self._credit.is_set() and depth <= self.low:
            self._credit.set()
        # hysteresis: re-arm only once the queue genuinely drained (half
        # the threshold) — a queue hovering AT the threshold must not warn
        # on every put
        if depth < self._warn_size // 2:
            self._warn_next = self._warn_size
        return item

    @property
    def gated(self) -> bool:
        """True while the credit gate is closed (depth crossed the high
        watermark and has not drained below the low one yet)."""
        return not self._credit.is_set()

    async def wait_for_credit(self) -> None:
        """Cooperative pause point for tasks that may stop producing
        (socket readers): returns once depth is back below the low
        watermark.  Consumers run on the same loop, so awaiting here is
        what lets them drain."""
        await self._credit.wait()

    def stats(self) -> Dict[str, float]:
        return {
            "depth": self.qsize(),
            "depth_hwm": self.depth_hwm,
            "capacity": self.capacity if self.capacity is not None else 0,
            "pauses": self.pauses,
            "overflows": self.overflows,
        }


class Backoff:
    """Capped exponential backoff with full jitter for overload retries.

    Same shape as :class:`fantoch_tpu.run.links.ReconnectPolicy` but for
    the client submission plane: each shed submission waits
    ``min(base * factor^attempt, cap)`` scaled by full jitter, floored by
    the server's retry-after hint (the server sees its own queue depth;
    the client should not retry sooner than that).
    """

    def __init__(
        self,
        base_ms: float = 25.0,
        factor: float = 2.0,
        cap_ms: float = 1000.0,
        rng: Optional[random.Random] = None,
    ):
        self.base_ms = base_ms
        self.factor = factor
        self.cap_ms = cap_ms
        self._rng = rng or random
        self.attempt = 0

    def next_delay_ms(self, retry_after_hint_ms: float = 0.0) -> float:
        delay = min(self.base_ms * (self.factor ** self.attempt), self.cap_ms)
        self.attempt += 1
        return max(retry_after_hint_ms, self._rng.uniform(0, delay))

    def reset(self) -> None:
        self.attempt = 0


def log_per_doubling(count: int) -> bool:
    """True on counts 1, 2, 4, 8, ... — the shared rate limit for
    per-shed warnings (a sustained burst sheds thousands of times; the
    log must keep shouting without spamming, like the queue warn)."""
    return count > 0 and count & (count - 1) == 0


def poisson_intervals(
    rate_per_s: float, rng: Optional[random.Random] = None
) -> Iterator[float]:
    """Seeded exponential inter-arrival gaps (seconds) for an open-loop
    Poisson arrival process at ``rate_per_s``."""
    assert rate_per_s > 0, rate_per_s
    rng = rng or random
    while True:
        yield rng.expovariate(rate_per_s)


class OpenLoopPacer:
    """Arrival pacing for one open-loop client: ``next_gap_s()`` yields
    the wait before the next submission — a fixed interval (the legacy
    ``open_loop_interval_ms`` mode) or seeded Poisson gaps at a target
    per-client rate."""

    def __init__(
        self,
        interval_ms: Optional[int] = None,
        rate_per_s: Optional[float] = None,
        seed: Optional[int] = None,
    ):
        assert (interval_ms is None) != (rate_per_s is None), (
            "exactly one of interval_ms / rate_per_s"
        )
        self._interval_ms = interval_ms
        self._gaps = (
            poisson_intervals(rate_per_s, random.Random(seed))
            if rate_per_s is not None
            else None
        )

    def next_gap_s(self) -> float:
        if self._gaps is not None:
            return next(self._gaps)
        return self._interval_ms / 1000.0
