"""The shared dispatch/drain pipeline core for device-resident serving.

Every device serving plane pays the same round shape: assemble a batch on
the host, dispatch one fused device program (async), fetch its outputs
(blocking), emit results.  On dispatch-dominated rigs the ~68 ms
host<->device round trip dwarfs the ~3 ms kernel (BENCH_TPU_LATEST
``dispatch_overhead_ms``), so the only way to keep the device busy is to
run dispatch N rounds ahead of drain — transfer of round i+1 and the
host-side result emit of round i-1 overlap with compute of round i, the
nonblocking-execution move of the GraphBLAS lazy-evaluation line
(PAPERS.md) applied to consensus serving.

This module is the one place that machinery lives (the ROADMAP item-5
refactor seam): drivers implement a ``dispatch(batch) -> token`` /
``drain(token) -> results`` split and inherit

  * :class:`PipelineCore` — a depth-K in-flight ring of round tokens
    (``step`` / ``step_pipelined`` / ``flush_pipeline``), per-dispatch
    wall-split counters, and the device busy/idle instrument
    (``device_idle_frac``);
  * :class:`IngestRing` — K+1 pre-staged host staging buffer sets for
    batch assembly, cycled round-robin so the columns a still-in-flight
    round reads (``jnp.asarray`` zero-copy aliases host numpy on the CPU
    backend) are never rewritten under it.

Depth semantics: ``pipeline_depth`` is the maximum number of
dispatched-but-undrained rounds ``step_pipelined`` leaves in flight, i.e.
the delivery lag in rounds.  Depth 1 is the classic double-buffered
overlap; deeper pipelines amortize jittery transfer latency at the cost
of K rounds of result lag.  ``step`` (synchronous) always flushes first,
so mixing the two is safe.

Donation discipline (the PR 4 XLA-ownership rule): the pipeline never
donates host staging buffers — only the drivers' device-resident *state*
is donated, and state rebuilds go through ``jnp.array`` copies.  Staging
columns are plain (non-donated) inputs, so ring reuse after drain is the
only aliasing hazard, and the ring's size (depth + 1) closes it.
"""

from __future__ import annotations

import os
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Sequence, Tuple

import numpy as np

ENV_PIPELINE_DEPTH = "FANTOCH_SERVING_PIPELINE_DEPTH"
DEFAULT_PIPELINE_DEPTH = 1


def requested_pipeline_depth(
    explicit: Optional[int] = None, config: Any = None
) -> Optional[int]:
    """The explicitly requested serving pipeline depth, by precedence:
    an explicit value, then ``Config.serving_pipeline_depth``, then the
    ``FANTOCH_SERVING_PIPELINE_DEPTH`` env var — or None when no channel
    requested one.  Any of the three spellings counts as the pipelining
    opt-in on CPU backends (they are one knob, not three)."""
    depth = explicit
    if depth is None and config is not None:
        depth = getattr(config, "serving_pipeline_depth", None)
    if depth is None:
        raw = os.environ.get(ENV_PIPELINE_DEPTH)
        if raw:
            depth = int(raw)
    return None if depth is None else int(depth)


def resolve_pipeline_depth(
    explicit: Optional[int] = None, config: Any = None
) -> int:
    """:func:`requested_pipeline_depth` with the default applied: 1 (the
    classic one-deep overlap) when nothing was requested."""
    depth = requested_pipeline_depth(explicit, config)
    if depth is None:
        depth = DEFAULT_PIPELINE_DEPTH
    if depth < 1:
        raise ValueError(f"serving pipeline depth must be >= 1, got {depth}")
    return depth


class IngestRing:
    """K+1 pre-staged host staging buffer sets, cycled round-robin.

    Each slot holds one set of named numpy columns (the per-round
    key/src/seq staging arrays).  ``acquire()`` resets the next slot's
    columns to their fill values in place and returns them — no per-round
    allocation, and a slot is only revisited after ``slots`` more
    acquires, which the pipeline guarantees is after its round drained
    (rounds in flight <= depth < slots).
    """

    __slots__ = ("_slots", "_specs", "_next")

    def __init__(
        self, slots: int, specs: Sequence[Tuple[str, tuple, Any, Any]]
    ):
        """``specs``: (name, shape, dtype, fill) per staging column."""
        assert slots >= 1
        self._specs = list(specs)
        self._slots = [
            tuple(
                np.full(shape, fill, dtype=dtype)
                for _name, shape, dtype, fill in self._specs
            )
            for _ in range(slots)
        ]
        self._next = 0

    @property
    def slots(self) -> int:
        return len(self._slots)

    def acquire(self) -> Tuple[np.ndarray, ...]:
        """The next slot's columns, reset in place to their fill values
        (in spec order)."""
        arrays = self._slots[self._next]
        self._next = (self._next + 1) % len(self._slots)
        for arr, (_name, _shape, _dtype, fill) in zip(arrays, self._specs):
            arr.fill(fill)
        return arrays


class BoundedSubmitRing:
    """Bounded FIFO of pending submissions feeding a serving loop — the
    device runtime's admission edge (run/backpressure.py plane).

    ``try_push`` refuses entries past ``capacity`` (the caller replies
    with a typed Overloaded frame instead of queueing without bound);
    the depth high-watermark rides the ring for the metrics snapshot,
    and the admission edge that refuses a command tallies it on
    ``sheds`` (the ring only *checks* the bound — counting belongs to
    whoever owns the reply, so one shed is never counted twice).
    ``capacity=None`` keeps the legacy unbounded behavior.
    """

    __slots__ = ("capacity", "depth_hwm", "sheds", "_items")

    def __init__(self, capacity: Optional[int] = None):
        assert capacity is None or capacity >= 1
        self.capacity = capacity
        self.depth_hwm = 0
        self.sheds = 0
        self._items: Deque[Any] = deque()

    def try_push(self, item: Any) -> bool:
        if self.capacity is not None and len(self._items) >= self.capacity:
            return False
        self._items.append(item)
        if len(self._items) > self.depth_hwm:
            self.depth_hwm = len(self._items)
        return True

    def popleft(self) -> Any:
        return self._items.popleft()

    def __len__(self) -> int:
        return len(self._items)

    def __bool__(self) -> bool:
        return bool(self._items)

    def stats(self) -> Dict[str, float]:
        return {
            "depth": len(self._items),
            "depth_hwm": self.depth_hwm,
            "capacity": self.capacity if self.capacity is not None else 0,
            "sheds": self.sheds,
        }


class PipelineCore:
    """Depth-K dispatch/drain pipelining plus the per-dispatch counters
    every device serving driver shares.

    Subclasses implement ``dispatch(batch) -> token`` (async: must not
    block on device completion) and ``drain(token) -> results`` (fetches
    outputs via :meth:`_fetch` and emits).  ``_pipeline_flush_needed``
    gates dispatches that would rebase state an in-flight round still
    references (sequence/clock/gid windows) — the pipeline retires every
    outstanding round first.

    Required subclass attribute: ``batch_size`` (the compiled per-round
    row capacity, read by the occupancy counters) must be set before
    ``_init_pipeline``.  ``seq_epochs`` (window-advance tally) is
    reported when present, 0 otherwise.
    """

    def _init_pipeline(self) -> None:
        self.pipeline_depth = DEFAULT_PIPELINE_DEPTH
        assert hasattr(self, "batch_size"), (
            "PipelineCore subclasses must set batch_size before "
            "_init_pipeline"
        )
        self._ring: Optional[IngestRing] = None  # lazy staging ring
        # per-dispatch observability (observability/device.py):
        # dispatched_rows vs dispatched_capacity is the batch occupancy;
        # dispatch/drain wall-ms split host assembly from device wait
        self.dispatches = 0
        self.dispatched_rows = 0
        self.dispatched_capacity = 0
        self.dispatch_wall_ms = 0.0
        self.drain_wall_ms = 0.0
        self.fetch_wall_ms = 0.0  # blocking device->host wait inside drains
        self.pipelined_rounds = 0  # rounds dispatched over an in-flight one
        self.chain_len = 1  # rounds the latest dispatch carried (gauge)
        # the in-flight ring: dispatched-but-undrained round tokens, FIFO
        self._inflight: Deque[Any] = deque()
        # rounds dispatched and not yet entered drain — during a drain
        # this counts OTHER in-flight rounds (unlike has_outstanding,
        # which is False mid-flush even with round k+1 dispatched), so
        # rebase paths can assert nothing is in flight
        self._undrained = 0
        # like _undrained but in protocol ROUNDS (a chained token carries
        # S rounds per dispatch): the clock-window margins are per round
        self._undrained_rounds = 0
        # device busy/idle instrument: a busy window opens when a dispatch
        # leaves the host (device has work) and closes at the fetch that
        # retires the LAST in-flight round; span is first dispatch ->
        # last fetch.  idle = span - busy = wall the device sat waiting
        # on host assembly/emit — the number the pipeline exists to kill.
        self._busy_t0: Optional[float] = None
        self._busy_ms = 0.0
        self._span_t0: Optional[float] = None
        self._span_end: Optional[float] = None

    def _staging(self, *specs) -> Tuple[np.ndarray, ...]:
        """The next pre-staged host staging slot for batch assembly:
        ``pipeline_depth + 1`` ring slots, so the columns a
        still-in-flight round zero-copy aliases (``jnp.asarray`` on the
        CPU backend) are never rewritten before that round drains."""
        slots = self.pipeline_depth + 1
        if self._ring is None or self._ring.slots < slots:
            self._ring = IngestRing(slots, specs)
        return self._ring.acquire()

    def reset_overlap_instrument(self) -> None:
        """Zero the busy/idle instrument (callers time a steady-state
        region after warm/compile rounds; requires nothing in flight so
        no busy window is open)."""
        assert self._undrained == 0, (
            "overlap-instrument reset with rounds in flight"
        )
        self._busy_t0 = self._span_t0 = self._span_end = None
        self._busy_ms = 0.0

    # --- the serving surface ---

    @property
    def has_outstanding(self) -> bool:
        """At least one dispatched-but-undrained pipelined round exists."""
        return bool(self._inflight)

    def step(self, batch) -> List[Any]:
        """One synchronous round: flush any pipelined rounds, dispatch,
        drain."""
        results = self.flush_pipeline()
        tok = self._dispatch_tracked(batch)
        results.extend(self._drain_tracked(tok))
        return results

    def step_chained(self, batches) -> List[Any]:
        """S rounds per call, synchronous.  The base implementation runs
        them as S plain steps (exact same results, no fusion); drivers
        with a fused multi-round program (NewtDeviceDriver) override to
        pay ONE dispatch round-trip for the whole chain — the serving
        loop routes through this surface unconditionally so chaining is
        a driver capability, not a call-site branch."""
        results = self.flush_pipeline()
        for batch in batches:
            results.extend(self.step(batch))
        return results

    def step_chained_pipelined(self, batches) -> List[Any]:
        """S rounds per call composed with the depth-K pipeline.  Base
        implementation: S consecutive ``step_pipelined`` rounds (the
        chain is a grouping hint, not a semantic change); fused drivers
        override to dispatch the chain as one token."""
        results: List[Any] = []
        for batch in batches:
            results.extend(self.step_pipelined(batch))
        return results

    def step_pipelined(self, batch) -> List[Any]:
        """Dispatch ``batch`` and drain only rounds beyond the configured
        ``pipeline_depth`` — results arrive up to ``pipeline_depth`` calls
        late in exchange for overlapping device compute with host batch
        assembly and the result-emit loop.  Call ``flush_pipeline`` to
        retire the tail."""
        if self._inflight and self._pipeline_flush_needed(batch):
            # an epoch/window rebase would invalidate an in-flight
            # round's identity or clock accounting — retire them all
            # first (rare: once per int32 window)
            early = self.flush_pipeline()
            self._inflight.append(self._dispatch_tracked(batch))
            return early
        return self._pipeline_dispatch(
            lambda: self.dispatch(batch), len(batch), self.batch_size, 1
        )

    def _pipeline_dispatch(
        self, fn, rows: int, capacity: int, rounds: int
    ) -> List[Any]:
        """The shared pipelined-dispatch tail: tally overlap, push the
        new round token, drain down to depth.  Chained drivers reuse it
        with their chain thunks (the caller handled any flush trigger)."""
        if self._inflight:
            self.pipelined_rounds += rounds
        self._inflight.append(self._track_dispatch(fn, rows, capacity, rounds))
        return self._drain_to_depth()

    def flush_pipeline(self) -> List[Any]:
        """Drain every outstanding pipelined round, oldest first."""
        results: List[Any] = []
        while self._inflight:
            results.extend(self._drain_tracked(self._inflight.popleft()))
        return results

    def _drain_to_depth(self) -> List[Any]:
        results: List[Any] = []
        while len(self._inflight) > self.pipeline_depth:
            results.extend(self._drain_tracked(self._inflight.popleft()))
        return results

    # --- tracked dispatch/drain plumbing ---

    def _dispatch_tracked(self, batch):
        return self._track_dispatch(
            lambda: self.dispatch(batch), len(batch), self.batch_size, 1
        )

    def _track_dispatch(self, fn, rows: int, capacity: int, rounds: int):
        t0 = time.perf_counter()
        if self._span_t0 is None:
            self._span_t0 = t0
        tok = fn()
        t1 = time.perf_counter()
        self.dispatch_wall_ms += (t1 - t0) * 1000.0
        self.dispatches += 1
        self.dispatched_rows += rows
        self.dispatched_capacity += capacity
        self.chain_len = max(1, rounds)
        self._undrained += 1
        self._undrained_rounds += rounds
        if self._busy_t0 is None:
            # the device has work from the moment the dispatch call
            # returns (the submit is async); host assembly before it
            # counts as idle, which is the point of the instrument
            self._busy_t0 = t1
        return tok

    def _drain_tracked(self, tok):
        # inside drain, _undrained counts OTHER in-flight rounds
        self._undrained -= 1
        self._undrained_rounds -= self._token_rounds(tok)
        t0 = time.perf_counter()
        out = self.drain(tok)
        self.drain_wall_ms += (time.perf_counter() - t0) * 1000.0
        return out

    def _token_rounds(self, tok) -> int:
        """Protocol rounds one dispatch token carries (chained drivers
        override for their chain tokens)."""
        return 1

    def _fetch(self, out):
        """ONE blocking pytree fetch for a round's outputs: device_get
        issues async copies for every leaf before blocking, so the round
        pays a single device->host round trip instead of one per field
        (through a remote-dispatch tunnel each blocking np.asarray costs
        a full ~76 ms round trip, BENCH_DEV round 5).  Also the busy/idle
        bookkeeping point: when this fetch retires the last in-flight
        round, the device goes idle until the next dispatch."""
        import jax

        t0 = time.perf_counter()
        out = jax.device_get(out)
        t1 = time.perf_counter()
        self.fetch_wall_ms += (t1 - t0) * 1000.0
        if self._undrained == 0 and self._busy_t0 is not None:
            self._busy_ms += (t1 - self._busy_t0) * 1000.0
            self._busy_t0 = None
        self._span_end = t1
        return out

    def _pipeline_flush_needed(self, batch) -> bool:
        """True when the upcoming dispatch may trigger a rebase that must
        not happen with rounds in flight; drivers extend with their
        window triggers."""
        return False

    # --- the counters (metrics snapshots / bench rows) ---

    def device_counters(self) -> Dict[str, float]:
        """Per-dispatch tallies for the metrics snapshot / bench rows:
        occupancy = dispatched_rows / dispatched_capacity; busy/span give
        ``device_idle_frac`` — the fraction of the serving span the
        device sat idle waiting on the host (the pipelined loop's whole
        job is driving it toward 0)."""
        now = time.perf_counter()
        busy_ms = self._busy_ms
        span_ms = 0.0
        if self._span_t0 is not None:
            span_end = self._span_end
            if self._busy_t0 is not None:
                # rounds still in flight: close the open windows at `now`
                # for a consistent mid-run snapshot
                busy_ms += (now - self._busy_t0) * 1000.0
                span_end = now
            if span_end is not None:
                span_ms = (span_end - self._span_t0) * 1000.0
        idle_frac = (
            max(0.0, 1.0 - busy_ms / span_ms) if span_ms > 0 else 0.0
        )
        # occupancy: rows actually carried / rows the dispatched rounds
        # could carry — the adaptive ingest batcher's whole job is
        # driving this toward 1 under load
        fill_frac = (
            self.dispatched_rows / self.dispatched_capacity
            if self.dispatched_capacity > 0 else 0.0
        )
        return {
            "device_dispatches": self.dispatches,
            "device_dispatched_rows": self.dispatched_rows,
            "device_batch_capacity": self.dispatched_capacity,
            "dispatch_fill_frac": round(fill_frac, 4),
            "serving_chain_len": self.chain_len,
            "device_dispatch_ms": round(self.dispatch_wall_ms, 3),
            "device_drain_ms": round(self.drain_wall_ms, 3),
            "device_fetch_ms": round(self.fetch_wall_ms, 3),
            "device_busy_ms": round(busy_ms, 3),
            "device_span_ms": round(span_ms, 3),
            "device_idle_frac": round(idle_frac, 4),
            "device_pipeline_depth": self.pipeline_depth,
            "device_pipelined_rounds": self.pipelined_rounds,
            "device_seq_epochs": getattr(self, "seq_epochs", 0),
        }
