"""Localhost whole-system harness: boots a full n-process TCP cluster plus
clients inside one asyncio loop.

Reference: fantoch/src/run/mod.rs:1030-1346 (`run_test_with_inspect_fun`) —
the reference boots every server and client as tokio tasks in one runtime
on random localhost ports; here they are asyncio tasks in one loop, and
instead of shipping Inspect closures through the periodic task we keep
direct references to the runtimes for post-run assertions.
"""

from __future__ import annotations

import asyncio
import socket
from typing import Dict, List, Optional, Tuple

from fantoch_tpu.client.client import Client
from fantoch_tpu.client.workload import Workload
from fantoch_tpu.core.config import Config
from fantoch_tpu.core.ids import ClientId, ProcessId, process_ids
from fantoch_tpu.run.client_runner import run_clients
from fantoch_tpu.run.process_runner import ProcessRuntime


_claimed_ports: set = set()


def free_port() -> int:
    """An OS-assigned free port, never handed out twice by this process.

    The probe socket is closed before the caller binds, so the kernel may
    recycle the port for a concurrent probe — within one process (the
    common harness pattern: allocate 2 ports x n processes up front) the
    claimed-set closes that race; across processes the startup retry in
    the runners covers the rest."""
    for _ in range(64):
        with socket.socket() as sock:
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            sock.bind(("127.0.0.1", 0))
            port = sock.getsockname()[1]
        if port not in _claimed_ports:
            _claimed_ports.add(port)
            return port
    raise RuntimeError("could not allocate a fresh localhost port")


async def run_localhost_cluster(
    protocol_cls: type,
    config: Config,
    workload: Workload,
    clients_per_process: int,
    open_loop_interval_ms: Optional[int] = None,
    arrival_rate_per_s: Optional[float] = None,
    arrival_seed: Optional[int] = None,
    deadline_ms: Optional[int] = None,
    extra_run_time_ms: int = 500,
    workers: int = 1,
    executors: int = 1,
    multiplexing: int = 1,
    peer_delays: Optional[Dict[ProcessId, Dict[ProcessId, int]]] = None,
    ping_sort: bool = False,
    observe_dir: Optional[str] = None,
    metrics_ports: Optional[Dict[ProcessId, int]] = None,
    runtime_kwargs: Optional[dict] = None,
    chaos=None,
) -> Tuple[Dict[ProcessId, ProcessRuntime], Dict[ClientId, Client]]:
    """Boot n*shard_count processes + clients, run the workload to
    completion, keep the cluster alive `extra_run_time_ms` (for GC rounds),
    then tear down.

    Multi-shard topology (mod.rs:786-838 region-index pattern): shard s
    owns ids s*n+1..=(s+1)*n; the process at offset o of shard s peers with
    its own shard plus the offset-o process of every other shard (its
    "closest" of that shard), mirroring the reference's
    connect-to-closest-per-shard rule (run/task/process.rs:21)."""
    if observe_dir is not None:
        import os

        os.makedirs(observe_dir, exist_ok=True)
    # lifecycle tracing: with a sample rate and an observe dir, every
    # runtime writes trace_p<pid>.jsonl and the client plane
    # trace_clients.jsonl — bin/obs.py consumes all of them together
    tracing = observe_dir is not None and config.trace_sample_rate > 0
    client_tracer = None
    if tracing:
        from fantoch_tpu.core.timing import RunTime
        from fantoch_tpu.observability.tracer import Tracer

        client_tracer = Tracer(
            RunTime(), f"{observe_dir}/trace_clients.jsonl",
            config.trace_sample_rate, clock="wall",
        )
    shard_count = config.shard_count
    shard_ids = {s: list(process_ids(s, config.n)) for s in range(shard_count)}
    all_pids = [pid for ids in shard_ids.values() for pid in ids]
    shard_of = {pid: s for s, ids in shard_ids.items() for pid in ids}
    offset_of = {pid: pid - shard_ids[shard_of[pid]][0] for pid in all_pids}
    peer_ports = {pid: free_port() for pid in all_pids}
    client_ports = {pid: free_port() for pid in all_pids}
    runtimes: Dict[ProcessId, ProcessRuntime] = {}
    for pid in all_pids:
        shard_id = shard_of[pid]
        ids = shard_ids[shard_id]
        offset = offset_of[pid]
        # localhost processes are equidistant except to themselves: the
        # distance-sorted list must lead with self (ping 0), like the
        # reference's ping sort (run/task/ping.rs:144), or a process's fast
        # quorum may exclude itself and its submits would rely on acks for
        # payloads it never stored
        sorted_processes = [(pid, shard_id)] + [
            (peer, shard_id) for peer in ids if peer != pid
        ]
        peers = {peer: ("127.0.0.1", peer_ports[peer]) for peer in ids if peer != pid}
        for other_shard, other_ids in shard_ids.items():
            if other_shard != shard_id:
                closest = other_ids[offset]
                sorted_processes.append((closest, other_shard))
                peers[closest] = ("127.0.0.1", peer_ports[closest])
        runtimes[pid] = ProcessRuntime(
            protocol_cls,
            pid,
            shard_id,
            config,
            listen_addr=("127.0.0.1", peer_ports[pid]),
            client_addr=("127.0.0.1", client_ports[pid]),
            peers=peers,
            sorted_processes=sorted_processes,
            workers=workers,
            executors=executors,
            multiplexing=multiplexing,
            peer_delays=(peer_delays or {}).get(pid),
            ping_sort=ping_sort,
            metrics_file=(
                f"{observe_dir}/metrics_p{pid}.gz" if observe_dir else None
            ),
            metrics_interval_ms=200,
            execution_log=(
                f"{observe_dir}/execution_p{pid}.log" if observe_dir else None
            ),
            trace_file=(
                f"{observe_dir}/trace_p{pid}.jsonl" if tracing else None
            ),
            # live telemetry: windowed series per process (plus the
            # client plane's below), and an optional exposition endpoint
            # per pid (metrics_ports={pid: port}; 0 = OS-assigned, read
            # the real one back from runtime.metrics_port)
            telemetry_file=(
                f"{observe_dir}/telemetry_p{pid}.jsonl" if observe_dir else None
            ),
            metrics_port=(metrics_ports or {}).get(pid),
            # flight recorder dumps land next to the traces they stitch
            # against (Config.flight_recorder resolves its own default
            # when no observe dir exists)
            flight_dir=(observe_dir if config.flight_recorder else None),
            **(runtime_kwargs or {}),
        )

    await asyncio.gather(*(runtime.start() for runtime in runtimes.values()))

    # one client pool per shard-0 process; each pool talks to the offset-o
    # process of every shard (mod.rs:1240-1290)
    client_groups: List[Tuple[List[ClientId], ProcessId]] = []
    next_client = 1
    for pid in shard_ids[0]:
        group = list(range(next_client, next_client + clients_per_process))
        next_client += clients_per_process
        client_groups.append((group, pid))

    # optional chaos driver runs alongside the clients (e.g. severing peer
    # links mid-run to exercise the reconnect path)
    chaos_task = (
        asyncio.ensure_future(chaos(runtimes)) if chaos is not None else None
    )
    client_task = asyncio.gather(
        *(
            run_clients(
                group,
                {
                    s: ("127.0.0.1", client_ports[shard_ids[s][offset_of[pid]]])
                    for s in range(shard_count)
                },
                workload,
                open_loop_interval_ms=open_loop_interval_ms,
                arrival_rate_per_s=arrival_rate_per_s,
                arrival_seed=arrival_seed,
                deadline_ms=deadline_ms,
                **({"tracer": client_tracer} if client_tracer is not None else {}),
                **(
                    {
                        "telemetry_file": (
                            f"{observe_dir}/telemetry_clients_p{pid}.jsonl"
                        ),
                        "telemetry_interval_ms": config.telemetry_interval_ms,
                    }
                    if observe_dir is not None
                    else {}
                ),
            )
            for group, pid in client_groups
        )
    )
    # a runtime failure (e.g. a typed QuorumLostError) must surface loudly
    # instead of hanging the clients forever
    failure_tasks = {
        asyncio.ensure_future(runtime.failed.wait()): pid
        for pid, runtime in runtimes.items()
    }
    try:
        done, _pending = await asyncio.wait(
            {client_task, *failure_tasks}, return_when=asyncio.FIRST_COMPLETED
        )
        if client_task not in done:
            failed = next(t for t in done if t in failure_tasks)
            pid = failure_tasks[failed]
            client_task.cancel()
            # reap the cancelled gather BEFORE raising: an un-awaited
            # cancellation can resurface as CancelledError during the
            # AssertionError's unwind and replace it out of asyncio.run
            try:
                await client_task
            except (asyncio.CancelledError, Exception):
                pass
            # a typed failure must also stop the survivors: their tasks
            # would otherwise outlive this coroutine and be cancelled by
            # the loop teardown mid-write
            await asyncio.gather(
                *(runtime.stop() for runtime in runtimes.values()),
                return_exceptions=True,
            )
            raise AssertionError(
                f"runtime p{pid} failed mid-run: {runtimes[pid].failure!r}"
            )
        results = client_task.result()
        if chaos_task is not None:
            await chaos_task
    finally:
        for task in failure_tasks:
            task.cancel()
        # on any failure path the chaos driver must not outlive the run
        # (it would keep poking runtimes that are being stopped)
        if chaos_task is not None and not chaos_task.done():
            chaos_task.cancel()
        # failure paths skip the clean close below: flush so the span
        # log's crash-consistent prefix covers everything emitted
        if client_tracer is not None:
            client_tracer.flush()

    await asyncio.sleep(extra_run_time_ms / 1000)
    # stop concurrently: a sequential shutdown leaves the last runtimes
    # watching already-stopped peers, and their failure detectors would
    # (correctly, but uselessly) report the shutdown as peer loss
    await asyncio.gather(*(runtime.stop() for runtime in runtimes.values()))
    if client_tracer is not None:
        client_tracer.close()

    clients: Dict[ClientId, Client] = {}
    for group in results:
        clients.update(group)
    return runtimes, clients


def run_overload_phase(
    protocol_cls,
    config: Config,
    workload: Workload,
    clients_per_process: int,
    arrival_rate_per_s: Optional[float] = None,
    arrival_seed: Optional[int] = None,
    deadline_ms: Optional[int] = None,
    extra_run_time_ms: int = 100,
) -> dict:
    """One measured load phase against a fresh localhost cluster — the
    shared instrument of ``bench.py bench_overload`` and
    ``scripts/overload_smoke.py`` (one implementation, so the CI gate and
    the bench row cannot drift on accounting semantics).

    Boots, drives the client pool (closed loop, or open-loop Poisson at
    ``arrival_rate_per_s`` per client), tears down; returns goodput,
    latency percentiles, the overload-plane tallies, and the depth
    high-watermarks split by queue family.  ``bound_violations`` lists
    queues whose depth high-watermark passed 2x their configured
    capacity: the capacity is a *pause watermark*, not a hard cap
    (``put_nowait`` never blocks — synchronous producers may overshoot
    while a gate drains, tallied as overflows), so bounded-ness is
    pinned as "never past 2x the watermark", while the truly hard bounds
    (the device submit ring, the admission limit) assert exactly.
    """
    runtimes, clients = asyncio.run(
        run_localhost_cluster(
            protocol_cls, config, workload, clients_per_process,
            arrival_rate_per_s=arrival_rate_per_s,
            arrival_seed=arrival_seed,
            deadline_ms=deadline_ms,
            extra_run_time_ms=extra_run_time_ms,
        )
    )
    latencies = sorted(
        value
        for client in clients.values()
        for value in client.data().latency_data()
    )
    # goodput over the SERVING span (first submit to last completion,
    # reconstructed from the client records) — not the harness wall,
    # which includes cluster boot/connect and would deflate the
    # saturation estimate the burst rates are calibrated against
    spans = [
        client.data().span_millis()
        for client in clients.values()
        if list(client.data().latency_data())
    ]
    wall_s = (
        (max(end for _s, end in spans) - min(start for start, _e in spans))
        / 1000.0
        if spans
        else 0.0
    )
    queue_hwm = unacked_hwm = 0
    violations = []
    for runtime in runtimes.values():
        for name, row in runtime.queue_stats().items():
            if name.startswith("unacked->"):
                unacked_hwm = max(unacked_hwm, row["depth_hwm"])
            else:
                queue_hwm = max(queue_hwm, row["depth_hwm"])
            if row["capacity"] and row["depth_hwm"] > 2 * row["capacity"]:
                violations.append((name, row["depth_hwm"], row["capacity"]))
    total = len(latencies)
    # device-plane counters folded across the cluster (None entries are
    # plane-off runtimes): the serving rows assert the plane actually
    # carried the run (dispatches > 0) instead of silently measuring the
    # host path
    from fantoch_tpu.observability.device import merge_counters

    device_counters: dict = {}
    for runtime in runtimes.values():
        per_runtime = runtime._device_counters()
        if per_runtime:
            # host-process-global: summing across co-hosted runtimes
            # would n-fold them (observability/device.py)
            per_runtime = dict(per_runtime)
            per_runtime.pop("jax_recompiles", None)
            per_runtime.pop("jax_compile_ms", None)
        merge_counters(device_counters, per_runtime)
    return {
        "completed": total,
        "device": device_counters,
        "goodput_cmds_per_s": int(total / wall_s) if wall_s > 0 else 0,
        "p50_ms": round(latencies[total // 2] / 1000.0, 2) if total else None,
        "p95_ms": (
            round(latencies[int(total * 0.95)] / 1000.0, 2) if total else None
        ),
        "p99_ms": (
            round(latencies[int(total * 0.99)] / 1000.0, 2) if total else None
        ),
        "sheds": sum(r.shed_submissions for r in runtimes.values()),
        "backpressure_pauses": sum(
            r.backpressure_pauses for r in runtimes.values()
        ),
        "client_retries": sum(c.overload_retries for c in clients.values()),
        "shed_commands": sum(c.shed_commands for c in clients.values()),
        "queue_depth_hwm": int(queue_hwm),
        "unacked_depth_hwm": int(unacked_hwm),
        "bound_violations": violations,
    }


async def run_device_server(
    config: Config,
    workload: Workload,
    client_count: int,
    *,
    protocol: str = "epaxos",
    batch_size: int = 64,
    key_buckets: int = 1024,
    key_width: int = 1,
    pending_capacity: int = 64,
    open_loop_interval_ms: Optional[int] = None,
    arrival_rate_per_s: Optional[float] = None,
    arrival_seed: Optional[int] = None,
    deadline_ms: Optional[int] = None,
    monitor_execution_order: bool = True,
    pipeline: Optional[bool] = None,
    pipeline_depth: Optional[int] = None,
    telemetry_file: Optional[str] = None,
    metrics_port: Optional[int] = None,
    trace_file: Optional[str] = None,
    flight_dir: Optional[str] = None,
):
    """Boot the TPU serving path (run/device_runner.py) on a localhost
    port and drive real TCP clients against it; returns
    ``(DeviceRuntime, clients)``.  A runtime failure tears the run down
    loudly instead of stalling the clients."""
    from fantoch_tpu.run.device_runner import DeviceRuntime

    port = free_port()
    runtime = DeviceRuntime(
        config,
        ("127.0.0.1", port),
        protocol=protocol,
        batch_size=batch_size,
        key_buckets=key_buckets,
        key_width=key_width,
        pending_capacity=pending_capacity,
        monitor_execution_order=monitor_execution_order,
        pipeline=pipeline,
        pipeline_depth=pipeline_depth,
        telemetry_file=telemetry_file,
        metrics_port=metrics_port,
        trace_file=trace_file,
        flight_dir=flight_dir,
    )
    await runtime.start()
    client_task = asyncio.ensure_future(
        run_clients(
            list(range(1, client_count + 1)),
            # the unified mesh server owns every shard: all shard ids map
            # to its one address (clients open one connection per shard)
            {s: ("127.0.0.1", port) for s in range(config.shard_count)},
            workload,
            open_loop_interval_ms=open_loop_interval_ms,
            arrival_rate_per_s=arrival_rate_per_s,
            arrival_seed=arrival_seed,
            deadline_ms=deadline_ms,
        )
    )
    failure_task = asyncio.ensure_future(runtime.failed.wait())
    try:
        done, _pending = await asyncio.wait(
            {client_task, failure_task}, return_when=asyncio.FIRST_COMPLETED
        )
        if failure_task in done:
            client_task.cancel()
            raise AssertionError(f"device runtime failed: {runtime.failure!r}")
        clients = client_task.result()
    finally:
        failure_task.cancel()
        await runtime.stop()
    return runtime, clients
