"""Durable per-process command log + snapshots: the restart plane's disk.

The reference's run layer assumes restartable processes (its GC only
reclaims commit info once a dot is *executed everywhere*, so a returning
replica can always be caught up from a live peer); this module supplies
the durable half of that assumption for our runner: an append-only log of
commit records (the protocol's ``to_executors`` stream) plus periodic
whole-state snapshots, so a crashed :class:`ProcessRuntime` restarts as
``load snapshot -> replay log tail -> MSync catch-up`` instead of staying
dead.

Design:

* **Framing** — every record is ``magic(2B) | length(4B) | crc32(4B) |
  payload`` with a pickled ``(kind, obj)`` payload.  The reader stops at
  the first short/corrupt frame: the same crash-consistent
  torn-tail-tolerant discipline as the tracer JSONL
  (observability/tracer.py) — a crash mid-append loses at most the
  record being written, never the prefix.  Reopening for append
  truncates the torn tail so new records never chain onto garbage.
* **Fsync policy** — ``always`` fsyncs every append (commit-durable
  before the frame is acknowledged anywhere), ``interval`` fsyncs on the
  runtime's periodic WAL tick (bounded loss window, the default), and
  ``never`` leaves durability to the OS.  One knob, resolved like
  ``serving_pipeline_depth``: explicit ``Config.wal_sync`` beats the
  ``FANTOCH_WAL_SYNC`` env var beats the ``interval`` default.
* **Segments + snapshots** — the log is a sequence of
  ``wal-<seq>.seg`` files.  ``save_snapshot`` first rotates to a fresh
  segment, then atomically (tmp + rename + dir fsync) writes
  ``snapshot-<seq>.bin`` whose tag names the first segment of its tail;
  segments below the tag (and older snapshots) are pruned.  Snapshot
  cadence rides the executed-everywhere GC retention: anything the
  snapshot captured is by construction at or past what peers may have
  GC'd, so ``snapshot + tail + MSync`` always reconnects to the mesh's
  retained history and the log stays finite.
* **Dot lease** — a restarted process must never re-issue a dot sequence
  it handed out before the crash.  ``lease`` records persist a high
  watermark in batches of :data:`DOT_LEASE_BATCH` (fsync'd regardless of
  policy: a lease is cheap and must not be outrun by its own dots);
  recovery resumes allocation above the highest lease seen.
* **Incarnation** — each recovery bumps a boot counter (``boot`` file).
  Peer links carry it in their handshake so receivers reset per-link
  sequence dedup for a restarted sender (its frames restart at seq 1 and
  must not be swallowed as duplicates of the previous life).
"""

from __future__ import annotations

import os
import pickle
import struct
import zlib
from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple

_MAGIC = 0xFA17
_HEADER = struct.Struct("<HII")  # magic, payload length, crc32(payload)

# dot-lease batch: one fsync'd lease record per this many allocations
DOT_LEASE_BATCH = 1024

WAL_SYNC_POLICIES = ("always", "interval", "never")


def resolve_wal_sync(config_value: Optional[str]) -> str:
    """One knob, ``serving_pipeline_depth`` style: explicit config value
    beats the FANTOCH_WAL_SYNC env var beats the ``interval`` default."""
    if config_value is not None:
        policy = config_value
    else:
        policy = os.environ.get("FANTOCH_WAL_SYNC") or "interval"
    if policy not in WAL_SYNC_POLICIES:
        raise ValueError(
            f"wal_sync must be one of {WAL_SYNC_POLICIES}, got {policy!r}"
        )
    return policy


@dataclass
class RecoveredState:
    """What :meth:`Wal.recover` found on disk."""

    snapshot: Optional[dict]  # save_snapshot payload, None on a fresh dir
    tail: List[Tuple[str, Any]] = field(default_factory=list)
    incarnation: int = 0
    dot_lease: int = 0
    # last executor emit frontier logged in the tail (None when no
    # frontier record survived): how far execution had provably gotten
    frontier: Any = None


def _segment_name(seq: int) -> str:
    return f"wal-{seq:08d}.seg"


def _snapshot_name(seq: int) -> str:
    return f"snapshot-{seq:08d}.bin"


def _listed(directory: str, prefix: str, suffix: str) -> List[Tuple[int, str]]:
    out = []
    for name in os.listdir(directory):
        if name.startswith(prefix) and name.endswith(suffix):
            try:
                seq = int(name[len(prefix) : -len(suffix)])
            except ValueError:
                continue
            out.append((seq, name))
    out.sort()
    return out


def _fsync_dir(directory: str) -> None:
    fd = os.open(directory, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def read_segment(path: str) -> Tuple[List[Tuple[str, Any]], int]:
    """Read one segment; returns (records, valid_byte_length).  Stops at
    the first torn/corrupt frame — the crash-consistent prefix ends
    there (same contract as ``tracer.read_trace``)."""
    records: List[Tuple[str, Any]] = []
    valid = 0
    with open(path, "rb") as fh:
        data = fh.read()
    offset = 0
    while offset + _HEADER.size <= len(data):
        magic, length, crc = _HEADER.unpack_from(data, offset)
        if magic != _MAGIC:
            break
        payload = data[offset + _HEADER.size : offset + _HEADER.size + length]
        if len(payload) < length or zlib.crc32(payload) != crc:
            break
        try:
            records.append(pickle.loads(payload))
        except Exception:
            break
        offset += _HEADER.size + length
        valid = offset
    return records, valid


class Wal:
    """Append-only durable log with segment rotation and snapshots.

    Construction alone never touches prior state; call :meth:`recover`
    once (before appending) to load it — recovery also truncates any torn
    tail and bumps the incarnation counter.
    """

    def __init__(self, directory: str, sync: str = "interval",
                 segment_bytes: int = 4 << 20):
        assert sync in WAL_SYNC_POLICIES, sync
        self.directory = directory
        self.sync_policy = sync
        self.segment_bytes = segment_bytes
        os.makedirs(directory, exist_ok=True)
        self._fh = None
        self._seq = 0  # current segment sequence
        self._dirty = False
        self.incarnation = 0
        self.appended = 0  # records appended this boot (observability)
        self.replayed = 0  # tail records handed to recover()'s caller

    # --- recovery ---

    def recover(self) -> RecoveredState:
        """Load the latest snapshot + the log tail past it, truncate any
        torn tail, bump the incarnation, and open for append."""
        directory = self.directory
        snapshots = _listed(directory, "snapshot-", ".bin")
        snapshot = None
        tail_from = 1
        while snapshots:
            seq, name = snapshots[-1]
            try:
                with open(os.path.join(directory, name), "rb") as fh:
                    snapshot = pickle.load(fh)
                tail_from = seq
                break
            except Exception:
                # torn snapshot (crash between create and rename cannot
                # happen — rename is atomic — but tolerate manual damage)
                snapshots.pop()
        segments = [
            (seq, name)
            for seq, name in _listed(directory, "wal-", ".seg")
            if seq >= tail_from
        ]
        tail: List[Tuple[str, Any]] = []
        dot_lease = 0 if snapshot is None else int(snapshot.get("dot_lease", 0))
        for index, (seq, name) in enumerate(segments):
            path = os.path.join(directory, name)
            records, valid = read_segment(path)
            tail.extend(records)
            size = os.path.getsize(path)
            if valid < size:
                # torn tail: only meaningful in the last segment, but a
                # mid-chain tear (lost writes) must also stop replay —
                # records past a tear may postdate state we did not see.
                # The dropped later segments are UNLINKED, not just
                # skipped: appends resume in the truncated segment, and
                # a later recovery would otherwise resurrect the stale
                # segments AFTER the new records (out-of-order replay)
                with open(path, "r+b") as fh:
                    fh.truncate(valid)
                for _seq, stale in segments[index + 1 :]:
                    os.unlink(os.path.join(directory, stale))
                del segments[index + 1 :]
                break
        frontier = None
        for kind, obj in tail:
            if kind == "lease":
                dot_lease = max(dot_lease, int(obj))
            elif kind == "frontier":
                frontier = obj  # last one wins (they are monotone)
        self.replayed = len(tail)
        # incarnation bump, persisted before anything else this boot
        boot_path = os.path.join(directory, "boot")
        incarnation = 0
        if os.path.exists(boot_path):
            try:
                with open(boot_path, "r") as fh:
                    incarnation = int(fh.read().strip() or 0)
            except ValueError:
                incarnation = 0
        self.incarnation = incarnation + 1
        tmp = boot_path + ".tmp"
        with open(tmp, "w") as fh:
            fh.write(str(self.incarnation))
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, boot_path)
        _fsync_dir(directory)
        # append to the last live segment (or start the first)
        self._seq = segments[-1][0] if segments else tail_from
        self._open_segment()
        return RecoveredState(snapshot, tail, self.incarnation, dot_lease, frontier)

    # --- append path ---

    def _open_segment(self) -> None:
        if self._fh is not None:
            self._fh.close()
        path = os.path.join(self.directory, _segment_name(self._seq))
        self._fh = open(path, "ab")

    def _ensure_open(self) -> None:
        if self._fh is None:
            self._seq = max(self._seq, 1)
            self._open_segment()

    def append(self, kind: str, obj: Any, force_sync: bool = False) -> None:
        self._ensure_open()
        payload = pickle.dumps((kind, obj))
        self._fh.write(_HEADER.pack(_MAGIC, len(payload), zlib.crc32(payload)))
        self._fh.write(payload)
        self._dirty = True
        self.appended += 1
        if force_sync or self.sync_policy == "always":
            self.sync(force=True)
        if self._fh.tell() >= self.segment_bytes:
            self.rotate()

    def append_lease(self, sequence: int) -> None:
        """Persist a dot-allocation high watermark.  Always fsync'd: a
        lease outrun by its own dots would let a restarted process
        re-issue live sequences."""
        self.append("lease", int(sequence), force_sync=True)

    def sync(self, force: bool = False) -> None:
        """Flush (and fsync unless the policy is ``never``) buffered
        appends; the runtime's periodic WAL tick drives the ``interval``
        policy through here."""
        if self._fh is None or not self._dirty:
            return
        self._fh.flush()
        if force or self.sync_policy != "never":
            os.fsync(self._fh.fileno())
        self._dirty = False

    def rotate(self) -> int:
        """Close the current segment and start the next; returns the new
        segment's sequence."""
        self.sync()
        self._ensure_open()
        self._seq += 1
        self._open_segment()
        return self._seq

    # --- snapshots ---

    def save_snapshot(self, payload: dict) -> None:
        """Atomically persist a state snapshot covering everything before
        the current log position, then prune segments (and snapshots) the
        new snapshot obsoletes — the rotation that keeps the log bounded
        by the snapshot cadence instead of run length."""
        tail_seq = self.rotate()
        path = os.path.join(self.directory, _snapshot_name(tail_seq))
        tmp = path + ".tmp"
        with open(tmp, "wb") as fh:
            pickle.dump(payload, fh)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
        _fsync_dir(self.directory)
        for seq, name in _listed(self.directory, "wal-", ".seg"):
            if seq < tail_seq:
                os.unlink(os.path.join(self.directory, name))
        for seq, name in _listed(self.directory, "snapshot-", ".bin"):
            if seq < tail_seq:
                os.unlink(os.path.join(self.directory, name))

    def close(self) -> None:
        if self._fh is not None:
            self.sync()
            self._fh.close()
            self._fh = None
