"""Crash-tolerant peer links: sequence/ack reliability + reconnect policy.

The reference opens each peer connection once at boot
(fantoch/src/run/task/process.rs:71-111) and treats any later connection
loss as fatal — acceptable on a supervised testbed, not for the ROADMAP's
production-scale target.  This module carries the state that lets the
runner (run/process_runner.py) survive mid-run connection loss:

* every peer link numbers its data frames; the receiver acks periodically
  and dedups by sequence, so after a reconnect the sender can resend its
  unacked window without double-delivering — TCP-like reliability that
  *survives* the TCP connection, which the protocols' quasi-reliable
  channel assumption actually requires;
* :class:`ReconnectPolicy` is the exponential-backoff-with-full-jitter
  schedule used both by mid-run reconnects and the initial boot dial;
* :class:`LinkState` owns one link's sender-side window, and
  :class:`PeerLinks` the per-peer bundle (``multiplexing`` links with the
  reference's random-writer pick, process.rs:680-696).
"""

from __future__ import annotations

import asyncio
import random
from collections import deque
from dataclasses import dataclass
from typing import Any, Deque, Dict, List, Optional, Tuple

from fantoch_tpu.run.backpressure import DEFAULT_UNACKED_CAP

# link-frame kinds (rw.py link framing)
KIND_DATA = 0
KIND_ACK = 1

# receiver acks every this many data frames (plus once per reconnect), so
# the sender's unacked window stays bounded without per-frame ack traffic
ACK_EVERY = 64


@dataclass(frozen=True)
class ReconnectPolicy:
    """Exponential backoff with full jitter, bounded attempts.

    ``delays`` yields the sleep before each attempt; once exhausted the
    peer is declared lost (PeerLostError -> quorum check).
    """

    attempts: int = 8
    base_s: float = 0.05
    factor: float = 2.0
    cap_s: float = 1.0
    jitter: float = 1.0  # fraction of the backoff drawn uniformly

    def delays(self, rng: Optional[random.Random] = None):
        rng = rng or random
        delay = self.base_s
        for _ in range(self.attempts):
            yield delay * (1.0 - self.jitter) + rng.uniform(0, delay * self.jitter)
            delay = min(delay * self.factor, self.cap_s)


class ClockOffsetEstimator:
    """Per-peer wall-clock offset from heartbeat RTT brackets.

    Each heartbeat carries the sender's clock; the reply echoes it plus
    the replier's clock at reply time.  One bracket gives the classic
    one-stamp NTP estimate ``off = t_remote - (t_send + t_recv) / 2``
    (peer clock minus ours, error bounded by rtt/2 plus the peer's
    turnaround, which rides inside the measured rtt here).  The
    estimator keeps the LOWEST-RTT sample per peer — the tightest error
    bound — and reports only improvements, so the tracer logs one
    ``k == "off"`` event per betterment rather than per heartbeat.
    The critical-path correlator (observability/critpath.py) consumes
    these to compare run-layer timestamps across processes; sim virtual
    time shares one clock and never needs it."""

    __slots__ = ("best",)

    def __init__(self) -> None:
        # peer -> (rtt_us, offset_us) of the best (lowest-rtt) sample
        self.best: Dict[int, Tuple[int, int]] = {}

    def sample(
        self, peer: int, t_send_us: int, t_remote_us: int, t_recv_us: int
    ) -> Optional[Tuple[int, int]]:
        """Fold one bracket; returns ``(rtt_us, offset_us)`` when it
        improves the peer's estimate, else None (including degenerate
        brackets where the clock stepped backwards mid-probe)."""
        rtt = t_recv_us - t_send_us
        if rtt < 0:
            return None
        offset = t_remote_us - (t_send_us + t_recv_us) // 2
        kept = self.best.get(peer)
        if kept is None or rtt < kept[0]:
            self.best[peer] = (rtt, offset)
            return (rtt, offset)
        return None

    def offset_us(self, peer: int) -> Optional[int]:
        kept = self.best.get(peer)
        return kept[1] if kept is not None else None


class LinkState:
    """Sender-side state of one reliable link to a peer."""

    __slots__ = (
        "peer_id",
        "addr",
        "index",
        "rw",
        "queue",
        "unacked",
        "unacked_cap",
        "unacked_hwm",
        "seq",
        "resend",
        "dead",
        "writer_task",
    )

    def __init__(
        self,
        peer_id: int,
        addr: Tuple[str, int],
        index: int,
        rw: Any,
        unacked_cap: int = DEFAULT_UNACKED_CAP,
    ):
        self.peer_id = peer_id
        self.addr = addr
        self.index = index
        self.rw = rw
        # overload control (run/backpressure.py): cap on the resend
        # window a live-but-slow peer may pin.  Dead peers already drop
        # frames (PeerLinks.put_nowait); a connected peer that reads but
        # never acks is the remaining unbounded-buffer path — past the
        # cap the link is declared lost via the existing typed
        # PeerLostError -> quorum-check route.  0 = uncapped (legacy)
        self.unacked_cap = unacked_cap
        self.unacked_hwm = 0
        # the one live writer task draining this link (runner-owned):
        # revival must cancel it before spawning a replacement — a stale
        # writer parked on queue.get() never observed dead=True, and two
        # writers interleaving one seq window silently lose frames
        self.writer_task = None
        # the queue the writer task drains (set by the runner; with a
        # delay line this is the line's sink, not the enqueue side)
        self.queue: Optional[asyncio.Queue] = None
        # (seq, frame) sent but not yet acked: the resend window
        self.unacked: Deque[Tuple[int, bytes]] = deque()
        self.seq = 0
        # set right after a reconnect: the writer replays unacked first
        self.resend = False
        self.dead = False

    def next_seq(self) -> int:
        self.seq += 1
        return self.seq

    def note_sent(self, seq: int, frame: bytes) -> bool:
        """Record a sent-but-unacked frame; returns True while the
        resend window is within its cap, False once the cap is crossed
        (the writer then declares the peer lost instead of buffering
        further)."""
        self.unacked.append((seq, frame))
        depth = len(self.unacked)
        if depth > self.unacked_hwm:
            self.unacked_hwm = depth
        return not self.over_unacked_cap()

    def over_unacked_cap(self) -> bool:
        return bool(self.unacked_cap) and len(self.unacked) > self.unacked_cap

    def ack(self, seq: int) -> None:
        while self.unacked and self.unacked[0][0] <= seq:
            self.unacked.popleft()


class PeerLinks:
    """The ``multiplexing`` reliable links to one peer; each send picks a
    random link (process.rs:71-97 + :680-696 send_to_one_writer), so
    same-peer messages may ride different links and arrive reordered —
    adversity the buffered-commit paths are built for.  Once the peer is
    declared lost, frames are dropped instead of queueing unboundedly."""

    __slots__ = ("queues", "links", "dead")

    def __init__(self) -> None:
        self.queues: List[asyncio.Queue] = []
        self.links: List[LinkState] = []
        self.dead = False

    def put_nowait(self, frame: Any) -> None:
        if self.dead:
            return
        if len(self.queues) == 1:
            self.queues[0].put_nowait(frame)
        else:
            random.choice(self.queues).put_nowait(frame)

    def mark_dead(self) -> None:
        self.dead = True
        for link in self.links:
            link.dead = True

    def mark_alive(self) -> None:
        """Revive a peer declared lost (it restarted, or the silence was
        a false positive): frames flow again and each link's writer —
        respawned by the runner — reconnects and resends its unacked
        window."""
        self.dead = False
        for link in self.links:
            link.dead = False
