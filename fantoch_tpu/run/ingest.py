"""Adaptive ingest batching for the serving edge.

The device kernels already amortize: one fused dispatch orders a whole
4096-slot round in ~3 ms, and ``step_chained`` proves ~0.9M cmds/s
in-dispatch.  End-to-end serving was ~25x slower because the serving
loops dispatch the instant anything is queued — under open-loop load a
round leaves with a handful of rows and the device round-trip is paid
per trickle, not per batch.  This module is the accumulate-fuse-
dispatch-lazily discipline of the GraphBLAS nonblocking-execution line
(PAPERS.md) applied to that edge, shared by every serving surface
(``DeviceRuntime._driver_task``, the process runner's executor pools,
the sim's open-loop arrivals, and ``OrderingPool`` shard rounds):

* :class:`AdaptiveIngestBatcher` — hold queued submissions until a
  **size target** or a **deadline budget** fills.  The size target
  tracks the recent queue-arrival rate (EWMA): the expected number of
  arrivals inside one deadline window, so under saturation rounds go
  out full and under a trickle the target collapses to 1 and nothing
  waits.  The deadline bounds the latency a queued command can pay to
  batching.  An **idle-system fast path** releases a lone closed-loop
  command immediately — sync latency never regresses.
* :class:`ChainAutoTuner` — pick S, the serving rounds fused per device
  dispatch (``step_chained_pipelined``), from the measured per-round
  host dispatch overhead vs in-dispatch device time (the PR 6 busy/span
  counters): grow S while the dispatch round-trip still dominates a
  round, shrink once it is amortized, clamp at
  ``Config.serving_chain_max``.

Knob resolution follows the ``serving_pipeline_depth`` one-knob rule
(run/pipeline.py): explicit argument > ``Config`` field > env var >
default — any spelling is the same knob, never three.

Time is injected (float milliseconds): the run layer passes a monotonic
wall clock, the sim its virtual clock — the batcher itself never reads
a clock, which is what makes the sim wire-through deterministic
(same-seed byte-identical traces with the batcher on).
"""

from __future__ import annotations

import math
import os
from typing import Any, List, Optional, Sequence, Tuple

ENV_INGEST_DEADLINE_MS = "FANTOCH_INGEST_DEADLINE_MS"
ENV_INGEST_TARGET = "FANTOCH_INGEST_TARGET"
ENV_SERVING_CHAIN_MAX = "FANTOCH_SERVING_CHAIN_MAX"

# the default latency budget a queued command may pay to batching: small
# against the ~68 ms remote dispatch round-trip the batch amortizes, and
# against any cross-region commit, yet ~the device kernel time — so a
# deadline-released round still carries most of a saturated window
DEFAULT_INGEST_DEADLINE_MS = 2.0
# chain-length ceiling for the auto-tuner: 8 rounds per dispatch already
# cuts per-round dispatch overhead 8x while keeping result lag bounded
DEFAULT_SERVING_CHAIN_MAX = 8


def requested_ingest_deadline_ms(
    explicit: Optional[float] = None, config: Any = None
) -> Optional[float]:
    """The explicitly requested ingest deadline budget, by precedence:
    an explicit value, then ``Config.ingest_deadline_ms``, then the
    ``FANTOCH_INGEST_DEADLINE_MS`` env var — or None when no channel
    requested one (callers that stay legacy-immediate unless asked, like
    the sim and the host executor pools, branch on this)."""
    deadline = explicit
    if deadline is None and config is not None:
        deadline = getattr(config, "ingest_deadline_ms", None)
    if deadline is None:
        raw = os.environ.get(ENV_INGEST_DEADLINE_MS)
        if raw:
            deadline = float(raw)
    return None if deadline is None else float(deadline)


def resolve_ingest_deadline_ms(
    explicit: Optional[float] = None, config: Any = None
) -> float:
    """:func:`requested_ingest_deadline_ms` with the default applied
    (2 ms).  0 is a valid resolution: batching off, release immediately
    (the legacy dispatch-on-anything behavior)."""
    deadline = requested_ingest_deadline_ms(explicit, config)
    if deadline is None:
        deadline = DEFAULT_INGEST_DEADLINE_MS
    if deadline < 0:
        raise ValueError(f"ingest deadline must be >= 0 ms, got {deadline}")
    return deadline


def resolve_ingest_target(
    explicit: Optional[int] = None, config: Any = None
) -> Optional[int]:
    """Fixed size-target override (explicit > ``Config.ingest_target`` >
    ``FANTOCH_INGEST_TARGET`` env).  None means adaptive: the batcher
    tracks the target from the EWMA arrival rate."""
    target = explicit
    if target is None and config is not None:
        target = getattr(config, "ingest_target", None)
    if target is None:
        raw = os.environ.get(ENV_INGEST_TARGET)
        if raw:
            target = int(raw)
    if target is None:
        return None
    target = int(target)
    if target < 1:
        raise ValueError(f"ingest target must be >= 1, got {target}")
    return target


def resolve_serving_chain_max(
    explicit: Optional[int] = None, config: Any = None
) -> int:
    """Chain-length ceiling for the auto-tuner (explicit >
    ``Config.serving_chain_max`` > ``FANTOCH_SERVING_CHAIN_MAX`` env >
    8).  1 disables chaining: every dispatch carries one round."""
    chain_max = explicit
    if chain_max is None and config is not None:
        chain_max = getattr(config, "serving_chain_max", None)
    if chain_max is None:
        raw = os.environ.get(ENV_SERVING_CHAIN_MAX)
        if raw:
            chain_max = int(raw)
    if chain_max is None:
        chain_max = DEFAULT_SERVING_CHAIN_MAX
    chain_max = int(chain_max)
    if chain_max < 1:
        raise ValueError(f"serving chain max must be >= 1, got {chain_max}")
    return chain_max


class AdaptiveIngestBatcher:
    """Release-gating for one serving queue: size target or deadline.

    The caller owns the queue; the batcher only decides *when* to
    release.  Protocol per iteration: ``note_arrivals(now_ms, n)`` as
    submissions land, then ``poll(now_ms, queued, idle_system)`` —
    ``(True, None)`` means release everything queued now,
    ``(False, wait_ms)`` means hold for up to ``wait_ms`` more (or until
    more arrivals make the size target), ``(False, None)`` means the
    queue is empty.  After a release, ``note_release(now_ms, rows)``
    closes the window and tallies the cause.

    Release causes:

    * **fast** — ``idle_system`` (nothing in flight anywhere): a lone
      closed-loop command dispatches immediately, whatever the EWMA
      says.  This is the sync-latency guarantee.
    * **size** — ``queued >= target`` where ``target`` is the expected
      arrivals per deadline window, ``ceil(ewma_rate * deadline)``
      clamped to ``[1, max_target]`` (or the fixed ``--ingest-target``
      override).  A cold EWMA targets 1, so batching only engages once
      sustained load is *measured*.
    * **deadline** — the oldest queued command has waited the full
      budget.

    A gap longer than ~8 deadline windows hard-resets the EWMA instead
    of decaying it: an idle period ends the throughput regime, and the
    first command after it must not inherit a stale high target.
    """

    __slots__ = (
        "deadline_ms", "max_target", "fixed_target", "_alpha",
        "_rate_per_ms", "_accum", "_last_arrival_ms", "_window_start",
        "_cause", "arrivals", "releases", "released_rows",
        "releases_fast", "releases_size", "releases_deadline",
    )

    def __init__(
        self,
        deadline_ms: float,
        max_target: int,
        fixed_target: Optional[int] = None,
        alpha: float = 0.2,
    ):
        assert deadline_ms >= 0 and max_target >= 1
        assert fixed_target is None or fixed_target >= 1
        self.deadline_ms = float(deadline_ms)
        self.max_target = int(max_target)
        self.fixed_target = fixed_target
        self._alpha = float(alpha)
        self._rate_per_ms = 0.0  # EWMA arrivals per millisecond
        self._accum = 0.0  # arrivals recorded at _last_arrival_ms
        self._last_arrival_ms: Optional[float] = None
        self._window_start: Optional[float] = None  # oldest unreleased wait
        self._cause: Optional[str] = None
        self.arrivals = 0
        self.releases = 0
        self.released_rows = 0
        self.releases_fast = 0
        self.releases_size = 0
        self.releases_deadline = 0

    def note_arrivals(self, now_ms: float, n: int = 1) -> None:
        """Fold ``n`` submissions arriving at ``now_ms`` into the EWMA
        and open the deadline window if it is not already open."""
        if n <= 0:
            return
        self.arrivals += n
        if self._window_start is None:
            self._window_start = now_ms
        last = self._last_arrival_ms
        self._last_arrival_ms = now_ms
        if last is None:
            self._accum = float(n)
            return
        dt = now_ms - last
        if dt <= 0.0:
            self._accum += n
            return
        inst = self._accum / dt
        self._accum = float(n)
        idle_bound = max(self.deadline_ms, 0.125) * 8.0
        if dt >= idle_bound:
            # the throughput regime ended across the gap: snap, don't
            # decay — a closed-loop client must see target 1 at once
            self._rate_per_ms = inst
        else:
            self._rate_per_ms += self._alpha * (inst - self._rate_per_ms)

    def rate_per_s(self) -> float:
        return self._rate_per_ms * 1000.0

    def target(self) -> int:
        """The current size target (rows that trigger a release)."""
        if self.fixed_target is not None:
            return min(self.fixed_target, self.max_target)
        if self.deadline_ms <= 0:
            return 1
        expected = math.ceil(self._rate_per_ms * self.deadline_ms)
        return max(1, min(int(expected), self.max_target))

    def poll(
        self, now_ms: float, queued: int, idle_system: bool = False
    ) -> Tuple[bool, Optional[float]]:
        """``(release, wait_ms)`` for ``queued`` pending submissions at
        ``now_ms``; ``idle_system`` is the fast-path witness (nothing in
        flight downstream — the queued command is alone in the system)."""
        if queued <= 0:
            self._window_start = None
            return (False, None)
        if self._window_start is None:
            # arrivals the caller never noted individually (e.g. drained
            # from an inner queue): the window opens at first sight
            self._window_start = now_ms
        if self.deadline_ms <= 0:
            self._cause = "size"
            return (True, None)
        if idle_system:
            self._cause = "fast"
            return (True, None)
        if queued >= self.target():
            self._cause = "size"
            return (True, None)
        waited = now_ms - self._window_start
        if waited >= self.deadline_ms:
            self._cause = "deadline"
            return (True, None)
        return (False, self.deadline_ms - waited)

    def note_release(self, now_ms: float, rows: int) -> None:
        """Tally one release of ``rows`` commands and close the window
        (the next arrival or poll reopens it)."""
        self.releases += 1
        self.released_rows += rows
        cause = self._cause or "size"
        if cause == "fast":
            self.releases_fast += 1
        elif cause == "deadline":
            self.releases_deadline += 1
        else:
            self.releases_size += 1
        self._cause = None
        self._window_start = None

    def counters(self) -> dict:
        """Tallies for the metrics snapshot (``ingest_target`` and
        ``ingest_rate_per_s`` are gauges, the rest monotone)."""
        return {
            "ingest_arrivals": self.arrivals,
            "ingest_releases": self.releases,
            "ingest_released_rows": self.released_rows,
            "ingest_releases_fast": self.releases_fast,
            "ingest_releases_size": self.releases_size,
            "ingest_releases_deadline": self.releases_deadline,
            "ingest_target": self.target(),
            "ingest_rate_per_s": round(self.rate_per_s(), 1),
        }


class ChainAutoTuner:
    """Auto-tuned S for chained serving (``step_chained_pipelined``).

    Starts at S=1 and adjusts from deltas of the shared PipelineCore
    counters: per-round host dispatch overhead
    (``dispatch_wall_ms / rounds``) vs per-round in-dispatch device time
    (``busy_ms / rounds``).  While the dispatch call still costs more
    than ``grow_frac`` of a round's device time, fusing more rounds per
    dispatch keeps paying — S doubles (fast convergence from cold).
    Once overhead falls under ``shrink_frac`` the chain HALVES
    (hysteresis between the two bands keeps S stable).  S moves on a
    strict pow2 schedule — double up, halve down, ceiling at the pow2
    floor of ``chain_max`` — because the chained step programs compile
    per chain length: a decrement schedule would bake every value in
    ``[1, chain_max]`` into a distinct compiled signature (the compile
    wall), while pow2 bounds the set at O(log chain_max) programs.
    Observations under ``min_dispatches`` new dispatches are deferred so
    one jittery round cannot thrash S.
    """

    __slots__ = (
        "chain", "chain_max", "grow_frac", "shrink_frac",
        "min_dispatches", "adjustments", "_last",
    )

    def __init__(
        self,
        chain_max: int,
        grow_frac: float = 0.25,
        shrink_frac: float = 0.05,
        min_dispatches: int = 8,
    ):
        assert chain_max >= 1
        self.chain = 1
        # pow2 floor: the largest chain the tuner will emit.  chain_max
        # itself may be arbitrary (config/env), but every EMITTED S must
        # come from the pow2 ladder (see the class docstring)
        self.chain_max = 1
        while self.chain_max * 2 <= int(chain_max):
            self.chain_max *= 2
        self.grow_frac = float(grow_frac)
        self.shrink_frac = float(shrink_frac)
        self.min_dispatches = int(min_dispatches)
        self.adjustments = 0
        self._last: Optional[Tuple[float, float, float, float]] = None

    def observe(
        self,
        dispatches: float,
        dispatch_wall_ms: float,
        busy_ms: float,
        rounds: float,
    ) -> int:
        """Feed cumulative counters; returns the (possibly adjusted)
        chain length.  Call as often as convenient — the tuner
        rate-limits itself by dispatch count."""
        if self._last is None:
            self._last = (dispatches, dispatch_wall_ms, busy_ms, rounds)
            return self.chain
        d_disp = dispatches - self._last[0]
        if d_disp < self.min_dispatches:
            return self.chain
        d_wall = dispatch_wall_ms - self._last[1]
        d_busy = busy_ms - self._last[2]
        d_rounds = rounds - self._last[3]
        self._last = (dispatches, dispatch_wall_ms, busy_ms, rounds)
        if d_rounds <= 0 or d_busy <= 0:
            return self.chain
        ratio = (d_wall / d_rounds) / (d_busy / d_rounds)
        if ratio > self.grow_frac and self.chain < self.chain_max:
            self.chain = min(self.chain * 2, self.chain_max)
            self.adjustments += 1
        elif ratio < self.shrink_frac and self.chain > 1:
            # halve, not decrement: stay on the pow2 ladder so shrink
            # never mints a fresh compiled chain program
            self.chain //= 2
            self.adjustments += 1
        return self.chain


def plan_ingest_releases(
    arrival_ms: Sequence[float], batcher: AdaptiveIngestBatcher
) -> List[Tuple[float, int, int]]:
    """Replay a sorted arrival-time column through a batcher, returning
    the release plan ``[(release_ms, start, end)]`` over half-open index
    groups — the offline coalescing used by ``OrderingPool`` shard
    rounds (and the unit tests' oracle for the online loops).  A
    deadline that expires between two arrivals releases at the deadline
    instant, without the later arrival; the tail releases at its
    window's deadline."""
    out: List[Tuple[float, int, int]] = []
    start = 0
    for i, t in enumerate(arrival_ms):
        pending = i - start
        if pending:
            opened = batcher._window_start
            deadline_at = (
                None if opened is None or batcher.deadline_ms <= 0
                else opened + batcher.deadline_ms
            )
            if deadline_at is not None and t >= deadline_at:
                batcher.poll(deadline_at, pending)
                # a deadline release by construction; the poll at the
                # computed instant can land 1 ulp short of the budget
                # (opened + d - opened < d in floats), so the cause is
                # pinned rather than trusted to the comparison
                batcher._cause = "deadline"
                batcher.note_release(deadline_at, pending)
                out.append((deadline_at, start, i))
                start = i
        batcher.note_arrivals(t, 1)
        pending = i + 1 - start
        release, _wait = batcher.poll(t, pending)
        if release:
            batcher.note_release(t, pending)
            out.append((t, start, i + 1))
            start = i + 1
    n = len(arrival_ms)
    if start < n:
        opened = batcher._window_start
        deadline_tail = opened is not None and batcher.deadline_ms > 0
        t = (
            opened + batcher.deadline_ms if deadline_tail
            else arrival_ms[n - 1]
        )
        batcher.poll(t, n - start)
        if deadline_tail:
            # pinned for the same 1-ulp reason as the in-loop release
            batcher._cause = "deadline"
        batcher.note_release(t, n - start)
        out.append((t, start, n))
    return out
