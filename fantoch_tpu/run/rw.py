"""Length-delimited framing over asyncio streams.

Reference: fantoch/src/run/rw/{mod,connection}.rs — the reference frames
with tokio's LengthDelimitedCodec + bincode; here frames are a u32
big-endian length prefix + pickled payload.  ``write`` queues without
flushing, ``send`` queues and flushes, mirroring the reference's explicit
flush control (rw/mod.rs:55-84) that lets writers batch small protocol
messages into one syscall.
"""

from __future__ import annotations

import asyncio
import pickle
import socket
import struct
from typing import Any, Optional

_LEN = struct.Struct(">I")
# link frames (peer connections after the handshake): u8 kind + u64 seq
# header inside the length-delimited frame; see run/links.py for the
# reliability protocol built on top
_LINK = struct.Struct(">BQ")


def serialize(value: Any) -> bytes:
    return pickle.dumps(value, protocol=pickle.HIGHEST_PROTOCOL)


def deserialize(payload: bytes) -> Any:
    return pickle.loads(payload)


async def connect_with_retry(
    addr: tuple, attempts: int = 120, backoff_s: float = 0.05
) -> "Rw":
    """Open a connection, retrying while the peer boots
    (process.rs:71-111; the client setup retries too, mod.rs:668-740).

    The backoff grows gently to ~1 s so the total budget is ~30 s: a
    freshly spawned server pays an interpreter + jax import before it
    can bind, which under a loaded single-core host exceeds a
    constant-50 ms budget (observed as suite-load flakes)."""
    last: Optional[OSError] = None
    delay = backoff_s
    for _ in range(attempts):
        try:
            reader, writer = await asyncio.open_connection(*addr)
            return Rw(reader, writer)
        except OSError as exc:
            last = exc
            await asyncio.sleep(delay)
            delay = min(delay * 1.2, 1.0)
    raise ConnectionError(f"could not connect to {addr}: {last!r}")


class Rw:
    """Framed reader/writer over one TCP connection."""

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        self._reader = reader
        self._writer = writer
        sock = writer.get_extra_info("socket")
        if sock is not None:
            # TCP_NODELAY, as the reference's Connection (connection.rs:46-51)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    async def recv(self) -> Optional[Any]:
        """Read one frame; None on clean EOF."""
        try:
            header = await self._reader.readexactly(_LEN.size)
        except (asyncio.IncompleteReadError, ConnectionResetError):
            return None
        (length,) = _LEN.unpack(header)
        payload = await self._reader.readexactly(length)
        return pickle.loads(payload)

    def write(self, value: Any) -> None:
        """Queue one frame without flushing."""
        self.write_frame(serialize(value))

    def write_frame(self, payload: bytes) -> None:
        """Queue one pre-serialized frame without flushing."""
        self._writer.write(_LEN.pack(len(payload)) + payload)

    async def send(self, value: Any) -> None:
        """Queue one frame and flush."""
        self.write(value)
        await self.flush()

    # --- link framing (peer connections; run/links.py reliability) ---

    def write_link_frame(self, kind: int, seq: int, payload: bytes) -> None:
        """Queue one sequence-numbered frame without flushing."""
        header = _LINK.pack(kind, seq)
        self._writer.write(_LEN.pack(len(header) + len(payload)) + header + payload)

    async def recv_link_frame(self) -> Optional[tuple]:
        """Read one (kind, seq, payload) link frame; None on EOF/reset."""
        try:
            header = await self._reader.readexactly(_LEN.size)
            (length,) = _LEN.unpack(header)
            body = await self._reader.readexactly(length)
        except (asyncio.IncompleteReadError, ConnectionResetError, OSError):
            return None
        kind, seq = _LINK.unpack_from(body)
        return kind, seq, body[_LINK.size :]

    async def flush(self) -> None:
        await self._writer.drain()

    def close(self) -> None:
        self._writer.close()

    def abort(self) -> None:
        """Hard-kill the underlying transport (chaos hook: simulates the
        network dropping the connection while both processes stay up)."""
        transport = self._writer.transport
        if transport is not None:
            transport.abort()
