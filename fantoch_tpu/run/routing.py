"""Worker-index routing: maps messages/events to worker pool indices.

Reference: fantoch/src/run/prelude.rs:11-35 and fantoch/src/run/pool.rs:106-124.
Messages with the same index always land on the same worker; two reserved
indices exist for the GC worker / leader (0) and protocol-specific workers
(e.g. Newt's clock-bump worker at 1).
"""

from __future__ import annotations

from typing import Optional, Tuple

from fantoch_tpu.core.ids import Dot

# worker index used by leader-based protocols
LEADER_WORKER_INDEX = 0
# worker index used for garbage collection (same as leader: leader-based
# protocols run gc in the leader/acceptor worker)
GC_WORKER_INDEX = 0
# number of reserved worker indices
WORKERS_INDEXES_RESERVED = 2

# An index is (reserved, index): the actual worker is
# `reserved + index % (pool_size - reserved)` (ignoring reservation when the
# pool is too small).  None means broadcast to all workers.
WorkerIndex = Optional[Tuple[int, int]]


def worker_index_no_shift(index: int) -> WorkerIndex:
    """Route to one of the reserved workers (index must be reserved)."""
    assert index < WORKERS_INDEXES_RESERVED
    return (0, index)


def worker_index_shift(index: int) -> WorkerIndex:
    """Route to a non-reserved worker."""
    return (WORKERS_INDEXES_RESERVED, index)


def worker_dot_index_shift(dot: Dot) -> WorkerIndex:
    """Route by dot sequence (the common case for leaderless protocols)."""
    return worker_index_shift(dot.sequence)


def resolve_index(index: WorkerIndex, pool_size: int) -> Optional[int]:
    """Compute the concrete pool position (None = broadcast).

    Reference: fantoch/src/run/pool.rs:115-124.
    """
    if index is None:
        return None
    reserved, idx = index
    if reserved < pool_size:
        return reserved + (idx % (pool_size - reserved))
    return idx % pool_size
