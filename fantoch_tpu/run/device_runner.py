"""The TPU serving path: a TCP client plane feeding the device-resident
multi-replica protocol step.

The reference's runner *is* its serving story —
fantoch/src/run/mod.rs:105-445 boots protocol + executor tasks behind TCP
and the clients' commands flow through the state machine one message at a
time.  The TPU-native serving story inverts the altitude: the whole
protocol round (dependency collection, fast-path check, Synod accept,
SCC resolution, GC watermark) is ONE device program over a
(replica x batch) mesh (fantoch_tpu/parallel/mesh_step.py), state stays
device-resident across rounds (donated), and the host only

  * feeds command batches in (array columns assembled from client
    submissions), and
  * drains execution orders out (applying them to the host KVStore and
    routing results back to client sessions through AggregatePending —
    the same client plane as the object runner).

``DeviceDriver`` is the host-side control loop (usable without any
networking: the driver dry-run and the simulator-style tests call it
directly); ``DeviceRuntime`` wraps it in the TCP client plane speaking the
exact wire protocol of fantoch_tpu/run/prelude.py, so ``bin/client.py``
and ``run_clients`` work unchanged against a device-step server.

The mesh models all replicas — on real TPU pods the replica axis spans
mesh slices wired by ICI, which is exactly the deployment the reference
reaches with one TCP mesh per geo-replica pair.

Partial replication (``Config.shard_count > 1``, epaxos-class and Newt):
ONE mesh carries every shard — shard s owns key buckets
``b % shard_count == s`` and replica rows ``[s*n, (s+1)*n)``; quorums
are per shard per key slot (mesh_step.protocol_step /
newt_protocol_step sharded modes).  Cross-shard dependencies resolve
inside the shared working set — the mesh-native answer to the
reference's cross-shard dep request RPCs
(fantoch_ps/src/executor/graph/mod.rs:279-408) — and a Newt multi-shard
command commits at the max of its shards' clocks (the MShardCommit
aggregation).  The client plane keeps
the per-shard-server wire contract: clients connect once per shard
(every shard maps to this server's address), Submit rides the target
shard's connection, and each touched shard answers with its own
CommandResult over that same connection.
"""

from __future__ import annotations

import asyncio
from collections import deque
from typing import Any, Deque, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from fantoch_tpu.core.command import Command
from fantoch_tpu.core.config import Config
from fantoch_tpu.core.ids import ClientId, Dot, ProcessId, Rifl, ShardId
from fantoch_tpu.core.kvs import KVStore
from fantoch_tpu.executor.aggregate import AggregatePending
from fantoch_tpu.executor.base import ExecutorResult
from fantoch_tpu.run.ingest import (
    AdaptiveIngestBatcher,
    ChainAutoTuner,
    resolve_ingest_deadline_ms,
    resolve_ingest_target,
    resolve_serving_chain_max,
)
from fantoch_tpu.run.pipeline import (
    BoundedSubmitRing,
    PipelineCore,
    requested_pipeline_depth,
    resolve_pipeline_depth,
)
from fantoch_tpu.run.prelude import (
    ClientHi,
    ClientHiAck,
    Overloaded,
    Register,
    Submit,
    ToClient,
)
from fantoch_tpu.run.rw import Rw
from fantoch_tpu.utils import key_hash, logger

Address = Tuple[str, int]


_BUCKET_CACHE_MAX = 1 << 18  # bound the string->bucket memo (~25 MB)


def _buckets(
    cmd: Command,
    shard_id: ShardId,
    key_buckets: int,
    shard_count: int = 1,
    cache: Optional[Dict] = None,
) -> List[int]:
    """Distinct key buckets for one command — the single definition shared
    by the driver's row builder and the session-boundary validator, so the
    two can never drift (colliding keys dedup, which only coarsens
    conflicts).

    ``cache`` memoizes the per-key FNV hash->bucket map (workloads repeat
    keys heavily — the hot-key half of the north-star workload is ONE
    key); it is cleared wholesale past ``_BUCKET_CACHE_MAX`` entries so a
    long-running server's key churn cannot grow it unboundedly.

    Sharded (shard_count > 1): buckets span EVERY shard the command
    touches, and bucket ``b`` encodes its owner as ``b % shard_count``
    (the sharded-key-axis contract of mesh_step.protocol_step); the
    ``shard_id`` argument is ignored — the unified mesh orders the whole
    command."""
    if cache is not None and len(cache) > _BUCKET_CACHE_MAX:
        cache.clear()
    if shard_count == 1:
        if cache is None:
            return sorted({key_hash(k) % key_buckets for k in cmd.keys(shard_id)})
        bs = set()
        for k in cmd.keys(shard_id):
            b = cache.get(k)
            if b is None:
                cache[k] = b = key_hash(k) % key_buckets
            bs.add(b)
        return sorted(bs)
    per_shard = key_buckets // shard_count
    bs = set()
    for sid in cmd.shards():
        for k in cmd.keys(sid):
            ck = (sid, k)
            b = None if cache is None else cache.get(ck)
            if b is None:
                b = sid + shard_count * (key_hash(k) % per_shard)
                if cache is not None:
                    cache[ck] = b
            bs.add(b)
    return sorted(bs)


def _bucket_row(
    cmd: Command,
    shard_id: ShardId,
    key_buckets: int,
    key_width: int,
    shard_count: int = 1,
    cache: Optional[Dict] = None,
):
    """Key-bucket row for one command (device key-row contract: a row must
    not repeat a bucket)."""
    buckets = _buckets(cmd, shard_id, key_buckets, shard_count, cache)
    assert 1 <= len(buckets) <= key_width, (
        f"command touches {len(buckets)} key buckets but the device state "
        f"was initialized with key_width={key_width}"
    )
    return buckets


class _DriverCore(PipelineCore):
    """The host-side machinery every device driver shares: the in-flight
    command registry, the overflow requeue channel, the KVStore, the
    serving tallies (the BaseProcess metrics twin), the 31-bit
    dot-sequence window, and — via :class:`PipelineCore`
    (run/pipeline.py) — the depth-K dispatch/drain pipeline with its
    staging ingest ring.  Keeping it in one place keeps the four
    protocol drivers from silently diverging on the registry/requeue
    contract.

    Sequence windowing: dots are unbounded host ints, device columns are
    int32.  The device only ever *compares* sequences among in-flight
    rows (tie-breaking, identity mirrors), so columns carry
    ``sequence - seq_base`` and the base advances to the oldest in-flight
    sequence whenever the window would overflow — the ClockWindow design
    of fantoch_tpu/ops/table_ops.py applied to dots (reference GC keeps
    dot state bounded the same way, fantoch/src/protocol/gc.rs:72-116).
    """

    # leave headroom so a full batch plus in-round growth never wraps
    SEQ_WINDOW_MAX = 2**31 - (1 << 20)

    def _init_core(
        self,
        shard_id: ShardId,
        batch_size: int,
        key_buckets: int,
        monitor_execution_order: bool,
    ) -> None:
        self.shard_id = shard_id
        self.shard_count = 1  # DeviceDriver overrides in sharded mode
        self.batch_size = batch_size
        self.key_buckets = key_buckets
        # commands in flight: registered at step entry, dropped at execution
        self._cmds: Dict[int, Tuple[Dot, Command]] = {}
        self._requeue: List[Tuple[Dot, Command]] = []
        self._bucket_cache: Dict = {}  # key -> bucket memo (see _buckets)
        self._seq_base = 0  # device seq column = dot.sequence - seq_base
        self.seq_epochs = 0  # window advances (observability)
        self.store = KVStore(monitor_execution_order)
        self.rounds = 0
        self.fast_paths = 0
        self.slow_paths = 0
        self.executed = 0
        self.stable_watermark = 0
        # the depth-K dispatch/drain pipeline + staging ingest ring +
        # per-dispatch counters (step/step_pipelined/flush_pipeline and
        # _staging come from PipelineCore; drivers implement the
        # dispatch()/drain() split)
        self._init_pipeline()

    @property
    def in_flight(self) -> int:
        """Commands registered but not yet executed (device pending)."""
        return len(self._cmds)

    def _pipeline_flush_needed(self, batch) -> bool:
        """True when the upcoming dispatch may trigger a rebase that
        must not happen with rounds in flight.  The dot drivers all
        share the sequence-window trigger; drivers add their own
        (gid epoch, clock window, slot log)."""
        if not batch:
            return False
        top = max(dot.sequence for dot, _ in batch) - self._seq_base
        return top >= self.SEQ_WINDOW_MAX

    def _init_sharded_mesh(
        self, mesh_step, num_replicas: int, shard_count: int,
        key_buckets: int, pending_capacity: int, key_width: int, mesh,
        init_state_fn,
    ):
        """Shared sharded-mesh setup (DeviceDriver + NewtDeviceDriver):
        num_replicas is PER SHARD, the state holds shard_count *
        num_replicas replica rows, bucket b % shard_count encodes the
        owning shard."""
        self.shard_count = shard_count
        assert key_buckets % shard_count == 0, (
            "key_buckets must split evenly across shards"
        )
        total_rows = shard_count * num_replicas
        self._mesh = (
            mesh
            if mesh is not None
            else mesh_step.make_mesh(num_replicas=total_rows)
        )
        self._state = init_state_fn(
            self._mesh,
            total_rows,
            key_buckets=key_buckets,
            pending_capacity=pending_capacity,
            key_width=key_width,
        )

    def _dispatch_dot_keyed(self, batch: List[Tuple[Dot, Command]]):
        """Shared dispatch body for the dot-keyed drivers (Newt/Caesar):
        assemble the fixed-size key/src/seq columns, register commands
        under packed (source, window sequence), and submit one device
        round; returns the round token for ``drain``."""
        import jax.numpy as jnp

        from fantoch_tpu.parallel.mesh_step import KEY_PAD

        assert len(batch) <= self.batch_size
        self._ensure_seq_window(batch)
        b = self.batch_size
        key, src, seq = self._staging(
            ("key", (b, self.key_width), np.int32, KEY_PAD),
            ("src", (b,), np.int32, 0),
            ("seq", (b,), np.int32, 0),
        )
        self._assemble_rows(batch, key, src, seq)

        self._state, out = self._step(
            self._state, jnp.asarray(key), jnp.asarray(src), jnp.asarray(seq)
        )
        self.rounds += 1
        return out

    def _assemble_rows(self, batch, key_rows, src_row, seq_row) -> None:
        """Fill one round's fixed-size key/src/seq columns in place and
        register each command under its packed (source, window sequence)
        — the caller guarantees the sequence window already fits."""
        for i, (dot, cmd) in enumerate(batch):
            buckets = _bucket_row(
                cmd, self.shard_id, self.key_buckets, self.key_width,
                self.shard_count, cache=self._bucket_cache,
            )
            key_rows[i, : len(buckets)] = buckets
            src_row[i] = dot.source
            seq_row[i] = self._device_seq(dot)
            self._cmds[self._packed(dot.source, seq_row[i])] = (dot, cmd)

    def _execute_ordered(
        self, order, executed, work_src, work_seq
    ) -> List[ExecutorResult]:
        """Pop and execute the round's executed rows in device order
        (shared by every drain; pad rows are registered by no one and
        skip)."""
        results: List[ExecutorResult] = []
        for w in order.tolist():
            if not executed[w]:
                continue
            entry = self._cmds.pop(
                self._packed(work_src[w], work_seq[w]), None
            )
            if entry is None:
                continue  # pad row
            results.extend(self._execute_entry(entry[1]))
            self.executed += 1
        return results

    def _requeue_rows(self, rows, work_src, work_seq, label: str) -> None:
        """Re-queue overflow-dropped working rows under their original
        dots (shared drain tail)."""
        requeued = 0
        for w in rows:
            entry = self._cmds.pop(
                self._packed(work_src[w], work_seq[w]), None
            )
            if entry is not None:
                requeued += 1
                self._requeue.append(entry)
        if requeued:
            logger.warning(
                "%s device pending overflow: re-queueing %d commands",
                label, requeued,
            )

    def _execute_entry(self, cmd: Command) -> List[ExecutorResult]:
        """Execute one ordered command against the KVStore.  Sharded mode:
        the unified mesh owns every shard's keyspace, so each touched
        shard's portion executes at the command's single execution point
        (the partials the per-shard executors would emit)."""
        if self.shard_count == 1:
            return cmd.execute(self.shard_id, self.store)
        results: List[ExecutorResult] = []
        for sid in cmd.shards():
            results.extend(cmd.execute(sid, self.store))
        return results

    def take_requeue(self) -> List[Tuple[Dot, Command]]:
        """Commands dropped by a device pending-buffer overflow, to be fed
        into the next batch by the caller."""
        out, self._requeue = self._requeue, []
        return out

    @property
    def has_requeue(self) -> bool:
        """Overflow-requeued commands are waiting (the serving loop's
        ingest gate never holds these — they were admitted a round ago)."""
        return bool(self._requeue)

    @staticmethod
    def _packed(src, seq) -> int:
        """Registry key for dot-identified commands (device-window seq)."""
        return (int(src) << 32) | int(seq)

    # --- the 31-bit dot-sequence window ---

    def _device_seq(self, dot: Dot) -> int:
        seq = dot.sequence - self._seq_base
        assert 0 <= seq < 2**31 - 1, (
            f"dot sequence {dot.sequence} outside the device window "
            f"(base {self._seq_base}); _ensure_seq_window must run first"
        )
        return seq

    def _ensure_seq_window(self, batch: List[Tuple[Dot, Command]]) -> None:
        """Advance the sequence window if this batch would overflow it.

        The new base is the oldest sequence still relevant to the device:
        min over in-flight registry dots, requeued dots, and the incoming
        batch.  Live device comparisons all involve rows at or above it,
        so the uniform shift is order-preserving; the driver-specific
        ``_shift_seq_state`` rebases device-resident and mirrored
        sequence columns."""
        if not batch:
            return
        top = max(dot.sequence for dot, _ in batch) - self._seq_base
        if top < self.SEQ_WINDOW_MAX:
            return
        # the rebase rewrites device-resident sequence columns an
        # in-flight round still references; _pipeline_flush_needed
        # shares the trigger, so pipelined paths flushed already
        assert self._undrained == 0, (
            "dot-sequence window advance with a pipelined round in flight"
        )
        live = [dot.sequence for dot, _ in batch]
        live += [dot.sequence for dot, _ in self._cmds.values()]
        live += [dot.sequence for dot, _ in self._requeue]
        floor = min(live)
        shift = floor - self._seq_base
        new_top = top - shift
        if shift <= 0 or new_top >= 2**31 - 1:
            # a long-pinned in-flight dot keeps the window span >= 2^31:
            # no rebase can fit it — fail loudly (asserts vanish under -O)
            raise RuntimeError(
                "dot-sequence window cannot advance: oldest in-flight "
                f"sequence {floor} leaves a span of {new_top} >= 2^31"
            )
        self._seq_base = floor
        self.seq_epochs += 1
        self._on_seq_window_advanced(shift)
        logger.info(
            "advanced dot-sequence window to base %d (epoch %d)",
            floor, self.seq_epochs,
        )

    def _on_seq_window_advanced(self, shift: int) -> None:
        """Rebase driver-held sequence state after a window advance: the
        dot-keyed registry and the device-resident pend_seq column — the
        dot-keyed drivers' shape.  (Dead device slots are masked by
        their key/slot columns and match no registry key, so the blind
        shift is safe.)  DeviceDriver overrides: its registry keys on
        gids and its device pend is masked by pend_gid."""
        import jax
        import jax.numpy as jnp

        self._rekey_registry_for_window()
        st = self._state
        pend_seq = np.asarray(st.pend_seq, dtype=np.int64) - shift
        # rebuilt state fields use jnp.array (an XLA-owned COPY), never
        # jnp.asarray: asarray zero-copy aliases the numpy buffer on the
        # CPU backend, and the step functions donate this state — donating
        # an alias hands numpy-owned memory to XLA (use-after-free).
        # Same rule at every _replace() rebase below.
        self._state = st._replace(
            pend_seq=jax.device_put(
                jnp.array(pend_seq.astype(np.int32)), st.pend_seq.sharding
            )
        )

    def _drain_and_carry(
        self, out, label: str, committed_noun: str
    ) -> List[ExecutorResult]:
        """The dot-keyed drivers' shared tail (Newt/Caesar): execute the
        round's executed rows in device order against the KVStore, using
        the step's own ``work_src``/``work_seq`` identity columns — the
        device pending buffer carries its identity, so no host mirror
        exists to drift (and a dispatched round can be drained later:
        dispatch/drain pipelining).  Committed overflow cannot be
        re-proposed (its timestamp already entered the replicas' tables)
        and fails loudly; uncommitted overflow re-queues under the
        original dot."""
        order = np.asarray(out.order)
        executed = np.asarray(out.executed)
        committed = np.asarray(out.committed)
        work_src = np.asarray(out.work_src)
        work_seq = np.asarray(out.work_seq)
        results = self._execute_ordered(order, executed, work_src, work_seq)

        # after the pops, registry keys == this round's carried rows;
        # committed first in working order (both device carries sort
        # committed rows ahead — carry_rank in the mesh steps); rows
        # beyond the device pending capacity were dropped there
        carried = [
            w
            for w in range(len(work_src))
            if self._packed(work_src[w], work_seq[w]) in self._cmds
        ]
        carried.sort(key=lambda w: (not committed[w], w))
        dropped = carried[self._pend_cap:]
        if any(committed[w] for w in dropped):
            raise RuntimeError(
                f"{label} device pending buffer overflowed with "
                f"committed-but-{committed_noun} commands: raise "
                "pending_capacity (a committed timestamp cannot be "
                "re-proposed)"
            )
        self._requeue_rows(dropped, work_src, work_seq, label)
        return results

    def _rekey_registry_for_window(self) -> None:
        """Shared helper for dot-keyed registries (Newt/Paxos): recompute
        packed keys under the new seq_base."""
        self._cmds = {
            self._packed(dot.source, dot.sequence - self._seq_base): entry
            for entry in self._cmds.values()
            for dot in (entry[0],)
        }


class _ChainToken(NamedTuple):
    """Round token for an S-rounds-in-one-dispatch chain
    (``NewtDeviceDriver.step_chained``): the un-fetched device outputs
    plus the chain length, so the pipeline can carry whole chains in
    flight and the drain can slice per-round outputs after ONE fetch."""

    outs: Any
    rounds: int


class DeviceDriver(_DriverCore):
    """Host control loop around the donated-state device protocol step.

    One ``step()`` call = one full commit+execute round for every replica
    at once.  The driver owns:

      * the device-resident ``ReplicaState`` (donated each step — the
        arrays never round-trip to the host),
      * the gid -> Command registry for commands in flight (committed rows
        execute in device order; quorum-degraded rows carry in the device
        pending buffer and stay registered),
      * the host KVStore + execution of ordered commands (the state
        machine is control-plane: string keys, tiny values — it stays on
        the host by design, fantoch/src/kvs.rs).

    Key hashing: string keys map to ``key_buckets`` conflict buckets.
    Bucket collisions create *false* dependencies — extra ordering, never
    missed ordering — so correctness is preserved and only parallelism is
    lost (same argument as the reference's worker-partitioned KeyDeps,
    which also orders by hash partition).
    """

    def __init__(
        self,
        num_replicas: int,
        *,
        batch_size: int = 256,
        key_buckets: int = 4096,
        key_width: int = 1,
        pending_capacity: int = 256,
        live_replicas: Optional[int] = None,
        shard_id: ShardId = 0,
        shard_count: int = 1,
        monitor_execution_order: bool = False,
        mesh=None,
    ):
        from fantoch_tpu.parallel import mesh_step

        self._init_core(shard_id, batch_size, key_buckets, monitor_execution_order)
        self.key_width = key_width
        self._init_sharded_mesh(
            mesh_step, num_replicas, shard_count, key_buckets,
            pending_capacity, key_width, mesh, mesh_step.init_state,
        )
        self._step = mesh_step.jit_protocol_step(
            self._mesh, live_replicas=live_replicas, shard_count=shard_count
        )
        self._next_gid = 0  # host mirror of state.next_gid
        self._frontier_base = 0  # executed-count carried across gid epochs
        self.gid_epochs = 0

    # --- the serving round ---

    def _bucket_row(self, cmd: Command) -> List[int]:
        return _bucket_row(
            cmd, self.shard_id, self.key_buckets, self.key_width,
            self.shard_count, self._bucket_cache,
        )

    # gid space is int32 and the key clock holds raw gids; when the space
    # nears exhaustion the epoch resets — rebase clock/frontier/pending
    # against the oldest in-flight gid instead of dying by assert
    # (the ClockWindow design of ops/table_ops.py applied to gids; the
    # reference's GC keeps dot state bounded forever the same way,
    # fantoch/src/protocol/gc.rs:72-116)
    GID_RESET_THRESHOLD = 2**31 - (1 << 20)

    def _gid_epoch_reset(self) -> None:
        import jax
        import jax.numpy as jnp

        st = self._state
        # after a step, registry keys == the gids still carried on-device
        delta = min(self._cmds.keys(), default=self._next_gid)
        if delta <= 0:
            raise RuntimeError(
                "gid epoch reset ineffective: a command from gid 0 is "
                "still in flight"
            )
        key_clock = np.asarray(st.key_clock, dtype=np.int64)
        # entries older than the oldest live gid clamp to -1 ("no live
        # predecessor") — exactly their meaning to dep pruning, which
        # treats out-of-working-set deps as already executed
        key_clock = np.where(key_clock >= delta, key_clock - delta, -1)
        pend_gid = np.asarray(st.pend_gid, dtype=np.int64)
        pend_gid = np.where(pend_gid >= 0, pend_gid - delta, -1)
        frontier = np.asarray(st.frontier, dtype=np.int64)
        fmin = int(frontier.min())
        self._frontier_base += fmin
        self._state = st._replace(
            key_clock=jax.device_put(
                jnp.array(key_clock.astype(np.int32)), st.key_clock.sharding
            ),
            frontier=jax.device_put(
                jnp.array((frontier - fmin).astype(np.int32)),
                st.frontier.sharding,
            ),
            next_gid=jax.device_put(
                jnp.int32(self._next_gid - delta), st.next_gid.sharding
            ),
            pend_gid=jax.device_put(
                jnp.array(pend_gid.astype(np.int32)), st.pend_gid.sharding
            ),
        )
        self._next_gid -= delta
        self._cmds = {g - delta: v for g, v in self._cmds.items()}
        self.gid_epochs += 1
        logger.info(
            "gid epoch reset: rebased by %d (epoch %d, next_gid %d)",
            delta, self.gid_epochs, self._next_gid,
        )

    def _on_seq_window_advanced(self, shift: int) -> None:
        import jax
        import jax.numpy as jnp

        # registry keys are gids — only the device pend_seq column carries
        # window sequences (dead slots are masked by pend_gid on-device)
        st = self._state
        pend_seq = np.asarray(st.pend_seq, dtype=np.int64) - shift
        pend_gid = np.asarray(st.pend_gid)
        pend_seq = np.where(pend_gid >= 0, pend_seq, -1)
        self._state = st._replace(
            pend_seq=jax.device_put(
                jnp.array(pend_seq.astype(np.int32)), st.pend_seq.sharding
            )
        )

    # step/step_pipelined/flush_pipeline come from _DriverCore; one
    # device round covers up to ``batch_size`` new commands (the rest of
    # the fixed batch is padding; excess raises) and returns the per-key
    # results of every command *executed* that round — including
    # commands carried from previous degraded rounds.  Pipelined, the
    # device round (or the remote-dispatch tunnel round trip) overlaps
    # the host's result-emit loop — the two halves measured within ~1 ms
    # of each other on CPU, so overlap ~halves the round (BENCH_DEV r5).

    def _pipeline_flush_needed(self, batch) -> bool:
        # a gid epoch reset rebases the registry and frontier base,
        # which drain reads — retire the in-flight round first (rare:
        # once per 2^31 gids)
        return (
            self._next_gid + self.batch_size >= self.GID_RESET_THRESHOLD
            or super()._pipeline_flush_needed(batch)
        )

    def dispatch(self, batch: List[Tuple[Dot, Command]]):
        """Assemble + dispatch one device round (async — does not block
        on device completion); returns the round token for ``drain``."""
        import jax.numpy as jnp

        assert len(batch) <= self.batch_size, (
            f"batch {len(batch)} exceeds the compiled batch size "
            f"{self.batch_size}; chunk at the caller"
        )
        from fantoch_tpu.parallel.mesh_step import KEY_PAD

        b = self.batch_size
        key, src, seq = self._staging(
            ("key", (b, self.key_width), np.int32, KEY_PAD),
            ("src", (b,), np.int32, 0),
            ("seq", (b,), np.int32, 0),
        )
        if self._next_gid + b >= self.GID_RESET_THRESHOLD:
            assert self._undrained == 0, (
                "gid epoch reset with a pipelined round in flight; "
                "flush_pipeline first"
            )
            self._gid_epoch_reset()
            if self._next_gid + b >= 2**31 - 1:
                raise RuntimeError(
                    "gid space exhausted: a long-stuck in-flight command "
                    "pins the epoch (oldest live gid too old to rebase)"
                )
        self._ensure_seq_window(batch)
        for i, (dot, cmd) in enumerate(batch):
            row = self._bucket_row(cmd)
            key[i, : len(row)] = row
            src[i] = dot.source
            seq[i] = self._device_seq(dot)
            self._cmds[self._next_gid + i] = (dot, cmd)

        self._state, out = self._step(
            self._state, jnp.asarray(key), jnp.asarray(src), jnp.asarray(seq)
        )
        self._next_gid += b
        self.rounds += 1
        return out

    def drain(self, out) -> List[ExecutorResult]:
        """Fetch one round's outputs and execute its resolved commands
        in device order against the KVStore."""
        # one pytree fetch, one device->host round trip, and the
        # busy/idle bookkeeping point (PipelineCore._fetch)
        out = self._fetch(out)

        order = np.asarray(out.order)
        resolved = np.asarray(out.resolved)
        gids = np.asarray(out.gids)
        fast = np.asarray(out.fast_path)
        self.stable_watermark = self._frontier_base + int(out.stable)

        results: List[ExecutorResult] = []
        for w in order.tolist():
            gid = int(gids[w])
            if gid < 0 or not resolved[w]:
                continue
            entry = self._cmds.pop(gid, None)
            if entry is None:
                continue  # padding row (registered by no one)
            _dot, cmd = entry
            results.extend(self._execute_entry(cmd))
            self.executed += 1
            if fast[w]:
                self.fast_paths += 1
        # valid new rows that missed the fast path took the Synod round
        self.slow_paths += int(out.slow_paths)

        # device pending overflow: rows beyond the pending capacity were
        # dropped by the device (loudly — out.pend_dropped).  Re-register
        # them for the next round under fresh gids: they never executed
        # and never entered any key clock, so resubmission is safe.
        if int(out.pend_dropped) > 0:
            carried = [
                int(gids[w])
                for w in range(len(gids))
                if gids[w] >= 0 and not resolved[w]
            ]  # working order == device carry order
            pend_cap = self._state.pend_gid.shape[0]
            dropped = carried[pend_cap:]
            logger.warning(
                "device pending buffer overflowed: re-queueing %d commands",
                len(dropped),
            )
            for gid in dropped:
                entry = self._cmds.pop(gid, None)
                if entry is not None:
                    self._requeue.append(entry)
        return results


class NewtDeviceDriver(_DriverCore):
    """Host control loop around the device-resident Newt timestamp round
    (parallel/mesh_step.newt_protocol_step): proposals, pmax commit
    clocks, count-of-max fast path and order-statistic stability all run
    as one device program; the host executes stable commands in
    (clock, dot) order against the KVStore.

    Commands carry up to ``key_width`` key buckets (a command executes
    once its clock is stable on every key it touches).  Commands are
    identified by their dot (timestamp ordering needs no gid), so the
    registry keys on packed (source, sequence).
    """

    def __init__(
        self,
        num_replicas: int,
        *,
        f: int = 1,
        tiny_quorums: bool = False,
        batch_size: int = 256,
        key_buckets: int = 4096,
        key_width: int = 1,
        pending_capacity: int = 256,
        live_replicas: Optional[int] = None,
        shard_id: ShardId = 0,
        shard_count: int = 1,
        monitor_execution_order: bool = False,
        mesh=None,
    ):
        from fantoch_tpu.parallel import mesh_step

        self._init_core(shard_id, batch_size, key_buckets, monitor_execution_order)
        self.key_width = key_width
        self._init_sharded_mesh(
            mesh_step, num_replicas, shard_count, key_buckets,
            pending_capacity, key_width, mesh, mesh_step.init_newt_state,
        )
        self._step = mesh_step.jit_newt_step(
            self._mesh, f=f, tiny_quorums=tiny_quorums,
            live_replicas=live_replicas, shard_count=shard_count,
        )
        # chained multi-round programs (step_chained), compiled per chain
        # length on first use
        self._step_kwargs = dict(
            f=f, tiny_quorums=tiny_quorums,
            live_replicas=live_replicas, shard_count=shard_count,
        )
        self._multi_step: Dict[int, object] = {}
        # no host identity mirror: the step outputs carry the working
        # rows' (src, seq) columns (NewtStepOutput.work_src/work_seq)
        self._pend_cap = pending_capacity
        self._clock_floor = 0  # timestamps GC'd below this (host int)
        self._max_clock = 0  # highest committed device clock seen
        self.clock_epochs = 0

    # timestamp clocks are int32 and grow ~1 per conflicting command per
    # bucket; when the stable watermark nears the cap, advance the clock
    # window (ops/table_ops.ClockWindow semantics: every live comparison
    # happens above the GC'd stable floor, so the uniform shift is
    # order-preserving; below-floor entries clamp to the bottom)
    CLOCK_RESET_THRESHOLD = 2**31 - (1 << 22)

    def _advance_clock_window(self, floor: int) -> None:
        import jax
        import jax.numpy as jnp

        from fantoch_tpu.ops.table_ops import shift_table

        st = self._state
        pend_clock = np.asarray(st.pend_clock, dtype=np.int64)
        live = pend_clock >= 0
        # committed-but-unstable clocks sit strictly above the stable
        # floor (stable would have executed them), so none clamp
        assert (pend_clock[live] > floor).all(), (
            "carried committed clock at/below the stable floor"
        )
        pend_clock = np.where(live, pend_clock - floor, -1)
        self._state = st._replace(
            key_clock=shift_table(st.key_clock, floor),
            vote_frontier=shift_table(st.vote_frontier, floor),
            pend_clock=jax.device_put(
                jnp.array(pend_clock.astype(np.int32)),
                st.pend_clock.sharding,
            ),
        )
        self._clock_floor += floor
        self.clock_epochs += 1
        logger.info(
            "advanced newt clock window by %d (epoch %d)",
            floor, self.clock_epochs,
        )

    def _pipeline_flush_needed(self, batch) -> bool:
        # drain may advance the clock window only with nothing in
        # flight (an in-flight round's clocks are in pre-shift units);
        # per-bucket clocks grow by at most the working-set size per
        # round, so a margin of one working set per in-flight round
        # (chains count their S rounds) plus the upcoming one guarantees
        # every drain stays under the threshold while rounds are
        # outstanding
        work = self._pend_cap + self.batch_size
        margin = (self._undrained_rounds + 1) * work
        return (
            self._max_clock + margin >= self.CLOCK_RESET_THRESHOLD
            or super()._pipeline_flush_needed(batch)
        )

    def dispatch(self, batch: List[Tuple[Dot, Command]]):
        """Assemble + dispatch one Newt round (async); returns the round
        token for ``drain``."""
        return self._dispatch_dot_keyed(batch)

    def _chain_windows_blocked(
        self, batches: List[List[Tuple[Dot, Command]]]
    ) -> bool:
        """True when a window rebase (clock or dot-sequence) could land
        mid-chain — inside one dispatch no rebase can happen, so such
        chains must take the per-round path (which rebases in drain as
        usual).  The clock margin counts every round still in flight
        plus this chain's S."""
        S = len(batches)
        work = self._pend_cap + self.batch_size
        top = max(
            (d.sequence for batch in batches for d, _ in batch), default=0
        ) - self._seq_base
        return (
            self._max_clock + (self._undrained_rounds + S) * work
            >= self.CLOCK_RESET_THRESHOLD
            or top >= self.SEQ_WINDOW_MAX
        )

    def _dispatch_chain(self, batches: List[List[Tuple[Dot, Command]]]):
        """Assemble + dispatch S rounds as ONE device program
        (parallel/mesh_step.jit_newt_multi_step, compiled per chain
        length on first use); returns the chain token for ``drain``.
        The caller checked ``_chain_windows_blocked`` first."""
        import jax.numpy as jnp

        from fantoch_tpu.parallel import mesh_step
        from fantoch_tpu.parallel.mesh_step import KEY_PAD

        S = len(batches)
        b = self.batch_size
        # chains allocate fresh staging (shape varies with S and chains
        # already amortize the dispatch; the ring serves the per-round
        # hot path)
        keys = np.full((S, b, self.key_width), KEY_PAD, dtype=np.int32)
        srcs = np.zeros((S, b), dtype=np.int32)
        seqs = np.zeros((S, b), dtype=np.int32)
        for r, batch in enumerate(batches):
            assert len(batch) <= b
            self._assemble_rows(batch, keys[r], srcs[r], seqs[r])
        multi = self._multi_step.get(S)
        if multi is None:
            multi = mesh_step.jit_newt_multi_step(
                self._mesh, **self._step_kwargs
            )
            self._multi_step[S] = multi
        self._state, outs = multi(
            self._state, jnp.asarray(keys), jnp.asarray(srcs),
            jnp.asarray(seqs),
        )
        self.rounds += S
        return _ChainToken(outs, S)

    def _token_rounds(self, tok) -> int:
        return tok.rounds if isinstance(tok, _ChainToken) else 1

    def step_chained(
        self, batches: List[List[Tuple[Dot, Command]]]
    ) -> List[ExecutorResult]:
        """S rounds in ONE device dispatch: the host assembles all S
        rounds' key/src/seq columns up front, the replica state threads
        round-to-round on device via ``lax.scan``, and the chain pays a
        single dispatch round-trip — on dispatch-dominated rigs (remote
        tunnels: ~68 ms of a 71 ms round) per-round cost drops toward
        kernel time, the serving twin of the votes-table plane's
        ``fused_table_rounds``."""
        results = self.flush_pipeline()
        S = len(batches)
        if S == 0:
            return results
        if self._chain_windows_blocked(batches):
            for batch in batches:
                results.extend(self.step(batch))
            return results
        tok = self._track_dispatch(
            lambda: self._dispatch_chain(batches),
            sum(len(b) for b in batches),
            S * self.batch_size,
            S,
        )
        results.extend(self._drain_tracked(tok))
        return results

    def step_chained_pipelined(
        self, batches: List[List[Tuple[Dot, Command]]]
    ) -> List[ExecutorResult]:
        """The composed serving mode: S in-dispatch rounds per chain x
        up to ``pipeline_depth`` chains in flight — chaining amortizes
        the dispatch round trip, pipelining overlaps the surviving
        transfer + host emit with device compute.  Results arrive up to
        ``pipeline_depth`` chains late; ``flush_pipeline`` retires the
        tail.  Chains that could cross a window rebase flush and fall
        back to synchronous per-round steps."""
        S = len(batches)
        if S == 0:
            return []
        if self._chain_windows_blocked(batches):
            results = self.flush_pipeline()
            for batch in batches:
                results.extend(self.step(batch))
            return results
        return self._pipeline_dispatch(
            lambda: self._dispatch_chain(batches),
            sum(len(b) for b in batches),
            S * self.batch_size,
            S,
        )

    def drain(self, tok) -> List[ExecutorResult]:
        """Fetch one round token's outputs (a single round or a whole
        chain — ONE device->host round trip either way) and execute its
        stable commands in (clock, dot) order."""
        from fantoch_tpu.parallel.mesh_step import NewtStepOutput

        if isinstance(tok, _ChainToken):
            outs = self._fetch(tok.outs)
            results: List[ExecutorResult] = []
            for r in range(tok.rounds):
                results.extend(
                    self._drain_round(
                        NewtStepOutput(*(np.asarray(a)[r] for a in outs))
                    )
                )
            return results
        return self._drain_round(self._fetch(tok))

    def _drain_round(self, out) -> List[ExecutorResult]:
        """One (already fetched) round's drain: advance watermark /
        clock-window bookkeeping and execute its stable commands."""
        device_wm = int(out.stable_watermark)
        # overflow trigger = the MAX committed clock (a hot key's clock
        # races ahead while cold keys pin the min watermark); the rebase
        # floor is still the stable watermark — the only provably-safe
        # shift
        clocks = np.asarray(out.clock)
        if clocks.size:
            self._max_clock = max(self._max_clock, int(clocks.max()))
        # int_max = "no keys seen this round" sentinel: skip both the
        # report and the window check
        if device_wm < 2**31 - 1:
            self.stable_watermark = self._clock_floor + device_wm
            if self._max_clock >= self.CLOCK_RESET_THRESHOLD:
                assert self._undrained == 0, (
                    "clock-window advance with a pipelined round in "
                    "flight (_pipeline_flush_needed must prevent this)"
                )
                if device_wm > 0:
                    self._advance_clock_window(device_wm)
                    self._max_clock -= device_wm
                if self._max_clock >= self.CLOCK_RESET_THRESHOLD:
                    # wm pinned at 0 (stalled voters) or lagging by the
                    # whole window: no safe rebase exists — fail loudly
                    # before int32 wraps
                    raise RuntimeError(
                        "newt clock window pinned: the stable floor lags "
                        "the hot key's clock by >= the whole window "
                        "(raise pending_capacity or investigate stalled "
                        "voters)"
                    )
        self.slow_paths += int(out.slow_paths)
        # fast/slow tallies are commit-time facts: a fast-committed command
        # may only *stabilize* (execute) rounds later, when the flag is no
        # longer set — counting at execution would undercount
        self.fast_paths += int(np.asarray(out.fast_path).sum())

        return self._drain_and_carry(out, "newt", "unstable")


class CaesarDeviceDriver(_DriverCore):
    """Host control loop around the device-resident Caesar round
    (parallel/mesh_step.caesar_protocol_step): timestamp proposals over
    the clock index, 3n/4+1 fast-quorum agreement, the MRetry
    counter-proposal folded into the same step, and wait-condition-gated
    execution in (clock, dot) order against the KVStore — the fourth
    consensus shape on the device plane
    (fantoch_ps/src/protocol/caesar.rs:216-451; execution =
    fantoch_ps/src/executor/pred/mod.rs:132-186).

    Carry contract is the Newt driver's: commands key on packed
    (source, window sequence); working-row identity comes from the step
    outputs (no host mirror); committed overflow cannot be re-proposed
    (a committed timestamp is final) and fails loudly, uncommitted
    overflow re-queues under the original dot.
    """

    # int32 timestamp headroom guard: Caesar has no per-key vote
    # frontier to derive a provably-safe rebase floor from (the Newt
    # driver's stable watermark), so exhaustion fails loudly instead of
    # windowing — at one clock tick per conflicting command per bucket,
    # that is > 2^31 conflicts on one bucket
    CLOCK_GUARD = 2**31 - (1 << 22)

    def __init__(
        self,
        num_replicas: int,
        *,
        batch_size: int = 256,
        key_buckets: int = 4096,
        key_width: int = 1,
        pending_capacity: int = 256,
        live_replicas: Optional[int] = None,
        shard_id: ShardId = 0,
        monitor_execution_order: bool = False,
        mesh=None,
    ):
        from fantoch_tpu.parallel import mesh_step

        self._init_core(shard_id, batch_size, key_buckets, monitor_execution_order)
        self.key_width = key_width
        self._mesh = (
            mesh
            if mesh is not None
            else mesh_step.make_mesh(num_replicas=num_replicas)
        )
        self._state = mesh_step.init_caesar_state(
            self._mesh,
            num_replicas,
            key_buckets=key_buckets,
            pending_capacity=pending_capacity,
            key_width=key_width,
        )
        self._step = mesh_step.jit_caesar_step(
            self._mesh, num_replicas=num_replicas, live_replicas=live_replicas
        )
        self._pend_cap = pending_capacity

    def dispatch(self, batch: List[Tuple[Dot, Command]]):
        """Assemble + dispatch one Caesar round (async); returns the
        round token for ``drain``."""
        return self._dispatch_dot_keyed(batch)

    def drain(self, out) -> List[ExecutorResult]:
        """Fetch one round's outputs and execute its wait-cleared
        commands in (clock, dot) order."""
        # one pytree fetch, one device->host round trip (PipelineCore)
        out = self._fetch(out)

        wm = int(out.watermark)
        if wm >= self.CLOCK_GUARD:
            raise RuntimeError(
                "caesar timestamp space nearing int32 exhaustion"
            )
        self.stable_watermark = max(self.stable_watermark, wm)
        self.slow_paths += int(out.slow_paths)
        self.fast_paths += int(np.asarray(out.fast_path).sum())

        return self._drain_and_carry(out, "caesar", "blocked")


class ProtocolError(Exception):
    """A client broke the wire contract: kills only its session, never
    the runtime (the per-connection failure isolation of the reference's
    client task, fantoch/src/run/task/process.rs:320-325)."""


class PaxosDeviceDriver(_DriverCore):
    """Host control loop around the device-resident leader-based slot
    round (parallel/mesh_step.paxos_protocol_step): replica 0 assigns
    consecutive slots, acceptor acks are one psum, and execution is
    strictly contiguous in slot order — the FPaxos/MultiSynod class
    (fantoch_ps/src/bin/fpaxos.rs served through fantoch/src/run/mod.rs:105)
    as a mesh program.

    Commands need no key rows (the slot log totally orders them), so
    ``key_width`` is None: the session validator accepts any width.  The
    registry keys on packed (source, sequence); working-row identity and
    the round's exec frontier come from the step outputs (no host
    mirror), so the driver serves through the shared dispatch/drain
    pipelining scaffold like the other three.
    """

    key_width = None  # slot order needs no key rows: any command width

    def __init__(
        self,
        num_replicas: int,
        *,
        f: int = 1,
        batch_size: int = 256,
        key_buckets: int = 4096,
        pending_capacity: int = 256,
        live_replicas: Optional[int] = None,
        shard_id: ShardId = 0,
        monitor_execution_order: bool = False,
        mesh=None,
    ):
        from fantoch_tpu.parallel import mesh_step

        self._init_core(shard_id, batch_size, key_buckets, monitor_execution_order)
        self._mesh = (
            mesh
            if mesh is not None
            else mesh_step.make_mesh(num_replicas=num_replicas)
        )
        self._state = mesh_step.init_paxos_state(
            self._mesh, pending_capacity=pending_capacity
        )
        self._step = mesh_step.jit_paxos_step(
            self._mesh,
            f=f,
            num_replicas=num_replicas,
            live_replicas=live_replicas,
        )
        # no host identity mirror (PaxosStepOutput.work_src/work_seq);
        # fast_paths stays 0 — leader-based: every commit is the one path
        self._pend_cap = pending_capacity
        self._slot_base = 0  # slots below base + exec_frontier executed
        self._next_slot = 0  # host mirror of state.next_slot
        self.slot_epochs = 0

    # the slot log is an int32 counter growing one per command; rebase
    # against the contiguous exec frontier (every live slot is at or
    # above it) before it can wrap
    SLOT_RESET_THRESHOLD = 2**31 - (1 << 20)

    def _slot_epoch_reset(self) -> None:
        import jax
        import jax.numpy as jnp

        st = self._state
        delta = int(st.exec_frontier)
        if delta <= 0:
            raise RuntimeError(
                "slot log exhausted: nothing executed, the frontier "
                "cannot rebase the slot space"
            )
        pend_slot = np.asarray(st.pend_slot, dtype=np.int64)
        live = pend_slot >= 0
        assert (pend_slot[live] >= delta).all(), (
            "carried slot below the contiguous exec frontier"
        )
        pend_slot = np.where(live, pend_slot - delta, -1)
        self._state = st._replace(
            next_slot=jax.device_put(
                jnp.int32(self._next_slot - delta), st.next_slot.sharding
            ),
            exec_frontier=jax.device_put(
                jnp.int32(0), st.exec_frontier.sharding
            ),
            pend_slot=jax.device_put(
                jnp.array(pend_slot.astype(np.int32)), st.pend_slot.sharding
            ),
        )
        self._next_slot -= delta
        self._slot_base += delta
        self.slot_epochs += 1
        logger.info(
            "paxos slot epoch reset: rebased by %d (epoch %d)",
            delta, self.slot_epochs,
        )

    def _pipeline_flush_needed(self, batch) -> bool:
        # a slot-epoch reset replaces next_slot/frontier/pending state
        # that an in-flight round's outputs reference pre-rebase; the
        # host slot mirror only advances at drain, so while rounds are
        # in flight the device counter leads it by up to one batch each
        return (
            self._next_slot + (self._undrained + 1) * self.batch_size
            >= self.SLOT_RESET_THRESHOLD
            or super()._pipeline_flush_needed(batch)
        )

    def dispatch(self, batch: List[Tuple[Dot, Command]]):
        """Assemble + dispatch one slot round (async); the token carries
        the batch length for drain's slot-counter accounting."""
        import jax.numpy as jnp

        assert len(batch) <= self.batch_size
        if self._next_slot + self.batch_size >= self.SLOT_RESET_THRESHOLD:
            assert self._undrained == 0, (
                "slot epoch reset with a round in flight "
                "(_pipeline_flush_needed must prevent this)"
            )
            self._slot_epoch_reset()
            if self._next_slot + self.batch_size >= 2**31 - 1:
                raise RuntimeError(
                    "slot log exhausted: the contiguous exec frontier is "
                    "pinned too far behind to rebase"
                )
        self._ensure_seq_window(batch)
        b = self.batch_size
        valid, src, seq = self._staging(
            ("valid", (b,), bool, False),
            ("src", (b,), np.int32, 0),
            ("seq", (b,), np.int32, 0),
        )
        for i, (dot, cmd) in enumerate(batch):
            valid[i] = True
            src[i] = dot.source
            seq[i] = self._device_seq(dot)
            self._cmds[self._packed(dot.source, seq[i])] = (dot, cmd)

        self._state, out = self._step(
            self._state, jnp.asarray(valid), jnp.asarray(src), jnp.asarray(seq)
        )
        self.rounds += 1
        return (out, len(batch))

    def drain(self, tok) -> List[ExecutorResult]:
        """Fetch one round's outputs and execute its contiguous slot
        prefix against the KVStore."""
        out, n_batch = tok
        # one pytree fetch, one device->host round trip (PipelineCore);
        # the round's own exec_frontier rides in the output, so a later
        # dispatched round cannot leak its frontier into this one
        out = self._fetch(out)

        order = np.asarray(out.order)
        executed = np.asarray(out.executed)
        slot = np.asarray(out.slot)
        work_src = np.asarray(out.work_src)
        work_seq = np.asarray(out.work_seq)
        # device slot counter: + new valid rows, - rolled-back overflow
        self._next_slot += n_batch - int(out.pend_dropped)
        self.stable_watermark = self._slot_base + int(out.exec_frontier)
        # every commit in the leader class takes the same (slow) path: one
        # accept round — mirror the tally convention of the object runner
        self.slow_paths += int(executed.sum())

        results = self._execute_ordered(order, executed, work_src, work_seq)

        # the device keeps the LOWEST pend_cap unexecuted slots (the log
        # stays dense); overflow rows are the highest slots and the
        # device rolled its slot counter back over them, so re-queueing
        # them under the same dot is safe: no acceptor holds durable
        # state for a rolled-back slot.
        carried = [
            w
            for w in range(len(work_src))
            if slot[w] >= 0
            and not executed[w]
            and self._packed(work_src[w], work_seq[w]) in self._cmds
        ]
        carried.sort(key=lambda w: int(slot[w]))
        self._requeue_rows(carried[self._pend_cap:], work_src, work_seq, "paxos")
        return results


class _DeviceClientSession:
    """Server side of one client connection against the device driver
    (the client.rs:79-260 role, minus dot routing — the driver orders)."""

    def __init__(self, runtime: "DeviceRuntime", rw: Rw):
        self.runtime = runtime
        self.rw = rw
        # one aggregation per shard: a multi-shard command answers with
        # one CommandResult PER SHARD (the per-shard-server contract the
        # client plane counts on, run/client_runner.py submit()); the
        # unified mesh server emits them all over the submit connection.
        driver = runtime.driver
        sids = (
            range(driver.shard_count)
            if driver.shard_count > 1
            else (driver.shard_id,)  # single-shard may sit on any shard id
        )
        self.pending_by_shard: Dict[ShardId, AggregatePending] = {
            sid: AggregatePending(runtime.process_id, sid) for sid in sids
        }
        # rifl -> (key -> owning shard), alive while results are pending
        self._key_shard: Dict[Rifl, Dict[str, ShardId]] = {}
        self._shards_left: Dict[Rifl, int] = {}
        self.client_ids: List[ClientId] = []
        self._flush_needed = asyncio.Event()

    def track(self, cmd: Command) -> None:
        """Register a submitted command for result aggregation."""
        for sid in cmd.shards():
            self.pending_by_shard[sid].wait_for(cmd)
        self._key_shard[cmd.rifl] = {
            key: sid for sid, key in cmd.all_keys()
        }
        self._shards_left[cmd.rifl] = cmd.shard_count

    def deliver(self, result: ExecutorResult) -> bool:
        """Route one per-key partial; returns True when the rifl is fully
        answered (all shards' CommandResults written)."""
        shards = self._key_shard.get(result.rifl)
        if shards is None:
            return True  # stale (session re-registered the rifl, or bug)
        sid = shards[result.key]
        done = self.pending_by_shard[sid].add_executor_result(result)
        if done is not None:
            tracer = self.runtime.tracer
            if tracer.enabled:
                tracer.span(
                    "executed", done.rifl, pid=self.runtime.process_id
                )
                tracer.edge(
                    "s", "Reply", self.runtime.process_id, 0, 0,
                    rifl=done.rifl,
                )
            self.rw.write(ToClient(done))
            self._flush_needed.set()
            self._shards_left[result.rifl] -= 1
            if self._shards_left[result.rifl] == 0:
                del self._key_shard[result.rifl]
                del self._shards_left[result.rifl]
                return True
        return False

    async def _flush_loop(self) -> None:
        while True:
            await self._flush_needed.wait()
            self._flush_needed.clear()
            await self.rw.flush()

    def _reject(self, cmd: Command, why: str) -> None:
        """Reply with an empty (zero-key) CommandResult — the client's
        bookkeeping keys on the rifl alone — instead of letting a
        malformed command reach the driver and trip an assert there."""
        from fantoch_tpu.core.command import CommandResult

        logger.warning(
            "rejecting command %s from client %s: %s",
            cmd.rifl, cmd.rifl.source, why,
        )
        self.rw.write(ToClient(CommandResult(cmd.rifl, 0)))
        self._flush_needed.set()

    def _shed(self, cmd: Command) -> None:
        """Admission-control shed: typed Overloaded reply + retry-after
        hint (run/backpressure.py plane; the client retries with capped
        backoff or sheds the command itself at its deadline)."""
        from fantoch_tpu.run.backpressure import log_per_doubling

        runtime = self.runtime
        ring = runtime._submit_queue
        ring.sheds += 1
        retry_after = runtime.retry_after_ms()
        if log_per_doubling(ring.sheds):
            logger.warning(
                "shedding submission %s from client %s: submit ring at its "
                "bound (%d >= %s); retry after %dms; %d sheds total",
                cmd.rifl, cmd.rifl.source, len(ring), ring.capacity,
                retry_after, ring.sheds,
            )
        self.rw.write(
            Overloaded(
                cmd.rifl, retry_after, depth=len(ring),
                limit=ring.capacity or 0,
            )
        )
        self._flush_needed.set()

    def _validate(self, cmd: Command) -> Optional[str]:
        """The session-boundary twin of the driver's `_bucket_row`
        contract; returns the rejection reason for commands the compiled
        device state cannot carry."""
        driver = self.runtime.driver
        # sharded: a shard id outside the compiled range would alias
        # another shard's buckets on-device (safe_key clamping) — reject
        # it at the wire, like any other contract breakage
        if driver.shard_count > 1:
            for sid in cmd.shards():
                if not 0 <= sid < driver.shard_count:
                    return (
                        f"command names shard {sid} but the server is "
                        f"compiled for {driver.shard_count} shard(s)"
                    )
        elif cmd.shard_count > 1:
            return (
                "multi-shard command submitted to a single-shard "
                "device server"
            )
        buckets = _buckets(
            cmd, driver.shard_id, driver.key_buckets, driver.shard_count,
            driver._bucket_cache,
        )
        if not buckets:
            return "command touches no keys"
        # key_width None = the driver needs no key rows (slot-ordered)
        if driver.key_width is not None and len(buckets) > driver.key_width:
            return (
                f"command touches {len(buckets)} key buckets but the device "
                f"state was compiled with key_width={driver.key_width}"
            )
        return None

    async def run(self) -> None:
        try:
            hi = await self.rw.recv()
            if hi is None:
                return  # clean close before handshake (port probe)
            if not isinstance(hi, ClientHi):
                raise ProtocolError(f"expected ClientHi, got {hi!r}")
            self.client_ids = hi.client_ids
            await self.rw.send(ClientHiAck())
            flusher = self.runtime.spawn(self._flush_loop(), fatal=False)
            sharded = self.runtime.driver.shard_count > 1
            try:
                while True:
                    msg = await self.rw.recv()
                    if msg is None:
                        break
                    if isinstance(msg, Register):
                        if sharded:
                            # the unified mesh executes every shard's
                            # portion behind the submit session; per-shard
                            # registration has nothing to set up
                            continue
                        raise ProtocolError(
                            "device-step serving is single-shard; Register "
                            "(multi-shard partial registration) has no "
                            "meaning here"
                        )
                    if not isinstance(msg, Submit):
                        raise ProtocolError(f"unexpected message {msg!r}")
                    cmd = msg.cmd
                    tracer = self.runtime.tracer
                    if tracer.enabled:
                        # ingress edge: client->server network vs queue
                        # split in the critpath report
                        tracer.edge(
                            "r", "Submit", 0, self.runtime.process_id, 0,
                            rifl=cmd.rifl,
                        )
                    why = self._validate(cmd)
                    if why is not None:
                        self._reject(cmd, why)
                        continue
                    if not self.runtime.has_capacity():
                        # admission control: the submit ring is at its
                        # bound — shed with a typed Overloaded + hint
                        # BEFORE tracking, so the retry re-runs the
                        # full path with no leftover aggregation state
                        self._shed(cmd)
                        continue
                    self.track(cmd)
                    self.runtime.rifl_sessions[cmd.rifl] = self
                    dot = self.runtime.dot_gen.next_id()
                    if tracer.enabled:
                        tracer.span(
                            "payload", cmd.rifl, dot=dot,
                            pid=self.runtime.process_id,
                        )
                    self.runtime.submit(dot, cmd)
            finally:
                flusher.cancel()
        finally:
            self.runtime.drop_session(self)
            # always close the transport: a session dying on ProtocolError
            # must leave the client an EOF, not a silent hang, and the
            # server must not leak the fd
            self.rw.close()


class DeviceRuntime:
    """TCP serving front of the device protocol step.

    Same wire protocol as ``ProcessRuntime``'s client plane (ClientHi /
    ClientHiAck / Submit / ToClient), so ``run_clients`` and
    ``bin/client.py`` drive it unchanged.  One driver task loops:
    drain submissions -> one device step -> route results to sessions.
    The device dispatch runs in a thread-pool executor so the event loop
    keeps serving connections during the (blocking) device round-trip.
    """

    def __init__(
        self,
        config: Config,
        client_addr: Address,
        *,
        protocol: str = "epaxos",
        process_id: ProcessId = 1,
        batch_size: int = 256,
        key_buckets: int = 4096,
        key_width: int = 1,
        pending_capacity: int = 256,
        live_replicas: Optional[int] = None,
        monitor_execution_order: bool = False,
        metrics_file: Optional[str] = None,
        metrics_interval_ms: int = 5000,
        pipeline: Optional[bool] = None,
        pipeline_depth: Optional[int] = None,
        ingest_deadline_ms: Optional[float] = None,
        ingest_target: Optional[int] = None,
        serving_chain_max: Optional[int] = None,
        mesh=None,
        telemetry_file: Optional[str] = None,
        metrics_port: Optional[int] = None,
        trace_file: Optional[str] = None,
        flight_dir: Optional[str] = None,
    ):
        from fantoch_tpu.core.ids import AtomicIdGen

        self.config = config
        self.process_id = process_id
        self.client_addr = client_addr
        if protocol in ("fpaxos", "caesar") and config.shard_count != 1:
            # the leader-based slot round and the Caesar round serve full
            # replication only (their host/object runners cover partial
            # replication); the dep-commit and Newt timestamp rounds both
            # serve a sharded key axis
            raise ValueError(
                f"device-step sharding serves the dep-commit and newt "
                f"rounds; {protocol} serving is single-shard"
            )
        if protocol == "newt":
            self.driver = NewtDeviceDriver(
                config.n,
                f=config.f,
                tiny_quorums=config.newt_tiny_quorums,
                batch_size=batch_size,
                key_buckets=key_buckets,
                key_width=key_width,
                pending_capacity=pending_capacity,
                live_replicas=live_replicas,
                shard_count=config.shard_count,
                monitor_execution_order=monitor_execution_order,
                mesh=mesh,
            )
        elif protocol == "caesar":
            self.driver = CaesarDeviceDriver(
                config.n,
                batch_size=batch_size,
                key_buckets=key_buckets,
                key_width=key_width,
                pending_capacity=pending_capacity,
                live_replicas=live_replicas,
                monitor_execution_order=monitor_execution_order,
                mesh=mesh,
            )
        elif protocol == "fpaxos":
            self.driver = PaxosDeviceDriver(
                config.n,
                f=config.f,
                batch_size=batch_size,
                key_buckets=key_buckets,
                pending_capacity=pending_capacity,
                live_replicas=live_replicas,
                monitor_execution_order=monitor_execution_order,
                mesh=mesh,
            )
        else:
            # the EPaxos-style dep-commit round serves every other label
            self.driver = DeviceDriver(
                config.n,
                batch_size=batch_size,
                key_buckets=key_buckets,
                key_width=key_width,
                pending_capacity=pending_capacity,
                live_replicas=live_replicas,
                shard_count=config.shard_count,
                monitor_execution_order=monitor_execution_order,
                mesh=mesh,
            )
        # in-flight depth: explicit arg > Config.serving_pipeline_depth >
        # FANTOCH_SERVING_PIPELINE_DEPTH env > 1 (run/pipeline.py) —
        # live serving and the bench rig share one resolution, and ANY
        # of the three spellings counts as the CPU pipelining opt-in
        depth_requested = (
            requested_pipeline_depth(pipeline_depth, config) is not None
        )
        self.pipeline_depth = resolve_pipeline_depth(pipeline_depth, config)
        self.driver.pipeline_depth = self.pipeline_depth
        if pipeline is None:
            # dispatch/drain overlap needs a compute resource besides the
            # host cores: on a CPU backend "device" rounds and the emit
            # loop share the same cores (measured 16% WORSE pipelined,
            # BENCH_DEV round 5), so auto-enable only off-CPU — unless a
            # pipeline depth was explicitly configured, which IS the
            # opt-in (depth > 1 is meaningless with pipelining off)
            device0 = np.asarray(self.driver._mesh.devices).flat[0]
            pipeline = (
                getattr(device0, "platform", "cpu") != "cpu"
                or depth_requested
            )
        # every driver implements the dispatch/drain split, so the
        # scaffold's step_pipelined is always available
        self.pipeline = bool(pipeline)
        # adaptive ingest batching (run/ingest.py): accumulate queued
        # submissions until the EWMA size target or the deadline budget
        # fills, so rounds dispatch full under load; the idle-system
        # fast path keeps the lone closed-loop command synchronous.
        # Same one-knob precedence as the depth above; deadline 0 turns
        # the gate off (legacy dispatch-on-anything)
        self.ingest_deadline_ms = resolve_ingest_deadline_ms(
            ingest_deadline_ms, config
        )
        self._batcher = AdaptiveIngestBatcher(
            self.ingest_deadline_ms,
            # the size target never exceeds what one release can carry:
            # a full chain of full rounds
            max_target=self.driver.batch_size
            * resolve_serving_chain_max(serving_chain_max, config),
            fixed_target=resolve_ingest_target(ingest_target, config),
        )
        # chained-by-default serving: every dispatch may fuse up to S
        # rounds (PipelineCore.step_chained_pipelined; Newt runs them as
        # ONE device program), with S auto-tuned from the measured
        # per-round dispatch overhead vs in-dispatch time
        self._chain_tuner = ChainAutoTuner(
            resolve_serving_chain_max(serving_chain_max, config)
        )
        self.dot_gen = AtomicIdGen(process_id)
        self.metrics_file = metrics_file
        self.metrics_interval_ms = metrics_interval_ms
        # live telemetry plane (observability/timeseries.py): one writer,
        # one cadence (Config.telemetry_interval_ms beats the argument)
        # for the windowed series AND the legacy JSON tallies snapshot
        self.telemetry_interval_ms = (
            config.telemetry_interval_ms
            if config.telemetry_interval_ms is not None
            else metrics_interval_ms
        )
        from fantoch_tpu.core.timing import RunTime

        self.time = RunTime()
        self.telemetry = None
        if telemetry_file is not None:
            from fantoch_tpu.observability.timeseries import SeriesWriter

            self.telemetry = SeriesWriter(
                telemetry_file, self.time,
                window_ms=self.telemetry_interval_ms,
            )
        # lifecycle tracing at the serving edge: client-hop edges plus
        # payload/executed spans per command (the device rounds stay
        # batch-attributed through the per-dispatch counters), so
        # `bin/obs.py critpath` stitches device serving traces too
        from fantoch_tpu.observability.tracer import NOOP_TRACER, Tracer

        self.tracer = NOOP_TRACER
        if trace_file is not None and config.trace_sample_rate > 0:
            self.tracer = Tracer(
                self.time, trace_file, config.trace_sample_rate, clock="wall"
            )
        # failure flight recorder (observability/recorder.py): black box
        # dumped on fatal driver failures
        self.flight = None
        self.flight_dir = flight_dir
        if config.flight_recorder:
            from fantoch_tpu.observability.exposition import profile_output_dir
            from fantoch_tpu.observability.recorder import FlightRecorder

            if self.flight_dir is None:
                self.flight_dir = profile_output_dir(
                    trace_file, telemetry_file, metrics_file
                )
            self.flight = FlightRecorder(
                self.time, pid=process_id, inner=self.tracer
            )
            self.tracer = self.flight
        self.metrics_port = metrics_port
        self.metrics_server = None
        # serving-edge throughput tallies (the submit/reply rate series)
        self.submitted = 0
        self.replied = 0
        # results route to the session that submitted the rifl (a client
        # holds one connection per shard; only the target shard's carries
        # the Submit)
        self.rifl_sessions: Dict[Rifl, _DeviceClientSession] = {}
        # bounded submit ring (run/pipeline.py): the device serving
        # loop's admission edge.  Config.admission_limit bounds queued
        # submissions; past it sessions shed with a typed Overloaded
        # reply + retry-after hint (None = legacy unbounded)
        self._submit_queue: BoundedSubmitRing = BoundedSubmitRing(
            capacity=config.admission_limit
        )
        self._tallies: Dict[str, int] = {}
        self._publish_tallies()
        self._work = asyncio.Event()
        self._tasks: set = set()
        self._servers: List[Any] = []
        self.failure: Optional[BaseException] = None
        self.failed = asyncio.Event()

    # --- lifecycle (mirrors ProcessRuntime's loud-failure contract) ---

    def spawn(self, coro, *, fatal: bool = True) -> asyncio.Task:
        """``fatal=True`` tasks (the driver loop, metrics) take the whole
        runtime down on crash; ``fatal=False`` tasks (per-client sessions)
        die alone — one misbehaving connection must not stop serving the
        others (fantoch/src/run/task/process.rs:320-325)."""
        task = asyncio.ensure_future(coro)
        task.add_done_callback(
            self._on_task_done if fatal else self._on_session_done
        )
        self._tasks.add(task)
        return task

    def _on_task_done(self, task: asyncio.Task) -> None:
        self._tasks.discard(task)
        if task.cancelled():
            return
        exc = task.exception()
        if exc is not None:
            logger.error("device runner task crashed: %r", exc)
            if self.failure is None:
                self.failure = exc
                self.failed.set()
                if self.flight is not None:
                    try:
                        self.flight.dump(
                            f"{self.flight_dir}/flight_p{self.process_id}.json",
                            f"{type(exc).__name__}: {exc}",
                        )
                    except OSError as dump_exc:
                        logger.error("flight dump failed: %r", dump_exc)
            self._teardown()

    def _on_session_done(self, task: asyncio.Task) -> None:
        self._tasks.discard(task)
        if task.cancelled():
            return
        exc = task.exception()
        if exc is not None:
            logger.warning("device client session closed with error: %r", exc)

    def _teardown(self) -> None:
        for task in list(self._tasks):
            task.cancel()
        for server in self._servers:
            server.close()

    def _arm_device_faults(self) -> None:
        """Arm the accelerator fault plane on any device planes the
        serving driver exposes (the ``device_planes`` seam shared with
        the executor pools): config knobs, ``FANTOCH_DEVICE_FAULT`` env
        rehearsal faults, and a flight-ring dump per failover.  The
        fused serving drivers expose no planes today, so this costs one
        empty-tuple check — the seam exists so a driver that grows a
        resident plane is covered without touching the runtime."""
        planes = tuple(
            getattr(self.driver, "device_planes", lambda: ())()
        )
        if not planes:
            return
        from fantoch_tpu.sim.device_faults import install_env_faults

        pid = self.process_id
        for plane in planes:
            plane.configure_faults(self.config, process_id=pid)
        install_env_faults(planes, process_id=pid)

        def on_failure(plane, exc):
            logger.warning(
                "p%s: %s plane failed over (%r); serving from host twin",
                pid, plane.plane_name, exc,
            )
            if self.flight is not None:
                try:
                    self.flight.dump(
                        f"{self.flight_dir}/flight_p{pid}_{plane.plane_name}.json",
                        f"device-failover: {plane.plane_name}: "
                        f"{type(exc).__name__}",
                    )
                except OSError as dump_exc:
                    logger.error("flight dump failed: %r", dump_exc)

        for plane in planes:
            plane.attach_failure_listener(on_failure)

    async def start(self) -> None:
        from fantoch_tpu.core.compile_cache import ensure_compile_cache
        from fantoch_tpu.observability.device import subscribe_recompiles

        subscribe_recompiles()
        # persistent compile cache before the first plane dispatch:
        # restarted/rebuilt runners reload their programs from disk
        # instead of re-paying the compile wall
        ensure_compile_cache(self.config)
        self._arm_device_faults()
        server = await asyncio.start_server(self._on_client, *self.client_addr)
        self._servers = [server]
        self.spawn(self._driver_task())
        if self.metrics_file is not None or self.telemetry is not None:
            self.spawn(self._telemetry_task())
        if self.metrics_port is not None:
            from fantoch_tpu.observability.exposition import (
                MetricsServer,
                profile_output_dir,
            )

            self.metrics_server = MetricsServer(
                self.telemetry_sample,
                self.metrics_port,
                labels={"pid": str(self.process_id)},
                profile_dir=profile_output_dir(
                    self.telemetry and self.telemetry.path, self.metrics_file
                ),
            )
            await self.metrics_server.start()
            self.metrics_port = self.metrics_server.port

    def _publish_tallies(self) -> None:
        """Called on the event-loop thread between device rounds (never
        concurrently with driver.step, which runs to completion on the
        pool thread before the loop resumes): the snapshot task reads this
        consistent copy, not live counters mid-mutation."""
        from fantoch_tpu.observability.device import (
            cache_hit_count,
            cache_miss_count,
            compile_ms,
            recompile_count,
        )

        d = self.driver
        self._tallies = {
            "submitted": self.submitted,
            "replied": self.replied,
            "rounds": d.rounds,
            "executed": d.executed,
            "fast_paths": d.fast_paths,
            "slow_paths": d.slow_paths,
            "in_flight": d.in_flight,
            "stable_watermark": d.stable_watermark,
            "queued": len(self._submit_queue),
            # overload plane: submit-ring bound, depth high-watermark,
            # and admission sheds (run/pipeline.BoundedSubmitRing)
            "queued_hwm": self._submit_queue.depth_hwm,
            "queue_capacity": self._submit_queue.capacity or 0,
            "shed_submissions": self._submit_queue.sheds,
            # per-dispatch device counters (observability/device.py)
            **d.device_counters(),
            # adaptive ingest batcher tallies (run/ingest.py)
            **self._batcher.counters(),
            "jax_recompiles": recompile_count(),
            "jax_compile_ms": compile_ms(),
            "jax_cache_hits": cache_hit_count(),
            "jax_cache_misses": cache_miss_count(),
        }

    def _write_metrics_snapshot(self) -> None:
        """Crash-consistent JSON tallies of the device rounds (the
        metrics-logger analog for the serving mode — round/path counts
        instead of per-message histograms; NOTE the on-disk format is JSON,
        not the process runner's gzip+pickle ProcessMetrics)."""
        from fantoch_tpu.run.observe import write_json_snapshot

        write_json_snapshot(self.metrics_file, dict(self._tallies))

    # gauge-natured tally keys: instantaneous values, not monotone
    # counters — the series and the exposition type them accordingly
    _GAUGE_TALLIES = frozenset({
        "in_flight", "stable_watermark", "queued", "queued_hwm",
        "queue_capacity", "device_idle_frac", "device_pipeline_depth",
        "dispatch_fill_frac", "serving_chain_len", "ingest_target",
        "ingest_rate_per_s",
    })

    def telemetry_sample(self):
        """The (counters, gauges, hists) triple for the series writer and
        the ``/metrics`` exposition, split out of the published tallies
        (names stay the bench/tally keys)."""
        counters: Dict[str, float] = {}
        gauges: Dict[str, float] = {}
        for name, value in self._tallies.items():
            (gauges if name in self._GAUGE_TALLIES else counters)[name] = value
        return counters, gauges, {}

    def _emit_telemetry(self) -> None:
        if self.telemetry is not None:
            counters, gauges, hists = self.telemetry_sample()
            self.telemetry.emit(
                f"p{self.process_id}", counters, gauges, hists
            )
            self.telemetry.flush()
        if self.metrics_file is not None:
            self._write_metrics_snapshot()

    async def _telemetry_task(self) -> None:
        while True:
            await asyncio.sleep(self.telemetry_interval_ms / 1000)
            self._emit_telemetry()
            self.tracer.flush()

    async def stop(self) -> None:
        if self.metrics_server is not None:
            await self.metrics_server.stop()
        tasks = list(self._tasks)
        self._teardown()
        await asyncio.gather(*tasks, return_exceptions=True)
        if self.metrics_file is not None or self.telemetry is not None:
            self._emit_telemetry()
        if self.telemetry is not None:
            self.telemetry.close()
        self.tracer.close()

    # --- client plane ---

    async def _on_client(self, reader, writer) -> None:
        session = _DeviceClientSession(self, Rw(reader, writer))
        self.spawn(session.run(), fatal=False)

    def has_capacity(self) -> bool:
        """Admission check for sessions: False once the submit ring sits
        at its bound (the session sheds with a typed Overloaded reply
        instead of queueing).  Check-then-submit is race-free: sessions
        and the driver share one cooperative loop."""
        ring = self._submit_queue
        return ring.capacity is None or len(ring) < ring.capacity

    def retry_after_ms(self) -> int:
        """The shed reply's retry-after hint, scaled by how many rounds
        of drain the current backlog represents."""
        base = self.config.overload_retry_after_ms
        ring = self._submit_queue
        if ring.capacity is None:
            return base
        return base * max(1, len(ring) // max(1, self.driver.batch_size))

    def submit(self, dot: Dot, cmd: Command) -> None:
        self.submitted += 1
        if not self._submit_queue.try_push((dot, cmd)):
            # unreachable via sessions (has_capacity() is checked on the
            # same cooperative tick, with no await between check and
            # submit) — a real exception, not an assert, so a future
            # caller that skips the check fails LOUDLY (the driver task
            # tears the runtime down) instead of silently dropping the
            # command under python -O
            from fantoch_tpu.errors import OverloadedError

            raise OverloadedError(
                len(self._submit_queue),
                self._submit_queue.capacity or 0,
                self.retry_after_ms(),
            )
        from time import monotonic

        self._batcher.note_arrivals(monotonic() * 1000.0, 1)
        self._work.set()

    def drop_session(self, session: "_DeviceClientSession") -> None:
        """Forget a closed session's in-flight rifls (their results have
        nowhere to go; the driver still executes them for the cluster)."""
        stale = [
            rifl for rifl, s in self.rifl_sessions.items() if s is session
        ]
        for rifl in stale:
            del self.rifl_sessions[rifl]

    def _deliver(self, results: List[ExecutorResult]) -> None:
        for result in results:
            session = self.rifl_sessions.get(result.rifl)
            if session is None:
                continue  # session closed mid-flight
            try:
                if session.deliver(result):
                    self.replied += 1
                    del self.rifl_sessions[result.rifl]
            except (ConnectionError, OSError) as exc:
                # runs on the (fatal) driver task: a half-closed client
                # connection must cost only its own results — but only
                # transport faults are session-scoped; logic errors
                # (aggregation invariants) still fail the runtime loudly
                logger.warning(
                    "dropping result for client %s (dead session): %r",
                    result.rifl.source, exc,
                )

    # --- the serving loop ---

    async def _driver_task(self) -> None:
        from time import monotonic

        loop = asyncio.get_running_loop()
        driver = self.driver
        # dispatch/drain pipelining (DeviceDriver only): under saturation
        # round k+1's device dispatch overlaps round k's host emit loop
        can_pipeline = self.pipeline
        batcher = self._batcher
        tuner = self._chain_tuner
        tracer = self.tracer
        idle_rounds = 0  # empty-input rounds yielding no results
        while True:
            if not self._submit_queue and can_pipeline and driver.has_outstanding:
                # the queue went quiet with a round still in flight:
                # retire it directly — its results must not strand, and
                # dispatching a padding-only round just to drain it would
                # waste a full device round.  A submission landing while
                # flush_pipeline runs on the pool thread is safe: this
                # task is the driver's only caller, so the flush retires
                # each in-flight round exactly once and the next loop
                # iteration re-evaluates the queue from scratch — the
                # arrival simply waits one flush, it can never interleave
                # a dispatch into the flushing pipeline
                results = await loop.run_in_executor(
                    None, driver.flush_pipeline
                )
                self._deliver(results)
                self._publish_tallies()
                continue
            if not self._submit_queue and driver.in_flight == 0:
                self._work.clear()
                await self._work.wait()
            # adaptive ingest gate (run/ingest.py): hold a part-empty
            # round while arrivals fill it toward the EWMA size target,
            # for at most the deadline budget.  Requeued overflow is
            # never held (it was admitted a round ago), nor are
            # pending-buffer progress rounds (empty queue, in_flight>0).
            # The idle-system fast path releases a lone closed-loop
            # command immediately, so sync latency never regresses.
            if (
                self._submit_queue
                and not driver.has_requeue
                and batcher.deadline_ms > 0
            ):
                release, wait_ms = batcher.poll(
                    monotonic() * 1000.0,
                    len(self._submit_queue),
                    idle_system=(
                        driver.in_flight == 0 and not driver.has_outstanding
                    ),
                )
                if not release:
                    self._work.clear()
                    # a submit that landed since the poll set _work
                    # before the clear — the wait returns immediately
                    try:
                        await asyncio.wait_for(
                            self._work.wait(), timeout=wait_ms / 1000.0
                        )
                    except asyncio.TimeoutError:
                        pass
                    continue
            # chained-by-default: assemble up to S rounds (the
            # auto-tuned chain length) from requeue + the released queue
            batches: List[List[Tuple[Dot, Command]]] = []
            pending = driver.take_requeue()
            released = 0
            while (pending or self._submit_queue) and len(batches) < tuner.chain:
                batch: List[Tuple[Dot, Command]] = []
                while pending and len(batch) < driver.batch_size:
                    batch.append(pending.pop(0))
                while self._submit_queue and len(batch) < driver.batch_size:
                    dot, cmd = self._submit_queue.popleft()
                    if tracer.enabled:
                        # batch release: payload->ingest is the queue +
                        # batching wait (critpath's ingest-batching
                        # bucket)
                        tracer.span(
                            "ingest", cmd.rifl, dot=dot, pid=self.process_id
                        )
                    batch.append((dot, cmd))
                    released += 1
                batches.append(batch)
            if len(batches) > 1:
                # canonicalize the dispatched chain length to the pow2
                # ladder: the chained step programs compile per chain
                # length, so dispatching whatever 1..S rounds the queue
                # happened to fill would mint a compiled program per
                # value — truncate to the pow2 floor and requeue the
                # remainder (it leads the next chain)
                keep = 1
                while keep * 2 <= len(batches):
                    keep *= 2
                for batch in reversed(batches[keep:]):
                    pending[:0] = batch
                del batches[keep:]
            if pending:
                # overflow past S full rounds goes back to the requeue
                # (next iteration dispatches it first)
                driver._requeue[:0] = pending
            if released:
                batcher.note_release(monotonic() * 1000.0, released)
            if not batches:
                batches = [[]]  # pending-buffer progress round
            # pipelining pays one round of delivery lag, so engage it only
            # when another batch is already waiting (throughput regime);
            # a lone closed-loop command keeps the immediate sync round.
            # An outstanding round forces the pipelined path regardless:
            # its results must come back in order ahead of this round's.
            pipeline = can_pipeline and (
                driver.has_outstanding or len(self._submit_queue) > 0
            )
            # blocking device dispatch off the event loop: connections and
            # result flushes stay live during the round.  Chains route
            # through the shared chained surface (one fused device
            # program on Newt, S plain rounds elsewhere)
            if len(batches) > 1:
                step = (
                    driver.step_chained_pipelined
                    if pipeline else driver.step_chained
                )
                results = await loop.run_in_executor(None, step, batches)
            else:
                results = await loop.run_in_executor(
                    None,
                    driver.step_pipelined if pipeline else driver.step,
                    batches[0],
                )
            # feed the chain auto-tuner the cumulative overlap counters
            # (it rate-limits itself by dispatch count)
            tuner.observe(
                driver.dispatches,
                driver.dispatch_wall_ms,
                driver.device_counters()["device_busy_ms"],
                driver.rounds,
            )
            self._deliver(results)
            self._publish_tallies()
            # commands stuck in the device pending buffer (degraded quorum)
            # with no new submissions would otherwise hot-spin device
            # rounds — including overflow-requeue cycles, whose batches are
            # non-empty but commit nothing; back off whenever a round made
            # no progress and no fresh submissions wait — interruptibly,
            # so a submit arriving mid-backoff starts the next round
            # immediately
            if (
                not results
                and not self._submit_queue
                and not (can_pipeline and driver.has_outstanding)
            ):
                idle_rounds += 1
                backoff = min(0.001 * (2 ** min(idle_rounds, 6)), 0.05)
                self._work.clear()
                # a submit that landed while driver.step ran set _work
                # before the clear — check the queue itself, not the event
                if not self._submit_queue:
                    try:
                        await asyncio.wait_for(
                            self._work.wait(), timeout=backoff
                        )
                    except asyncio.TimeoutError:
                        pass
            else:
                idle_rounds = 0
