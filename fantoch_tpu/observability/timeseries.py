"""Windowed time-series telemetry over the exact-histogram metrics plane.

The tracing plane (PR 5) answers "where did *this command's* time go" —
post hoc, span by span.  This layer answers "where is the time going
*right now*": every ``Config.telemetry_interval_ms`` (default 1 s) each
source emits one *window line* — per-window rates for its monotone
counters, a per-window snapshot (count/mean/p50/p95/p99/max) of each
exact histogram's delta, and its instantaneous gauges — into a
torn-tail-tolerant JSONL ring.  One schema, two timelines:

- the sim runner emits on virtual time (same seed => byte-identical
  series — the PR-2 determinism contract extended to telemetry);
- the run-layer runtimes (process / device / client) emit on wall time
  from a periodic task — the same cadence that writes the legacy metrics
  snapshot, so there is ONE telemetry writer per process.

A window line is canonical JSON (sorted keys, compact separators)::

    {"ctr": {name: cumulative_total},     # monotone counters
     "g":   {name: gauge},                # instantaneous values
     "h":   {name: {count, mean, p50, p95, p99, max}},  # window delta
     "k":   "win", "rate": {name: per_second}, "seq": n,
     "src": "p1", "t": <micros>, "w": <window_ms>}

``rate`` is the counter delta over the *realized* window (the wall
timeline's sleeps jitter; the denominator is measured, not assumed).
``h`` snapshots only histograms that saw samples this window — an empty
window emits ``"h": {}`` rather than repeating stale percentiles.

The file is a *ring*: after ``ring_windows`` lines the live file rotates
to ``<path>.1`` (one previous generation kept), so a long-running server
bounds its telemetry disk to ~2 rings.  The reader merges both
generations and, like the tracer's, tolerates a torn final line (crash
mid-write) per file.

No reference counterpart: ``fantoch_prof``'s metrics_logger ships only
post-hoc aggregates; this is the live instrument ROADMAP items 1 and 3
are tuned with.
"""

from __future__ import annotations

import json
import os
from collections import Counter
from typing import Any, Dict, List, Optional, Tuple

from fantoch_tpu.core.metrics import Histogram

# one knob's built-in default (Config.telemetry_interval_ms resolves
# over it): per-second windows, the classic dstat/Prometheus cadence
DEFAULT_WINDOW_MS = 1000
# ring bound: windows kept per generation (two generations on disk)
DEFAULT_RING_WINDOWS = 4096

# the key set every process-level source must carry (scrape validation
# and `obs watch` both key on these; names match the bench/tally keys)
REQUIRED_PROCESS_COUNTERS = ("submitted", "replied")


def hist_window_row(hist: Histogram) -> Dict[str, float]:
    """One histogram's window snapshot: the p50/p95/p99 shape consumers
    (watch, exposition, the regress gate) read without replaying the
    value->count map."""
    return {
        "count": hist.count,
        "mean": round(hist.mean(), 1),
        "p50": hist.percentile(0.50),
        "p95": hist.percentile(0.95),
        "p99": hist.percentile(0.99),
        "max": float(hist.max()),
    }


def _delta_hist(cur: Counter, prev: Counter) -> Histogram:
    """Exact histogram of the samples that arrived since the previous
    window (cumulative counters subtract exactly — the point of keeping
    exact value->count maps instead of decaying sketches)."""
    hist = Histogram()
    for value, count in cur.items():
        delta = count - prev.get(value, 0)
        if delta > 0:
            hist.increment(value, delta)
    return hist


class SeriesWriter:
    """Multi-source window emitter over one JSONL ring.

    ``time`` is any :class:`fantoch_tpu.core.timing.SysTime` — the sim
    passes its virtual clock (byte-identical same-seed series), the run
    layer its wall clock.  One writer may carry several sources (the sim
    emits every process + the client plane into one file); per-source
    delta state keys on ``src``.

    ``emit`` takes *cumulative* counters and histograms: the writer owns
    the delta/rate arithmetic, so sources stay a plain "what are my
    totals right now" sample with no windowing logic at every call site.
    """

    def __init__(
        self,
        path: str,
        time,
        window_ms: int = DEFAULT_WINDOW_MS,
        ring_windows: int = DEFAULT_RING_WINDOWS,
    ):
        assert window_ms >= 1 and ring_windows >= 1
        self.path = path
        self.window_ms = window_ms
        self._time = time
        self._ring_windows = ring_windows
        # a fresh writer owns the whole ring: drop a previous run's
        # rotated generation, or the reader would prefer its (higher-seq)
        # stale windows over this run's live ones
        try:
            os.remove(path + ".1")
        except FileNotFoundError:
            pass
        self._fh = open(path, "w", buffering=1 << 16)
        self._lines = 0
        self._closed = False
        # src -> (prev_t_us, prev counter totals, prev histogram maps)
        self._prev: Dict[str, Tuple[int, Dict[str, float], Dict[str, Counter]]] = {}
        self._seq: Dict[str, int] = {}
        self._t0 = time.micros()

    def emit(
        self,
        src: str,
        counters: Optional[Dict[str, float]] = None,
        gauges: Optional[Dict[str, float]] = None,
        hists: Optional[Dict[str, Histogram]] = None,
    ) -> Dict[str, Any]:
        """Write one window line for ``src``; returns the emitted dict.

        The first window of a source spans from writer construction (the
        run's start) to now, so early activity is rated, not lost."""
        now = self._time.micros()
        counters = counters or {}
        hists = hists or {}
        prev_t, prev_ctr, prev_hists = self._prev.get(
            src, (self._t0, {}, {})
        )
        dt_s = max(now - prev_t, 1) / 1e6
        rate = {
            name: round((value - prev_ctr.get(name, 0.0)) / dt_s, 3)
            for name, value in sorted(counters.items())
        }
        hist_rows: Dict[str, Dict[str, float]] = {}
        cur_hists: Dict[str, Counter] = {}
        for name, hist in sorted(hists.items()):
            cur = Counter(dict(hist.values()))
            cur_hists[name] = cur
            delta = _delta_hist(cur, prev_hists.get(name, Counter()))
            if delta.count:
                hist_rows[name] = hist_window_row(delta)
        seq = self._seq.get(src, 0)
        ev: Dict[str, Any] = {
            "k": "win",
            "src": src,
            "seq": seq,
            "t": now,
            "w": self.window_ms,
            "ctr": dict(sorted(counters.items())),
            "rate": rate,
            "g": dict(sorted((gauges or {}).items())),
            "h": hist_rows,
        }
        self._write(ev)
        self._seq[src] = seq + 1
        self._prev[src] = (now, dict(counters), cur_hists)
        return ev

    def _write(self, ev: Dict[str, Any]) -> None:
        if self._closed:
            return
        # canonical serialization: same-seed sim series must be
        # byte-identical (the tracer's discipline)
        self._fh.write(json.dumps(ev, sort_keys=True, separators=(",", ":")))
        self._fh.write("\n")
        self._lines += 1
        if self._lines >= self._ring_windows:
            self._rotate()

    def _rotate(self) -> None:
        """Ring rollover: the live generation becomes ``<path>.1`` (the
        previous one is dropped) and a fresh live file starts.  Delta
        state survives rotation — cumulative counters keep counting."""
        self._fh.close()
        os.replace(self.path, self.path + ".1")
        self._fh = open(self.path, "w", buffering=1 << 16)
        self._lines = 0

    def flush(self) -> None:
        if not self._closed:
            self._fh.flush()

    def close(self) -> None:
        if not self._closed:
            self._fh.flush()
            self._fh.close()
            self._closed = True


def _read_one(path: str) -> List[Dict[str, Any]]:
    out: List[Dict[str, Any]] = []
    with open(path, "r") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                break  # torn tail — the crash-consistent prefix ends here
    return out


def read_series(path: str) -> List[Dict[str, Any]]:
    """Read a telemetry ring: the rotated generation (``<path>.1``) first,
    then the live file, each tolerating a truncated final line.  A crash
    mid-rotation leaves at worst one whole generation missing — never a
    misparse."""
    out: List[Dict[str, Any]] = []
    for candidate in (path + ".1", path):
        if os.path.exists(candidate):
            out.extend(_read_one(candidate))
    return out


def latest_windows(
    events: List[Dict[str, Any]],
) -> Dict[str, Dict[str, Any]]:
    """Most recent window per source — what a live view renders."""
    out: Dict[str, Dict[str, Any]] = {}
    for ev in events:
        if ev.get("k") == "win":
            prev = out.get(ev["src"])
            if prev is None or ev["seq"] >= prev["seq"]:
                out[ev["src"]] = ev
    return out
