"""Chrome/Perfetto trace-event JSON conversion.

Emits the (legacy, universally-supported) Trace Event Format that both
``chrome://tracing`` and https://ui.perfetto.dev load directly:

- one complete (``ph: "X"``) event per span *segment* on the
  coordinating process's track, ``tid`` = issuing client, args carrying
  the rifl/dot and any stage meta (path decision, batch id);
- counter (``ph: "C"``) events for the device-plane tallies, one track
  per counter name;
- metadata (``ph: "M"``) events naming process tracks.

Timestamps are microseconds, exactly as recorded (virtual in sim
traces, wall clock in run traces).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List

from fantoch_tpu.observability.report import assemble_spans, span_segments

# track for client-side-only spans; host-global counters (emitted with no
# pid, e.g. jax_recompiles) get their own track rather than polluting it
CLIENT_PID = 0
GLOBAL_PID = -1


def to_perfetto(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Convert a span-event stream to a trace-event JSON object."""
    spans = assemble_spans(events)
    trace: List[Dict[str, Any]] = []
    pids = set()
    for span in spans.values():
        dot = span["dot"]
        rifl = span["rifl"]
        # the span's kept timeline: the coordinator, or (dotless,
        # leader-based) the first process observed — never mislabel
        # protocol work as client-side
        pid = span["pid"] if span["pid"] is not None else CLIENT_PID
        pids.add(pid)
        for name, ta, tb in span_segments(span):
            args: Dict[str, Any] = {"rifl": f"{rifl[0]}.{rifl[1]}"}
            if dot is not None:
                args["dot"] = f"{dot[0]}.{dot[1]}"
            stage_to = name.split("->", 1)[1]
            meta = span["meta"].get(stage_to)
            if meta:
                args.update(meta)
            trace.append(
                {
                    "name": name,
                    "cat": "dot",
                    "ph": "X",
                    "ts": ta,
                    "dur": tb - ta,
                    "pid": pid,
                    "tid": rifl[0],
                    "args": args,
                }
            )
    for ev in events:
        if ev.get("k") != "ctr":
            continue
        pid = ev.get("pid")
        if pid is None:
            pid = GLOBAL_PID
        pids.add(pid)
        trace.append(
            {
                "name": ev["name"],
                "cat": "device",
                "ph": "C",
                "ts": ev["t"],
                "pid": pid,
                "args": {"value": ev["v"]},
            }
        )
    for pid in sorted(pids):
        trace.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "args": {
                    "name": (
                        "clients" if pid == CLIENT_PID
                        else "global" if pid == GLOBAL_PID
                        else f"p{pid}"
                    )
                },
            }
        )
    return {"traceEvents": trace, "displayTimeUnit": "ms"}


def write_perfetto(events: List[Dict[str, Any]], path: str) -> int:
    """Write the converted trace; returns the number of trace events."""
    obj = to_perfetto(events)
    with open(path, "w") as fh:
        json.dump(obj, fh)
    return len(obj["traceEvents"])


def validate_perfetto(obj: Dict[str, Any]) -> None:
    """Assert the minimal trace-event invariants the viewers rely on
    (used by tests and the trace-smoke gate)."""
    assert isinstance(obj.get("traceEvents"), list), "traceEvents missing"
    for ev in obj["traceEvents"]:
        assert "ph" in ev and "pid" in ev, ev
        if ev["ph"] == "X":
            assert "ts" in ev and "dur" in ev and ev["dur"] >= 0, ev
        elif ev["ph"] == "C":
            assert "ts" in ev and "value" in ev["args"], ev
