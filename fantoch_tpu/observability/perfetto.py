"""Chrome/Perfetto trace-event JSON conversion.

Emits the (legacy, universally-supported) Trace Event Format that both
``chrome://tracing`` and https://ui.perfetto.dev load directly:

- one complete (``ph: "X"``) event per span *segment* on the
  coordinating process's track, ``tid`` = issuing client, args carrying
  the rifl/dot and any stage meta (path decision, batch id);
- flow (``ph: "s"`` / ``"f"``) event pairs per matched message edge —
  the arrows between process tracks that show WHERE a span's wait
  crossed the network (the critpath stitching, rendered);
- counter (``ph: "C"``) events for the device-plane tallies, one track
  per counter name;
- metadata (``ph: "M"``) events naming process tracks.

Timestamps are microseconds, exactly as recorded (virtual in sim
traces, wall clock in run traces).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List

from fantoch_tpu.observability.report import assemble_spans, span_segments

# track for client-side-only spans; host-global counters (emitted with no
# pid, e.g. jax_recompiles) get their own track rather than polluting it
CLIENT_PID = 0
GLOBAL_PID = -1


def to_perfetto(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Convert a span-event stream to a trace-event JSON object."""
    spans = assemble_spans(events)
    trace: List[Dict[str, Any]] = []
    pids = set()
    for span in spans.values():
        dot = span["dot"]
        rifl = span["rifl"]
        # the span's kept timeline: the coordinator, or (dotless,
        # leader-based) the first process observed — never mislabel
        # protocol work as client-side
        pid = span["pid"] if span["pid"] is not None else CLIENT_PID
        pids.add(pid)
        for name, ta, tb in span_segments(span):
            args: Dict[str, Any] = {"rifl": f"{rifl[0]}.{rifl[1]}"}
            if dot is not None:
                args["dot"] = f"{dot[0]}.{dot[1]}"
            stage_to = name.split("->", 1)[1]
            meta = span["meta"].get(stage_to)
            if meta:
                args.update(meta)
            trace.append(
                {
                    "name": name,
                    "cat": "dot",
                    "ph": "X",
                    "ts": ta,
                    "dur": tb - ta,
                    "pid": pid,
                    "tid": rifl[0],
                    "args": args,
                }
            )
    # flow arrows between process tracks: one s/f pair per matched
    # message edge (the critpath stitching, rendered).  Flows bind to
    # the rifl's track when the dot resolves to a known span, so the
    # arrow lands on the same row as the span's segments
    from fantoch_tpu.observability.critpath import match_edges

    rifl_of_dot = {
        tuple(span["dot"]): span["rifl"]
        for span in spans.values()
        if span["dot"] is not None
    }
    dot_edges, _client_edges = match_edges(events)
    for dot, hops in sorted(dot_edges.items()):
        tid = rifl_of_dot.get(dot, (0,))[0]
        for hop in hops:
            if hop["ts"] is None or hop["tr"] is None:
                continue  # half-observed hop (drop, or unsampled side)
            if hop["tr"] < hop["ts"]:
                # raw timestamps only here: a cross-machine skew larger
                # than the flight would draw a backwards arrow — skip
                # (the critpath correlator, not the viewer, owns offsets)
                continue
            # dst is part of the id: run-layer broadcasts share ONE seq
            # across the fan-out (dst disambiguates on the wire too)
            flow_id = (
                f"{dot[0]}.{dot[1]}:{hop['src']}.{hop['seq']}>{hop['dst']}"
            )
            pids.update((hop["src"], hop["dst"]))
            trace.append({
                "name": hop["mt"], "cat": "edge", "ph": "s",
                "id": flow_id, "ts": hop["ts"], "pid": hop["src"],
                "tid": tid,
            })
            trace.append({
                "name": hop["mt"], "cat": "edge", "ph": "f", "bp": "e",
                "id": flow_id, "ts": hop["tr"], "pid": hop["dst"],
                "tid": tid,
            })
    for ev in events:
        if ev.get("k") != "ctr":
            continue
        pid = ev.get("pid")
        if pid is None:
            pid = GLOBAL_PID
        pids.add(pid)
        trace.append(
            {
                "name": ev["name"],
                "cat": "device",
                "ph": "C",
                "ts": ev["t"],
                "pid": pid,
                "args": {"value": ev["v"]},
            }
        )
    for pid in sorted(pids):
        trace.append(
            {
                "name": "process_name",
                "ph": "M",
                "pid": pid,
                "args": {
                    "name": (
                        "clients" if pid == CLIENT_PID
                        else "global" if pid == GLOBAL_PID
                        else f"p{pid}"
                    )
                },
            }
        )
    return {"traceEvents": trace, "displayTimeUnit": "ms"}


def write_perfetto(events: List[Dict[str, Any]], path: str) -> int:
    """Write the converted trace; returns the number of trace events."""
    obj = to_perfetto(events)
    with open(path, "w") as fh:
        json.dump(obj, fh)
    return len(obj["traceEvents"])


def validate_perfetto(obj: Dict[str, Any]) -> None:
    """Assert the minimal trace-event invariants the viewers rely on
    (used by tests and the trace-smoke gate)."""
    assert isinstance(obj.get("traceEvents"), list), "traceEvents missing"
    flows: dict = {}
    for ev in obj["traceEvents"]:
        assert "ph" in ev and "pid" in ev, ev
        if ev["ph"] == "X":
            assert "ts" in ev and "dur" in ev and ev["dur"] >= 0, ev
        elif ev["ph"] == "C":
            assert "ts" in ev and "value" in ev["args"], ev
        elif ev["ph"] in ("s", "f"):
            assert "ts" in ev and "id" in ev, ev
            flows.setdefault(ev["id"], []).append(ev)
    for flow_id, pair in flows.items():
        # every flow id must form a start+finish pair whose finish does
        # not precede its start (the arrow the viewers draw)
        phases = sorted(ev["ph"] for ev in pair)
        assert phases == ["f", "s"], (flow_id, phases)
        start = next(ev for ev in pair if ev["ph"] == "s")
        finish = next(ev for ev in pair if ev["ph"] == "f")
        assert finish["ts"] >= start["ts"], (flow_id, start, finish)
