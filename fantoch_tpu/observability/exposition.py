"""Prometheus-text exposition + on-demand device profiling.

Every runtime can serve its live telemetry sample over plain HTTP
(``--metrics-port``): ``GET /metrics`` renders the same
(counters, gauges, histograms) triple the time-series writer windows,
as Prometheus text format 0.0.4 —

- monotone counters as ``fantoch_<name>_total`` (names match the bench
  and tally keys, so a dashboard's query and a BENCH row's key agree);
- gauges as ``fantoch_<name>``;
- exact histograms as real Prometheus histograms: cumulative
  power-of-two ``le`` buckets derived from the value->count map, plus
  ``_sum``/``_count``.

``GET /profile?ms=N`` starts an on-demand ``jax.profiler`` capture for N
milliseconds and saves the device trace next to the obs dir — the
dispatch-wall investigation (ROADMAP item 1) can be profiled *in situ*
on the serving rig, no restart.  ``install_profile_signal`` arms the
same capture on SIGUSR2 for rigs without the port open.

The HTTP layer is deliberately tiny (asyncio streams, GET only, one
response per connection): a scrape endpoint, not a web server.  A tiny
parser (:func:`parse_prometheus`) rides along for tests and
``obs scrape --json`` — rendering and parsing round-trip, so exposition
well-formedness is CI-checked instead of discovered by the first real
Prometheus pointed at it.
"""

from __future__ import annotations

import asyncio
import json
import re
import time as _time
from typing import Any, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from fantoch_tpu.core.metrics import Histogram
from fantoch_tpu.utils import logger

PREFIX = "fantoch_"

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>\S+)$"
)
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="([^"]*)"')


def metric_name(name: str) -> str:
    """Bench/tally key -> Prometheus metric name (prefixed, sanitized)."""
    return PREFIX + _NAME_RE.sub("_", str(name))


def _fmt(value: float) -> str:
    """Canonical sample value: integers render without a trailing .0."""
    if isinstance(value, bool):
        return "1" if value else "0"
    if isinstance(value, int) or float(value).is_integer():
        return str(int(value))
    return repr(float(value))


def _labels_str(labels: Optional[Dict[str, str]], extra: str = "") -> str:
    parts = [f'{k}="{v}"' for k, v in sorted((labels or {}).items())]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def hist_buckets(hist: Histogram) -> List[Tuple[float, int]]:
    """Cumulative power-of-two buckets over an exact histogram:
    ``[(le, cumulative_count)]`` ending with ``(inf, count)``.  Bounds
    double from 1 up to the first power covering the max value, so the
    bucket count is ~log2(max) regardless of sample count."""
    values = list(hist.values())
    bounds: List[float] = [1.0]
    if values:
        top = max(v for v, _c in values)
        while bounds[-1] < top:
            bounds.append(bounds[-1] * 2)
    out: List[Tuple[float, int]] = []
    for bound in bounds:
        out.append((bound, sum(c for v, c in values if v <= bound)))
    out.append((float("inf"), hist.count))
    return out


def render_prometheus(
    counters: Optional[Dict[str, float]] = None,
    gauges: Optional[Dict[str, float]] = None,
    hists: Optional[Dict[str, Histogram]] = None,
    labels: Optional[Dict[str, str]] = None,
) -> str:
    """The (counters, gauges, histograms) telemetry triple as Prometheus
    text exposition format 0.0.4."""
    lines: List[str] = []
    base = _labels_str(labels)
    for name, value in sorted((counters or {}).items()):
        metric = metric_name(name) + "_total"
        lines.append(f"# TYPE {metric} counter")
        lines.append(f"{metric}{base} {_fmt(value)}")
    for name, value in sorted((gauges or {}).items()):
        metric = metric_name(name)
        lines.append(f"# TYPE {metric} gauge")
        lines.append(f"{metric}{base} {_fmt(value)}")
    for name, hist in sorted((hists or {}).items()):
        metric = metric_name(name)
        lines.append(f"# TYPE {metric} histogram")
        for le, cum in hist_buckets(hist):
            le_s = "+Inf" if le == float("inf") else _fmt(le)
            bucket_labels = _labels_str(labels, f'le="{le_s}"')
            lines.append(f"{metric}_bucket{bucket_labels} {cum}")
        total = sum(v * c for v, c in hist.values())
        lines.append(f"{metric}_sum{base} {_fmt(total)}")
        lines.append(f"{metric}_count{base} {_fmt(hist.count)}")
    return "\n".join(lines) + "\n"


def parse_prometheus(text: str) -> Dict[str, Dict[Tuple[Tuple[str, str], ...], float]]:
    """Tiny exposition parser: ``{metric: {labelset: value}}``.

    Validates well-formedness as it goes — every sample must follow a
    ``# TYPE`` declaration of its family, histogram buckets must be
    cumulative and end at ``+Inf`` — and raises ``ValueError`` on any
    violation (the round-trip test and ``obs scrape --json`` both lean
    on this being strict)."""
    out: Dict[str, Dict[Tuple[Tuple[str, str], ...], float]] = {}
    typed: Dict[str, str] = {}
    bucket_state: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 4 and parts[1] == "TYPE":
                typed[parts[2]] = parts[3]
            continue
        match = _SAMPLE_RE.match(line)
        if match is None:
            raise ValueError(f"malformed sample line: {raw!r}")
        name = match.group("name")
        labels = tuple(sorted(_LABEL_RE.findall(match.group("labels") or "")))
        value_s = match.group("value")
        value = float("inf") if value_s == "+Inf" else float(value_s)
        family = re.sub(r"_(total|bucket|sum|count)$", "", name)
        if name not in typed and family not in typed:
            raise ValueError(f"sample {name!r} precedes its # TYPE line")
        if name.endswith("_bucket"):
            le = dict(labels).get("le")
            if le is None:
                raise ValueError(f"histogram bucket without le: {raw!r}")
            rest = tuple(kv for kv in labels if kv[0] != "le")
            key = (family, rest)
            prev = bucket_state.get(key, -1.0)
            if value < prev:
                raise ValueError(
                    f"non-cumulative buckets for {family}: {value} < {prev}"
                )
            bucket_state[key] = value
        out.setdefault(name, {})[labels] = value
    for family, kind in typed.items():
        if kind == "histogram":
            has_inf = any(
                dict(labels).get("le") == "+Inf"
                for labels in out.get(family + "_bucket", {})
            )
            if not has_inf:
                raise ValueError(f"histogram {family} missing +Inf bucket")
    return out


# --- on-demand device profiling ---

_capture_active = False


def profile_output_dir(*candidates: Optional[str]) -> str:
    """Where profiling artifacts land: next to the first configured
    observability path among ``candidates`` (telemetry series, metrics
    file), else the working directory.  ONE rule shared by the HTTP
    trigger, the SIGUSR2 handler, and both runtimes — so every trigger
    spelling saves captures to the same place."""
    import os

    for path in candidates:
        if path:
            return os.path.dirname(os.path.abspath(path))
    return "."


async def capture_device_profile(out_dir: str, ms: int) -> Dict[str, Any]:
    """One jax.profiler capture of ``ms`` milliseconds, saved under
    ``out_dir/device_trace_<epoch_ms>``.  Serialized (one capture at a
    time) and cooperative: the sleep yields, so serving continues while
    the profiler records it."""
    global _capture_active
    try:
        from jax import profiler
    except Exception as exc:  # noqa: BLE001 — jax absent: report, don't die
        return {"error": f"jax.profiler unavailable: {exc!r}"}
    if _capture_active:
        return {"error": "a capture is already running"}
    ms = max(1, min(int(ms), 60_000))
    path = f"{out_dir}/device_trace_{_time.time_ns() // 1_000_000}"
    _capture_active = True
    try:
        profiler.start_trace(path)
        await asyncio.sleep(ms / 1000)
        profiler.stop_trace()
    except Exception as exc:  # noqa: BLE001 — a failed capture must not kill serving
        return {"error": f"profiler capture failed: {exc!r}"}
    finally:
        _capture_active = False
    logger.warning("device profile captured: %s (%d ms)", path, ms)
    return {"path": path, "ms": ms}


def install_profile_signal(out_dir: str, ms: int = 1000) -> bool:
    """Arm SIGUSR2 to trigger a device-profile capture (for rigs without
    the metrics port open: ``kill -USR2 <pid>`` mid-run).  Returns False
    where signals can't be installed (non-main thread, Windows)."""
    import signal

    try:
        loop = asyncio.get_running_loop()
        loop.add_signal_handler(
            signal.SIGUSR2,
            lambda: asyncio.ensure_future(capture_device_profile(out_dir, ms)),
        )
        return True
    except (NotImplementedError, RuntimeError, ValueError):
        return False


class MetricsServer:
    """Plain-asyncio exposition endpoint.

    ``sample_fn`` returns the (counters, gauges, hists) triple (and may
    be a bound runtime method — it runs on the event loop between
    handler steps, so it reads a consistent snapshot).  Routes:

    - ``GET /metrics``        -> Prometheus text exposition
    - ``GET /profile?ms=N``   -> jax.profiler capture, JSON reply
    - anything else           -> 404
    """

    def __init__(
        self,
        sample_fn,
        port: int,
        host: str = "127.0.0.1",
        labels: Optional[Dict[str, str]] = None,
        profile_dir: str = ".",
    ):
        self._sample_fn = sample_fn
        self._host = host
        self.port = port
        self._labels = labels
        self._profile_dir = profile_dir
        self._server: Optional[asyncio.base_events.Server] = None

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, self._host, self.port
        )
        # port 0 = OS-assigned: publish the real one
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    async def _handle(self, reader, writer) -> None:
        try:
            request = await asyncio.wait_for(reader.readline(), 10.0)
            # drain headers up to the blank line (we never read a body)
            while True:
                line = await asyncio.wait_for(reader.readline(), 10.0)
                if line in (b"\r\n", b"\n", b""):
                    break
            parts = request.decode("latin-1").split()
            if len(parts) < 2 or parts[0] != "GET":
                await self._respond(writer, 405, "text/plain", "GET only\n")
                return
            url = urlparse(parts[1])
            if url.path == "/metrics":
                counters, gauges, hists = self._sample_fn()
                body = render_prometheus(counters, gauges, hists, self._labels)
                await self._respond(
                    writer, 200, "text/plain; version=0.0.4", body
                )
            elif url.path == "/profile":
                try:
                    ms = int(parse_qs(url.query).get("ms", ["1000"])[0])
                except ValueError:
                    await self._respond(
                        writer, 400, "application/json",
                        json.dumps({"error": "ms must be an integer"}) + "\n",
                    )
                    return
                result = await capture_device_profile(self._profile_dir, ms)
                await self._respond(
                    writer,
                    200 if "path" in result else 503,
                    "application/json",
                    json.dumps(result) + "\n",
                )
            else:
                await self._respond(writer, 404, "text/plain", "not found\n")
        except (asyncio.TimeoutError, ConnectionError, OSError, ValueError):
            pass  # a broken scraper is the scraper's problem
        finally:
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass

    @staticmethod
    async def _respond(writer, status: int, ctype: str, body: str) -> None:
        reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
                  405: "Method Not Allowed",
                  503: "Service Unavailable"}.get(status, "OK")
        payload = body.encode()
        writer.write(
            (
                f"HTTP/1.1 {status} {reason}\r\n"
                f"Content-Type: {ctype}\r\n"
                f"Content-Length: {len(payload)}\r\n"
                "Connection: close\r\n\r\n"
            ).encode()
        )
        writer.write(payload)
        await writer.drain()
