"""Device-plane counters: per-dispatch tallies and XLA recompile events.

The device planes (the resident votes-table plane, the serving drivers,
the batched graph resolver) do their work in fused dispatches, so
per-item latency attribution stops at the batch boundary — what remains
observable is *per-dispatch*: how many dispatches, how full each batch
was, how much kernel wall time, and whether XLA recompiled mid-run (the
classic silent latency cliff).  These counters ride two channels:

- folded into the periodic metrics snapshot
  (:class:`fantoch_tpu.run.observe.ProcessMetrics.device`);
- emitted as tracer counter events so a Perfetto timeline shows them
  next to the spans of the batches they carried.

Recompiles are counted by subscribing to ``jax.monitoring`` duration
events; the subscription is process-global and idempotent.  With the
persistent compilation cache on (core/compile_cache.py), the raw
``.../backend_compile_duration`` event is ambiguous — it wraps
``compile_or_get_cached``, so it fires for disk retrievals too.  The
listener therefore PAIRS each duration event with the cache hit/miss
event that jax emits immediately before it: a duration event preceded
by a cache hit is a retrieval (counted in :func:`cache_hit_count`, its
wall in :func:`compile_ms` — retrieval stalls serving just like a
compile, only shorter), everything else is a TRUE compile.  That makes
``jax_recompiles == 0`` the proof a warm-cache sweep never paid XLA.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

_recompiles = 0
_compile_ms = 0.0
_cache_hits = 0
_cache_misses = 0
_pending_hits = 0
_subscribed = False


def subscribe_recompiles() -> bool:
    """Start counting XLA backend compiles and persistent-cache traffic
    (idempotent; returns whether the jax.monitoring hooks installed).
    Safe to call before any jax work — the listeners cost nothing until
    a compile happens."""
    global _subscribed
    if _subscribed:
        return True
    try:
        from jax import monitoring
    except Exception:  # jax absent or too old: counters just stay 0
        return False

    def _on_event(key: str) -> None:
        global _cache_hits, _cache_misses, _pending_hits
        # the persistent-cache outcome events fire BEFORE the duration
        # event of the compile-or-retrieve they describe (verified on the
        # pinned jax); a pending hit reclassifies that duration event as
        # a retrieval
        if key.endswith("compilation_cache/cache_hits"):
            _cache_hits += 1
            _pending_hits += 1
        elif key.endswith("compilation_cache/cache_misses"):
            _cache_misses += 1

    def _on_duration(key: str, secs: float) -> None:
        global _recompiles, _compile_ms, _pending_hits
        if key.endswith("backend_compile_duration"):
            if _pending_hits > 0:
                _pending_hits -= 1
            else:
                _recompiles += 1
            # cumulative compile WALL, not just the count: one ~50s cold
            # compile starves heartbeats/serving for its whole duration
            # (PR 14's resolve_graph_plane_step programs) — a count of 1
            # hides that; the milliseconds name it.  Retrieval wall is
            # included: a warm run's compile_ms is the disk-load cost.
            _compile_ms += secs * 1000.0

    try:
        monitoring.register_event_listener(_on_event)
    except Exception:  # noqa: BLE001 — older jax: hits/misses stay 0 and
        pass  # every duration event counts as a compile (pre-cache rule)
    monitoring.register_event_duration_secs_listener(_on_duration)
    _subscribed = True
    return True


def recompile_count() -> int:
    """TRUE XLA backend compiles observed since
    :func:`subscribe_recompiles` (0 when never subscribed); persistent-
    cache retrievals are excluded — see the module docstring."""
    return _recompiles


def compile_ms() -> float:
    """Cumulative XLA backend compile-or-retrieve wall milliseconds since
    :func:`subscribe_recompiles` — host-process-global like
    :func:`recompile_count` (co-hosted runtimes must not sum it)."""
    return round(_compile_ms, 1)


def cache_hit_count() -> int:
    """Persistent-compilation-cache hits (disk retrievals instead of XLA
    compiles) since :func:`subscribe_recompiles`."""
    return _cache_hits


def cache_miss_count() -> int:
    """Persistent-compilation-cache misses (programs that went to XLA)
    since :func:`subscribe_recompiles`."""
    return _cache_misses


# fold semantics per counter kind: most keys are monotone tallies and
# SUM across executors; gauges would be nonsense summed — ratios are
# dropped (derive_idle_frac recomputes from the folded walls) and
# configuration gauges fold by max
_RATIO_KEYS = frozenset({"device_idle_frac"})
_GAUGE_MAX_KEYS = frozenset(
    {
        "device_pipeline_depth",
        "pred_plane_slot_capacity",
        "graph_plane_slot_capacity",
        # plane health gauge (0 healthy / 1 rebuilding / 2 suspect /
        # 3 failed — ordered by numeric severity, so the max IS the
        # worst health across co-hosted executors)
        "table_plane_health",
        "pred_plane_health",
        "graph_plane_health",
    }
)


def merge_counters(
    into: Dict[str, float], add: Optional[Dict[str, float]]
) -> Dict[str, float]:
    """Accumulate one executor's counter dict into a process-level one
    (used by the metrics snapshot fold): tallies sum, ratio keys are
    skipped (:func:`derive_idle_frac` recomputes them from the folded
    busy/span walls), configuration gauges (pipeline depth) fold by
    max."""
    if add:
        for name, value in add.items():
            if name in _RATIO_KEYS:
                continue
            if name in _GAUGE_MAX_KEYS:
                into[name] = max(into.get(name, 0), value)
            else:
                into[name] = into.get(name, 0) + value
    return into


def derive_idle_frac(counters: Dict[str, float]) -> Dict[str, float]:
    """Recompute ``device_idle_frac`` from (possibly folded)
    ``device_busy_ms`` / ``device_span_ms`` wall totals: the fraction of
    the serving span the device sat idle waiting on host assembly/emit —
    the number the pipelined serving loop (run/pipeline.py) exists to
    drive toward 0.  Spans of co-hosted executors overlap in wall time,
    so after a fold this is an approximation (busy and span inflate
    together); per-driver counters are exact."""
    span = counters.get("device_span_ms", 0.0)
    if span and span > 0:
        busy = counters.get("device_busy_ms", 0.0)
        counters["device_idle_frac"] = round(
            max(0.0, 1.0 - busy / span), 4
        )
    return counters
