"""Span assembly and stage-latency breakdown over a trace event stream.

A span log holds *events* (possibly from several processes and the
client plane); this module reduces them to one span per command and a
per-stage latency report whose segments telescope exactly to the
client-observed latency: ``sum(stage durations) == reply - submit`` for
every span with both endpoints, so the breakdown *explains* the latency
histogram instead of approximating it.

Canonical-event selection: client stages (``submit``/``reply``) come
from client events; process stages prefer the coordinator's timeline
(``pid == dot.source``) so the same stage observed at every replica does
not smear the span — but a stage the coordinator never emitted (it
crashed; recovery committed the dot elsewhere) falls back to the
earliest replica observation rather than vanishing.  Spans without a
dot (leader-based protocols) keep the earliest event per stage, and
out-of-chain stages (``recovery``) always do — the recoverer is never
the dead coordinator.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Tuple

from fantoch_tpu.core.metrics import Histogram
from fantoch_tpu.observability.tracer import EXTRA_STAGES, STAGES

SpanKey = Tuple[int, int]  # (rifl.source, rifl.sequence)


def assemble_spans(events: Iterable[Dict[str, Any]]) -> Dict[SpanKey, Dict[str, Any]]:
    """Reduce span events to ``rifl -> {"dot", "pid", "stages":
    {stage: t_us}, "meta": {stage: m}}`` using the canonical-event
    selection above (``pid`` is the process whose timeline the span
    keeps: the coordinator, or the first process observed for dotless
    spans)."""
    events = [ev for ev in events if ev.get("k") == "span"]
    # pass 1: the dot each rifl resolved to (stamped at the payload stage)
    dots: Dict[SpanKey, Tuple[int, int]] = {}
    for ev in events:
        dot = ev.get("dot")
        if dot is not None:
            dots.setdefault(tuple(ev["rifl"]), tuple(dot))
    spans: Dict[SpanKey, Dict[str, Any]] = {}
    # per (span, stage): True when the kept event is canonical (a client
    # event, or the coordinator's own) — canonical beats fallback,
    # fallback keeps the earliest observation
    canon: Dict[Tuple[SpanKey, str], bool] = {}
    for ev in events:
        rifl = tuple(ev["rifl"])
        stage = ev["stage"]
        span = spans.setdefault(
            rifl,
            {"rifl": rifl, "dot": dots.get(rifl), "pid": None,
             "stages": {}, "meta": {}},
        )
        dot = span["dot"]
        key = (rifl, stage)
        seen = stage in span["stages"]
        if "cid" in ev:
            keep, canonical = not seen, True
        elif stage in EXTRA_STAGES or dot is None:
            # out-of-chain stages (the recoverer is never the dead
            # coordinator) and dotless (leader-based) spans: earliest
            # observation wins
            keep = not seen or ev["t"] < span["stages"][stage]
            canonical = False
        elif ev.get("pid") == dot[0]:
            # the coordinator's own timeline: replaces any replica
            # fallback, first coordinator observation wins
            keep, canonical = not (seen and canon[key]), True
        else:
            # replica re-observation: fallback so the stage survives a
            # crashed coordinator; earliest wins, never beats canonical
            keep = not seen or (
                not canon[key] and ev["t"] < span["stages"][stage]
            )
            canonical = False
        if keep:
            span["stages"][stage] = ev["t"]
            canon[key] = canonical
            if "m" in ev:
                span["meta"][stage] = ev["m"]
            elif stage in span["meta"]:
                del span["meta"][stage]
            if span["pid"] is None and "pid" in ev:
                span["pid"] = ev["pid"]
    for span in spans.values():
        if span["dot"] is not None:
            span["pid"] = span["dot"][0]
    return spans


def span_segments(span: Dict[str, Any]) -> List[Tuple[str, int, int]]:
    """Consecutive canonical-stage segments present in one span:
    ``[(name, t_start, t_end)]`` with names like ``"submit->payload"``.
    Segments are between consecutive *present* stages, so they telescope
    to ``reply - submit`` whatever stages a protocol emits."""
    present = [(s, span["stages"][s]) for s in STAGES if s in span["stages"]]
    return [
        (f"{a}->{b}", ta, tb)
        for (a, ta), (b, tb) in zip(present, present[1:])
    ]


def stage_breakdown(
    spans: Dict[SpanKey, Dict[str, Any]],
) -> Dict[str, Histogram]:
    """Per-segment latency histograms (microseconds) plus ``end_to_end``
    (reply - submit).  Feeds the exact-histogram machinery of
    :mod:`fantoch_tpu.core.metrics` so percentiles match the rest of the
    metrics plane."""
    hists: Dict[str, Histogram] = {}
    for span in spans.values():
        for name, ta, tb in span_segments(span):
            hists.setdefault(name, Histogram()).increment(tb - ta)
        stages = span["stages"]
        if "submit" in stages and "reply" in stages:
            hists.setdefault("end_to_end", Histogram()).increment(
                stages["reply"] - stages["submit"]
            )
    return hists


def monotonic_violations(
    spans: Dict[SpanKey, Dict[str, Any]],
) -> List[Tuple[SpanKey, str]]:
    """Spans whose canonical stages run backwards (should be empty; a
    non-empty result means a hook site or clock is lying)."""
    bad = []
    for rifl, span in spans.items():
        for name, ta, tb in span_segments(span):
            if tb < ta:
                bad.append((rifl, name))
    return bad


def counters_total(events: Iterable[Dict[str, Any]]) -> Dict[str, float]:
    """Final value per counter name (counters are emitted as running
    totals; the last observation wins per (name, pid), then pids sum —
    except depth gauges, where the cluster-wide value is the worst
    process's, not the sum of everyone's)."""
    last: Dict[Tuple[str, Optional[int]], float] = {}
    for ev in events:
        if ev.get("k") == "ctr":
            last[(ev["name"], ev.get("pid"))] = ev["v"]
    out: Dict[str, float] = {}
    for (name, _pid), value in last.items():
        if name.endswith(("_hwm", "_plane_health")) or name == "queue_depth":
            # gauges: the cluster-wide value is the worst process's
            # (plane health is severity-ordered, so max IS worst)
            out[name] = max(out.get(name, 0), value)
        else:
            out[name] = out.get(name, 0) + value
    return out


def _hist_row(hist: Histogram) -> Dict[str, float]:
    return {
        "count": hist.count,
        "mean_us": round(hist.mean(), 1),
        "p50_us": hist.percentile(0.50),
        "p95_us": hist.percentile(0.95),
        "p99_us": hist.percentile(0.99),
        "max_us": hist.max(),
    }


def summarize(events: List[Dict[str, Any]]) -> Dict[str, Any]:
    """The ``obs summarize`` payload: span totals, stage coverage,
    per-segment p50/p95/p99, end-to-end stats, device counters."""
    spans = assemble_spans(events)
    hists = stage_breakdown(spans)
    coverage: Dict[str, int] = {s: 0 for s in STAGES}
    for span in spans.values():
        for stage in span["stages"]:
            if stage in coverage:
                coverage[stage] += 1
    segment_order = [
        f"{a}->{b}" for a, b in zip(STAGES, STAGES[1:])
    ]
    segments = {
        name: _hist_row(hists[name])
        for name in segment_order + sorted(
            k for k in hists if k not in segment_order and k != "end_to_end"
        )
        if name in hists
    }
    out: Dict[str, Any] = {
        "spans": len(spans),
        "events": len(events),
        "stage_coverage": coverage,
        "segments": segments,
        "monotonic_violations": len(monotonic_violations(spans)),
    }
    if "end_to_end" in hists:
        out["end_to_end"] = _hist_row(hists["end_to_end"])
    counters = counters_total(events)
    if counters:
        out["device_counters"] = counters
    return out


def diff_stages(
    a: List[Dict[str, Any]],
    b: List[Dict[str, Any]],
    tol_frac: float = 0.5,
    tol_abs_us: int = 20_000,
    limit: int = 10,
) -> Dict[str, Any]:
    """Tolerance diff of assembled span *stage latencies* between two
    traces — the comparison that works for run-layer (wall-clock) logs,
    where byte identity can never hold.  Spans match by rifl (same
    workload/seed => same rifls); each matched span's per-segment
    durations must agree within ``tol_abs_us + tol_frac * max(a, b)``.
    Returns ``{"matched", "only_a", "only_b", "mismatches": [lines]}``
    — empty mismatch/only lists mean the two runs have the same latency
    *structure* within tolerance."""
    spans_a = assemble_spans(a)
    spans_b = assemble_spans(b)
    only_a = sorted(set(spans_a) - set(spans_b))
    only_b = sorted(set(spans_b) - set(spans_a))
    mismatches: List[str] = []
    matched = 0
    for rifl in sorted(set(spans_a) & set(spans_b)):
        matched += 1
        seg_a = {n: tb - ta for n, ta, tb in span_segments(spans_a[rifl])}
        seg_b = {n: tb - ta for n, ta, tb in span_segments(spans_b[rifl])}
        for name in sorted(set(seg_a) | set(seg_b)):
            if len(mismatches) >= limit:
                mismatches.append("... (diff truncated)")
                return {
                    "matched": matched, "only_a": only_a, "only_b": only_b,
                    "mismatches": mismatches,
                }
            da, db = seg_a.get(name), seg_b.get(name)
            if da is None or db is None:
                mismatches.append(
                    f"span {rifl}: segment {name} present in only one trace "
                    f"({da} vs {db})"
                )
                continue
            tol = tol_abs_us + tol_frac * max(da, db)
            if abs(da - db) > tol:
                mismatches.append(
                    f"span {rifl}: {name} {da}us vs {db}us "
                    f"(delta {abs(da - db)}us > tol {tol:.0f}us)"
                )
    return {
        "matched": matched, "only_a": only_a, "only_b": only_b,
        "mismatches": mismatches,
    }


def diff_events(
    a: List[Dict[str, Any]], b: List[Dict[str, Any]], limit: int = 10
) -> List[str]:
    """Structural diff of two event streams (order-sensitive — two
    same-seed sim traces must match event for event).  Returns
    human-readable mismatch lines, empty when identical."""
    import json

    out: List[str] = []
    if len(a) != len(b):
        out.append(f"event count differs: {len(a)} vs {len(b)}")
    for i, (ea, eb) in enumerate(zip(a, b)):
        if ea != eb:
            out.append(
                f"event {i}: "
                f"{json.dumps(ea, sort_keys=True)} != "
                f"{json.dumps(eb, sort_keys=True)}"
            )
            if len(out) >= limit:
                out.append("... (diff truncated)")
                break
    return out
