"""Failure flight recorder: a bounded in-memory ring of *unsampled*
trace events, dumped as a black box when something goes wrong.

The sampled tracer (observability/tracer.py) answers "where does the
p99 go" for commands that hashed into the sample; when a typed failure
fires (``DivergenceError``, ``StalledExecutionError``, an auditor
``Violation``, a WAL-restart boot) the evidence that matters is
whatever happened *just before it* — usually commands that did NOT
sample in.  The :class:`FlightRecorder` closes that gap: it implements
the tracer protocol (span / counter / edge / offset), records EVERY
event into a lock-light bounded ring (`collections.deque(maxlen=...)`
— appends are atomic under both the GIL and cooperative asyncio), and
forwards to the real sampled tracer underneath, so hook sites keep one
``self.tracer`` seam and pay one extra dict append per event.

On a trigger the ring dumps to ``flight_p<pid>.json`` (one file per
process; a shared sim ring splits by the events' ``pid``).  Dumps are
self-describing JSON readable by :func:`read_flight`, and
:func:`flight_events` re-synthesizes the stream (header included) so
the critical-path correlator (observability/critpath.py) stitches
flight dumps exactly like live span logs — every failure ships a
replayable black box.

Triggers: any fatal runtime failure (run/process_runner.py ``_fail``),
typed sim stalls (sim/runner.py), a WAL-restart boot (the new life's
replay + rejoin events), ``SIGUSR1`` (:func:`install_flight_signal`),
and fuzz findings (sim/fuzz.py attaches dumps to repro artifacts).
"""

from __future__ import annotations

import json
import os
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from fantoch_tpu.observability.tracer import (
    NOOP_TRACER,
    counter_event,
    edge_event,
    offset_event,
    span_event,
)

FLIGHT_FORMAT = "fantoch-flight-v1"

# ring bound: ~last N events per process (the "last few seconds" at
# serving rates; env-overridable for long-window rigs)
DEFAULT_FLIGHT_EVENTS = 1 << 16


def flight_capacity(explicit: Optional[int] = None) -> int:
    """config > FANTOCH_FLIGHT_EVENTS env > built-in default."""
    if explicit is not None:
        return int(explicit)
    env = os.environ.get("FANTOCH_FLIGHT_EVENTS")
    return int(env) if env else DEFAULT_FLIGHT_EVENTS


class FlightRecorder:
    """Tracer-protocol tee: ring-record everything, forward to the
    (sampling) inner tracer.  ``enabled`` is True so hook sites build
    event payloads; ``sample`` answers True so meta-bearing sites (the
    commit deps stamp) build their meta for the ring — the inner tracer
    still applies its own deterministic sampling on forward."""

    enabled = True

    def __init__(
        self,
        time,
        pid: Optional[int] = None,
        inner=NOOP_TRACER,
        capacity: Optional[int] = None,
        clock: str = "wall",
    ):
        self._time = time
        self.pid = pid
        self.inner = inner
        self.clock = getattr(inner, "clock", None) or clock
        self._ring: deque = deque(maxlen=flight_capacity(capacity))
        self.dumps: List[str] = []

    # --- tracer protocol ---

    @property
    def sample_rate(self) -> float:
        return getattr(self.inner, "sample_rate", 0.0)

    @property
    def path(self):
        return getattr(self.inner, "path", None)

    def sample(self, rifl) -> bool:
        return True

    def span(self, stage, rifl, dot=None, pid=None, cid=None, meta=None) -> None:
        self._ring.append(
            span_event(
                self._time.micros(), stage, rifl,
                dot=dot, pid=pid, cid=cid, meta=meta,
            )
        )
        self.inner.span(stage, rifl, dot=dot, pid=pid, cid=cid, meta=meta)

    def counter(self, name, value, pid=None, meta=None) -> None:
        self._ring.append(
            counter_event(self._time.micros(), name, value, pid=pid, meta=meta)
        )
        self.inner.counter(name, value, pid=pid, meta=meta)

    def edge(self, io, mtype, src, dst, seq, dot=None, rifl=None) -> None:
        self._ring.append(
            edge_event(
                self._time.micros(), io, mtype, src, dst, seq,
                dot=dot, rifl=rifl,
            )
        )
        self.inner.edge(io, mtype, src, dst, seq, dot=dot, rifl=rifl)

    def offset(self, pid, peer, offset_us, rtt_us) -> None:
        self._ring.append(
            offset_event(self._time.micros(), pid, peer, offset_us, rtt_us)
        )
        self.inner.offset(pid, peer, offset_us, rtt_us)

    def flush(self) -> None:
        self.inner.flush()

    def close(self) -> None:
        self.inner.close()

    # --- the black box ---

    def events(self) -> List[Dict[str, Any]]:
        return list(self._ring)

    def dump(self, path: str, reason: str) -> str:
        """Write the whole ring as one self-describing JSON black box."""
        _write_blob(
            path, self.pid, self.clock, reason,
            self._time.micros(), self.events(),
        )
        self.dumps.append(path)
        return path

    def dump_all(self, out_dir: str, reason: str) -> List[str]:
        """Split the ring by owning process and write one
        ``flight_p<pid>.json`` per process (+ ``flight_clients.json``
        for client-plane events) — the shape a shared sim ring dumps in,
        and what a per-runtime ring with a known pid degrades to."""
        if self.pid is not None:
            return [self.dump(f"{out_dir}/flight_p{self.pid}.json", reason)]
        by_owner: Dict[Any, List[Dict[str, Any]]] = {}
        for ev in self._ring:
            by_owner.setdefault(_event_owner(ev), []).append(ev)
        t_us = self._time.micros()
        paths = []
        for owner in sorted(by_owner, key=str):
            name = (
                "flight_clients.json" if owner is None
                else f"flight_p{owner}.json"
            )
            paths.append(
                _write_blob(
                    f"{out_dir}/{name}", owner, self.clock, reason,
                    t_us, by_owner[owner],
                )
            )
        self.dumps.extend(paths)
        return paths


def _write_blob(
    path: str,
    pid: Any,
    clock: str,
    reason: str,
    t_us: int,
    events: List[Dict[str, Any]],
) -> str:
    """The one flight-dump shape — every dump path writes through here."""
    blob = {
        "format": FLIGHT_FORMAT,
        "pid": pid,
        "clock": clock,
        "reason": reason,
        "dumped_at_us": t_us,
        "events": events,
    }
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "w") as fh:
        json.dump(blob, fh, sort_keys=True, separators=(",", ":"))
        fh.write("\n")
    return path


def _event_owner(ev: Dict[str, Any]):
    """Which process's black box an event belongs in: its ``pid``, the
    emitting side of an edge (sender for ``"s"``, receiver for ``"r"``),
    or None for client-plane events (``cid`` only)."""
    pid = ev.get("pid")
    if pid is not None:
        return pid
    if ev.get("k") == "edge":
        owner = ev["src"] if ev.get("io") == "s" else ev["dst"]
        # client-plane hops mark their client side as 0 (the perfetto
        # CLIENT_PID convention): those belong to the process side
        return owner if owner != 0 else (
            ev["dst"] if ev.get("io") == "s" else ev["src"]
        )
    return None


def read_flight(path: str) -> Tuple[Dict[str, Any], List[Dict[str, Any]]]:
    """Load one flight dump; returns (meta, events)."""
    with open(path) as fh:
        blob = json.load(fh)
    assert blob.get("format") == FLIGHT_FORMAT, f"not a flight dump: {path}"
    events = blob.pop("events")
    return blob, events


def flight_events(paths: List[str]) -> List[Dict[str, Any]]:
    """Merge flight dumps back into one trace-shaped event stream (a
    synthesized ``hdr`` per dump carries the clock domain), so the
    critical-path correlator consumes black boxes exactly like live
    span logs."""
    events: List[Dict[str, Any]] = []
    for path in paths:
        meta, evs = read_flight(path)
        events.append({"k": "hdr", "clock": meta.get("clock", "wall"), "v": 1})
        events.extend(evs)
    return events


def install_flight_signal(recorder: FlightRecorder, out_dir: str) -> bool:
    """Arm SIGUSR1 to dump the flight ring on demand (``kill -USR1``
    against a live server: a black box without killing the run).
    Returns False where signals can't be installed."""
    import asyncio
    import signal

    def _dump() -> None:
        if recorder.pid is not None:
            recorder.dump(
                f"{out_dir}/flight_p{recorder.pid}.json", "SIGUSR1"
            )
        else:
            recorder.dump_all(out_dir, "SIGUSR1")

    try:
        loop = asyncio.get_running_loop()
        loop.add_signal_handler(signal.SIGUSR1, _dump)
        return True
    except (NotImplementedError, RuntimeError, ValueError):
        return False
