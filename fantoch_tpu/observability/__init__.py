"""Per-dot lifecycle tracing plane (no direct reference counterpart —
fantoch only ships aggregate counters via fantoch_prof; this package adds
the per-command attribution layer those counters cannot answer).

- :mod:`tracer` — sampled span emission (one schema for sim virtual time
  and run wall clock) into a crash-consistent JSONL log;
- :mod:`report` — span assembly, stage-latency breakdown (p50/p95/p99 per
  stage over :class:`fantoch_tpu.core.metrics.Histogram`), trace diff;
- :mod:`perfetto` — Chrome/Perfetto trace-event JSON conversion;
- :mod:`device` — device-plane counters (dispatches, occupancy,
  recompiles via jax.monitoring) folded into metrics snapshots;
- :mod:`timeseries` — live windowed telemetry (per-window rates +
  histogram snapshots) as torn-tail-tolerant JSONL rings, on both
  timelines (sim virtual time / run wall time);
- :mod:`exposition` — Prometheus-text ``/metrics`` endpoint plus the
  on-demand ``jax.profiler`` capture trigger (HTTP ``/profile?ms=N`` or
  SIGUSR2).
"""

from fantoch_tpu.observability.tracer import (
    EXTRA_STAGES,
    NOOP_TRACER,
    STAGES,
    Tracer,
    read_trace,
    span_hash,
)
from fantoch_tpu.observability.device import (
    cache_hit_count,
    cache_miss_count,
    recompile_count,
    subscribe_recompiles,
)
from fantoch_tpu.observability.timeseries import (
    SeriesWriter,
    latest_windows,
    read_series,
)

__all__ = [
    "SeriesWriter",
    "latest_windows",
    "read_series",
    "EXTRA_STAGES",
    "NOOP_TRACER",
    "STAGES",
    "Tracer",
    "read_trace",
    "span_hash",
    "cache_hit_count",
    "cache_miss_count",
    "recompile_count",
    "subscribe_recompiles",
]
