"""Sampled per-dot span tracing: one schema across sim and run.

Every traced command leaves a sequence of *span events* — one JSON line
per lifecycle stage — keyed by its rifl (the id that exists from client
submit to client reply; the dot joins at the ``payload`` stage, once the
coordinator assigns it).  The same schema is emitted by the sim runner
(virtual timestamps from :class:`fantoch_tpu.core.timing.SimTime`) and
the run layer (wall clock), so a same-seed sim trace and a localhost
trace are directly diffable: the PR-2 deterministic-trace property
extended from message order to latency structure.

Canonical stage chain (monotonic within a span)::

    submit -> payload -> path -> commit -> ready -> executed -> reply

- ``submit``/``reply`` are client-side (events carry ``cid``);
- ``payload`` is the coordinator assigning the dot and owning the
  payload; ``path`` is the fast/slow decision; ``commit`` the commit;
- ``ready`` is the executor's stable/resolved point, ``executed`` the
  KVStore execution (events carry ``pid``; the report keeps the
  coordinator's timeline — ``pid == dot.source`` — so replicated stages
  do not overlap).

``recovery`` is an extra out-of-chain stage stamped when a dot enters
recovery consensus.  *Counter events* (``k == "ctr"``) carry device-plane
tallies (dispatch counts, batch occupancy, recompiles, kernel wall-ms)
attached to the trace timeline.

Sampling is a deterministic hash of the span id (:func:`span_hash` over
``(rifl.source, rifl.sequence)``) against ``Config.trace_sample_rate``:
the same seed yields the same sampled dot set, with no RNG state touched
(the sim's determinism contract).  With the rate at 0 the tracer is the
:data:`NOOP_TRACER` singleton — one attribute check per hook site.

The log is crash-consistent JSONL: every line is a self-contained event
written with sorted keys and compact separators (same-seed sim runs are
byte-identical); a reader tolerates a truncated final line.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

# canonical per-command stage chain, in lifecycle order
STAGES = (
    "submit",
    "payload",
    "path",
    "commit",
    "ready",
    "executed",
    "reply",
)
# out-of-chain stages (do not participate in the stage-latency breakdown)
EXTRA_STAGES = ("recovery",)

_MASK64 = (1 << 64) - 1
_SAMPLE_SPACE = 1 << 32


def span_hash(source: int, sequence: int) -> int:
    """Deterministic 32-bit mix of a (source, sequence) id pair
    (splitmix64 finalizer over a golden-ratio combine).  Used for
    sampling: stable across processes and runs, independent of
    PYTHONHASHSEED and of any RNG state."""
    x = (source * 0x9E3779B97F4A7C15 + sequence * 0xBF58476D1CE4E5B9) & _MASK64
    x ^= x >> 31
    x = (x * 0x94D049BB133111EB) & _MASK64
    x ^= x >> 29
    return x & (_SAMPLE_SPACE - 1)


def _noop() -> "_NoopTracer":
    return NOOP_TRACER


class _NoopTracer:
    """Zero-cost disabled tracer: hook sites guard on ``.enabled`` and
    never build event payloads.  Pickles (and deep-copies) back to the
    module singleton so protocol state holding it stays picklable (the
    model checker pickles whole protocol instances)."""

    enabled = False
    sample_rate = 0.0

    def sample(self, rifl) -> bool:
        return False

    def span(self, stage, rifl, dot=None, pid=None, cid=None, meta=None) -> None:
        pass

    def counter(self, name, value, pid=None, meta=None) -> None:
        pass

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass

    def __reduce__(self):
        return (_noop, ())


NOOP_TRACER = _NoopTracer()


class Tracer:
    """Lock-light span emitter over a monotonic time source.

    ``time`` is any :class:`fantoch_tpu.core.timing.SysTime` — the sim
    passes its virtual clock, the run layer its wall clock — so emission
    sites never thread timestamps through.  Writes are buffered complete
    lines; ``flush()`` is cheap and the run layer calls it periodically
    (crash consistency = the on-disk prefix is always parseable).
    """

    enabled = True

    def __init__(self, time, path: str, sample_rate: float = 1.0,
                 flush_every: int = 512):
        self._time = time
        self.path = path
        self.sample_rate = max(0.0, min(1.0, float(sample_rate)))
        self._threshold = int(self.sample_rate * _SAMPLE_SPACE)
        self._fh = open(path, "w", buffering=1 << 16)
        self._flush_every = flush_every
        self._pending = 0
        self._closed = False

    # --- sampling ---

    def sample(self, rifl) -> bool:
        """Deterministic verdict for a span id (a Rifl or any
        (source, sequence) pair)."""
        return span_hash(rifl[0], rifl[1]) < self._threshold

    # --- emission ---

    def span(
        self,
        stage: str,
        rifl,
        dot=None,
        pid: Optional[int] = None,
        cid: Optional[int] = None,
        meta: Optional[Dict[str, Any]] = None,
    ) -> None:
        if span_hash(rifl[0], rifl[1]) >= self._threshold:
            return
        ev: Dict[str, Any] = {
            "k": "span",
            "stage": stage,
            "rifl": [rifl[0], rifl[1]],
            "t": self._time.micros(),
        }
        if dot is not None:
            ev["dot"] = [dot[0], dot[1]]
        if pid is not None:
            ev["pid"] = pid
        if cid is not None:
            ev["cid"] = cid
        if meta:
            ev["m"] = meta
        self._write(ev)

    def counter(
        self,
        name: str,
        value,
        pid: Optional[int] = None,
        meta: Optional[Dict[str, Any]] = None,
    ) -> None:
        ev: Dict[str, Any] = {
            "k": "ctr",
            "name": name,
            "v": value,
            "t": self._time.micros(),
        }
        if pid is not None:
            ev["pid"] = pid
        if meta:
            ev["m"] = meta
        self._write(ev)

    def _write(self, ev: Dict[str, Any]) -> None:
        if self._closed:
            return
        # sorted keys + compact separators: same-seed sim traces must be
        # byte-identical, so serialization is fully canonical
        self._fh.write(json.dumps(ev, sort_keys=True, separators=(",", ":")))
        self._fh.write("\n")
        self._pending += 1
        if self._pending >= self._flush_every:
            self.flush()

    def flush(self) -> None:
        if not self._closed:
            self._fh.flush()
            self._pending = 0

    def close(self) -> None:
        if not self._closed:
            self._fh.flush()
            self._fh.close()
            self._closed = True


def read_trace(path: str) -> List[Dict[str, Any]]:
    """Read a JSONL span log; a truncated final line (crash mid-write) is
    dropped, everything before it is returned."""
    out: List[Dict[str, Any]] = []
    with open(path, "r") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                break  # torn tail — the crash-consistent prefix ends here
    return out
