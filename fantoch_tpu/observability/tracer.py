"""Sampled per-dot span tracing: one schema across sim and run.

Every traced command leaves a sequence of *span events* — one JSON line
per lifecycle stage — keyed by its rifl (the id that exists from client
submit to client reply; the dot joins at the ``payload`` stage, once the
coordinator assigns it).  The same schema is emitted by the sim runner
(virtual timestamps from :class:`fantoch_tpu.core.timing.SimTime`) and
the run layer (wall clock), so a same-seed sim trace and a localhost
trace are directly diffable: the PR-2 deterministic-trace property
extended from message order to latency structure.

Canonical stage chain (monotonic within a span)::

    submit -> payload -> path -> commit -> ready -> executed -> reply

- ``submit``/``reply`` are client-side (events carry ``cid``);
- ``payload`` is the coordinator assigning the dot and owning the
  payload; ``path`` is the fast/slow decision; ``commit`` the commit;
- ``ready`` is the executor's stable/resolved point, ``executed`` the
  KVStore execution (events carry ``pid``; the report keeps the
  coordinator's timeline — ``pid == dot.source`` — so replicated stages
  do not overlap).

``recovery`` is an extra out-of-chain stage stamped when a dot enters
recovery consensus.  *Counter events* (``k == "ctr"``) carry device-plane
tallies (dispatch counts, batch occupancy, recompiles, kernel wall-ms)
attached to the trace timeline.

Beyond spans and counters the schema carries three more event kinds,
added for cross-process critical-path attribution
(:mod:`fantoch_tpu.observability.critpath`):

- ``k == "hdr"``: one header line per log naming the clock domain —
  ``"virtual"`` (sim: one shared clock, no skew) or ``"wall"`` (run
  layer: every process stamps its own wall clock, so the correlator
  must resolve per-peer offsets before cross-process math);
- ``k == "edge"``: one *message-edge* event per side of a cross-process
  hop (``io == "s"`` at the sender, ``"r"`` at the receiver), paired by
  ``(src, seq)`` — a per-sender monotone sequence carried on the wire —
  so a send stitches to its delivery causally, Dapper-style.  Edges are
  sampled by the same deterministic hash as spans (by rifl for
  client<->server hops, by dot for peer protocol messages), so a
  sampled span's edges are present whenever its dot/rifl hashes in;
- ``k == "off"``: a clock-offset estimate for one peer pair
  (``off`` = peer clock minus local clock in us, ``rtt`` the probe
  round-trip that bounds its error), emitted by the run layer whenever
  a heartbeat RTT sample improves the estimate (run/links.py).

Sampling is a deterministic hash of the span id (:func:`span_hash` over
``(rifl.source, rifl.sequence)``) against ``Config.trace_sample_rate``:
the same seed yields the same sampled dot set, with no RNG state touched
(the sim's determinism contract).  With the rate at 0 the tracer is the
:data:`NOOP_TRACER` singleton — one attribute check per hook site.

The log is crash-consistent JSONL: every line is a self-contained event
written with sorted keys and compact separators (same-seed sim runs are
byte-identical); a reader tolerates a truncated final line.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

# canonical per-command stage chain, in lifecycle order
STAGES = (
    "submit",
    "payload",
    # batch release: stamped when the adaptive ingest batcher
    # (run/ingest.py) releases the command's round toward dispatch —
    # payload->ingest IS the ingest-queue + batching wait, so the
    # deadline budget is attributed, never hidden in a merged segment
    "ingest",
    "path",
    "commit",
    "ready",
    "executed",
    "reply",
)
# out-of-chain stages (do not participate in the stage-latency breakdown)
EXTRA_STAGES = ("recovery",)

_MASK64 = (1 << 64) - 1
_SAMPLE_SPACE = 1 << 32


def span_hash(source: int, sequence: int) -> int:
    """Deterministic 32-bit mix of a (source, sequence) id pair
    (splitmix64 finalizer over a golden-ratio combine).  Used for
    sampling: stable across processes and runs, independent of
    PYTHONHASHSEED and of any RNG state."""
    x = (source * 0x9E3779B97F4A7C15 + sequence * 0xBF58476D1CE4E5B9) & _MASK64
    x ^= x >> 31
    x = (x * 0x94D049BB133111EB) & _MASK64
    x ^= x >> 29
    return x & (_SAMPLE_SPACE - 1)


# --- canonical event builders ---
#
# ONE place constructs each event kind: the live Tracer serializes
# these to JSONL, and the flight recorder (observability/recorder.py)
# rings the same dicts unsampled — so the correlator can never see two
# schemas drift apart.


def span_event(t_us, stage, rifl, dot=None, pid=None, cid=None, meta=None):
    ev: Dict[str, Any] = {
        "k": "span", "stage": stage, "rifl": [rifl[0], rifl[1]], "t": t_us,
    }
    if dot is not None:
        ev["dot"] = [dot[0], dot[1]]
    if pid is not None:
        ev["pid"] = pid
    if cid is not None:
        ev["cid"] = cid
    if meta:
        ev["m"] = meta
    return ev


def counter_event(t_us, name, value, pid=None, meta=None):
    ev: Dict[str, Any] = {"k": "ctr", "name": name, "v": value, "t": t_us}
    if pid is not None:
        ev["pid"] = pid
    if meta:
        ev["m"] = meta
    return ev


def edge_event(t_us, io, mtype, src, dst, seq, dot=None, rifl=None):
    ev: Dict[str, Any] = {
        "k": "edge", "io": io, "mt": mtype, "src": src, "dst": dst,
        "seq": seq, "t": t_us,
    }
    if dot is not None:
        ev["dot"] = [dot[0], dot[1]]
    if rifl is not None:
        ev["rifl"] = [rifl[0], rifl[1]]
    return ev


def offset_event(t_us, pid, peer, offset_us, rtt_us):
    return {
        "k": "off", "pid": pid, "peer": peer, "off": offset_us,
        "rtt": rtt_us, "t": t_us,
    }


def edge_dot(msg: Any):
    """The dot a protocol message's trace edges key on: a single
    ``.dot`` field (MCollect/MCollectAck/MCommit/... across the
    leaderless protocols).  Batched array messages and slot-keyed
    (leader-based) frames carry no single dot — their spans stitch via
    the client edges alone."""
    dot = getattr(msg, "dot", None)
    if isinstance(dot, tuple) and len(dot) == 2:
        return dot
    return None


def _noop() -> "_NoopTracer":
    return NOOP_TRACER


class _NoopTracer:
    """Zero-cost disabled tracer: hook sites guard on ``.enabled`` and
    never build event payloads.  Pickles (and deep-copies) back to the
    module singleton so protocol state holding it stays picklable (the
    model checker pickles whole protocol instances)."""

    enabled = False
    sample_rate = 0.0

    def sample(self, rifl) -> bool:
        return False

    def span(self, stage, rifl, dot=None, pid=None, cid=None, meta=None) -> None:
        pass

    def counter(self, name, value, pid=None, meta=None) -> None:
        pass

    def edge(self, io, mtype, src, dst, seq, dot=None, rifl=None) -> None:
        pass

    def offset(self, pid, peer, offset_us, rtt_us) -> None:
        pass

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass

    def __reduce__(self):
        return (_noop, ())


NOOP_TRACER = _NoopTracer()


class Tracer:
    """Lock-light span emitter over a monotonic time source.

    ``time`` is any :class:`fantoch_tpu.core.timing.SysTime` — the sim
    passes its virtual clock, the run layer its wall clock — so emission
    sites never thread timestamps through.  Writes are buffered complete
    lines; ``flush()`` is cheap and the run layer calls it periodically
    (crash consistency = the on-disk prefix is always parseable).
    """

    enabled = True

    def __init__(self, time, path: str, sample_rate: float = 1.0,
                 flush_every: int = 512, clock: str = "virtual"):
        assert clock in ("virtual", "wall"), clock
        self._time = time
        self.path = path
        self.clock = clock
        self.sample_rate = max(0.0, min(1.0, float(sample_rate)))
        self._threshold = int(self.sample_rate * _SAMPLE_SPACE)
        self._fh = open(path, "w", buffering=1 << 16)
        self._flush_every = flush_every
        self._pending = 0
        self._closed = False
        # one header line names the clock domain: "wall" logs need the
        # correlator's offset resolution before cross-process math,
        # "virtual" logs share one clock by construction
        self._write({"k": "hdr", "clock": clock, "v": 1})

    # --- sampling ---

    def sample(self, rifl) -> bool:
        """Deterministic verdict for a span id (a Rifl or any
        (source, sequence) pair)."""
        return span_hash(rifl[0], rifl[1]) < self._threshold

    # --- emission ---

    def span(
        self,
        stage: str,
        rifl,
        dot=None,
        pid: Optional[int] = None,
        cid: Optional[int] = None,
        meta: Optional[Dict[str, Any]] = None,
    ) -> None:
        if span_hash(rifl[0], rifl[1]) >= self._threshold:
            return
        self._write(
            span_event(
                self._time.micros(), stage, rifl,
                dot=dot, pid=pid, cid=cid, meta=meta,
            )
        )

    def counter(
        self,
        name: str,
        value,
        pid: Optional[int] = None,
        meta: Optional[Dict[str, Any]] = None,
    ) -> None:
        self._write(
            counter_event(self._time.micros(), name, value, pid=pid, meta=meta)
        )

    def edge(
        self,
        io: str,
        mtype: str,
        src: int,
        dst: int,
        seq: int,
        dot=None,
        rifl=None,
    ) -> None:
        """One side of a cross-process message hop (``io`` = ``"s"`` at
        the sender, ``"r"`` at the receiver), paired by ``(src, seq)``.
        Sampled by the rifl when given (client<->server hops), else by
        the dot (peer protocol messages) — both through the same hash,
        so a rate-1.0 trace stitches every span."""
        key = rifl if rifl is not None else dot
        if key is None or span_hash(key[0], key[1]) >= self._threshold:
            return
        self._write(
            edge_event(
                self._time.micros(), io, mtype, src, dst, seq,
                dot=dot, rifl=rifl,
            )
        )

    def offset(self, pid: int, peer: int, offset_us: int, rtt_us: int) -> None:
        """A per-peer clock-offset estimate (peer clock minus ``pid``'s,
        microseconds) with the probe RTT that bounds its error — emitted
        whenever a better (lower-RTT) heartbeat sample lands."""
        self._write(
            offset_event(self._time.micros(), pid, peer, offset_us, rtt_us)
        )

    def _write(self, ev: Dict[str, Any]) -> None:
        if self._closed:
            return
        # sorted keys + compact separators: same-seed sim traces must be
        # byte-identical, so serialization is fully canonical
        self._fh.write(json.dumps(ev, sort_keys=True, separators=(",", ":")))
        self._fh.write("\n")
        self._pending += 1
        if self._pending >= self._flush_every:
            self.flush()

    def flush(self) -> None:
        if not self._closed:
            self._fh.flush()
            self._pending = 0

    def close(self) -> None:
        if not self._closed:
            self._fh.flush()
            self._fh.close()
            self._closed = True


def read_trace(path: str) -> List[Dict[str, Any]]:
    """Read a JSONL span log; a truncated final line (crash mid-write) is
    dropped, everything before it is returned."""
    out: List[Dict[str, Any]] = []
    with open(path, "r") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                break  # torn tail — the crash-consistent prefix ends here
    return out
