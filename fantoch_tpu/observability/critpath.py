"""Causal critical-path attribution over a stitched multi-process trace.

The span reports (observability/report.py) telescope one coordinator's
timeline; this module answers the next question — *what was each
command actually waiting on* — by stitching spans causally across
processes (Dapper-style, via the ``k == "edge"`` message events the
tracer now emits) and walking each span's DAG backwards from the
client-observed reply:

- ``submit -> payload`` splits into client→coordinator network flight
  (the ``Submit`` ingress edge) and coordinator ingest queueing;
- ``payload -> path`` is the quorum wait: the *blocking* edge is the
  latest ack delivered at the coordinator before the fast/slow
  decision, and it names WHICH peer was slowest, decomposed into
  outbound network / remote turnaround / return network via the
  matching request edge;
- ``commit -> ready`` is the dependency wait: the committed-deps stamp
  on the commit span names WHICH dot the executor was blocked on (the
  dependency whose own commit landed last at the coordinator);
- ``executed -> reply`` splits into result emit and coordinator→client
  network flight (the ``Reply`` edge).

Every attribution vector is built ON the span's stage segments, so the
entries telescope *exactly* to ``reply - submit`` — the blame report
explains the latency histogram, it never approximates it.

Clocks: sim traces share one virtual clock (``hdr.clock == "virtual"``)
and need no correction.  Run-layer traces stamp per-process wall
clocks; cross-process math first resolves per-peer offsets from the
heartbeat RTT samples the run layer emits (``k == "off"``,
run/links.ClockOffsetEstimator — best = lowest-RTT sample, NTP-style
error bound rtt/2), and client↔coordinator offsets from the spans'
own request/reply brackets (min-RTT over the trace).  Flight-recorder
dumps (observability/recorder.py) re-enter through the very same
correlator via ``flight_events``.
"""

from __future__ import annotations

from typing import Any, Dict, Iterable, List, Optional, Tuple

from fantoch_tpu.core.metrics import Histogram
from fantoch_tpu.observability.report import (
    assemble_spans,
    counters_total,
    span_segments,
)

SpanKey = Tuple[int, int]

# client-plane hop names (edges paired by rifl, not (src, seq))
INGRESS = "Submit"
REPLY = "Reply"


# --- edge + offset collection ---


def wall_clock(events: Iterable[Dict[str, Any]]) -> bool:
    """True when any contributing log stamped wall-clock time (run
    layer): cross-process math then needs offset resolution."""
    return any(
        ev.get("k") == "hdr" and ev.get("clock") == "wall" for ev in events
    )


def match_edges(
    events: Iterable[Dict[str, Any]],
) -> Tuple[Dict[Tuple[int, int], List[Dict[str, Any]]], Dict[Tuple[SpanKey, str], Dict[str, Any]]]:
    """Pair send/recv edge events.

    Returns ``(dot_edges, client_edges)``: per-dot lists of matched
    peer hops ``{"mt", "src", "dst", "seq", "ts", "tr"}`` (``ts`` =
    send time on the sender's clock, ``tr`` = receive time on the
    receiver's; either may be None for a half-observed hop), and the
    earliest client-plane edge per ``(rifl, kind)`` (the other half of
    a client hop is the client's own submit/reply span event).

    Hops pair on ``(src, seq, dst, dot)``: the run layer allocates one
    seq per broadcast (dst disambiguates the fan-out; the frame still
    serializes once), and including the dot refuses to pair halves
    from different commands even if seq spaces ever collide (e.g. a
    peer's log retaining a previous incarnation's edges).  Duplicate
    deliveries (nemesis dup, reconnect resend) keep the EARLIEST
    receive — the first delivery is what unblocks the receiver."""
    sends: Dict[Tuple, Dict[str, Any]] = {}
    recvs: Dict[Tuple, Dict[str, Any]] = {}
    client: Dict[Tuple[SpanKey, str], Dict[str, Any]] = {}
    for ev in events:
        if ev.get("k") != "edge":
            continue
        if "rifl" in ev and ev["mt"] in (INGRESS, REPLY):
            key = (tuple(ev["rifl"]), ev["mt"])
            kept = client.get(key)
            if kept is None or ev["t"] < kept["t"]:
                client[key] = ev
            continue
        if "dot" not in ev:
            continue
        pair_key = (ev["src"], ev["seq"], ev["dst"], tuple(ev["dot"]))
        if ev["io"] == "s":
            sends.setdefault(pair_key, ev)
        else:
            kept = recvs.get(pair_key)
            if kept is None or ev["t"] < kept["t"]:
                recvs[pair_key] = ev
    dot_edges: Dict[Tuple[int, int], List[Dict[str, Any]]] = {}
    for pair_key in sends.keys() | recvs.keys():
        ev = sends.get(pair_key) or recvs[pair_key]
        recv = recvs.get(pair_key)
        send = sends.get(pair_key)
        dot_edges.setdefault(tuple(ev["dot"]), []).append({
            "mt": ev["mt"],
            "src": ev["src"],
            "dst": ev["dst"],
            "seq": ev["seq"],
            "ts": send["t"] if send is not None else None,
            "tr": recv["t"] if recv is not None else None,
        })
    return dot_edges, client


class OffsetTable:
    """Pairwise clock-offset resolution.  ``best[(p, q)]`` holds the
    lowest-RTT ``(rtt_us, off_us)`` sample where ``off ≈ q's clock -
    p's clock`` as estimated BY ``p``.  ``shift(frm, to)`` returns the
    additive correction that moves a timestamp stamped on ``frm``'s
    clock into ``to``'s frame (0 in the virtual-clock domain, or when
    no sample exists for the pair)."""

    def __init__(self, events: Iterable[Dict[str, Any]], wall: bool):
        self.wall = wall
        self.best: Dict[Tuple[int, int], Tuple[int, int]] = {}
        for ev in events:
            if ev.get("k") != "off":
                continue
            key = (ev["pid"], ev["peer"])
            kept = self.best.get(key)
            if kept is None or ev["rtt"] < kept[0]:
                self.best[key] = (ev["rtt"], ev["off"])

    def shift(self, frm: Optional[int], to: Optional[int]) -> int:
        if not self.wall or frm == to or frm is None or to is None:
            return 0
        # direct: `to` measured frm's clock as off = frm_clock - to_clock
        direct = self.best.get((to, frm))
        if direct is not None:
            return -direct[1]
        reverse = self.best.get((frm, to))
        if reverse is not None:
            return reverse[1]
        return 0

    def rows(self) -> List[Dict[str, Any]]:
        return [
            {"pid": pid, "peer": peer, "offset_us": off, "rtt_us": rtt}
            for (pid, peer), (rtt, off) in sorted(self.best.items())
        ]


def estimate_client_offsets(
    spans: Dict[SpanKey, Dict[str, Any]],
    client_edges: Dict[Tuple[SpanKey, str], Dict[str, Any]],
    wall: bool,
) -> Dict[Tuple[int, int], int]:
    """Client-plane → coordinator clock offsets, one per (client id,
    coordinator pid) pair, from the spans' own request/reply brackets:
    for each span with all four stamps (submit t0 / ingress t1 /
    reply-send t2 / reply t3) the NTP estimate is ``off = ((t1-t0) -
    (t3-t2)) / 2`` with error bounded by the bracket RTT — keep the
    lowest-RTT sample per pair.  Keyed per CLIENT, not just per
    coordinator: distinct client processes (distinct machines) have
    distinct clocks, and one client's tight bracket must not correct
    another's timestamps.  Zero in the virtual-clock domain."""
    if not wall:
        return {}
    best: Dict[Tuple[int, int], Tuple[int, int]] = {}
    for rifl, span in spans.items():
        pid = span["pid"]
        stages = span["stages"]
        ingress = client_edges.get((rifl, INGRESS))
        reply_send = client_edges.get((rifl, REPLY))
        if (
            pid is None
            or ingress is None
            or reply_send is None
            or "submit" not in stages
            or "reply" not in stages
        ):
            continue
        t0, t3 = stages["submit"], stages["reply"]
        t1, t2 = ingress["t"], reply_send["t"]
        rtt = (t3 - t0) - (t2 - t1)
        if rtt < 0:
            continue
        off = ((t1 - t0) - (t3 - t2)) // 2
        key = (rifl[0], pid)
        kept = best.get(key)
        if kept is None or rtt < kept[0]:
            best[key] = (rtt, off)
    return {key: off for key, (_rtt, off) in best.items()}


def commit_times(
    events: Iterable[Dict[str, Any]],
) -> Dict[Tuple[Tuple[int, int], int], int]:
    """Earliest observed ``commit`` stamp per (dot, pid) — the
    dependency-wait walk asks when each dep became committed AT the
    blocked span's coordinator."""
    out: Dict[Tuple[Tuple[int, int], int], int] = {}
    for ev in events:
        if ev.get("k") != "span" or ev.get("stage") != "commit":
            continue
        dot = ev.get("dot")
        pid = ev.get("pid")
        if dot is None or pid is None:
            continue
        key = (tuple(dot), pid)
        if key not in out or ev["t"] < out[key]:
            out[key] = ev["t"]
    return out


# --- per-span attribution ---


def _clamp(value: float, lo: float, hi: float) -> float:
    return max(lo, min(value, hi))


def attribute_span(
    span: Dict[str, Any],
    dot_edges: Dict[Tuple[int, int], List[Dict[str, Any]]],
    client_edges: Dict[Tuple[SpanKey, str], Dict[str, Any]],
    offsets: OffsetTable,
    client_offsets: Dict[int, int],
    commit_at: Dict[Tuple[Tuple[int, int], int], int],
) -> Dict[str, Any]:
    """One command's attribution vector.

    ``stages`` are the span's own telescoping segments (their sum IS
    ``reply - submit`` whenever both endpoints exist — exact by
    construction); ``blame`` decorates them with the blocking cause
    resolved from the edge DAG: the client/coordinator network splits,
    the slowest-quorum-member decomposition, the blocking dependency
    dot, and the out-of-chain recovery detour when one occurred."""
    rifl = span["rifl"]
    dot = span["dot"]
    pid = span["pid"]
    stages = span["stages"]
    segs = span_segments(span)
    vector: Dict[str, Any] = {
        "rifl": list(rifl),
        "dot": list(dot) if dot is not None else None,
        "pid": pid,
        "stages": {name: tb - ta for name, ta, tb in segs},
    }
    total = (
        stages["reply"] - stages["submit"]
        if "submit" in stages and "reply" in stages
        else None
    )
    vector["total_us"] = total
    blame: Dict[str, Any] = {}
    off_client = client_offsets.get((rifl[0], pid), 0)

    # submit -> first process stage: network flight vs ingest queue
    ingress = client_edges.get((rifl, INGRESS))
    first_seg = segs[0] if segs else None
    if ingress is not None and first_seg is not None and first_seg[0].startswith("submit->"):
        seg_us = first_seg[2] - first_seg[1]
        net = _clamp(ingress["t"] - (stages["submit"] + off_client), 0, seg_us)
        blame["client_net_us"] = int(net)
        blame["coord_queue_us"] = int(seg_us - net)

    # payload -> ingest: the adaptive batcher's hold (run/ingest.py) —
    # an explicit bucket, already a stage segment so it telescopes by
    # construction (the deadline budget is attributed, not hidden)
    if "payload" in stages and "ingest" in stages:
        blame["ingest_batching_us"] = int(
            _clamp(stages["ingest"] - stages["payload"], 0, float("inf"))
        )

    # payload -> path: the quorum wait and its slowest member
    if dot is not None and pid is not None and "path" in stages:
        edges = dot_edges.get(tuple(dot), ())
        acks = [
            e for e in edges
            if e["dst"] == pid and e["tr"] is not None and e["tr"] <= stages["path"]
        ]
        if acks:
            blocking = max(acks, key=lambda e: e["tr"])
            peer = blocking["src"]
            # the quorum wait starts when the round left ingest (the
            # batching hold has its own bucket above); payload is the
            # pre-batching fallback
            start = stages.get("ingest", stages.get("payload"))
            if start is None and "submit" in stages:
                # payload stamp lost (a restart truncates the
                # coordinator's log): submit is on the CLIENT clock —
                # shift it into the coordinator's domain first
                start = stages["submit"] + off_client
            quorum: Dict[str, Any] = {
                "pid": peer,
                "mt": blocking["mt"],
                "wait_us": (
                    int(_clamp(blocking["tr"] - start, 0, float("inf")))
                    if start is not None else None
                ),
            }
            # decompose via the matching outbound request hop
            request = min(
                (
                    e for e in edges
                    if e["src"] == pid and e["dst"] == peer and e["ts"] is not None
                ),
                key=lambda e: e["ts"],
                default=None,
            )
            shift = offsets.shift(peer, pid)
            if blocking["ts"] is not None:
                remote_send = blocking["ts"] + shift
                quorum["back_net_us"] = int(
                    _clamp(blocking["tr"] - remote_send, 0, float("inf"))
                )
                if request is not None and request["tr"] is not None:
                    remote_recv = request["tr"] + shift
                    quorum["out_net_us"] = int(
                        _clamp(remote_recv - request["ts"], 0, float("inf"))
                    )
                    quorum["remote_us"] = int(
                        _clamp(remote_send - remote_recv, 0, float("inf"))
                    )
            blame["quorum"] = quorum

    # commit -> ready: the blocking dependency
    deps = span["meta"].get("commit", {}).get("deps")
    if deps and pid is not None and "commit" in stages and "ready" in stages:
        observed = [
            (commit_at[key], list(dep))
            for dep in deps
            if (key := (tuple(dep), pid)) in commit_at
        ]
        if observed:
            t_dep, dep = max(observed)
            blame["dep"] = {
                "dot": dep,
                "commit_us": t_dep,
                "wait_us": int(
                    _clamp(
                        t_dep - stages["commit"], 0,
                        stages["ready"] - stages["commit"],
                    )
                ),
            }

    # executed -> reply: result emit vs return network flight
    reply_send = client_edges.get((rifl, REPLY))
    last_seg = segs[-1] if segs else None
    if reply_send is not None and last_seg is not None and last_seg[0].endswith("->reply"):
        seg_us = last_seg[2] - last_seg[1]
        net = _clamp(
            (stages["reply"] + off_client) - reply_send["t"], 0, seg_us
        )
        blame["reply_net_us"] = int(net)
        blame["emit_us"] = int(seg_us - net)

    # out-of-chain recovery detour: name it when the dot took one
    if "recovery" in stages:
        ref = stages.get("commit", stages.get("reply"))
        blame["recovery"] = {
            "entered_us": stages["recovery"],
            "to_commit_us": (
                int(ref - stages["recovery"]) if ref is not None else None
            ),
        }

    vector["blame"] = blame
    vector["stitched"] = _is_stitched(span, blame, ingress, reply_send)
    return vector


def _is_stitched(span, blame, ingress, reply_send) -> bool:
    """A span counts as *stitched* when every cross-process transition
    it exhibits was resolved from edges: the client hops both matched,
    and — for dotted spans that record a fast/slow decision — the
    blocking quorum ack was found.  Process-only spans (no client
    endpoints, e.g. an abandoned command) never count."""
    stages = span["stages"]
    if "submit" not in stages or "reply" not in stages:
        return False
    if ingress is None or reply_send is None:
        return False
    if span["dot"] is not None and "path" in stages and "quorum" not in blame:
        return False
    return True


# --- the blame report ---


def critpath_report(
    events: List[Dict[str, Any]],
    percentile: float = 0.99,
    exemplars: int = 3,
) -> Dict[str, Any]:
    """Assemble spans + edges + offsets and reduce to the p99 blame
    payload: stitch coverage, per-segment totals, the tail cohort's
    mean attribution per stage, the per-peer quorum-blame and
    network/skew tables, and the worst exemplar vectors."""
    wall = wall_clock(events)
    spans = assemble_spans(events)
    dot_edges, client_edges = match_edges(events)
    offsets = OffsetTable(events, wall)
    client_offsets = estimate_client_offsets(spans, client_edges, wall)
    commit_at = commit_times(events)
    vectors = [
        attribute_span(
            span, dot_edges, client_edges, offsets, client_offsets, commit_at
        )
        for span in spans.values()
    ]
    complete = [v for v in vectors if v["total_us"] is not None]
    stitched = [v for v in complete if v["stitched"]]
    # exactness audit: stage segments must telescope to reply - submit
    telescoping_violations = sum(
        1 for v in complete if sum(v["stages"].values()) != v["total_us"]
    )
    e2e = Histogram()
    for v in complete:
        e2e.increment(v["total_us"])
    threshold = e2e.percentile(percentile) if complete else 0
    cohort = [v for v in complete if v["total_us"] >= threshold]

    def _stage_means(vecs: List[Dict[str, Any]]) -> Dict[str, int]:
        sums: Dict[str, int] = {}
        counts: Dict[str, int] = {}
        for v in vecs:
            for name, us in v["stages"].items():
                sums[name] = sums.get(name, 0) + us
                counts[name] = counts.get(name, 0) + 1
        return {
            name: sums[name] // counts[name] for name in sums
        }

    def _quorum_table(vecs: List[Dict[str, Any]]) -> Dict[int, Dict[str, Any]]:
        table: Dict[int, Dict[str, Any]] = {}
        for v in vecs:
            quorum = v["blame"].get("quorum")
            if quorum is None or quorum.get("wait_us") is None:
                continue
            row = table.setdefault(
                quorum["pid"],
                {"count": 0, "wait_us": 0, "net_us": 0, "remote_us": 0},
            )
            row["count"] += 1
            row["wait_us"] += quorum["wait_us"]
            row["net_us"] += quorum.get("out_net_us", 0) + quorum.get(
                "back_net_us", 0
            )
            row["remote_us"] += quorum.get("remote_us", 0)
        for row in table.values():
            for key in ("wait_us", "net_us", "remote_us"):
                row[f"mean_{key}"] = row.pop(key) // max(1, row["count"])
        return table

    def _ingest_row(vecs: List[Dict[str, Any]]) -> Dict[str, int]:
        waits = [
            v["blame"]["ingest_batching_us"]
            for v in vecs
            if "ingest_batching_us" in v["blame"]
        ]
        return {
            "spans": len(waits),
            "mean_us": sum(waits) // len(waits) if waits else 0,
            "max_us": max(waits) if waits else 0,
        }

    p99_means = _stage_means(cohort)
    dominant = max(p99_means.items(), key=lambda kv: kv[1])[0] if p99_means else None
    counters = counters_total(events)
    device = {
        name: value
        for name, value in counters.items()
        if name.startswith("device_") or name.endswith(
            ("_dispatches", "_kernel_ms", "_resident_uploads",
             "_failovers", "_rebuilds", "_degraded_ms", "_plane_health")
        )
    }
    recoveries = sum(1 for v in complete if "recovery" in v["blame"])
    degraded = _degraded_serving_row(counters)
    report: Dict[str, Any] = {
        "clock": "wall" if wall else "virtual",
        "spans": len(complete),
        "stitched": len(stitched),
        "stitch_rate": (
            round(len(stitched) / len(complete), 4) if complete else 0.0
        ),
        "telescoping_violations": telescoping_violations,
        "end_to_end_p99_us": threshold,
        "stage_means_us": _stage_means(complete),
        "p99": {
            "threshold_us": threshold,
            "count": len(cohort),
            "stage_means_us": p99_means,
            "dominant_stage": dominant,
        },
        "quorum_blame": _quorum_table(complete),
        "p99_quorum_blame": _quorum_table(cohort),
        # the adaptive batcher's hold, as an explicit bucket (exact:
        # each entry is the span's payload->ingest stage segment)
        "ingest_batching": _ingest_row(complete),
        "p99_ingest_batching": _ingest_row(cohort),
        "recovered_spans": recoveries,
        # accelerator degraded-serving blame: wall spent serving from
        # the host twin after a device failover (per plane), so a tail
        # dominated by twin-speed serving is named instead of smeared
        # across the stage segments it inflates
        "degraded_serving": degraded,
        "peers": offsets.rows(),
        # string-keyed for JSON: one estimate per (client, coordinator)
        "client_offsets_us": {
            f"c{cid}->p{pid}": off
            for (cid, pid), off in sorted(client_offsets.items())
        },
        "exemplars": sorted(
            cohort, key=lambda v: -(v["total_us"] or 0)
        )[:exemplars],
    }
    if device:
        report["device"] = device
    return report


def _degraded_serving_row(counters: Dict[str, float]) -> Dict[str, Any]:
    """The degraded-serving blame bucket: per-plane host-twin serving
    wall (``*_plane_degraded_ms``) plus failover/rebuild tallies from
    the trace's counter events.  Empty planes dict when no plane ever
    degraded — the common case costs one dict scan."""
    planes: Dict[str, Dict[str, float]] = {}
    for name, value in counters.items():
        for suffix in ("_degraded_ms", "_failovers", "_rebuilds"):
            if name.endswith(f"_plane{suffix}"):
                plane = name[: -len(f"_plane{suffix}")]
                planes.setdefault(
                    plane, {"degraded_ms": 0.0, "failovers": 0, "rebuilds": 0}
                )[suffix[1:]] = value
    planes = {
        plane: row
        for plane, row in planes.items()
        if row["failovers"] or row["degraded_ms"]
    }
    return {
        "planes": planes,
        "degraded_ms": round(
            sum(row["degraded_ms"] for row in planes.values()), 3
        ),
        "failovers": int(sum(row["failovers"] for row in planes.values())),
        "rebuilds": int(sum(row["rebuilds"] for row in planes.values())),
    }


def dominant_quorum_peer(report: Dict[str, Any], tail: bool = True) -> Optional[int]:
    """The peer contributing the most TOTAL quorum wait (count x mean;
    tail cohort by default) — what the SlowProcess/delayed-link
    assertions key on.  Total wait, not blame count: a topology where
    one peer sits in most fast quorums is blamed often for small waits,
    and the deliberately slowed peer must still dominate."""
    table = report["p99_quorum_blame" if tail else "quorum_blame"]
    if not table:
        return None
    return max(
        table.items(),
        key=lambda kv: (kv[1]["count"] * kv[1]["mean_wait_us"], -kv[0]),
    )[0]
