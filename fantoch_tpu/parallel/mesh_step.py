"""Multi-chip SPMD protocol step: replica x batch sharding over a device mesh.

The reference scales by (1) geo-replication — n processes each running the
protocol state machine (fantoch/src/protocol/base.rs) — and (2) per-key /
per-dot sharding inside each process (fantoch/src/run/pool.rs:115-124).
The TPU-native equivalents are two mesh axes:

  * ``replica`` — each mesh slice along this axis holds one (or a block of)
    replica's protocol state: its key-clock table (the analog of
    ``KeyDeps``, fantoch_ps/src/protocol/common/graph/deps/keys/sequential.rs)
    and its executed frontier.  Quorum aggregation (the MCollectAck fan-in,
    fantoch_ps/src/protocol/epaxos.rs:305-370) becomes ``pmax``/``pmin``
    collectives along this axis — riding ICI instead of TCP.
  * ``batch`` — commands of one round are sharded along this axis; per-key
    conflict detection is local work + one ``all_gather`` (commands are
    tiny: a key bucket and a dot), and the dependency-graph resolution
    (fantoch_ps/src/executor/graph/tarjan.rs) runs batched via
    :mod:`fantoch_tpu.ops.graph_resolve`.

One :func:`protocol_step` is the analog of delivering a full
MCollect -> MCollectAck -> MCommit -> execute round for B commands on all
replicas at once:

  1. per-replica dependency computation (scatter/gather over the replica's
     key-clock shard) — each replica reports the latest conflicting command
     it knows (``KeyDeps::add_cmd``);
  2. fast-path check: EPaxos commits on the fast path iff *all* fast-quorum
     replicas report identical deps (epaxos.rs:339-345) — here
     ``pmax == pmin`` along ``replica``;
  3. final deps = union = elementwise max along ``replica`` (with
     latest-per-key sequential deps, union of singletons is the max dot);
  4. batched SCC/topological resolution of the committed batch
     (ops/graph_resolve.resolve_functional), shared across the ``batch``
     axis via one small all_gather;
  5. state update: scatter-max the new dots into every replica's key-clock
     and advance the executed frontier.

All state stays device-resident across steps (donated), so the host only
feeds command batches and drains execution orders.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

shard_map = jax.shard_map

from fantoch_tpu.ops.graph_resolve import TERMINAL, resolve_functional

REPLICA_AXIS = "replica"
BATCH_AXIS = "batch"


class ReplicaState(NamedTuple):
    """Per-replica device-resident protocol state.

    ``key_clock[R, K]``: global id (see below) of the latest committed
    command per key bucket, per replica; -1 when none.  The analog of the
    per-process sequential ``KeyDeps`` map.

    ``frontier[R]``: number of commands this replica has committed+executed
    (the AEClock frontier of fantoch/src/protocol/gc.rs, collapsed to a
    counter in this dense batched regime where execution is in rounds).
    """

    key_clock: jax.Array  # int32[R, K]
    frontier: jax.Array  # int32[R]
    next_gid: jax.Array  # int32[] — global id of the next batch's first cmd


class StepOutput(NamedTuple):
    order: jax.Array  # int32[B] execution order (batch indices)
    resolved: jax.Array  # bool[B]
    fast_path: jax.Array  # bool[B] — committed on the fast path
    deps_gid: jax.Array  # int32[B] — final dependency (global id, -1 none)


def make_mesh(n_devices: int | None = None) -> Mesh:
    """Factor the device list into a (replica, batch) mesh.

    Replica axis gets the smaller factor (real deployments have 3..11
    replicas; batches are wide).
    """
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    replica = 1
    for cand in range(min(n, 8), 0, -1):
        if n % cand == 0 and cand <= n // cand:
            replica = cand
            break
    import numpy as np

    dev_array = np.array(devices).reshape(replica, n // replica)
    return Mesh(dev_array, (REPLICA_AXIS, BATCH_AXIS))


def init_state(mesh: Mesh, num_replicas: int, key_buckets: int = 4096) -> ReplicaState:
    """Device-resident initial state, sharded over the replica axis."""
    sharding = NamedSharding(mesh, P(REPLICA_AXIS, None))
    key_clock = jax.device_put(
        jnp.full((num_replicas, key_buckets), -1, dtype=jnp.int32), sharding
    )
    frontier = jax.device_put(
        jnp.zeros((num_replicas,), dtype=jnp.int32),
        NamedSharding(mesh, P(REPLICA_AXIS)),
    )
    next_gid = jax.device_put(jnp.int32(0), NamedSharding(mesh, P()))
    return ReplicaState(key_clock, frontier, next_gid)


def _intra_batch_chain(key: jax.Array) -> jax.Array:
    """dep_in_batch[i] = latest j < i with key[j] == key[i], else -1.

    Stable-sort by key, then each element's predecessor within its key run
    is its intra-batch dependency — the tensorized ``KeyDeps::add_cmd``
    latest-per-key chain for commands of the same round.
    """
    batch = key.shape[0]
    idx = jnp.arange(batch, dtype=jnp.int32)
    perm = jnp.argsort(key, stable=True).astype(jnp.int32)
    sorted_key = key[perm]
    prev_same = jnp.where(
        (idx > 0) & (sorted_key == jnp.roll(sorted_key, 1)),
        jnp.roll(perm, 1),
        jnp.int32(TERMINAL),
    )
    return jnp.zeros((batch,), jnp.int32).at[perm].set(prev_same)


def protocol_step(
    state: ReplicaState,
    key: jax.Array,  # int32[B] key buckets, replicated
    dot_src: jax.Array,  # int32[B]
    dot_seq: jax.Array,  # int32[B]
    *,
    mesh: Mesh,
) -> Tuple[ReplicaState, StepOutput]:
    """One batched commit+execute round over the (replica, batch) mesh."""
    num_replicas, key_buckets = state.key_clock.shape
    batch = key.shape[0]

    def step(key_clock, frontier, next_gid, key_l, dot_src_l, dot_seq_l):
        # local blocks: key_clock [r_blk, K], key_l [b_blk] (sharded batch)
        # 1. full batch view of the keys (commands are tiny; one gather)
        key_full = jax.lax.all_gather(key_l, BATCH_AXIS, tiled=True)  # [B]
        dot_src_f = jax.lax.all_gather(dot_src_l, BATCH_AXIS, tiled=True)
        dot_seq_f = jax.lax.all_gather(dot_seq_l, BATCH_AXIS, tiled=True)

        gid = next_gid + jnp.arange(batch, dtype=jnp.int32)  # global ids

        # 2. per-replica deps: intra-batch chain, else the replica's
        # key-clock entry (KeyDeps::add_cmd per replica)
        chain = _intra_batch_chain(key_full)  # [B] batch index or -1
        prior = key_clock[:, key_full]  # [r_blk, B] global id or -1
        dep_gid = jnp.where(
            chain >= 0, gid[jnp.maximum(chain, 0)], prior
        )  # [r_blk, B]

        # 3. quorum aggregation along the replica axis (the MCollectAck
        # fan-in): fast path iff all replicas reported the same dep.
        dep_max = jax.lax.pmax(dep_gid.max(axis=0), REPLICA_AXIS)  # [B]
        dep_min = jax.lax.pmin(dep_gid.min(axis=0), REPLICA_AXIS)  # [B]
        fast = dep_max == dep_min
        final_gid = dep_max  # union of latest-per-key singletons = max

        # 4. batched resolution of the committed round (all deps are within
        # this batch or already executed, so prune pre-batch deps).
        dep_idx = jnp.where(
            final_gid >= next_gid, final_gid - next_gid, jnp.int32(TERMINAL)
        )
        res = resolve_functional(dep_idx, dot_src_f, dot_seq_f)

        # 5. state update: every replica learns the committed dots
        # (scatter-max by key; later commands in the batch win)
        new_clock = key_clock.at[:, key_full].max(gid[None, :])
        new_frontier = frontier + res.resolved.sum().astype(jnp.int32)
        return (
            new_clock,
            new_frontier,
            next_gid + batch,
            res.order,
            res.resolved,
            fast,
            final_gid,
        )

    specs_in = (
        P(REPLICA_AXIS, None),  # key_clock
        P(REPLICA_AXIS),  # frontier
        P(),  # next_gid
        P(BATCH_AXIS),  # key
        P(BATCH_AXIS),  # dot_src
        P(BATCH_AXIS),  # dot_seq
    )
    specs_out = (
        P(REPLICA_AXIS, None),
        P(REPLICA_AXIS),
        P(),
        P(),  # order (replicated full-batch)
        P(),
        P(),
        P(),
    )
    # check_vma=False: outputs derived from all_gather/pmax results are
    # replicated by construction, but the static VMA analysis cannot see
    # through the gather+argsort chain.
    fn = shard_map(
        step, mesh=mesh, in_specs=specs_in, out_specs=specs_out, check_vma=False
    )
    new_clock, new_frontier, new_gid, order, resolved, fast, deps = fn(
        state.key_clock, state.frontier, state.next_gid, key, dot_src, dot_seq
    )
    return (
        ReplicaState(new_clock, new_frontier, new_gid),
        StepOutput(order, resolved, fast, deps),
    )


def jit_protocol_step(mesh: Mesh):
    """jit-compiled step with donated device-resident state."""
    import functools

    return jax.jit(
        functools.partial(protocol_step, mesh=mesh), donate_argnums=(0,)
    )
