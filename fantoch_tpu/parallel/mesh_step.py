"""Multi-chip SPMD protocol step: replica x batch sharding over a device mesh.

The reference scales by (1) geo-replication — n processes each running the
protocol state machine (fantoch/src/protocol/base.rs) — and (2) per-key /
per-dot sharding inside each process (fantoch/src/run/pool.rs:115-124).
The TPU-native equivalents are two mesh axes:

  * ``replica`` — each mesh slice along this axis holds one (or a block of)
    replica's protocol state: its key-clock table (the analog of
    ``KeyDeps``, fantoch_ps/src/protocol/common/graph/deps/keys/sequential.rs)
    and its executed frontier.  Quorum aggregation (the MCollectAck fan-in,
    fantoch_ps/src/protocol/epaxos.rs:305-370) becomes ``pmax``/``pmin``
    collectives along this axis — riding ICI instead of TCP.
  * ``batch`` — commands of one round are sharded along this axis; per-key
    conflict detection is local work + one ``all_gather`` (commands are
    tiny: a key bucket and a dot), and the dependency-graph resolution
    (fantoch_ps/src/executor/graph/tarjan.rs) runs batched via
    :mod:`fantoch_tpu.ops.graph_resolve`.

One :func:`protocol_step` is the analog of delivering a full
MCollect -> MCollectAck -> [MConsensus -> MConsensusAck] -> MCommit ->
execute round for B commands on all replicas at once:

  1. per-replica dependency computation (scatter/gather over the replica's
     key-clock shard) — each replica reports the latest conflicting command
     it knows (``KeyDeps::add_cmd``);
  2. fast-path check over the **fast quorum only** (the first
     ``fast_quorum_size`` replicas, mirroring the distance-sorted quorum of
     fantoch/src/protocol/base.rs:59-131): EPaxos commits on the fast path
     iff all fast-quorum replicas report identical deps (epaxos.rs:339-345)
     — here a masked ``pmax == pmin`` along ``replica``;
  3. slow path (Synod accept round, fantoch_ps/src/protocol/common/synod/
     single.rs): for fast-path misses the coordinator proposes the *union*
     of fast-quorum deps (= masked max over singletons) at ballot 0 via the
     skip-prepare trick (single.rs:86); replica accept indicators are
     counted with a ``psum`` along ``replica`` and the command commits once
     ``acks >= write_quorum_size`` (f + 1);
  4. batched SCC/topological resolution of the committed batch
     (ops/graph_resolve.resolve_functional), shared across the ``batch``
     axis via one small all_gather;
  5. state update: scatter-max the committed dots into every replica's
     key-clock, advance the executed frontier, and compute the GC stability
     watermark = ``pmin`` of all replicas' frontiers (the AEClock meet of
     fantoch/src/protocol/gc.rs:72-116, collapsed to a counter in this
     dense round-based regime).

All state stays device-resident across steps (donated), so the host only
feeds command batches and drains execution orders.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# jax >= 0.5 exposes shard_map at top level; 0.4.x keeps it experimental
# and spells the replication-check kwarg check_rep instead of check_vma
shard_map = getattr(jax, "shard_map", None)
if shard_map is None:
    from jax.experimental.shard_map import shard_map as _shard_map_experimental

    def shard_map(f, *, check_vma=None, **kwargs):
        if check_vma is not None:
            kwargs["check_rep"] = check_vma
        return _shard_map_experimental(f, **kwargs)

from fantoch_tpu.ops.graph_resolve import (
    MISSING,
    TERMINAL,
    resolve_functional,
    resolve_general,
)

REPLICA_AXIS = "replica"
BATCH_AXIS = "batch"
KEY_PAD = -1  # empty key slot in a [.., KW] key matrix


class ReplicaState(NamedTuple):
    """Per-replica device-resident protocol state.

    ``key_clock[R, K]``: global id (see below) of the latest committed
    command per key bucket, per replica; -1 when none.  The analog of the
    per-process sequential ``KeyDeps`` map.

    ``frontier[R]``: number of commands this replica has committed+executed
    (the AEClock frontier of fantoch/src/protocol/gc.rs, collapsed to a
    counter in this dense batched regime where execution is in rounds).

    ``pend_*[Pcap]``: the device-resident pending buffer — commands a
    previous round could not execute (failed Synod quorum, or blocked
    behind one) carry into the next round instead of being dropped
    (VERDICT r2 weak #4 liveness fix).  Slot empty iff ``pend_gid == -1``;
    replicated across the mesh (pending commands are global protocol
    state, like the reference's per-dot info store awaiting commit).

    ``pend_key`` is ``int32[Pcap, KW]``: commands carry up to KW key
    buckets (multi-key commands, command.rs:12-19), padded with KEY_PAD.
    """

    key_clock: jax.Array  # int32[R, K]
    frontier: jax.Array  # int32[R]
    next_gid: jax.Array  # int32[] — global id of the next batch's first cmd
    pend_key: jax.Array  # int32[Pcap, KW]
    pend_src: jax.Array  # int32[Pcap]
    pend_seq: jax.Array  # int32[Pcap]
    pend_gid: jax.Array  # int32[Pcap] (-1 = empty slot)


class StepOutput(NamedTuple):
    """Per-round outputs over the W = Pcap + B working rows (pending
    buffer first, then the new batch; a working row's command is
    identified by ``gids``)."""

    order: jax.Array  # int32[W] execution order (working-row indices)
    resolved: jax.Array  # bool[W] — executed this round
    fast_path: jax.Array  # bool[W] — committed on the fast path
    deps_gid: jax.Array  # int32[W, KW] — final deps (global ids, -1 none)
    gids: jax.Array  # int32[W] — global id per working row (-1 = empty)
    slow_paths: jax.Array  # int32[] — commands that took the Synod round
    stable: jax.Array  # int32[] — GC watermark: min executed frontier
    pending: jax.Array  # int32[] — commands carried to the next round
    pend_dropped: jax.Array  # int32[] — overflow beyond the pending capacity


def quorum_sizes(num_replicas: int) -> Tuple[int, int]:
    """(fast_quorum_size, write_quorum_size) for EPaxos with minority f.

    Delegates to the shared protocol-fact formula
    (Config.epaxos_quorum_sizes; EPaxos ignores config.f)."""
    from fantoch_tpu.core.config import Config

    return Config(num_replicas, 0).epaxos_quorum_sizes()


def shard_of_row(row: int, num_replicas_total: int, shard_count: int) -> int:
    """Owning shard of a replica row — the row-order contract tests pin.

    Replica rows are **shard-major**: shard ``s`` owns the contiguous
    block ``[s*n, (s+1)*n)`` of the ``num_replicas_total = n * shard_count``
    rows (protocol_step computes ``row // per_shard`` on-device; this is
    the host-side mirror).  Host placement that wants a shard's quorum
    fan-in on ICI must therefore map whole *blocks* — not strided rows —
    onto one host (parallel/multihost.py validates exactly that).
    """
    assert num_replicas_total % shard_count == 0
    return row // (num_replicas_total // shard_count)


def make_mesh(
    n_devices: int | None = None, num_replicas: int | None = None
) -> Mesh:
    """Factor the device list into a (replica, batch) mesh.

    Replica axis gets the smaller factor (real deployments have 3..11
    replicas; batches are wide).  When ``num_replicas`` is given, the
    replica axis must divide it (each device slice holds a whole number of
    replica blocks — init_state's sharding contract).
    """
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    n = len(devices)
    replica = 1
    for cand in range(min(n, 8), 0, -1):
        if (
            n % cand == 0
            and cand <= n // cand
            and (num_replicas is None or num_replicas % cand == 0)
        ):
            replica = cand
            break
    import numpy as np

    dev_array = np.array(devices).reshape(replica, n // replica)
    return Mesh(dev_array, (REPLICA_AXIS, BATCH_AXIS))


def init_state(
    mesh: Mesh,
    num_replicas: int,
    key_buckets: int = 4096,
    pending_capacity: int = 256,
    key_width: int = 1,
) -> ReplicaState:
    """Device-resident initial state, sharded over the replica axis.

    ``key_width``: max key buckets per command (multi-key commands route
    through the general resolver on-mesh)."""
    sharding = NamedSharding(mesh, P(REPLICA_AXIS, None))
    key_clock = jax.device_put(
        jnp.full((num_replicas, key_buckets), -1, dtype=jnp.int32), sharding
    )
    frontier = jax.device_put(
        jnp.zeros((num_replicas,), dtype=jnp.int32),
        NamedSharding(mesh, P(REPLICA_AXIS)),
    )
    rep = NamedSharding(mesh, P())
    next_gid = jax.device_put(jnp.int32(0), rep)

    def empty(shape):  # distinct buffers: donated state must not alias
        return jax.device_put(jnp.full(shape, -1, dtype=jnp.int32), rep)

    cap = pending_capacity
    return ReplicaState(
        key_clock, frontier, next_gid,
        empty((cap, key_width)), empty((cap,)), empty((cap,)), empty((cap,)),
    )


def _intra_batch_chain(keys: jax.Array) -> jax.Array:
    """chain[i, w] = latest row j < i sharing key keys[i, w], else -1.

    Stable-sort the flattened (row-major) key slots, then each slot's
    predecessor within its key run is the latest earlier slot of the same
    key — the tensorized ``KeyDeps::add_cmd`` latest-per-key chain for
    commands of the same round, one dependency slot per key.  Rows must
    not repeat a key (commands hold distinct keys), so an in-run
    predecessor is always an earlier row.
    """
    batch, kw = keys.shape
    flat = keys.reshape(-1)
    n = flat.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    perm = jnp.argsort(flat, stable=True).astype(jnp.int32)
    sorted_key = flat[perm]
    prev_same = jnp.where(
        (idx > 0) & (sorted_key == jnp.roll(sorted_key, 1)),
        jnp.roll(perm, 1) // kw,  # predecessor's row
        jnp.int32(TERMINAL),
    )
    return jnp.zeros((n,), jnp.int32).at[perm].set(prev_same).reshape(batch, kw)


def protocol_step(
    state: ReplicaState,
    key: jax.Array,  # int32[B] or int32[B, KW] key buckets, replicated
    dot_src: jax.Array,  # int32[B]
    dot_seq: jax.Array,  # int32[B]
    *,
    mesh: Mesh,
    live_replicas: int | None = None,
    shard_count: int = 1,
) -> Tuple[ReplicaState, StepOutput]:
    """One batched commit+execute round over the (replica, batch) mesh.

    ``key`` may carry up to KW distinct key buckets per command (KEY_PAD
    pads unused slots); multi-key rounds resolve through the general
    out-degree-KW resolver (ops/graph_resolve.resolve_general), whose
    arrival-order fast path covers the clean-commit case and whose
    iterative pass handles quorum-failure MISSING blocking.

    ``live_replicas``: replicas (global rows) < this count respond to the
    Synod accept round; the rest are crashed/partitioned for the round.
    With fewer than write_quorum live replicas, slow-path commands do NOT
    commit this round (and neither does anything depending on them).
    Default: all replicas live.

    ``shard_count`` (partial replication, the mesh-native answer to
    fantoch_ps/src/protocol/partial.rs + the cross-shard dep requests of
    fantoch_ps/src/executor/graph/mod.rs:279-408): the replica rows
    factor into ``shard_count`` shards of ``R / shard_count`` replicas
    each, and key bucket ``b`` belongs to shard ``b % shard_count``.
    Quorums are per shard *per key slot* — a multi-shard command commits
    only when every touched shard's quorum agrees — and a replica's key
    clock learns only its own shard's buckets.  Cross-shard dependencies
    need no request RPCs at all: the working set is globally visible on
    the mesh, so the resolver orders a multi-shard command after ALL its
    deps (both shards') in the same gather it uses for one shard.
    """
    num_replicas, key_buckets = state.key_clock.shape
    if key.ndim == 1:
        key = key[:, None]
    batch, key_width = key.shape
    assert key_width == state.pend_key.shape[1], (
        "key width must match init_state(key_width=...)"
    )
    pend_cap = state.pend_gid.shape[0]
    work = pend_cap + batch  # working rows: pending buffer first, then new
    assert num_replicas % shard_count == 0, (
        "replica rows must factor into shard_count equal shards"
    )
    per_shard = num_replicas // shard_count
    fast_quorum, write_quorum = quorum_sizes(per_shard)
    if live_replicas is None:
        live_replicas = num_replicas
    replica_blocks = num_replicas // mesh.shape[REPLICA_AXIS]
    int_min = jnp.iinfo(jnp.int32).min
    int_max = jnp.iinfo(jnp.int32).max

    def step(
        key_clock, frontier, next_gid, pend_key, pend_src, pend_seq, pend_gid,
        key_l, dot_src_l, dot_seq_l,
    ):
        # local blocks: key_clock [r_blk, K], key_l [b_blk, KW] (sharded
        # batch).  1. full batch view of the keys (commands are tiny; one
        # gather), prefixed with the carried pending buffer (older commands
        # first so intra-batch chains point the right way)
        key_new = jax.lax.all_gather(key_l, BATCH_AXIS, tiled=True)  # [B, KW]
        src_new = jax.lax.all_gather(dot_src_l, BATCH_AXIS, tiled=True)
        seq_new = jax.lax.all_gather(dot_seq_l, BATCH_AXIS, tiled=True)

        widx = jnp.arange(work, dtype=jnp.int32)
        gid = jnp.concatenate(
            [pend_gid, next_gid + jnp.arange(batch, dtype=jnp.int32)]
        )  # [W]
        valid = gid >= 0  # empty pending slots are invalid rows
        key_cat = jnp.concatenate([pend_key, key_new], axis=0)  # [W, KW]
        real_slot = valid[:, None] & (key_cat != KEY_PAD)  # [W, KW]
        # pad slots and invalid rows get unique out-of-range keys:
        # singleton runs, no chain links, no key-clock read
        slot_iota = jnp.arange(work * key_width, dtype=jnp.int32).reshape(
            work, key_width
        )
        key_full = jnp.where(real_slot, key_cat, key_buckets + slot_iota)
        dot_src_f = jnp.where(valid, jnp.concatenate([pend_src, src_new]), 0)
        dot_seq_f = jnp.where(valid, jnp.concatenate([pend_seq, seq_new]), 0)

        # 2. per-replica deps, one slot per key: intra-working-batch chain,
        # else the replica's key-clock entry (KeyDeps::add_cmd per replica)
        chain = _intra_batch_chain(key_full)  # [W, KW] working row or -1
        safe_key = jnp.minimum(key_full, key_buckets - 1)
        prior = jnp.where(real_slot[None], key_clock[:, safe_key], -1)
        dep_gid = jnp.where(
            chain >= 0, gid[jnp.maximum(chain, 0)], prior
        )  # [r_blk, W, KW]

        # 3. MCollectAck fan-in over each key slot's *shard* fast quorum =
        # the first fast_quorum member rows of the shard owning the slot's
        # bucket (distance-sorted quorum, base.rs:59-131; bucket b belongs
        # to shard b % shard_count).  Fast path iff every quorum replica
        # reported the same deps on every key slot (check_union,
        # epaxos.rs:339-345) — for a multi-shard command that is every
        # touched shard's quorum at once.  Pad slots have no real bucket:
        # their dep is -1 on every replica, so any shard's quorum agrees.
        row = (
            jax.lax.axis_index(REPLICA_AXIS) * replica_blocks
            + jnp.arange(replica_blocks, dtype=jnp.int32)
        )  # global replica row ids of this block
        slot_shard = jnp.where(
            real_slot, key_cat % shard_count, 0
        )  # [W, KW]
        row_shard = (row // per_shard)[:, None, None]  # [r_blk, 1, 1]
        row_member = (row % per_shard)[:, None, None]
        in_fq = (row_shard == slot_shard[None]) & (
            row_member < fast_quorum
        )  # [r_blk, W, KW]
        fq_max = jax.lax.pmax(
            jnp.where(in_fq, dep_gid, int_min).max(axis=0), REPLICA_AXIS
        )  # [W, KW]
        fq_min = jax.lax.pmin(
            jnp.where(in_fq, dep_gid, int_max).min(axis=0), REPLICA_AXIS
        )  # [W, KW]
        fast = (fq_max == fq_min).all(axis=-1) & valid
        # slow-path proposal: union of fast-quorum deps (= per-slot max
        # over latest-per-key singletons), Synod ballot 0 / skip-prepare
        # (synod single.rs:86) — same value either way, so the committed
        # deps are fq_max; what the slow path adds is the accept round.
        final_gid = fq_max  # [W, KW]

        # Synod accept round for fast-path misses: every *live* replica
        # of a slot's shard accepts the ballot-0 proposal (no competing
        # coordinator within a round; crashed replicas don't respond);
        # acks are a per-shard psum and a command commits once EVERY
        # touched shard reaches write_quorum (f+1).  This is the
        # MConsensusAck fan-in (+ the per-shard aggregation of
        # partial.rs:37-142, collapsed into the same round).
        live = (row < live_replicas)[:, None]  # [r_blk, 1]
        shard_live_local = jnp.zeros((shard_count,), jnp.int32).at[
            row // per_shard
        ].add(live[:, 0].astype(jnp.int32))
        shard_live = jax.lax.psum(shard_live_local, REPLICA_AXIS)  # [S]
        acks_slot = shard_live[slot_shard]  # [W, KW]
        slow_ok = jnp.where(
            real_slot, acks_slot >= write_quorum, True
        ).all(axis=-1)
        committed = (fast | slow_ok) & valid
        slow_paths = ((~fast) & valid).sum().astype(jnp.int32)

        # 4. batched resolution of the committed working set.  A final dep
        # is either a working row (pending gids included — matched via a
        # sorted-gid searchsorted join) or already executed (pruned to
        # TERMINAL).  Uncommitted commands are MISSING: they stay
        # unresolved and so does everything dependency-chained to them.
        masked_gid = jnp.where(valid, gid, int_max)
        sort_row = jnp.argsort(masked_gid).astype(jnp.int32)
        sort_gid = masked_gid[sort_row]
        j = jnp.clip(
            jnp.searchsorted(sort_gid, jnp.maximum(final_gid, 0)), 0, work - 1
        )  # [W, KW]
        in_work = (final_gid >= 0) & (sort_gid[j] == final_gid)
        dep_idx = jnp.where(in_work, sort_row[j], jnp.int32(TERMINAL))
        dep_idx = jnp.where(committed[:, None], dep_idx, jnp.int32(MISSING))
        dep_idx = jnp.where(valid[:, None], dep_idx, jnp.int32(TERMINAL))
        if key_width == 1:
            # exact O(log W) doubling: resolves every non-missing-blocked
            # row regardless of chain depth
            res = resolve_functional(dep_idx[:, 0], dot_src_f, dot_seq_f)
        else:
            # general resolver; max_iters = 2*W+8 guarantees convergence
            # for committed acyclic rows (>= one vertex finalizes per
            # iteration) and the while_loop's changed-flag exits early on
            # the typical round, so degraded rounds cannot strand
            # committed commands past the pending buffer
            res = resolve_general(
                dep_idx, dot_src_f, dot_seq_f, max_iters=2 * work + 8
            )
        executed = res.resolved & committed

        # 5. state update: every *live* replica learns the *executed* dots
        # on the buckets of ITS OWN shard (scatter-max by key slot; later
        # commands in the batch win) — a shard's replicas never store
        # other shards' key state (partial replication).  Only executed
        # gids enter the key clock: the next round prunes
        # out-of-working-set deps as already-executed (step 4), which is
        # only sound if the clock never holds an unexecuted gid.
        own_slot = row_shard == slot_shard[None]  # [r_blk, W, KW]
        clock_upd = jnp.where(
            live[..., None]
            & own_slot
            & (executed[None, :, None] & real_slot[None]),
            gid[None, :, None],
            jnp.int32(-1),
        )  # [r_blk, W, KW]
        new_clock = key_clock.at[:, safe_key].max(clock_upd)
        new_frontier = frontier + jnp.where(
            live[:, 0], executed.sum().astype(jnp.int32), 0
        )
        # GC stability watermark: the meet of all replicas' executed
        # frontiers (gc.rs stable()), here a pmin over the replica axis.
        stable = jax.lax.pmin(new_frontier.min(), REPLICA_AXIS)

        # 6. pending carry (the liveness fix): valid-but-unexecuted rows
        # survive into the next round's buffer, oldest first; overflow
        # beyond the capacity is dropped *loudly* (pend_dropped).
        carry = valid & ~executed
        # stable sort: carried rows first, in working order
        carry_order = jnp.argsort(jnp.where(carry, widx, int_max)).astype(jnp.int32)
        take = carry_order[:pend_cap]
        is_carry = carry[take]
        new_pend_gid = jnp.where(is_carry, gid[take], -1)
        new_pend_key = jnp.where(is_carry[:, None], key_cat[take], KEY_PAD)
        new_pend_src = jnp.where(is_carry, dot_src_f[take], -1)
        new_pend_seq = jnp.where(is_carry, dot_seq_f[take], -1)
        pending = carry.sum().astype(jnp.int32)
        pend_dropped = jnp.maximum(pending - pend_cap, 0).astype(jnp.int32)

        return (
            new_clock,
            new_frontier,
            next_gid + batch,
            new_pend_key,
            new_pend_src,
            new_pend_seq,
            new_pend_gid,
            res.order,
            executed,
            fast,
            jnp.where(real_slot, final_gid, -1),
            jnp.where(valid, gid, -1),
            slow_paths,
            stable,
            jnp.minimum(pending, pend_cap),
            pend_dropped,
        )

    specs_in = (
        P(REPLICA_AXIS, None),  # key_clock
        P(REPLICA_AXIS),  # frontier
        P(),  # next_gid
        P(),  # pend_key
        P(),  # pend_src
        P(),  # pend_seq
        P(),  # pend_gid
        P(BATCH_AXIS),  # key
        P(BATCH_AXIS),  # dot_src
        P(BATCH_AXIS),  # dot_seq
    )
    specs_out = (
        P(REPLICA_AXIS, None),
        P(REPLICA_AXIS),
        P(),
        P(),  # pend_key
        P(),  # pend_src
        P(),  # pend_seq
        P(),  # pend_gid
        P(),  # order (replicated full working set)
        P(),
        P(),
        P(),  # deps_gid
        P(),  # gids
        P(),  # slow_paths
        P(),  # stable
        P(),  # pending
        P(),  # pend_dropped
    )
    # check_vma=False: outputs derived from all_gather/pmax results are
    # replicated by construction, but the static VMA analysis cannot see
    # through the gather+argsort chain.
    fn = shard_map(
        step, mesh=mesh, in_specs=specs_in, out_specs=specs_out, check_vma=False
    )
    (
        new_clock, new_frontier, new_gid,
        new_pend_key, new_pend_src, new_pend_seq, new_pend_gid,
        order, executed, fast, deps, gids, slow, stable, pending, dropped,
    ) = fn(
        state.key_clock, state.frontier, state.next_gid,
        state.pend_key, state.pend_src, state.pend_seq, state.pend_gid,
        key, dot_src, dot_seq,
    )
    return (
        ReplicaState(
            new_clock, new_frontier, new_gid,
            new_pend_key, new_pend_src, new_pend_seq, new_pend_gid,
        ),
        StepOutput(
            order, executed, fast, deps, gids, slow, stable, pending, dropped
        ),
    )


def jit_protocol_step(
    mesh: Mesh, live_replicas: int | None = None, shard_count: int = 1
):
    """jit-compiled step with donated device-resident state."""
    import functools

    return jax.jit(
        functools.partial(
            protocol_step,
            mesh=mesh,
            live_replicas=live_replicas,
            shard_count=shard_count,
        ),
        donate_argnums=(0,),
    )


# ---------------------------------------------------------------------------
# Newt/Tempo on the mesh: timestamp consensus + stability
# ---------------------------------------------------------------------------


class NewtMeshState(NamedTuple):
    """Device-resident Newt replica state over the mesh.

    ``key_clock[R, K]``: per-replica timestamp clock per key bucket (the
    SequentialKeyClocks map, fantoch_ps/src/protocol/common/table/clocks/
    keys/sequential.rs:9-105).  ``vote_frontier[R, K]``: per-replica
    contiguous vote frontier per key (the RangeEventSet frontier of the
    VotesTable, collapsed to a watermark in this dense round-based regime
    where votes are always consumed contiguously).

    Pending buffer: commands a previous round could not *execute* —
    either uncommitted (degraded quorum; ``pend_clock == -1``) or
    committed-but-unstable (their timestamp above the stability
    watermark; ``pend_clock`` holds the committed clock).  Slot empty iff
    ``pend_key == KEY_PAD``.
    """

    key_clock: jax.Array  # int32[R, K]
    vote_frontier: jax.Array  # int32[R, K]
    pend_key: jax.Array  # int32[Pcap, KW] (KEY_PAD = empty slot/row)
    pend_src: jax.Array  # int32[Pcap]
    pend_seq: jax.Array  # int32[Pcap]
    pend_clock: jax.Array  # int32[Pcap] (-1 = not committed)


class NewtStepOutput(NamedTuple):
    """Outputs over the W = Pcap + B working rows (pending first)."""

    order: jax.Array  # int32[W] — stable rows first, (clock, dot) sorted
    executed: jax.Array  # bool[W] — committed AND stable this round
    committed: jax.Array  # bool[W]
    fast_path: jax.Array  # bool[W]
    clock: jax.Array  # int32[W] — committed timestamp (-1 uncommitted)
    slow_paths: jax.Array  # int32[]
    stable_watermark: jax.Array  # int32[] — min stable clock over keys seen
    pending: jax.Array  # int32[]
    pend_dropped: jax.Array  # int32[]
    # working-row dot identity (pending buffer + this round's batch):
    # the drivers key their registries on these, so a drain never needs a
    # host-side mirror of the device pending buffer — which is what lets
    # a dispatched round be drained later (dispatch/drain pipelining)
    work_src: jax.Array  # int32[W]
    work_seq: jax.Array  # int32[W]


def newt_quorum_sizes(
    num_replicas: int, f: int, tiny_quorums: bool = False
) -> Tuple[int, int, int]:
    """(fast_quorum, write_quorum, stability_threshold) — the shared
    protocol-fact formula (Config.newt_quorum_sizes, newt.rs:90-100)."""
    from fantoch_tpu.core.config import Config

    return Config(
        num_replicas, f, newt_tiny_quorums=tiny_quorums
    ).newt_quorum_sizes()


def init_newt_state(
    mesh: Mesh,
    num_replicas: int,
    key_buckets: int = 4096,
    pending_capacity: int = 256,
    key_width: int = 1,
) -> NewtMeshState:
    sharding = NamedSharding(mesh, P(REPLICA_AXIS, None))
    zeros_rk = jax.device_put(
        jnp.zeros((num_replicas, key_buckets), dtype=jnp.int32), sharding
    )
    rep = NamedSharding(mesh, P())
    cap = pending_capacity

    def pend(shape, value):
        return jax.device_put(jnp.full(shape, value, dtype=jnp.int32), rep)

    return NewtMeshState(
        zeros_rk,
        jax.device_put(jnp.zeros((num_replicas, key_buckets), jnp.int32), sharding),
        pend((cap, key_width), KEY_PAD),
        pend((cap,), -1), pend((cap,), -1), pend((cap,), -1),
    )


def _segmented_proposal(prior_of_row, key_full, work):
    """Per-replica batched clock proposal over the working set: same-key
    rows receive consecutive clocks continuing from the replica's prior —
    the tensorized ``SequentialKeyClocks::proposal`` over one round,
    built on the same segmented max-scan core as the device votes-table
    plane (ops/table_ops.segmented_running_max).

    ``prior_of_row``: int32[r_blk, W] — the proposing replica's current
    clock for each row's key.  Returns proposals of the same shape.
    """
    from fantoch_tpu.ops.table_ops import segmented_running_max

    widx = jnp.arange(work, dtype=jnp.int32)
    perm = jnp.argsort(key_full, stable=True).astype(jnp.int32)
    k_sorted = key_full[perm]
    seg_start = jnp.concatenate(
        [jnp.ones((1,), bool), k_sorted[1:] != k_sorted[:-1]]
    )
    seg_id = jnp.cumsum(seg_start.astype(jnp.int32)) - 1
    group_first = jax.lax.associative_scan(
        jnp.maximum, jnp.where(seg_start, widx, 0)
    )
    rank = widx - group_first

    base = prior_of_row[:, perm] + 1  # [r_blk, W] in sorted order
    running = segmented_running_max(seg_id, base - rank, axis=-1)
    clock_sorted = rank + running
    return jnp.zeros_like(base).at[:, perm].set(clock_sorted)


def newt_protocol_step(
    state: NewtMeshState,
    key: jax.Array,  # int32[B] or int32[B, KW] key buckets (KEY_PAD pads)
    dot_src: jax.Array,  # int32[B]
    dot_seq: jax.Array,  # int32[B]
    *,
    mesh: Mesh,
    f: int = 1,
    tiny_quorums: bool = False,
    live_replicas: int | None = None,
    shard_count: int = 1,
) -> Tuple[NewtMeshState, NewtStepOutput]:
    """One batched Newt round: timestamp proposal, max aggregation over
    the fast quorum, count-of-max fast path, Synod accept for misses, and
    stability-ordered execution (newt.rs:272-338 + 527-546; stability =
    fantoch_ps/src/executor/table/mod.rs:247-270).

    Collective layout: proposals are per-replica local work on the
    key-clock shard; the commit clock is a ``pmax`` over the fast quorum;
    the fast-path count-of-max and the Synod ack count are ``psum``s; the
    per-key stable clock is an order statistic over an ``all_gather`` of
    the vote frontiers along ``replica``.

    Multi-key commands (KW > 1): each key slot proposes within its key's
    run independently and the row's proposal is the max over its slots —
    within one round two conflicting commands may therefore tie, breaking
    by dot in the (clock, dot) sort id (the host twin's strictly
    sequential within-round clocks are a refinement; across rounds the
    committed clock still strictly dominates every key it touched).  A
    command executes when its clock is stable on EVERY key it touches.

    ``shard_count`` (partial replication, mirroring the sharded epaxos
    round above and the reference's MShardCommit clock aggregation —
    fantoch_ps/src/protocol/partial.rs + newt.rs mcollect_actions): the
    replica rows factor into ``shard_count`` shards of
    ``R / shard_count`` each; key bucket ``b`` belongs to shard
    ``b % shard_count``; quorums (fast count-of-max, Synod acks) and the
    stability order statistic are per shard *per key slot*; a
    multi-shard command's commit clock is the max over its slots'
    shard-local commit clocks and it executes only when that clock is
    stable on every key it touches (each key judged by its own shard's
    frontiers).  A replica's key-clock/frontier learn only its own
    shard's buckets.
    """
    num_replicas, key_buckets = state.key_clock.shape
    if key.ndim == 1:
        key = key[:, None]
    batch, key_width = key.shape
    assert key_width == state.pend_key.shape[1], (
        "key width must match init_newt_state(key_width=...)"
    )
    pend_cap = state.pend_key.shape[0]
    work = pend_cap + batch
    assert num_replicas % shard_count == 0, (
        "replica rows must factor into shard_count equal shards"
    )
    per_shard = num_replicas // shard_count
    fast_quorum, write_quorum, stability_threshold = newt_quorum_sizes(
        per_shard, f, tiny_quorums
    )
    if live_replicas is None:
        live_replicas = num_replicas
    replica_blocks = num_replicas // mesh.shape[REPLICA_AXIS]
    int_min = jnp.iinfo(jnp.int32).min

    def step(
        key_clock, vote_frontier, pend_key, pend_src, pend_seq, pend_clock,
        key_l, src_l, seq_l,
    ):
        key_new = jax.lax.all_gather(key_l, BATCH_AXIS, tiled=True)  # [B, KW]
        src_new = jax.lax.all_gather(src_l, BATCH_AXIS, tiled=True)
        seq_new = jax.lax.all_gather(seq_l, BATCH_AXIS, tiled=True)

        widx = jnp.arange(work, dtype=jnp.int32)
        key_cat = jnp.concatenate([pend_key, key_new], axis=0)  # [W, KW]
        valid = (key_cat != KEY_PAD).any(axis=-1)
        src_f = jnp.where(valid, jnp.concatenate([pend_src, src_new]), 0)
        seq_f = jnp.where(valid, jnp.concatenate([pend_seq, seq_new]), 0)
        prior_clock = jnp.concatenate(
            [pend_clock, jnp.full((batch,), -1, jnp.int32)]
        )  # committed clock carried from earlier rounds, -1 = none
        already_committed = prior_clock >= 0

        # pad slots / already-committed rows must not consume proposals:
        # private out-of-range keys make them singleton runs
        propose = valid & ~already_committed
        real_slot = valid[:, None] & (key_cat != KEY_PAD)  # [W, KW]
        propose_slot = propose[:, None] & real_slot
        slot_iota = jnp.arange(work * key_width, dtype=jnp.int32).reshape(
            work, key_width
        )
        key_full = jnp.where(propose_slot, key_cat, key_buckets + slot_iota)
        safe_key = jnp.minimum(key_full, key_buckets - 1)  # [W, KW]

        # shard geometry: bucket b belongs to shard b % shard_count; a
        # replica row r is member (r % per_shard) of shard (r // per_shard)
        row = (
            jax.lax.axis_index(REPLICA_AXIS) * replica_blocks
            + jnp.arange(replica_blocks, dtype=jnp.int32)
        )
        slot_shard = jnp.where(real_slot, key_cat % shard_count, 0)  # [W, KW]
        row_shard = (row // per_shard)[:, None, None]  # [r_blk, 1, 1]
        own_slot = row_shard == slot_shard[None]  # [r_blk, W, KW]

        # per-replica-block per-slot proposals over the flattened slots
        # (only the owning shard's replicas read their key clock; other
        # replicas' lanes compute masked-out garbage)
        prior_rows = jnp.where(
            propose_slot[None] & own_slot, key_clock[:, safe_key], 0
        )  # [r_blk, W, KW]
        slot_prop = _segmented_proposal(
            prior_rows.reshape(replica_blocks, work * key_width),
            key_full.reshape(work * key_width),
            work * key_width,
        ).reshape(replica_blocks, work, key_width)

        # MCollectAck aggregation: a replica's proposal for a row is ONE
        # clock per shard it owns — the max over the row's slots in that
        # shard (the reference's proposal is per command, newt.rs:272-338)
        # — aggregated over that shard's fast quorum (its first
        # fast_quorum member rows).  Fast path iff EVERY touched shard's
        # max was reported by >= f of its quorum members (newt.rs:527-546
        # via QuorumClocks max_count; the multi-shard fast path needs
        # every touched shard fast).  For shard_count == 1 this is
        # exactly the row-level aggregation of the unsharded round, for
        # every key width.
        shard_ids = jnp.arange(shard_count, dtype=jnp.int32)
        slot_onehot = (
            propose_slot[:, :, None] & (slot_shard[:, :, None] == shard_ids)
        )  # [W, KW, S]
        touched = slot_onehot.any(axis=1)  # [W, S]
        shard_prop = jnp.where(
            slot_onehot[None], slot_prop[..., None], int_min
        ).max(axis=2)  # [r_blk, W, S] — this replica's per-shard row clock
        rep_shard = (row // per_shard)[:, None] == shard_ids[None]  # [r_blk, S]
        in_fq_rs = (
            ((row % per_shard) < fast_quorum)[:, None] & rep_shard
        )[:, None, :]  # [r_blk, 1, S]
        shard_fq_max = jax.lax.pmax(
            jnp.where(in_fq_rs, shard_prop, int_min).max(axis=0), REPLICA_AXIS
        )  # [W, S]
        shard_reports = jax.lax.psum(
            (in_fq_rs & (shard_prop == shard_fq_max[None]))
            .astype(jnp.int32)
            .sum(axis=0),
            REPLICA_AXIS,
        )  # [W, S]
        fast = (
            jnp.where(touched, shard_reports >= f, True).all(axis=-1)
            & propose
        )
        # the commit clock: max over the touched shards' commit clocks
        # (the MShardCommit max aggregation, partial.rs:37-142);
        # propose rows always have >= 1 real slot, others read 0
        fq_max = jnp.where(
            propose,
            jnp.where(touched, shard_fq_max, int_min).max(axis=-1),
            0,
        )  # [W]

        # Synod ballot-0 accept round for fast-path misses: every touched
        # shard must reach write_quorum (f + 1) live acks
        live = (row < live_replicas)[:, None]
        shard_live_local = jnp.zeros((shard_count,), jnp.int32).at[
            row // per_shard
        ].add(live[:, 0].astype(jnp.int32))
        shard_live = jax.lax.psum(shard_live_local, REPLICA_AXIS)  # [S]
        slow_ok = jnp.where(
            propose_slot, shard_live[slot_shard] >= write_quorum, True
        ).all(axis=-1)
        newly_committed = (fast | slow_ok) & propose
        committed = already_committed | newly_committed
        clock = jnp.where(
            newly_committed, fq_max, jnp.where(already_committed, prior_clock, -1)
        )
        slow_paths = (propose & ~fast).sum().astype(jnp.int32)

        # vote/frontier update: each slot's OWNING shard's live replicas
        # chase every committed clock with (detached) votes — scatter-max
        # into both tables over the key slots; other shards' replicas
        # never learn foreign buckets
        upd = jnp.where(
            live[..., None]
            & own_slot
            & (committed[None, :, None] & real_slot[None]),
            clock[None, :, None],
            0,
        )  # [r_blk, W, KW]
        new_key_clock = key_clock.at[:, safe_key].max(
            jnp.where(propose_slot[None], upd, 0)
        )
        # committed carried rows also vote (their key_full is private; use
        # the real key for the frontier scatter)
        real_key = jnp.minimum(
            jnp.where(real_slot, key_cat, 0), key_buckets - 1
        )  # [W, KW]
        new_frontier = vote_frontier.at[:, real_key].max(upd)
        # also reflect proposals consumed by this round in the key clock
        # (live is [r_blk, 1]: broadcasts over the key axis)
        new_key_clock = jnp.where(
            live, jnp.maximum(new_key_clock, new_frontier), new_key_clock
        )

        # stability: per-key (n - threshold)-th smallest frontier across
        # the key's OWNING shard's replicas (mod.rs:247-270; n is the
        # shard size under partial replication) — gather the replica
        # axis, sort within each shard's contiguous row block, then each
        # bucket reads its owner shard's order statistic
        full_frontier = jax.lax.all_gather(
            new_frontier, REPLICA_AXIS, tiled=True
        )  # [R, K]
        shard_stable = jnp.sort(
            full_frontier.reshape(shard_count, per_shard, key_buckets), axis=1
        )[:, per_shard - stability_threshold]  # [S, K]
        bucket_ids = jnp.arange(key_buckets, dtype=jnp.int32)
        stable_clock = shard_stable[bucket_ids % shard_count, bucket_ids]  # [K]
        slot_stable = jnp.where(
            real_slot, clock[:, None] <= stable_clock[real_key], True
        )
        fully_stable = committed & valid & slot_stable.all(axis=-1)
        # per-key holdback (multi-key only matters): a command stable on
        # key A but blocked by its other key must also block every
        # HIGHER-(clock, dot) command on A, or A's timestamp order breaks
        # across rounds (the reference avoids this by executing per-key
        # ops independently; whole-command execution needs the gate).
        # rank = position in the global (clock, dot) order; a key's
        # holdback is the min rank among its committed-but-blocked rows.
        safe_clock = jnp.where(committed & valid, clock, jnp.iinfo(jnp.int32).max)
        order_cd = jnp.lexsort((seq_f, src_f, safe_clock)).astype(jnp.int32)
        rank_of = jnp.zeros((work,), jnp.int32).at[order_cd].set(
            jnp.arange(work, dtype=jnp.int32)
        )
        blocked = committed & valid & ~fully_stable
        hold = jnp.full((key_buckets,), work, jnp.int32).at[real_key].min(
            jnp.where(
                blocked[:, None] & real_slot, rank_of[:, None], jnp.int32(work)
            )
        )
        clear = jnp.where(
            real_slot, rank_of[:, None] < hold[real_key], True
        ).all(axis=-1)
        executed = fully_stable & clear

        # execution order: stable rows by (clock, dot) — the VotesTable
        # sort id (mod.rs:18)
        sort_key = jnp.where(executed, clock, jnp.iinfo(jnp.int32).max)
        order = jnp.lexsort((seq_f, src_f, sort_key)).astype(jnp.int32)

        # pending carry: valid unexecuted rows (uncommitted or unstable).
        # Committed rows take priority — their clocks already entered the
        # key/vote tables, so dropping one would force a re-proposal at a
        # higher clock and break the committed (clock, dot) order; an
        # uncommitted drop merely retries.  Within each class, working
        # order is preserved (stable sort keys).
        carry = valid & ~executed
        work32 = jnp.int32(work)
        carry_rank = jnp.where(
            carry,
            jnp.where(committed, widx, widx + work32),
            jnp.iinfo(jnp.int32).max,
        )
        carry_order = jnp.argsort(carry_rank).astype(jnp.int32)
        take = carry_order[:pend_cap]
        is_carry = carry[take]
        new_pend_key = jnp.where(is_carry[:, None], key_cat[take], KEY_PAD)
        new_pend_src = jnp.where(is_carry, src_f[take], -1)
        new_pend_seq = jnp.where(is_carry, seq_f[take], -1)
        new_pend_clock = jnp.where(is_carry, clock[take], -1)
        pending = carry.sum().astype(jnp.int32)
        pend_dropped = jnp.maximum(pending - pend_cap, 0).astype(jnp.int32)

        seen = jnp.zeros((key_buckets,), bool).at[real_key].max(real_slot)
        watermark = jnp.where(seen, stable_clock, jnp.iinfo(jnp.int32).max).min()

        return (
            new_key_clock, new_frontier,
            new_pend_key, new_pend_src, new_pend_seq, new_pend_clock,
            order, executed, committed, fast & valid, clock,
            slow_paths, watermark,
            jnp.minimum(pending, pend_cap), pend_dropped,
            src_f, seq_f,
        )

    specs_in = (
        P(REPLICA_AXIS, None),  # key_clock
        P(REPLICA_AXIS, None),  # vote_frontier
        P(), P(), P(), P(),  # pending buffer
        P(BATCH_AXIS), P(BATCH_AXIS), P(BATCH_AXIS),
    )
    specs_out = (
        P(REPLICA_AXIS, None),
        P(REPLICA_AXIS, None),
        P(), P(), P(), P(),  # pending buffer
        P(), P(), P(), P(), P(),  # order/executed/committed/fast/clock
        P(), P(), P(), P(),  # slow/watermark/pending/dropped
        P(), P(),  # work identity columns
    )
    fn = shard_map(
        step, mesh=mesh, in_specs=specs_in, out_specs=specs_out, check_vma=False
    )
    (
        kc, vf, pk, ps_, pq, pc,
        order, executed, committed, fast, clock,
        slow, watermark, pending, dropped,
        work_src, work_seq,
    ) = fn(
        state.key_clock, state.vote_frontier,
        state.pend_key, state.pend_src, state.pend_seq, state.pend_clock,
        key, dot_src, dot_seq,
    )
    return (
        NewtMeshState(kc, vf, pk, ps_, pq, pc),
        NewtStepOutput(
            order, executed, committed, fast, clock,
            slow, watermark, pending, dropped,
            work_src, work_seq,
        ),
    )


def jit_newt_step(
    mesh: Mesh,
    f: int = 1,
    tiny_quorums: bool = False,
    live_replicas: int | None = None,
    shard_count: int = 1,
):
    """jit-compiled Newt round with donated device-resident state."""
    import functools

    return jax.jit(
        functools.partial(
            newt_protocol_step,
            mesh=mesh,
            f=f,
            tiny_quorums=tiny_quorums,
            live_replicas=live_replicas,
            shard_count=shard_count,
        ),
        donate_argnums=(0,),
    )


def newt_protocol_multi_step(
    state: NewtMeshState,
    keys: jax.Array,  # int32[S, B] or int32[S, B, KW] — S chained rounds
    dot_srcs: jax.Array,  # int32[S, B]
    dot_seqs: jax.Array,  # int32[S, B]
    *,
    mesh: Mesh,
    f: int = 1,
    tiny_quorums: bool = False,
    live_replicas: int | None = None,
    shard_count: int = 1,
) -> Tuple[NewtMeshState, NewtStepOutput]:
    """S chained Newt rounds in ONE dispatch via ``lax.scan`` — the
    votes-table plane's in-dispatch chaining (ops/table_ops.
    fused_table_rounds) applied to the mesh serving family: replica
    state threads round-to-round on device and the host pays one
    dispatch round-trip for the whole chain, which is what drops
    ``serving_newt_round_ms`` on dispatch-dominated rigs.

    Outputs are the per-round :class:`NewtStepOutput` arrays stacked on a
    leading ``S`` axis; the caller drains all S rounds afterwards (the
    dispatch/drain pipelining contract of ``work_src``/``work_seq``).
    """

    def body(carry, xs):
        key, src, seq = xs
        new_state, out = newt_protocol_step(
            carry, key, src, seq,
            mesh=mesh, f=f, tiny_quorums=tiny_quorums,
            live_replicas=live_replicas, shard_count=shard_count,
        )
        return new_state, out

    return jax.lax.scan(body, state, (keys, dot_srcs, dot_seqs))


def jit_newt_multi_step(
    mesh: Mesh,
    f: int = 1,
    tiny_quorums: bool = False,
    live_replicas: int | None = None,
    shard_count: int = 1,
):
    """jit-compiled multi-round Newt chain with donated state (one
    compile per S shape; S rides the input's leading axis)."""
    import functools

    return jax.jit(
        functools.partial(
            newt_protocol_multi_step,
            mesh=mesh,
            f=f,
            tiny_quorums=tiny_quorums,
            live_replicas=live_replicas,
            shard_count=shard_count,
        ),
        donate_argnums=(0,),
    )


# ---------------------------------------------------------------------------
# leader-based (FPaxos / MultiPaxos) slot round: the third consensus class
# ---------------------------------------------------------------------------


class PaxosMeshState(NamedTuple):
    """Device state for the leader-based slot round.

    ``next_slot``: the leader's next log slot.  ``exec_frontier``: slots
    executed so far (execution is in contiguous slot order — the
    SlotExecutor contract, fantoch_tpu/executor/slot.py).  Pending buffer
    carries accepted-but-uncommitted commands with their slots (a leader
    retries the SAME slot after a failed accept round — MultiPaxos
    slot stickiness, fantoch_tpu/protocol/common/multi_synod.py)."""

    next_slot: jax.Array  # int32[]
    exec_frontier: jax.Array  # int32[] — slots < this executed
    pend_slot: jax.Array  # int32[Pcap] (-1 empty)
    pend_src: jax.Array  # int32[Pcap]
    pend_seq: jax.Array  # int32[Pcap]


class PaxosStepOutput(NamedTuple):
    order: jax.Array  # int32[W] — executed rows in slot order first
    executed: jax.Array  # bool[W]
    committed: jax.Array  # bool[W]
    slot: jax.Array  # int32[W] (-1 = pad row)
    pending: jax.Array  # int32[]
    pend_dropped: jax.Array  # int32[]
    # this round's exec frontier and working-row dot identity (see
    # NewtStepOutput.work_src) — the driver reads the round's own
    # frontier even when a later round has already been dispatched
    exec_frontier: jax.Array  # int32[]
    work_src: jax.Array  # int32[W]
    work_seq: jax.Array  # int32[W]


def init_paxos_state(
    mesh: Mesh, pending_capacity: int = 256
) -> PaxosMeshState:
    rep = NamedSharding(mesh, P())

    def pend(value):
        return jax.device_put(
            jnp.full((pending_capacity,), value, dtype=jnp.int32), rep
        )

    return PaxosMeshState(
        jax.device_put(jnp.int32(0), rep),
        jax.device_put(jnp.int32(0), rep),
        pend(-1), pend(-1), pend(-1),
    )


def paxos_protocol_step(
    state: PaxosMeshState,
    valid: jax.Array,  # bool[B] — real command rows (pads False)
    dot_src: jax.Array,  # int32[B]
    dot_seq: jax.Array,  # int32[B]
    *,
    mesh: Mesh,
    f: int = 1,
    num_replicas: int | None = None,
    live_replicas: int | None = None,
) -> Tuple[PaxosMeshState, PaxosStepOutput]:
    """One leader-based accept round for a batch of commands
    (fantoch_tpu/protocol/fpaxos.py over MultiSynod; quorum = f + 1).

    Replica 0 is the leader: it assigns consecutive slots (pending rows
    keep their previous slots — MultiPaxos slot stickiness) and runs the
    accept round for the whole batch at once — acceptor acks are a
    ``psum`` over the live replicas; a slot commits at f + 1 acks.
    Execution is strictly contiguous in slot order: committed slots above
    a gap (an uncommitted earlier slot) wait in the pending buffer,
    exactly the SlotExecutor semantics.
    """
    if num_replicas is None:
        num_replicas = 2 * mesh.shape[REPLICA_AXIS]
    batch = valid.shape[0]
    pend_cap = state.pend_slot.shape[0]
    work = pend_cap + batch
    quorum = f + 1
    if live_replicas is None:
        live_replicas = num_replicas
    replica_blocks = num_replicas // mesh.shape[REPLICA_AXIS]
    int_max = jnp.iinfo(jnp.int32).max

    def step(
        next_slot, exec_frontier, pend_slot, pend_src, pend_seq,
        valid_l, src_l, seq_l,
    ):
        valid_new = jax.lax.all_gather(valid_l, BATCH_AXIS, tiled=True)
        src_new = jax.lax.all_gather(src_l, BATCH_AXIS, tiled=True)
        seq_new = jax.lax.all_gather(seq_l, BATCH_AXIS, tiled=True)

        widx = jnp.arange(work, dtype=jnp.int32)
        carried = pend_slot >= 0
        valid_cat = jnp.concatenate([carried, valid_new])
        src_f = jnp.concatenate([pend_src, src_new])
        seq_f = jnp.concatenate([pend_seq, seq_new])

        # leader slot assignment: pending rows keep their slots; new valid
        # rows get consecutive slots from next_slot (prefix-sum ranks)
        is_new = jnp.concatenate([jnp.zeros((pend_cap,), bool), valid_new])
        new_rank = jnp.cumsum(is_new.astype(jnp.int32)) - 1
        slot_pend = jnp.concatenate(
            [pend_slot, jnp.full((batch,), -1, jnp.int32)]
        )
        slot = jnp.where(
            slot_pend >= 0,
            slot_pend,
            jnp.where(is_new, next_slot + new_rank, -1),
        )

        # accept round: every live replica acks every proposed slot
        # (ballot-0 leader; crashed replicas stay silent) — the ack count
        # is one scalar psum of live acceptors
        row = (
            jax.lax.axis_index(REPLICA_AXIS) * replica_blocks
            + jnp.arange(replica_blocks, dtype=jnp.int32)
        )
        live = row < live_replicas  # [r_blk]
        acks = jax.lax.psum(live.astype(jnp.int32).sum(), REPLICA_AXIS)
        committed = valid_cat & (slot >= 0) & (acks >= quorum)

        # contiguous slot execution: sort committed slots and count the
        # run that extends exec_frontier without a gap
        sort_slot = jnp.where(committed, slot, int_max)
        order = jnp.argsort(sort_slot).astype(jnp.int32)
        ordered_slots = sort_slot[order]
        pos = jnp.arange(work, dtype=jnp.int32)
        contiguous = ordered_slots == exec_frontier + pos
        # prefix of the sorted committed slots with no gap
        run = jnp.cumprod(contiguous.astype(jnp.int32)) == 1
        executed_sorted = run & (ordered_slots < int_max)
        executed = jnp.zeros((work,), bool).at[order].set(executed_sorted)
        n_exec = executed_sorted.sum().astype(jnp.int32)
        new_frontier = exec_frontier + n_exec

        # pending carry in SLOT order (lowest first): the in-flight slots
        # are exactly [exec_frontier, next_slot), so keeping the lowest
        # pend_cap makes any overflow drop the top slots — which the slot
        # counter then ROLLS BACK, keeping the log dense.  Without the
        # rollback a dropped slot is an un-fillable hole that freezes the
        # contiguous frontier forever (livelock).  Dropped commands are
        # reported via pend_dropped and must be resubmitted by the caller
        # (in this dense round model no acceptor holds durable state for
        # an unexecuted slot, so reassigning it is safe).
        carry = valid_cat & ~executed
        carry_order = jnp.argsort(jnp.where(carry, slot, int_max)).astype(jnp.int32)
        take = carry_order[:pend_cap]
        is_carry = carry[take]
        new_pend_slot = jnp.where(is_carry, slot[take], -1)
        new_pend_src = jnp.where(is_carry, src_f[take], -1)
        new_pend_seq = jnp.where(is_carry, seq_f[take], -1)
        pending = carry.sum().astype(jnp.int32)
        dropped = jnp.maximum(pending - pend_cap, 0).astype(jnp.int32)

        new_next = next_slot + is_new.sum().astype(jnp.int32) - dropped
        return (
            new_next, new_frontier,
            new_pend_slot, new_pend_src, new_pend_seq,
            order, executed, committed, slot,
            jnp.minimum(pending, pend_cap),
            dropped,
            src_f, seq_f,
        )

    specs_in = (
        P(), P(), P(), P(), P(),
        P(BATCH_AXIS), P(BATCH_AXIS), P(BATCH_AXIS),
    )
    specs_out = (P(),) * 13
    fn = shard_map(
        step, mesh=mesh, in_specs=specs_in, out_specs=specs_out, check_vma=False
    )
    (
        next_slot, frontier, ps_, px, pq,
        order, executed, committed, slot, pending, dropped,
        work_src, work_seq,
    ) = fn(
        state.next_slot, state.exec_frontier,
        state.pend_slot, state.pend_src, state.pend_seq,
        valid, dot_src, dot_seq,
    )
    return (
        PaxosMeshState(next_slot, frontier, ps_, px, pq),
        PaxosStepOutput(
            order, executed, committed, slot, pending, dropped,
            frontier, work_src, work_seq,
        ),
    )


def jit_paxos_step(
    mesh: Mesh,
    f: int = 1,
    num_replicas: int | None = None,
    live_replicas: int | None = None,
):
    """jit-compiled leader-based slot round with donated state."""
    import functools

    return jax.jit(
        functools.partial(
            paxos_protocol_step,
            mesh=mesh,
            f=f,
            num_replicas=num_replicas,
            live_replicas=live_replicas,
        ),
        donate_argnums=(0,),
    )


# ---------------------------------------------------------------------------
# Caesar on the mesh: timestamp + predecessors with the wait condition —
# the fourth consensus shape (fantoch_ps/src/protocol/caesar.rs:216-451,
# execution = fantoch_ps/src/executor/pred/mod.rs:132-186)
# ---------------------------------------------------------------------------


class CaesarMeshState(NamedTuple):
    """Device-resident Caesar replica state over the mesh.

    ``key_clock[R, K]``: per-replica highest timestamp known per key
    bucket (the per-key clock index of caesar.rs:786-838, collapsed to a
    max in this dense round regime — predecessors below the executed
    frontier are GC'd, so only the ceiling matters to new proposals).

    Pending buffer: commands a previous round could not execute — either
    uncommitted (``pend_clock == -1``: retry quorum unreachable) or
    committed-but-blocked behind an uncommitted lower-clock conflict
    (the wait condition; ``pend_clock`` holds the committed timestamp).
    """

    key_clock: jax.Array  # int32[R, K]
    pend_key: jax.Array  # int32[Pcap, KW] (KEY_PAD = empty)
    pend_src: jax.Array  # int32[Pcap]
    pend_seq: jax.Array  # int32[Pcap]
    pend_clock: jax.Array  # int32[Pcap] (-1 = not committed)


class CaesarStepOutput(NamedTuple):
    """Outputs over the W = Pcap + B working rows (pending first)."""

    order: jax.Array  # int32[W] — executed rows first, (clock, dot) sorted
    executed: jax.Array  # bool[W]
    committed: jax.Array  # bool[W]
    fast_path: jax.Array  # bool[W]
    clock: jax.Array  # int32[W] — committed timestamp (-1 uncommitted)
    slow_paths: jax.Array  # int32[] — retry (counter-proposal) rounds
    watermark: jax.Array  # int32[] — max executed clock this round
    pending: jax.Array  # int32[]
    pend_dropped: jax.Array  # int32[]
    # working-row dot identity (see NewtStepOutput.work_src)
    work_src: jax.Array  # int32[W]
    work_seq: jax.Array  # int32[W]


def init_caesar_state(
    mesh: Mesh,
    num_replicas: int,
    key_buckets: int = 4096,
    pending_capacity: int = 256,
    key_width: int = 1,
) -> CaesarMeshState:
    sharding = NamedSharding(mesh, P(REPLICA_AXIS, None))
    key_clock = jax.device_put(
        jnp.zeros((num_replicas, key_buckets), dtype=jnp.int32), sharding
    )
    rep = NamedSharding(mesh, P())

    def pend(shape, value):
        return jax.device_put(
            jnp.full(shape, value, dtype=jnp.int32), rep
        )

    cap = pending_capacity
    return CaesarMeshState(
        key_clock,
        pend((cap, key_width), KEY_PAD),
        pend((cap,), -1),
        pend((cap,), -1),
        pend((cap,), -1),
    )


def caesar_protocol_step(
    state: CaesarMeshState,
    key: jax.Array,  # int32[B] or int32[B, KW] key buckets (KEY_PAD pads)
    dot_src: jax.Array,  # int32[B]
    dot_seq: jax.Array,  # int32[B]
    *,
    mesh: Mesh,
    num_replicas: int | None = None,
    live_replicas: int | None = None,
) -> Tuple[CaesarMeshState, CaesarStepOutput]:
    """One batched Caesar round: timestamp proposal, fast-quorum (3n/4+1)
    agreement, the MRetry counter-proposal as a second masked aggregation
    in the same step, and wait-condition-gated execution in (clock, dot)
    order (caesar.rs:216-451).

    Collective layout: proposals are per-replica local work on the
    key-clock shard; fast agreement is ``pmax == pmin`` over the fast
    quorum; the retry clock is a ``pmax`` over the LIVE replicas (the
    aggregated counter-proposal of MProposeAck ok=false) and commits iff
    the live count reaches the write quorum (majority) — a ``psum``.

    Execution models the PredecessorsExecutor's two phases in the dense
    regime: per key bucket, committed rows execute in (clock, dot) order
    up to the first uncommitted conflict (phase 1: a predecessor of
    unknown fate blocks; phase 2: lower-clock predecessors execute
    first); a multi-key row blocked on one bucket holds back every
    higher-(clock, dot) row on its other buckets — the same gate the
    Newt round uses, with commit-ness in place of vote stability.
    """
    R, key_buckets = state.key_clock.shape
    if num_replicas is None:
        num_replicas = R
    if key.ndim == 1:
        key = key[:, None]
    batch, key_width = key.shape
    assert key_width == state.pend_key.shape[1]
    pend_cap = state.pend_key.shape[0]
    work = pend_cap + batch
    from fantoch_tpu.core.config import Config

    fast_quorum, write_quorum = Config(num_replicas, 0).caesar_quorum_sizes()
    if live_replicas is None:
        live_replicas = num_replicas
    replica_blocks = num_replicas // mesh.shape[REPLICA_AXIS]
    int_min = jnp.iinfo(jnp.int32).min
    int_max = jnp.iinfo(jnp.int32).max

    def step(
        key_clock, pend_key, pend_src, pend_seq, pend_clock,
        key_l, src_l, seq_l,
    ):
        key_new = jax.lax.all_gather(key_l, BATCH_AXIS, tiled=True)
        src_new = jax.lax.all_gather(src_l, BATCH_AXIS, tiled=True)
        seq_new = jax.lax.all_gather(seq_l, BATCH_AXIS, tiled=True)

        widx = jnp.arange(work, dtype=jnp.int32)
        key_cat = jnp.concatenate([pend_key, key_new], axis=0)  # [W, KW]
        valid = (key_cat != KEY_PAD).any(axis=-1)
        src_f = jnp.where(valid, jnp.concatenate([pend_src, src_new]), 0)
        seq_f = jnp.where(valid, jnp.concatenate([pend_seq, seq_new]), 0)
        prior_clock = jnp.concatenate(
            [pend_clock, jnp.full((batch,), -1, jnp.int32)]
        )
        already_committed = prior_clock >= 0

        # timestamp proposal per replica block (clock ceiling + 1, with
        # within-round same-bucket runs taking consecutive values) — the
        # coordinator's Clock(seq, pid) assignment, computed by every
        # replica from its own clock index (caesar.rs:247-263)
        propose = valid & ~already_committed
        real_slot = valid[:, None] & (key_cat != KEY_PAD)
        propose_slot = propose[:, None] & real_slot
        slot_iota = jnp.arange(work * key_width, dtype=jnp.int32).reshape(
            work, key_width
        )
        key_full = jnp.where(propose_slot, key_cat, key_buckets + slot_iota)
        safe_key = jnp.minimum(key_full, key_buckets - 1)
        prior_rows = jnp.where(
            propose_slot[None], key_clock[:, safe_key], 0
        )  # [r_blk, W, KW]
        slot_prop = _segmented_proposal(
            prior_rows.reshape(replica_blocks, work * key_width),
            key_full.reshape(work * key_width),
            work * key_width,
        ).reshape(replica_blocks, work, key_width)
        proposal = jnp.where(
            propose_slot[None], slot_prop, int_min
        ).max(axis=-1)
        proposal = jnp.where(propose[None, :], proposal, 0)  # [r_blk, W]

        # fast path: the whole fast quorum (3n/4 + 1) reports the same
        # timestamp — everyone said ok to the coordinator's proposal
        # (caesar.rs MProposeAck ok=true unanimously)
        row = (
            jax.lax.axis_index(REPLICA_AXIS) * replica_blocks
            + jnp.arange(replica_blocks, dtype=jnp.int32)
        )
        in_fq = (row < fast_quorum)[:, None]
        fq_max = jax.lax.pmax(
            jnp.where(in_fq, proposal, int_min).max(axis=0), REPLICA_AXIS
        )
        fq_min = jax.lax.pmin(
            jnp.where(in_fq, proposal, int_max).min(axis=0), REPLICA_AXIS
        )
        fast = (fq_max == fq_min) & propose

        # MRetry as a second masked aggregation in the same step: the
        # counter-proposal clock is the max over every LIVE replica's
        # proposal, and it commits iff a write quorum (majority) is live
        # to ack it (caesar.rs:367-405 + MRetryAck counting)
        live = (row < live_replicas)[:, None]
        retry_clock = jax.lax.pmax(
            jnp.where(live, proposal, int_min).max(axis=0), REPLICA_AXIS
        )
        live_count = jax.lax.psum(
            live[:, 0].astype(jnp.int32).sum(), REPLICA_AXIS
        )
        slow_ok = (live_count >= write_quorum) & propose & ~fast
        newly_committed = fast | slow_ok
        committed = already_committed | newly_committed
        clock = jnp.where(
            newly_committed,
            jnp.where(fast, fq_max, retry_clock),
            jnp.where(already_committed, prior_clock, -1),
        )
        slow_paths = (propose & ~fast).sum().astype(jnp.int32)

        # wait-condition-gated execution (the PredecessorsExecutor dense
        # twin): per bucket, committed rows execute in (clock, dot) order
        # up to the first blocked conflict.  Uncommitted rows hold their
        # current (only-growing) counter-proposal clock — blocking
        # higher-clock commits behind them is exactly phase 1's
        # unknown-fate wait, and can only be conservative.
        #
        # Unlike Newt's gate, one pass is NOT enough here: commitment is
        # not clock-monotone per bucket (an uncommitted retry can sit at
        # a LOWER clock than a committed multi-key row), so a committed
        # row held back on one bucket must transitively hold back every
        # higher-(clock, dot) row on its OTHER buckets — a monotone
        # fixpoint over the blocked set (grows only; <= W iterations,
        # typically 1-2).
        order_clock = jnp.where(committed, clock, retry_clock)
        safe_clock = jnp.where(valid, order_clock, int_max)
        order_cd = jnp.lexsort((seq_f, src_f, safe_clock)).astype(jnp.int32)
        rank_of = jnp.zeros((work,), jnp.int32).at[order_cd].set(
            jnp.arange(work, dtype=jnp.int32)
        )
        real_key = jnp.minimum(
            jnp.where(real_slot, key_cat, 0), key_buckets - 1
        )

        def gate_clear(blocked):
            hold = jnp.full((key_buckets,), work, jnp.int32).at[real_key].min(
                jnp.where(
                    blocked[:, None] & real_slot,
                    rank_of[:, None],
                    jnp.int32(work),
                )
            )
            return jnp.where(
                real_slot, rank_of[:, None] < hold[real_key], True
            ).all(axis=-1)

        def gate_body(state):
            blocked, _changed = state
            clear = gate_clear(blocked)
            new_blocked = valid & (~committed | ~clear)
            return new_blocked, (new_blocked & ~blocked).any()

        blocked0 = valid & ~committed
        blocked1, changed0 = gate_body((blocked0, jnp.bool_(True)))
        blocked, _ = jax.lax.while_loop(
            lambda s: s[1], gate_body, (blocked1, changed0)
        )
        clear = gate_clear(blocked)
        executed = committed & valid & clear

        # execution order among the executed: (clock, dot) — timestamp
        # order among conflicts, the executor's contract
        sort_key = jnp.where(executed, clock, int_max)
        order = jnp.lexsort((seq_f, src_f, sort_key)).astype(jnp.int32)

        # clock-index update: live replicas learn every committed
        # timestamp on its buckets (clock_join) and their own consumed
        # proposals — uncommitted proposals occupy the index too, which
        # is what keeps later proposals strictly above them
        # (the key-clock add of caesar.rs:786-838)
        learn = jnp.maximum(
            jnp.where(
                committed[None, :, None] & real_slot[None],
                clock[None, :, None],
                0,
            ),
            jnp.where(propose_slot[None], proposal[..., None], 0),
        )  # [r_blk, W, KW]
        upd = jnp.where(live[..., None] & real_slot[None], learn, 0)
        new_key_clock = key_clock.at[:, real_key].max(upd)

        # pending carry: committed rows first (their timestamps are
        # final — dropping one would have to re-propose at a different
        # clock, breaking committed order), then uncommitted, working
        # order within each class
        carry = valid & ~executed
        work32 = jnp.int32(work)
        carry_rank = jnp.where(
            carry,
            jnp.where(committed, widx, widx + work32),
            int_max,
        )
        carry_order = jnp.argsort(carry_rank).astype(jnp.int32)
        take = carry_order[:pend_cap]
        is_carry = carry[take]
        new_pend_key = jnp.where(is_carry[:, None], key_cat[take], KEY_PAD)
        new_pend_src = jnp.where(is_carry, src_f[take], -1)
        new_pend_seq = jnp.where(is_carry, seq_f[take], -1)
        new_pend_clock = jnp.where(is_carry, clock[take], -1)
        pending = carry.sum().astype(jnp.int32)
        pend_dropped = jnp.maximum(pending - pend_cap, 0).astype(jnp.int32)

        watermark = jnp.where(executed, clock, 0).max()

        return (
            new_key_clock,
            new_pend_key, new_pend_src, new_pend_seq, new_pend_clock,
            order, executed, committed, fast, clock,
            slow_paths, watermark,
            jnp.minimum(pending, pend_cap), pend_dropped,
            src_f, seq_f,
        )

    specs_in = (
        P(REPLICA_AXIS, None),
        P(), P(), P(), P(),
        P(BATCH_AXIS), P(BATCH_AXIS), P(BATCH_AXIS),
    )
    specs_out = (
        P(REPLICA_AXIS, None),
        P(), P(), P(), P(),
        P(), P(), P(), P(), P(),
        P(), P(), P(), P(),
        P(), P(),  # work identity columns
    )
    fn = shard_map(
        step, mesh=mesh, in_specs=specs_in, out_specs=specs_out, check_vma=False
    )
    (
        kc, pk, ps_, pq, pc,
        order, executed, committed, fast, clock,
        slow, watermark, pending, dropped,
        work_src, work_seq,
    ) = fn(
        state.key_clock,
        state.pend_key, state.pend_src, state.pend_seq, state.pend_clock,
        key, dot_src, dot_seq,
    )
    return (
        CaesarMeshState(kc, pk, ps_, pq, pc),
        CaesarStepOutput(
            order, executed, committed, fast, clock,
            slow, watermark, pending, dropped,
            work_src, work_seq,
        ),
    )


def jit_caesar_step(
    mesh: Mesh,
    num_replicas: int | None = None,
    live_replicas: int | None = None,
):
    """jit-compiled Caesar round with donated device-resident state."""
    import functools

    return jax.jit(
        functools.partial(
            caesar_protocol_step,
            mesh=mesh,
            num_replicas=num_replicas,
            live_replicas=live_replicas,
        ),
        donate_argnums=(0,),
    )
