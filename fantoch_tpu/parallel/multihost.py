"""Multi-host (replica, batch) meshes: DCN for quorums, ICI for batches.

The reference scales across machines with one NCCL/MPI-style TCP link per
replica pair (fantoch/src/run/mod.rs:105-445 — every process connects to
every peer); collectives do not exist, so topology never matters. Here the
device plane IS collective (parallel/mesh_step.py), so on a multi-host
TPU deployment the mesh layout decides which interconnect each collective
rides:

* the **replica axis carries the quorum fan-ins** — masked ``pmax/pmin``
  agreement, ``psum`` accept counts, GC stability ``pmin`` — all small
  frontier-shaped reductions that model WAN consensus rounds in the first
  place.  They are latency-bound and tiny, exactly what DCN (between
  hosts) is acceptable for; replicas are also distinct failure domains,
  which only makes sense across hosts.
* the **batch axis carries the bandwidth** — the per-shard sorts, gathers
  and scatters over the command batch.  Those want ICI, i.e. must stay
  within one host's chips.

``make_multihost_mesh`` therefore maps processes (hosts) to the replica
axis and each host's local chips to the batch axis.  ``make_mesh``
(mesh_step.py) keeps its single-host behavior; this module is additive
and degrades to it when only one process is present, so everything
dryrun/CI runs today is unchanged.

Bootstrap: on real multi-host slices call :func:`distributed_init` (a
thin, idempotent gate around ``jax.distributed.initialize``) on every
host before building the mesh — the standard jax multi-controller
recipe.  Every driver in run/device_runner.py accepts ``mesh=`` and every
``init_*_state``/``jit_*_step`` in mesh_step.py takes the mesh it is
given, so a multi-host mesh drops into the existing serving stack
unchanged.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax

from fantoch_tpu.parallel.mesh_step import (
    BATCH_AXIS,
    REPLICA_AXIS,
    Mesh,
    make_mesh,
)
from fantoch_tpu.utils import logger

_DISTRIBUTED_INITIALIZED = False


# auto-detected clusters get a short barrier timeout: a CI runner that
# merely *carries* SLURM env vars (no actual peers) must fail fast and
# fall back to single-host instead of blocking on jax's ~300 s default
AUTO_DETECT_INIT_TIMEOUT_S = 30


def distributed_init(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    initialization_timeout_s: Optional[int] = None,
) -> bool:
    """Idempotently initialize jax's multi-controller runtime.

    Returns True when ``jax.distributed.initialize`` ran (or had already
    run via this gate), False when single-process operation was detected
    (no coordinator and no cluster env) and nothing was done — callers can
    use the same code path on laptops, CI and pods.

    Timeouts: with an explicit ``coordinator_address`` the operator named
    a real cluster, so jax's long default barrier (~300 s, slow pod
    boots) stands unless ``initialization_timeout_s`` overrides it.  On
    the auto-detect path (cluster env vars only) the barrier is capped at
    ``AUTO_DETECT_INIT_TIMEOUT_S`` so a stray SLURM_JOB_ID on a
    peer-less runner degrades to single-host in seconds, not minutes.
    """
    global _DISTRIBUTED_INITIALIZED
    if _DISTRIBUTED_INITIALIZED:
        return True
    import os

    # cluster hints jax.distributed.initialize can auto-detect from
    # (explicit coordinator > jax's own env > SLURM > TPU pod metadata)
    cluster_env = ("JAX_COORDINATOR_ADDRESS", "SLURM_JOB_ID", "TPU_WORKER_HOSTNAMES")
    if coordinator_address is None and not any(
        v in os.environ for v in cluster_env
    ):
        # no explicit coordinator and no cluster environment: single host
        return False
    kwargs = {}
    if initialization_timeout_s is not None:
        kwargs["initialization_timeout"] = initialization_timeout_s
    elif coordinator_address is None:
        kwargs["initialization_timeout"] = AUTO_DETECT_INIT_TIMEOUT_S
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            **kwargs,
        )
    except (ValueError, RuntimeError) as exc:
        if coordinator_address is not None:
            raise  # the operator asked for a specific cluster: fail loudly
        # a half-present cluster env (e.g. a single-chip rig that sets
        # TPU_WORKER_HOSTNAMES) from which jax cannot derive a
        # coordinator: fall back to single-host rather than killing the
        # server over a hint
        logger.warning(
            "cluster env detected but jax.distributed could not "
            "initialize (%r); continuing single-host", exc,
        )
        return False
    _DISTRIBUTED_INITIALIZED = True
    return True


def group_by_process(devices: Sequence) -> list:
    """Group a device list by ``process_index``, each group sorted by
    device id, groups ordered by process index.  Raises on ragged
    topologies (hosts with different chip counts) — a mesh needs a
    rectangle, and a ragged slice means the deployment is broken."""
    by_proc: dict = {}
    for d in devices:
        by_proc.setdefault(d.process_index, []).append(d)
    groups = [
        sorted(by_proc[p], key=lambda d: d.id) for p in sorted(by_proc)
    ]
    sizes = {len(g) for g in groups}
    if len(sizes) > 1:
        raise ValueError(
            f"ragged multi-host topology: per-host chip counts {sorted(sizes)}"
        )
    return groups


def make_multihost_mesh(
    num_replicas: Optional[int] = None, shard_count: int = 1
) -> Mesh:
    """(replica, batch) mesh with hosts on the replica axis.

    Single-process: defers to ``make_mesh`` (identical behavior, so CI /
    dryrun / the virtual-device suite are unaffected).  Multi-process:
    process p's chips form row p — the replica axis crosses hosts (DCN,
    quorum fan-ins), the batch axis stays on-host (ICI, batch sorts).

    ``num_replicas`` is the mesh's **total replica-axis row count**.  In
    sharded mode the device state holds ``n * shard_count`` rows in
    shard-major order (mesh_step.shard_of_row: shard s owns rows
    ``[s*n, (s+1)*n)``) — callers must size the mesh against that total,
    NOT the per-shard ``n`` (run/device_runner.py ``_init_sharded_mesh``
    builds ``shard_count * num_replicas`` rows).  When given it must be a
    multiple of the host count, mirroring ``make_mesh``'s divisibility
    contract (init_state shards whole replica blocks per row), and with
    ``shard_count > 1`` each host row should additionally hold whole
    shard blocks, or a shard's quorum fan-in straddles hosts and rides
    DCN instead of ICI (warned, not fatal: it is a performance contract,
    not a correctness one).
    """
    import numpy as np

    devices = jax.devices()
    groups = group_by_process(devices)
    if len(groups) == 1:
        return make_mesh(num_replicas=num_replicas)
    hosts = len(groups)
    if num_replicas is not None:
        if num_replicas % hosts != 0:
            raise ValueError(
                f"num_replicas={num_replicas} (total replica rows, i.e. "
                f"n * shard_count) must be a multiple of the host count "
                f"{hosts} (whole replica blocks per mesh row)"
            )
        if shard_count > 1:
            rows_per_host = num_replicas // hosts
            per_shard = num_replicas // shard_count
            if rows_per_host % per_shard != 0:
                logger.warning(
                    "multihost mesh: %d rows/host does not hold whole "
                    "shard blocks of %d rows (shard-major order, "
                    "mesh_step.shard_of_row) — sharded quorum fan-ins "
                    "will cross hosts on DCN instead of staying on ICI",
                    rows_per_host,
                    per_shard,
                )
    dev_array = np.array(groups)  # (hosts, chips_per_host)
    return Mesh(dev_array, (REPLICA_AXIS, BATCH_AXIS))
