"""Multi-host (replica, batch) meshes: DCN for quorums, ICI for batches.

The reference scales across machines with one NCCL/MPI-style TCP link per
replica pair (fantoch/src/run/mod.rs:105-445 — every process connects to
every peer); collectives do not exist, so topology never matters. Here the
device plane IS collective (parallel/mesh_step.py), so on a multi-host
TPU deployment the mesh layout decides which interconnect each collective
rides:

* the **replica axis carries the quorum fan-ins** — masked ``pmax/pmin``
  agreement, ``psum`` accept counts, GC stability ``pmin`` — all small
  frontier-shaped reductions that model WAN consensus rounds in the first
  place.  They are latency-bound and tiny, exactly what DCN (between
  hosts) is acceptable for; replicas are also distinct failure domains,
  which only makes sense across hosts.
* the **batch axis carries the bandwidth** — the per-shard sorts, gathers
  and scatters over the command batch.  Those want ICI, i.e. must stay
  within one host's chips.

``make_multihost_mesh`` therefore maps processes (hosts) to the replica
axis and each host's local chips to the batch axis.  ``make_mesh``
(mesh_step.py) keeps its single-host behavior; this module is additive
and degrades to it when only one process is present, so everything
dryrun/CI runs today is unchanged.

Bootstrap: on real multi-host slices call :func:`distributed_init` (a
thin, idempotent gate around ``jax.distributed.initialize``) on every
host before building the mesh — the standard jax multi-controller
recipe.  Every driver in run/device_runner.py accepts ``mesh=`` and every
``init_*_state``/``jit_*_step`` in mesh_step.py takes the mesh it is
given, so a multi-host mesh drops into the existing serving stack
unchanged.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax

from fantoch_tpu.parallel.mesh_step import (
    BATCH_AXIS,
    REPLICA_AXIS,
    Mesh,
    make_mesh,
)
from fantoch_tpu.utils import logger

_DISTRIBUTED_INITIALIZED = False


def distributed_init(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Idempotently initialize jax's multi-controller runtime.

    Returns True when ``jax.distributed.initialize`` ran (or had already
    run via this gate), False when single-process operation was detected
    (no coordinator and no cluster env) and nothing was done — callers can
    use the same code path on laptops, CI and pods.
    """
    global _DISTRIBUTED_INITIALIZED
    if _DISTRIBUTED_INITIALIZED:
        return True
    import os

    # cluster hints jax.distributed.initialize can auto-detect from
    # (explicit coordinator > jax's own env > SLURM > TPU pod metadata)
    cluster_env = ("JAX_COORDINATOR_ADDRESS", "SLURM_JOB_ID", "TPU_WORKER_HOSTNAMES")
    if coordinator_address is None and not any(
        v in os.environ for v in cluster_env
    ):
        # no explicit coordinator and no cluster environment: single host
        return False
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except (ValueError, RuntimeError) as exc:
        if coordinator_address is not None:
            raise  # the operator asked for a specific cluster: fail loudly
        # a half-present cluster env (e.g. a single-chip rig that sets
        # TPU_WORKER_HOSTNAMES) from which jax cannot derive a
        # coordinator: fall back to single-host rather than killing the
        # server over a hint
        logger.warning(
            "cluster env detected but jax.distributed could not "
            "initialize (%r); continuing single-host", exc,
        )
        return False
    _DISTRIBUTED_INITIALIZED = True
    return True


def group_by_process(devices: Sequence) -> list:
    """Group a device list by ``process_index``, each group sorted by
    device id, groups ordered by process index.  Raises on ragged
    topologies (hosts with different chip counts) — a mesh needs a
    rectangle, and a ragged slice means the deployment is broken."""
    by_proc: dict = {}
    for d in devices:
        by_proc.setdefault(d.process_index, []).append(d)
    groups = [
        sorted(by_proc[p], key=lambda d: d.id) for p in sorted(by_proc)
    ]
    sizes = {len(g) for g in groups}
    if len(sizes) > 1:
        raise ValueError(
            f"ragged multi-host topology: per-host chip counts {sorted(sizes)}"
        )
    return groups


def make_multihost_mesh(num_replicas: Optional[int] = None) -> Mesh:
    """(replica, batch) mesh with hosts on the replica axis.

    Single-process: defers to ``make_mesh`` (identical behavior, so CI /
    dryrun / the virtual-device suite are unaffected).  Multi-process:
    process p's chips form row p — the replica axis crosses hosts (DCN,
    quorum fan-ins), the batch axis stays on-host (ICI, batch sorts).
    When ``num_replicas`` is given it must be a multiple of the host
    count, mirroring ``make_mesh``'s divisibility contract
    (init_state shards whole replica blocks per row).
    """
    import numpy as np

    devices = jax.devices()
    groups = group_by_process(devices)
    if len(groups) == 1:
        return make_mesh(num_replicas=num_replicas)
    if num_replicas is not None and num_replicas % len(groups) != 0:
        raise ValueError(
            f"num_replicas={num_replicas} must be a multiple of the host "
            f"count {len(groups)} (whole replica blocks per mesh row)"
        )
    dev_array = np.array(groups)  # (hosts, chips_per_host)
    return Mesh(dev_array, (REPLICA_AXIS, BATCH_AXIS))
