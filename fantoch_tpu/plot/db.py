"""Results database: index experiment output directories.

Reference: fantoch_plot/src/db/*.rs (``ResultsDB``/``Search`` over
serialized ExperimentConfig + metrics + client data).  Each experiment
directory is one ``run_experiment`` output (fantoch_tpu/exp/bench.py);
``search`` filters by any ExperimentConfig field.
"""

from __future__ import annotations

import glob
import json
import os
import pickle
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class ExperimentResult:
    path: str
    config: Dict[str, Any]
    outcome: Dict[str, Any]
    _client_data: Optional[Dict] = field(default=None, repr=False)
    _metrics: Optional[Dict[int, Any]] = field(default=None, repr=False)
    _device_tallies: Optional[Dict[int, Dict[str, int]]] = field(
        default=None, repr=False
    )

    @property
    def name(self) -> str:
        return os.path.basename(self.path)

    def latencies_us(self) -> List[int]:
        """All client-observed latencies (microseconds), pooled."""
        if self._client_data is None:
            with open(os.path.join(self.path, "client_data.pkl"), "rb") as fh:
                self._client_data = pickle.load(fh)
        out: List[int] = []
        for data in self._client_data.values():
            out.extend(data.latency_data())
        return out

    def process_metrics(self) -> Dict[int, Any]:
        """pid -> ProcessMetrics snapshot (fantoch_tpu/run/observe.py)."""
        if self._metrics is None:
            from fantoch_tpu.run.observe import read_metrics_snapshot

            self._metrics = {}
            for path in glob.glob(os.path.join(self.path, "metrics_p*.gz")):
                pid = int(os.path.basename(path)[len("metrics_p"):-len(".gz")])
                self._metrics[pid] = read_metrics_snapshot(path)
        return self._metrics

    def device_tallies(self) -> Dict[int, Dict[str, int]]:
        """pid -> device-serving JSON tallies (run/device_runner.py
        ``--metrics-file``: rounds/executed/fast_paths/slow_paths/...).
        Empty for object-runner experiments, whose metrics are the
        gzip+pickle ProcessMetrics indexed by :meth:`process_metrics`."""
        if self._device_tallies is None:
            self._device_tallies = {}
            for path in glob.glob(os.path.join(self.path, "metrics_p*.json")):
                pid = int(os.path.basename(path)[len("metrics_p"):-len(".json")])
                with open(path) as fh:
                    self._device_tallies[pid] = json.load(fh)
        return self._device_tallies

    def protocol_totals(self) -> Dict[str, int]:
        """Summed fast/slow/stable counters across processes.  Device
        experiments contribute their fast_paths/slow_paths tallies;
        ``stable`` stays 0 there (the device plane tracks a stability
        *watermark*, not a per-command stable count — see
        ``device_tallies`` for the raw record)."""
        from fantoch_tpu.protocol import ProtocolMetricsKind

        totals = {"fast_path": 0, "slow_path": 0, "stable": 0}
        for snap in self.process_metrics().values():
            for worker in snap.workers:
                totals["fast_path"] += (
                    worker.get_aggregated(ProtocolMetricsKind.FAST_PATH) or 0
                )
                totals["slow_path"] += (
                    worker.get_aggregated(ProtocolMetricsKind.SLOW_PATH) or 0
                )
                totals["stable"] += (
                    worker.get_aggregated(ProtocolMetricsKind.STABLE) or 0
                )
        for tallies in self.device_tallies().values():
            totals["fast_path"] += tallies.get("fast_paths", 0)
            totals["slow_path"] += tallies.get("slow_paths", 0)
        return totals


class ResultsDB:
    def __init__(self, root: str):
        self.root = root
        self.results: List[ExperimentResult] = []
        for manifest in sorted(glob.glob(os.path.join(root, "*", "manifest.json"))):
            with open(manifest) as fh:
                data = json.load(fh)
            self.results.append(
                ExperimentResult(
                    os.path.dirname(manifest), data["config"], data["outcome"]
                )
            )

    def __len__(self) -> int:
        return len(self.results)

    def search(self, where=None, **filters: Any) -> List[ExperimentResult]:
        """Results whose config matches every given field; a filter value
        may be a predicate over the field (the Search-refine shape of
        fantoch_plot/src/db), and ``where`` an arbitrary predicate over
        the whole result.  E.g.::

            db.search(protocol="epaxos", f=1)
            db.search(clients_per_process=lambda c: c >= 4)
            db.search(where=lambda r: r.outcome["throughput_cmds_per_s"] > 1e5)
        """
        out = []
        for result in self.results:
            ok = all(
                v(result.config.get(k)) if callable(v)
                else result.config.get(k) == v
                for k, v in filters.items()
            )
            if ok and (where is None or where(result)):
                out.append(result)
        return out


# --- scenario-observatory curves document (exp/scenarios.py) ---


def save_curves(doc: Dict[str, Any], path: str) -> str:
    """Persist a throughput-latency curves document as canonical JSON
    (sorted keys, fixed separators): the artifact is part of the
    scenario's byte-identity contract, so no timestamps, no float repr
    drift, no key-order nondeterminism."""
    with open(path, "w") as fh:
        fh.write(json.dumps(doc, sort_keys=True, separators=(",", ":")))
        fh.write("\n")
    return path


def load_curves(path: str) -> Dict[str, Any]:
    """Inverse of :func:`save_curves` (round-trip tested)."""
    with open(path) as fh:
        return json.load(fh)
