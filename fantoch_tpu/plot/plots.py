"""Plots over the results DB.

Reference: fantoch_plot/src/lib.rs:179-1664 — latency bars, CDFs,
throughput-latency curves and metrics tables, rendered with matplotlib
(via pyo3 there, natively here; Agg backend, file output only).
"""

from __future__ import annotations

from typing import List

import numpy as np

from fantoch_tpu.plot.db import ExperimentResult

# headless: the reference renders to files too (fantoch_plot output
# dir).  force=True pins Agg even when another import (or MPLBACKEND)
# already selected an interactive backend — CI runs with no display, and
# a late Qt/Tk selection would crash the first savefig, not the import.
import matplotlib

matplotlib.use("Agg", force=True)
import matplotlib.pyplot as plt  # noqa: E402


def _label(result: ExperimentResult) -> str:
    cfg = result.config
    return f"{cfg['protocol']} n={cfg['n']} f={cfg['f']}"


def latency_cdf(results: List[ExperimentResult], path: str) -> str:
    """Per-experiment latency CDFs (lib.rs cdf_plot analog)."""
    fig, ax = plt.subplots(figsize=(7, 4.5))
    for result in results:
        lat_ms = np.sort(np.asarray(result.latencies_us())) / 1000.0
        ys = np.arange(1, len(lat_ms) + 1) / len(lat_ms)
        ax.plot(lat_ms, ys, label=_label(result), drawstyle="steps-post")
    ax.set_xlabel("latency (ms)")
    ax.set_ylabel("CDF")
    ax.set_ylim(0, 1)
    ax.legend(fontsize=8)
    ax.grid(alpha=0.3)
    fig.tight_layout()
    fig.savefig(path, dpi=120)
    plt.close(fig)
    return path


def latency_percentiles(
    results: List[ExperimentResult], path: str, percentiles=(50, 95, 99)
) -> str:
    """Grouped percentile bars per experiment (latency_plot analog)."""
    fig, ax = plt.subplots(figsize=(7, 4.5))
    width = 0.8 / len(percentiles)
    xs = np.arange(len(results))
    for j, p in enumerate(percentiles):
        vals = [
            float(np.percentile(np.asarray(r.latencies_us()), p)) / 1000.0
            for r in results
        ]
        ax.bar(xs + j * width, vals, width, label=f"p{p}")
    ax.set_xticks(xs + width * (len(percentiles) - 1) / 2)
    ax.set_xticklabels([_label(r) for r in results], fontsize=8, rotation=15)
    ax.set_ylabel("latency (ms)")
    ax.legend()
    ax.grid(axis="y", alpha=0.3)
    fig.tight_layout()
    fig.savefig(path, dpi=120)
    plt.close(fig)
    return path


def throughput_latency(
    results: List[ExperimentResult], path: str, percentile: float = 50
) -> str:
    """Throughput vs latency scatter/curve across experiments
    (throughput_latency_plot analog): one point per experiment, meant for
    a client-count sweep of the same protocol config."""
    fig, ax = plt.subplots(figsize=(7, 4.5))
    by_proto = {}
    for r in results:
        by_proto.setdefault(r.config["protocol"], []).append(r)
    for proto, rs in sorted(by_proto.items()):
        rs = sorted(rs, key=lambda r: r.outcome["throughput_cmds_per_s"])
        xs = [r.outcome["throughput_cmds_per_s"] for r in rs]
        ys = [
            float(np.percentile(np.asarray(r.latencies_us()), percentile)) / 1000.0
            for r in rs
        ]
        ax.plot(xs, ys, marker="o", label=proto)
    ax.set_xlabel("throughput (cmds/s)")
    ax.set_ylabel(f"p{percentile:.0f} latency (ms)")
    ax.legend()
    ax.grid(alpha=0.3)
    fig.tight_layout()
    fig.savefig(path, dpi=120)
    plt.close(fig)
    return path


def metrics_table(results: List[ExperimentResult]) -> str:
    """Process/executor metrics table (fantoch_plot lib.rs:1491-1664
    analog): per experiment, fast/slow/stable totals plus executor
    chain-size and execution-delay statistics from the snapshot files."""
    from fantoch_tpu.executor.base import ExecutorMetricsKind

    lines = [
        f"{'experiment':<34} {'fast':>8} {'slow':>8} {'stable':>8} "
        f"{'chain p99':>10} {'exec delay p99 (ms)':>20}"
    ]
    for result in results:
        totals = result.protocol_totals()
        chain = delay = None
        for snap in result.process_metrics().values():
            for ex in snap.executors:
                if ex is None:  # executor type without metrics
                    continue
                h = ex.get_collected(ExecutorMetricsKind.CHAIN_SIZE)
                if h is not None and h.count:
                    chain = max(chain or 0, h.percentile(0.99))
                h = ex.get_collected(ExecutorMetricsKind.EXECUTION_DELAY)
                if h is not None and h.count:
                    delay = max(delay or 0, h.percentile(0.99))
        lines.append(
            f"{result.name:<34} {totals['fast_path']:>8} "
            f"{totals['slow_path']:>8} {totals['stable']:>8} "
            f"{chain if chain is not None else '-':>10} "
            f"{delay if delay is not None else '-':>20}"
        )
    return "\n".join(lines)


def resource_table(results: List[ExperimentResult]) -> str:
    """Machine resource table from the experiment's dstat-analog series
    (telemetry-window JSONL; fantoch_plot dstat tables,
    fantoch_exp/src/bench.rs:203-258): mean/max cpu and mean mem/net
    over the run."""
    import os

    from fantoch_tpu.exp.monitor import load_samples  # CSV fallback inside

    lines = [
        f"{'experiment':<34} {'cpu% avg':>9} {'cpu% max':>9} "
        f"{'mem MB avg':>11} {'net rx KB/s':>12} {'net tx KB/s':>12}"
    ]
    for result in results:
        rows = load_samples(os.path.join(result.path, "resources.jsonl"))
        if not rows:
            lines.append(
                f"{result.name:<34} {'-':>9} {'-':>9} {'-':>11} {'-':>12} "
                f"{'-':>12}"
            )
            continue
        cpu = [r["cpu_pct"] for r in rows]
        mem = [r["mem_used_mb"] for r in rows]
        rx = [r["net_rx_kbps"] for r in rows]
        tx = [r["net_tx_kbps"] for r in rows]
        lines.append(
            f"{result.name:<34} {np.mean(cpu):>9.1f} {np.max(cpu):>9.1f} "
            f"{np.mean(mem):>11.0f} {np.mean(rx):>12.1f} {np.mean(tx):>12.1f}"
        )
    return "\n".join(lines)


def fast_path_split(results: List[ExperimentResult], path: str) -> str:
    """Stacked fast/slow commit counts per experiment (the metrics-table
    analog of lib.rs:1491-1664, as a bar chart)."""
    fig, ax = plt.subplots(figsize=(7, 4.5))
    xs = np.arange(len(results))
    fast = []
    slow = []
    for r in results:
        totals = r.protocol_totals()
        fast.append(totals["fast_path"])
        slow.append(totals["slow_path"])
    ax.bar(xs, fast, 0.6, label="fast path")
    ax.bar(xs, slow, 0.6, bottom=fast, label="slow path")
    ax.set_xticks(xs)
    ax.set_xticklabels([_label(r) for r in results], fontsize=8, rotation=15)
    ax.set_ylabel("commits")
    ax.legend()
    ax.grid(axis="y", alpha=0.3)
    fig.tight_layout()
    fig.savefig(path, dpi=120)
    plt.close(fig)
    return path


def heatmap(
    results: List[ExperimentResult],
    path: str,
    x_field: str = "workers",
    y_field: str = "executors",
    value: str = "throughput_cmds_per_s",
) -> str:
    """Config-grid heatmap (lib.rs heatmap_plot:870-917 analog): one cell
    per (x_field, y_field) config pair, colored by an outcome metric —
    the reference uses it for per-process CPU over protocol x clients;
    any two ExperimentConfig fields work here."""
    xs = sorted({r.config[x_field] for r in results})
    ys = sorted({r.config[y_field] for r in results})
    grid = np.full((len(ys), len(xs)), np.nan)
    for r in results:
        i = ys.index(r.config[y_field])
        j = xs.index(r.config[x_field])
        cell = r.outcome[value]
        if np.isnan(grid[i, j]) or cell > grid[i, j]:
            grid[i, j] = cell  # several client counts: keep the max
    fig, ax = plt.subplots(figsize=(1.2 + len(xs), 1.0 + len(ys)))
    im = ax.imshow(grid, origin="lower", aspect="auto", cmap="viridis")
    for i in range(len(ys)):
        for j in range(len(xs)):
            if not np.isnan(grid[i, j]):
                ax.text(j, i, f"{grid[i, j]:.0f}", ha="center", va="center",
                        color="w", fontsize=8)
    ax.set_xticks(range(len(xs)), [str(x) for x in xs])
    ax.set_yticks(range(len(ys)), [str(y) for y in ys])
    ax.set_xlabel(x_field)
    ax.set_ylabel(y_field)
    fig.colorbar(im, ax=ax, label=value)
    fig.tight_layout()
    fig.savefig(path, dpi=120)
    plt.close(fig)
    return path


def intra_machine_scalability(
    results: List[ExperimentResult], path: str, x_field: str = "workers"
) -> str:
    """Max throughput as intra-process parallelism grows (lib.rs
    intra_machine_scalability_plot:919-974): one line per protocol, x =
    the parallelism knob, y = best throughput over client counts."""
    fig, ax = plt.subplots(figsize=(7, 4.5))
    by_proto = {}
    for r in results:
        by_proto.setdefault(r.config["protocol"], {})
        knob = r.config[x_field]
        cur = by_proto[r.config["protocol"]].get(knob, 0)
        by_proto[r.config["protocol"]][knob] = max(
            cur, r.outcome["throughput_cmds_per_s"]
        )
    for proto, series in sorted(by_proto.items()):
        xs = sorted(series)
        ax.plot(xs, [series[x] for x in xs], marker="o", label=proto)
    ax.set_xlabel(x_field)
    ax.set_ylabel("max throughput (cmds/s)")
    ax.legend()
    ax.grid(alpha=0.3)
    fig.tight_layout()
    fig.savefig(path, dpi=120)
    plt.close(fig)
    return path


# --- scenario-observatory saturation curves (exp/scenarios.py) ---


def _curve_label(curve: dict) -> str:
    return f"{curve['protocol']} n={curve['n']} f={curve['f']}"


def curve_axes(curve: dict):
    """(goodput xs, {"p50"|"p95"|"p99": ys}) for one curves-document
    curve, sorted so the goodput axis is monotone non-decreasing (points
    arrive in offered-rate order; past the knee goodput can regress, and
    a latency-vs-goodput line that doubles back is unreadable).  Points
    with no completed commands (no percentiles) are dropped."""
    points = [p for p in curve["points"] if p.get("p50_ms") is not None]
    points = sorted(points, key=lambda p: p["goodput_cmds_per_s"])
    xs = [p["goodput_cmds_per_s"] for p in points]
    ys = {
        "p50": [p["p50_ms"] for p in points],
        "p95": [p["p95_ms"] for p in points],
        "p99": [p["p99_ms"] for p in points],
    }
    return xs, ys


def render_saturation(doc: dict):
    """Throughput-latency saturation figure for a curves document (the
    fantoch_plot throughput-latency analog over a scenario's offered-rate
    sweep): per curve, p50/p95/p99 vs goodput; the detected knee gets a
    marker (label "knee"); points that shed or ran degraded (PR 8/17
    counters) get annotations.  Returns the Figure (tests inspect the
    object model; :func:`saturation_curves` saves it)."""
    fig, ax = plt.subplots(figsize=(7, 4.5))
    styles = {"p50": "-", "p95": "--", "p99": ":"}
    for curve in doc["curves"]:
        xs, ys = curve_axes(curve)
        if not xs:
            continue
        base = None
        for q, style in styles.items():
            (line,) = ax.plot(
                xs, ys[q], style, marker="o", markersize=3,
                color=base, label=f"{_curve_label(curve)} {q}",
            )
            base = line.get_color()
        knee = curve.get("knee")
        if knee is not None and knee.get("p99_ms") is not None:
            ax.plot(
                [knee["goodput_cmds_per_s"]], [knee["p99_ms"]],
                marker="X", markersize=12, color=base, linestyle="none",
                label="knee",
            )
        for p in curve["points"]:
            if p.get("p99_ms") is None:
                continue
            tags = []
            if p.get("sheds"):
                tags.append(f"shed {p['sheds']}")
            if p.get("degraded_ms"):
                tags.append("degraded")
            if tags:
                ax.annotate(
                    ", ".join(tags),
                    (p["goodput_cmds_per_s"], p["p99_ms"]),
                    fontsize=7, textcoords="offset points", xytext=(4, 4),
                )
    ax.set_xlabel("goodput (cmds/s)")
    ax.set_ylabel("latency (ms)")
    ax.set_title(f"{doc['scenario']} ({doc['timeline']} timeline)")
    ax.legend(fontsize=7)
    ax.grid(alpha=0.3)
    fig.tight_layout()
    return fig


def saturation_curves(doc: dict, path: str) -> str:
    """Render :func:`render_saturation` to ``path``."""
    fig = render_saturation(doc)
    fig.savefig(path, dpi=120)
    plt.close(fig)
    return path


def inter_machine_scalability(results: List[ExperimentResult], path: str) -> str:
    """Grouped throughput bars as the site count grows (lib.rs
    inter_machine_scalability_plot:976-1120): x = n, one bar per
    protocol, height = best throughput over client counts."""
    ns = sorted({r.config["n"] for r in results})
    protos = sorted({r.config["protocol"] for r in results})
    best = {}
    for r in results:
        key = (r.config["protocol"], r.config["n"])
        best[key] = max(best.get(key, 0), r.outcome["throughput_cmds_per_s"])
    fig, ax = plt.subplots(figsize=(7, 4.5))
    width = 0.8 / max(len(protos), 1)
    xs = np.arange(len(ns))
    for j, proto in enumerate(protos):
        vals = [best.get((proto, n), 0) for n in ns]
        ax.bar(xs + j * width, vals, width, label=proto)
    ax.set_xticks(xs + width * (len(protos) - 1) / 2)
    ax.set_xticklabels([f"n={n}" for n in ns])
    ax.set_ylabel("max throughput (cmds/s)")
    ax.legend()
    ax.grid(axis="y", alpha=0.3)
    fig.tight_layout()
    fig.savefig(path, dpi=120)
    plt.close(fig)
    return path
