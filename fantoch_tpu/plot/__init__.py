"""Results database + plots (the fantoch_plot analog).

Reference: fantoch_plot/src/{lib,db/*,plot/*}.rs — a results DB over
serialized experiment configs + metrics, and latency/CDF/throughput
plots rendered through matplotlib (the reference reaches matplotlib via
pyo3; here it is native).
"""

from fantoch_tpu.plot.db import (
    ExperimentResult,
    ResultsDB,
    load_curves,
    save_curves,
)

__all__ = [
    "ExperimentResult",
    "ResultsDB",
    "load_curves",
    "plots",
    "save_curves",
]
