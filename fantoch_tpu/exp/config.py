"""ExperimentConfig: one experiment's full parameterization + flag gen.

Reference: fantoch_exp/src/config.rs — ``ProtocolConfig::to_args`` /
``ClientConfig::to_args`` (:134-230, :320-378) serialize the experiment
into the binaries' flag sets; ``ExperimentConfig`` (:380-472) is the
record the results DB indexes by.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional, Tuple


@dataclass(frozen=True)
class ExperimentConfig:
    protocol: str
    n: int
    f: int
    shard_count: int = 1
    clients_per_process: int = 1
    commands_per_client: int = 100
    key_gen: str = "conflict_rate"  # or "zipf"
    conflict_rate: int = 50
    zipf_coefficient: float = 1.0
    keys_per_shard: int = 1_000_000
    keys_per_command: int = 1
    payload_size: int = 0
    read_only_percentage: int = 0
    open_loop_interval_ms: Optional[int] = None
    # parallelism (prod defaults in the reference: 16/16/32,
    # fantoch_exp/src/config.rs:20-41 — localhost defaults are small)
    workers: int = 1
    executors: int = 1
    multiplexing: int = 1
    batched_graph_executor: bool = False
    gc_interval_ms: int = 50
    # TPU serving path: one --device-step server (the whole protocol
    # round as a device program) instead of an n-process TCP mesh; the
    # same client binary, results pipeline and plots apply
    device_step: bool = False
    device_batch: int = 256
    # None derives from keys_per_command — the device state must admit as
    # many key buckets per command as the workload sends, or the server
    # rejects the commands; an explicit value still overrides
    device_key_width: Optional[int] = None
    extra_flags: Tuple[str, ...] = field(default_factory=tuple)

    def name(self) -> str:
        """Directory-friendly experiment name (config.rs:464-472)."""
        kg = (
            f"cr{self.conflict_rate}"
            if self.key_gen == "conflict_rate"
            else f"zipf{self.zipf_coefficient}"
        )
        dev = "dev_" if self.device_step else ""
        return (
            f"{dev}{self.protocol}_n{self.n}_f{self.f}_s{self.shard_count}_"
            f"{kg}_k{self.keys_per_command}_c{self.clients_per_process}"
        )

    def to_dict(self) -> Dict:
        return asdict(self)

    # --- flag generation (the to_args analogs) ---

    def server_args(
        self,
        process_id: int,
        shard_id: int,
        port: int,
        client_port: int,
        addresses: str,
        sorted_processes: str,
        observe_dir: Optional[str] = None,
        shared_machine: bool = False,
    ) -> List[str]:
        args = [
            "--protocol", self.protocol,
            "--id", str(process_id),
            "--shard-id", str(shard_id),
            "--port", str(port),
            "--client-port", str(client_port),
            "--addresses", addresses,
            "--sorted", sorted_processes,
            "-n", str(self.n),
            "-f", str(self.f),
            "--shard-count", str(self.shard_count),
            "--workers", str(self.workers),
            "--executors", str(self.executors),
            "--multiplexing", str(self.multiplexing),
            "--gc-interval", str(self.gc_interval_ms),
        ]
        if shared_machine:
            # a forgiving failure detector for servers sharing one machine
            # (often one core, under a concurrently-running test suite),
            # where >8s of scheduler starvation is normal — the default
            # window would read it as peer death, trip the quorum check,
            # and tear sessions down with commands outstanding (VERDICT
            # r5's under-load flake).  Real multi-host runs keep the
            # default detector so failover latency stays measurable
            args += ["--heartbeat-interval", "2", "--heartbeat-misses", "60"]
        if self.batched_graph_executor:
            args.append("--batched-graph-executor")
        if self.protocol == "fpaxos":
            args += ["--leader", "1"]
        if self.protocol == "newt":
            args += ["--newt-detached-send-interval", "50"]
        if observe_dir:
            args += [
                "--metrics-file", f"{observe_dir}/metrics_p{process_id}.gz",
                "--metrics-interval", "500",
                "--execution-log", f"{observe_dir}/execution_p{process_id}.log",
            ]
        args += list(self.extra_flags)
        return args

    def device_server_args(
        self, client_port: int, observe_dir: Optional[str] = None
    ) -> List[str]:
        """Flags for the single --device-step server (the TPU serving
        path): no peer mesh, no worker pools — the round is one device
        program; metrics are the serving JSON tallies."""
        args = [
            "--protocol", self.protocol,
            "--device-step",
            "--id", "1",
            "--client-port", str(client_port),
            "-n", str(self.n),
            "-f", str(self.f),
            "--shard-count", str(self.shard_count),
            "--device-batch", str(self.device_batch),
            "--device-key-width",
            str(self.device_key_width or self.keys_per_command),
        ]
        if observe_dir:
            args += [
                "--metrics-file", f"{observe_dir}/metrics_p1.json",
                "--metrics-interval", "500",
            ]
        args += list(self.extra_flags)
        return args

    def client_args(
        self, ids: str, addresses: str, metrics_file: Optional[str] = None
    ) -> List[str]:
        args = [
            "--ids", ids,
            "--addresses", addresses,
            "--key-gen", self.key_gen,
            "--conflict-rate", str(self.conflict_rate),
            "--zipf-coefficient", str(self.zipf_coefficient),
            "--keys-per-shard", str(self.keys_per_shard),
            "--keys-per-command", str(self.keys_per_command),
            "--commands-per-client", str(self.commands_per_client),
            "--read-only-percentage", str(self.read_only_percentage),
            "--payload-size", str(self.payload_size),
            "--shard-count", str(self.shard_count),
        ]
        if self.open_loop_interval_ms is not None:
            args += ["--interval", str(self.open_loop_interval_ms)]
        if metrics_file:
            args += ["--metrics-file", metrics_file]
        return args
