"""Experiment orchestration (the fantoch_exp analog).

Reference: fantoch_exp/src/{lib,bench,machine,config}.rs + testbed/{aws,
baremetal,local}.rs — launches a testbed, generates the full server/client
flag sets from an ``ExperimentConfig``, runs the binaries, and collects
logs + metrics into a results directory that fantoch_tpu.plot consumes.

The localhost testbed is fully functional (subprocess-driven CLI
binaries — the analog of testbed/local.rs); AWS/baremetal orchestration
(tsunami/rusoto in the reference) is out of scope for this environment
and raises with a clear message.
"""

from fantoch_tpu.exp.config import ExperimentConfig
from fantoch_tpu.exp.bench import run_experiment, run_sweep
from fantoch_tpu.exp.scenarios import (
    ScenarioSpec,
    canonical_expansion,
    detect_knee,
    expand,
    load_spec,
    run_scenario,
)

__all__ = [
    "ExperimentConfig",
    "ScenarioSpec",
    "canonical_expansion",
    "detect_knee",
    "expand",
    "load_spec",
    "run_experiment",
    "run_scenario",
    "run_sweep",
]
