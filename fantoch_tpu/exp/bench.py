"""Experiment driver: boot a testbed, run one experiment, collect results.

Reference: fantoch_exp/src/bench.rs:43-260 (run the protocol + client
binaries with generated flags, wait for completion, pull metrics files)
and testbed/local.rs (the localhost testbed).  Each experiment leaves a
results directory::

    <output_dir>/<config.name()>/
        manifest.json        — the ExperimentConfig + outcome summary
        client_data.pkl      — per-client latency data (client binary)
        client_summary.json  — the client binary's stdout summary
        metrics_p*.gz        — per-process metrics snapshots
        execution_p*.log     — per-process execution logs
        server_p*.log        — server stdout/stderr

which fantoch_tpu.plot's ResultsDB indexes.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import subprocess
import sys
import time
from typing import Dict, Optional

from fantoch_tpu.exp.config import ExperimentConfig


def _cli_env() -> Dict[str, str]:
    env = dict(os.environ)
    env["FANTOCH_PLATFORM"] = env.get("FANTOCH_PLATFORM", "cpu")
    env.pop("JAX_PLATFORMS", None)
    repo = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    return env


def run_experiment(
    config: ExperimentConfig,
    output_dir: str,
    testbed: str = "localhost",
    client_timeout_s: int = 600,
) -> Dict:
    """Run one experiment end to end; returns the manifest dict."""
    if testbed != "localhost":
        raise NotImplementedError(
            f"testbed {testbed!r}: the reference's AWS/baremetal orchestration "
            "(fantoch_exp/src/testbed/{aws,baremetal}.rs over tsunami/rusoto) "
            "has no cloud access in this environment; use 'localhost'"
        )
    from fantoch_tpu.core.ids import process_ids
    from fantoch_tpu.run.harness import free_port

    exp_dir = os.path.join(output_dir, config.name())
    os.makedirs(exp_dir, exist_ok=True)

    shard_ids = {s: list(process_ids(s, config.n)) for s in range(config.shard_count)}
    all_pids = [(pid, s) for s, ids in shard_ids.items() for pid in ids]
    offset_of = {pid: pid - shard_ids[s][0] for pid, s in all_pids}
    peer_ports = {pid: free_port() for pid, _ in all_pids}
    client_ports = {pid: free_port() for pid, _ in all_pids}

    env = _cli_env()
    servers = []
    logs = []
    # dstat analog: machine resource CSV for the plot layer's tables
    from fantoch_tpu.exp.monitor import ResourceMonitor

    monitor = ResourceMonitor(os.path.join(exp_dir, "resources.csv"))
    monitor.start()
    try:
        for pid, shard in all_pids:
            ids = shard_ids[shard]
            offset = offset_of[pid]
            peers = [p for p in ids if p != pid]
            sorted_entries = [f"{pid}:{shard}"] + [f"{p}:{shard}" for p in peers]
            for other, other_ids in shard_ids.items():
                if other != shard:
                    closest = other_ids[offset]
                    peers.append(closest)
                    sorted_entries.append(f"{closest}:{other}")
            addresses = ",".join(f"{p}=127.0.0.1:{peer_ports[p]}" for p in peers)
            args = config.server_args(
                pid,
                shard,
                peer_ports[pid],
                client_ports[pid],
                addresses,
                ",".join(sorted_entries),
                observe_dir=exp_dir,
            )
            log = open(os.path.join(exp_dir, f"server_p{pid}.log"), "w")
            logs.append(log)
            servers.append(
                subprocess.Popen(
                    [sys.executable, "-m", "fantoch_tpu.bin.server", *args],
                    stdout=log,
                    stderr=subprocess.STDOUT,
                    env=env,
                )
            )

        # clients attach to the offset-0 process of every shard
        client_addresses = ",".join(
            f"{s}=127.0.0.1:{client_ports[ids[0]]}" for s, ids in shard_ids.items()
        )
        n_clients = config.clients_per_process * config.n
        client = subprocess.run(
            [
                sys.executable,
                "-m",
                "fantoch_tpu.bin.client",
                *config.client_args(
                    f"1-{n_clients}",
                    client_addresses,
                    metrics_file=os.path.join(exp_dir, "client_data.pkl"),
                ),
            ],
            capture_output=True,
            text=True,
            timeout=client_timeout_s,
            env=env,
        )
        if client.returncode != 0:
            raise RuntimeError(
                f"client failed:\n{client.stdout}\n{client.stderr}"
            )
        summary = json.loads(client.stdout.strip().splitlines()[-1])
        with open(os.path.join(exp_dir, "client_summary.json"), "w") as fh:
            json.dump(summary, fh)
        # let the metrics loggers take a final-interval snapshot
        time.sleep(0.7)
    finally:
        monitor.stop()
        for proc in servers:
            proc.send_signal(signal.SIGINT)
        for proc in servers:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
        for log in logs:
            log.close()

    manifest = {
        "config": config.to_dict(),
        "name": config.name(),
        "outcome": {
            "commands": summary["commands"],
            "latency_ms": summary["latency_ms"],
            # measured inside the client binary, excluding its startup
            "wall_s": summary["elapsed_s"],
            "throughput_cmds_per_s": summary["throughput_cmds_per_s"],
        },
    }
    with open(os.path.join(exp_dir, "manifest.json"), "w") as fh:
        json.dump(manifest, fh, indent=2)
    return manifest


def run_sweep(
    base: ExperimentConfig,
    output_dir: str,
    clients_sweep,
    testbed: str = "localhost",
    client_timeout_s: int = 600,
) -> list:
    """The reference's main experiment shape: the same protocol config at
    increasing client counts (fantoch_exp/src/bin/main.rs clients_per
    sweep), producing one experiment dir per point — exactly what
    plot.throughput_latency needs for a real curve."""
    manifests = []
    for clients in clients_sweep:
        cfg = dataclasses.replace(base, clients_per_process=clients)
        manifests.append(
            run_experiment(
                cfg,
                output_dir,
                testbed=testbed,
                client_timeout_s=client_timeout_s,
            )
        )
    return manifests
