"""Experiment driver: boot a testbed, run one experiment, collect results.

Reference: fantoch_exp/src/bench.rs:43-260 (run the protocol + client
binaries with generated flags, wait for completion, pull metrics files)
over a testbed (testbed/local.rs for localhost, testbed/baremetal.rs for
SSH host lists — see fantoch_tpu/exp/testbed.py).  Each experiment leaves
a results directory::

    <output_dir>/<config.name()>/
        manifest.json        — the ExperimentConfig + outcome summary
        client_data.pkl      — per-client latency data (client binary)
        client_summary.json  — the client binary's stdout summary
        metrics_p*.gz        — per-process metrics snapshots (pulled)
        execution_p*.log     — per-process execution logs (pulled)
        server_p*.log        — server stdout/stderr
        resources.jsonl      — driver-machine resource series (dstat analog,
                               telemetry-window JSONL schema)

which fantoch_tpu.plot's ResultsDB indexes.  One driver body serves every
testbed: the testbed object owns addressing, launch transport, and
artifact pull (so a real SSH cluster differs from localhost only in the
HostsTestbed constructor).
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
import time
from typing import Dict

from fantoch_tpu.exp.config import ExperimentConfig
from fantoch_tpu.utils import logger

# server artifacts land here relative to each process's workdir, then are
# pulled into the experiment dir
_RESULTS_REL = "testbed_results"
# per-process profiler artifact filename by run mode — one definition for
# the spawn wrapper and the result pull
_PROFILE_ARTIFACTS = {
    "cprofile": "profile_p{pid}.prof",
    "memory": "memory_p{pid}.txt",
}


def _cli_env() -> Dict[str, str]:
    from fantoch_tpu.exp.testbed import cli_env

    return cli_env()


def run_experiment(
    config: ExperimentConfig,
    output_dir: str,
    testbed="localhost",
    client_timeout_s: int = 600,
    run_mode: str = "release",
) -> Dict:
    """Run one experiment end to end; returns the manifest dict.

    ``testbed``: "localhost" (subprocesses on this machine), or a
    :class:`fantoch_tpu.exp.testbed.HostsTestbed` (SSH host list — the
    baremetal.rs analog: stage the tree, launch remotely, pull results).
    A caller-provided HostsTestbed is caller-owned (reuse it across a
    sweep); its locally staged copies are removed in a ``finally`` here
    since stage() re-creates them on demand.

    ``run_mode``: "release" (plain servers), "cprofile" (CPU — the
    RunMode::Flamegraph analog) or "memory" (tracemalloc — the
    RunMode::Heaptrack analog); fantoch_exp/src/lib.rs:26-67.  Under a
    profiling mode every server runs wrapped, its artifact is pulled with
    the results (cProfile additionally gets a cumulative-time top-30 text
    rendering); both profilers dump in a ``finally``, so the SIGINT
    teardown still produces the artifact."""
    from fantoch_tpu.exp.testbed import HostsTestbed, LocalTestbed

    assert run_mode in ("release", "cprofile", "memory"), run_mode
    if testbed == "localhost":
        testbed = LocalTestbed()
    elif not isinstance(testbed, HostsTestbed):
        raise NotImplementedError(
            f"testbed {testbed!r}: the reference's AWS orchestration "
            "(fantoch_exp/src/testbed/aws.rs over tsunami/rusoto) has no "
            "cloud access in this environment; use 'localhost' or a "
            "HostsTestbed (exp/testbed.py)"
        )
    try:
        return _run_experiment_testbed(
            config, output_dir, testbed, client_timeout_s, run_mode
        )
    finally:
        if not testbed.use_ssh:
            testbed.cleanup()


def _run_experiment_testbed(
    config: ExperimentConfig,
    output_dir: str,
    testbed,
    client_timeout_s: int,
    run_mode: str = "release",
) -> Dict:
    from fantoch_tpu.core.ids import process_ids
    from fantoch_tpu.exp.monitor import RESOURCES_FILE, ResourceMonitor

    exp_dir = os.path.join(output_dir, config.name())
    os.makedirs(exp_dir, exist_ok=True)
    testbed.stage()
    testbed.prepare(exp_dir)

    if config.device_step:
        # TPU serving path: ONE server hosts the whole (replica x batch)
        # mesh — no peer processes, no peer mesh; clients open one
        # connection per shard, all to the same address
        shard_ids = {0: [1]}
        all_pids = [(1, 0)]
        offset_of = {1: 0}
        host_of = {1: 0}
    else:
        shard_ids = {s: list(process_ids(s, config.n)) for s in range(config.shard_count)}
        all_pids = [(pid, s) for s, ids in shard_ids.items() for pid in ids]
        offset_of = {pid: pid - shard_ids[s][0] for pid, s in all_pids}
        host_of = {pid: i for i, (pid, _s) in enumerate(all_pids)}

    servers = []
    logs = []
    # dstat analog: driver-machine resource CSV for the plot layer's tables
    monitor = ResourceMonitor(os.path.join(exp_dir, RESOURCES_FILE))
    monitor.start()
    try:
        for pid, shard in all_pids:
            if config.device_step:
                args = config.device_server_args(
                    testbed.client_port(pid), observe_dir=_RESULTS_REL
                )
            else:
                ids = shard_ids[shard]
                offset = offset_of[pid]
                peers = [p for p in ids if p != pid]
                sorted_entries = [f"{pid}:{shard}"] + [f"{p}:{shard}" for p in peers]
                for other, other_ids in shard_ids.items():
                    if other != shard:
                        closest = other_ids[offset]
                        peers.append(closest)
                        sorted_entries.append(f"{closest}:{other}")
                addresses = ",".join(
                    f"{p}={testbed.addr(host_of[p])}:{testbed.peer_port(p)}"
                    for p in peers
                )
                args = config.server_args(
                    pid,
                    shard,
                    testbed.peer_port(pid),
                    testbed.client_port(pid),
                    addresses,
                    ",".join(sorted_entries),
                    observe_dir=_RESULTS_REL,  # workdir-relative; pulled below
                    # local (non-ssh) testbeds co-locate every server on
                    # this machine: forgive scheduler starvation in the
                    # failure detector (real multi-host runs keep defaults)
                    shared_machine=not getattr(testbed, "use_ssh", False),
                )
            log = open(os.path.join(exp_dir, f"server_p{pid}.log"), "w")
            logs.append(log)
            servers.append(
                (
                    pid,
                    testbed.spawn(
                        host_of[pid],
                        "fantoch_tpu.bin.server",
                        args,
                        log,
                        pre_dirs=[_RESULTS_REL],
                        profile_artifact=(
                            f"{_RESULTS_REL}/"
                            + _PROFILE_ARTIFACTS[run_mode].format(pid=pid)
                            if run_mode in _PROFILE_ARTIFACTS
                            else None
                        ),
                        profile_kind=(
                            "memory" if run_mode == "memory" else "cprofile"
                        ),
                        pidfile=f"{_RESULTS_REL}/server_p{pid}.pid",
                    ),
                )
            )

        # clients run on the driver machine against the offset-0 process of
        # every shard (device-step: every shard lives on the one server)
        if config.device_step:
            one = f"{testbed.addr(0)}:{testbed.client_port(1)}"
            client_addresses = ",".join(
                f"{s}={one}" for s in range(config.shard_count)
            )
        else:
            client_addresses = ",".join(
                f"{s}={testbed.addr(host_of[ids[0]])}:{testbed.client_port(ids[0])}"
                for s, ids in shard_ids.items()
            )
        n_clients = config.clients_per_process * config.n
        client = subprocess.run(
            [
                sys.executable,
                "-m",
                "fantoch_tpu.bin.client",
                *config.client_args(
                    f"1-{n_clients}",
                    client_addresses,
                    metrics_file=os.path.join(exp_dir, "client_data.pkl"),
                ),
            ],
            capture_output=True,
            text=True,
            timeout=client_timeout_s,
            env=_cli_env(),
        )
        if client.returncode != 0:
            raise RuntimeError(
                f"client failed:\n{client.stdout}\n{client.stderr}"
            )
        summary = json.loads(client.stdout.strip().splitlines()[-1])
        with open(os.path.join(exp_dir, "client_summary.json"), "w") as fh:
            json.dump(summary, fh)
        # let the metrics loggers take a final-interval snapshot
        time.sleep(0.7)
    finally:
        monitor.stop()
        for pid, proc in servers:
            # in-band on both transports: over ssh a plain client exit
            # would SIGHUP-kill the remote python, skipping cProfile's
            # dump and the final metrics snapshot
            testbed.interrupt(
                proc, host_of[pid], f"{_RESULTS_REL}/server_p{pid}.pid"
            )
        for _pid, proc in servers:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
        for log in logs:
            log.close()

    # pull per-process artifacts back from the machines that produced them
    pulled = []
    if config.device_step:
        # the device server's tallies are JSON; there is no execution log
        suffixes = ["metrics_p{pid}.json"]
    else:
        suffixes = ["metrics_p{pid}.gz", "execution_p{pid}.log"]
    if run_mode in _PROFILE_ARTIFACTS:
        suffixes.append(_PROFILE_ARTIFACTS[run_mode])
    for pid, _shard in all_pids:
        for pattern in suffixes:
            rel = pattern.format(pid=pid)
            if testbed.pull(
                host_of[pid],
                f"{_RESULTS_REL}/{rel}",
                os.path.join(exp_dir, rel),
            ):
                pulled.append(rel)
    if run_mode == "cprofile":
        # render each profile to text (the flamegraph-artifact analog:
        # human-readable without tooling)
        import pstats

        for pid, _shard in all_pids:
            prof = os.path.join(
                exp_dir, _PROFILE_ARTIFACTS["cprofile"].format(pid=pid)
            )
            if not os.path.exists(prof):
                continue
            txt = os.path.join(exp_dir, f"profile_p{pid}.txt")
            try:
                with open(txt, "w") as fh:
                    stats = pstats.Stats(prof, stream=fh)
                    stats.sort_stats("cumulative").print_stats(30)
                pulled.append(os.path.basename(txt))
            except Exception as exc:  # noqa: BLE001 — a SIGKILLed server
                # leaves a truncated dump; the experiment's results must
                # still be indexed
                logger.warning("unreadable profile %s: %r", prof, exc)

    manifest = {
        "config": config.to_dict(),
        "name": config.name(),
        "run_mode": run_mode,
        "testbed": {**testbed.describe(), "pulled": pulled},
        "outcome": {
            "commands": summary["commands"],
            "latency_ms": summary["latency_ms"],
            # measured inside the client binary, excluding its startup
            "wall_s": summary["elapsed_s"],
            "throughput_cmds_per_s": summary["throughput_cmds_per_s"],
        },
    }
    with open(os.path.join(exp_dir, "manifest.json"), "w") as fh:
        json.dump(manifest, fh, indent=2)
    return manifest


def run_sweep(
    base: ExperimentConfig,
    output_dir: str,
    clients_sweep,
    testbed="localhost",
    client_timeout_s: int = 600,
    run_mode: str = "release",
) -> list:
    """The reference's main experiment shape: the same protocol config at
    increasing client counts (fantoch_exp/src/bin/main.rs clients_per
    sweep), producing one experiment dir per point — exactly what
    plot.throughput_latency needs for a real curve."""
    manifests = []
    for clients in clients_sweep:
        cfg = dataclasses.replace(base, clients_per_process=clients)
        manifests.append(
            run_experiment(
                cfg,
                output_dir,
                testbed=testbed,
                client_timeout_s=client_timeout_s,
                run_mode=run_mode,
            )
        )
    return manifests
