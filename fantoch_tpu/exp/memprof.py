"""tracemalloc wrapper for experiment servers — the RunMode::Heaptrack
analog (fantoch_exp/src/lib.rs:26-67: a memory profiler wraps the server
binary and its artifact is pulled with the results).

Usage (what the testbeds exec):

    python -m fantoch_tpu.exp.memprof -o ARTIFACT -m MODULE [args...]

Starts tracemalloc, runs MODULE as ``__main__`` and writes a text report
(total current/peak traced bytes, top allocation sites by line, top
tracebacks) to ARTIFACT in a ``finally`` — the SIGINT teardown the
testbeds use to stop servers still produces the artifact, mirroring the
cProfile mode's finally-dump behavior.
"""

from __future__ import annotations

import runpy
import sys
import tracemalloc

_FRAMES = 12  # traceback depth kept per allocation
_TOP_LINES = 40
_TOP_TRACES = 10


def _write_report(artifact: str) -> None:
    snapshot = tracemalloc.take_snapshot()
    current, peak = tracemalloc.get_traced_memory()
    with open(artifact, "w") as f:
        f.write(
            f"# tracemalloc: current={current} bytes, peak={peak} bytes "
            f"({_FRAMES} frames/alloc)\n\n# top {_TOP_LINES} by line\n"
        )
        for stat in snapshot.statistics("lineno")[:_TOP_LINES]:
            f.write(f"{stat}\n")
        f.write(f"\n# top {_TOP_TRACES} by traceback\n")
        for stat in snapshot.statistics("traceback")[:_TOP_TRACES]:
            f.write(f"{stat.size / 1024:.1f} KiB in {stat.count} blocks\n")
            for line in stat.traceback.format():
                f.write(line + "\n")
            f.write("\n")


def main() -> None:
    argv = sys.argv
    if len(argv) < 5 or argv[1] != "-o" or argv[3] != "-m":
        raise SystemExit(
            "usage: python -m fantoch_tpu.exp.memprof -o ARTIFACT -m MODULE [args...]"
        )
    artifact, module = argv[2], argv[4]
    sys.argv = [module, *argv[5:]]
    tracemalloc.start(_FRAMES)
    try:
        runpy.run_module(module, run_name="__main__", alter_sys=True)
    finally:
        _write_report(artifact)


if __name__ == "__main__":
    main()
