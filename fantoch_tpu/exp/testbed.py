"""Host-list (SSH/baremetal) testbed: plain machines, no cloud API.

Reference: fantoch_exp/src/testbed/baremetal.rs — the reference reads a
machines file, sets each host up over SSH (tsunami's baremetal provider),
launches the protocol/client binaries remotely, and pulls artifacts back.
The analog here:

* ``HostsTestbed([...])`` takes ``user@host`` entries; ``stage()`` rsyncs
  the repo to every distinct host, ``spawn()`` launches a framework
  binary on host *i* via ``ssh host 'cd <dir> && python -m ...'``, and
  ``pull()`` copies result files back.
* ``use_ssh=False`` runs the SAME built command strings through
  ``bash -c`` against a locally staged copy — the whole orchestration
  layer (staging, remote command construction, artifact pull) runs and is
  testable on machines with no sshd (this rig), and a real cluster only
  changes the transport.

``exp.bench.run_experiment(config, out, testbed=HostsTestbed(...))``
drives a whole experiment through it; ``LocalTestbed`` implements the
same interface with plain subprocesses on this machine (the localhost
testbed of testbed/local.rs), so the experiment driver has ONE body.
"""

from __future__ import annotations

import os
import shlex
import shutil
import signal
import subprocess
import sys
from typing import Dict, List, Optional


def cli_env(platform: str = "cpu") -> Dict[str, str]:
    """Environment scrub for framework subprocesses: pin the backend via
    FANTOCH_PLATFORM (in-Python forcing — a JAX_PLATFORMS env var hangs
    interpreter start under TPU sitecustomize hooks, so it is stripped),
    and put the repo on PYTHONPATH."""
    env = dict(os.environ)
    env["FANTOCH_PLATFORM"] = env.get("FANTOCH_PLATFORM", platform)
    env.pop("JAX_PLATFORMS", None)
    repo = os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    return env


class LocalTestbed:
    """Subprocesses on this machine behind the HostsTestbed interface."""

    use_ssh = False
    hosts: List[str] = ["localhost"]

    def __init__(self) -> None:
        self._ports: Dict[int, int] = {}
        self._workdir: Optional[str] = None

    def describe(self) -> Dict:
        return {"kind": "localhost"}

    def addr(self, _index: int) -> str:
        return "127.0.0.1"

    def _port(self, slot: int) -> int:
        from fantoch_tpu.run.harness import free_port

        if slot not in self._ports:
            self._ports[slot] = free_port()
        return self._ports[slot]

    def peer_port(self, pid: int) -> int:
        return self._port(pid)

    def client_port(self, pid: int) -> int:
        return self._port(10_000 + pid)

    def stage(self) -> None:
        pass

    def prepare(self, exp_dir: str) -> None:
        """The experiment dir doubles as the (only) workdir: artifacts
        land in place and pull() is a no-op existence check."""
        self._workdir = exp_dir

    def spawn(
        self,
        index: int,
        module: str,
        args: List[str],
        stdout,
        pre_dirs: Optional[List[str]] = None,
        profile_artifact: Optional[str] = None,
        pidfile: Optional[str] = None,
        profile_kind: str = "cprofile",
    ) -> subprocess.Popen:
        """``profile_artifact``: workdir-relative artifact path — the
        server runs under a profiler that writes there on exit (the
        RunMode::Flamegraph/Heaptrack analogs,
        fantoch_exp/src/lib.rs:26-67: a profiler wraps the server binary
        and its artifact is pulled with the results).  ``profile_kind``:
        "cprofile" (CPU, .prof) or "memory" (tracemalloc text report via
        fantoch_tpu.exp.memprof).  ``pidfile`` is unused locally
        (interrupt() signals the child directly)."""
        assert self._workdir is not None, "prepare(exp_dir) first"
        env = cli_env()
        for d in pre_dirs or []:
            os.makedirs(os.path.join(self._workdir, d), exist_ok=True)
        cmd = [sys.executable, "-m", module, *args]
        if profile_artifact is not None:
            wrapper = (
                ["cProfile", "-o"] if profile_kind == "cprofile"
                else ["fantoch_tpu.exp.memprof", "-o"]
            )
            cmd = [
                sys.executable, "-m", *wrapper, profile_artifact,
                "-m", module, *args,
            ]
        return subprocess.Popen(
            cmd,
            stdout=stdout,
            stderr=subprocess.STDOUT,
            env=env,
            cwd=self._workdir,
        )

    def pull(self, _index: int, remote_rel: str, local_path: str) -> bool:
        src = os.path.join(self._workdir or "", remote_rel)
        if not os.path.exists(src):
            return False
        if os.path.abspath(src) != os.path.abspath(local_path):
            shutil.copyfile(src, local_path)
        return True

    def interrupt(self, proc: subprocess.Popen, _index: int, _pidfile_rel: str) -> None:
        """Deliver SIGINT to a spawned server (local: straight to the
        child — cProfile's finally-dump fires on KeyboardInterrupt)."""
        proc.send_signal(signal.SIGINT)

    def cleanup(self) -> None:
        pass

_SSH_OPTS = [
    "-o", "StrictHostKeyChecking=no",
    "-o", "BatchMode=yes",
]
_STAGE_EXCLUDES = [".git", "__pycache__", ".jax_cache", ".pytest_cache"]


class HostsTestbed:
    """A list of SSH-reachable machines serving as the cluster."""

    def __init__(
        self,
        hosts: List[str],
        *,
        use_ssh: bool = True,
        remote_dir: str = "~/fantoch_tpu_run",
        python: str = "python3",
        base_port: int = 7800,
        platform: str = "cpu",
        repo_dir: Optional[str] = None,
    ):
        assert hosts, "a hosts testbed needs at least one host"
        self.hosts = list(hosts)
        self.use_ssh = use_ssh
        self.remote_dir = remote_dir
        self.python = python
        self.base_port = base_port
        # backend the remote servers force in-Python (a TPU cluster passes
        # platform="tpu" — the transport is the only other difference from
        # a localhost run)
        self.platform = platform
        self.repo_dir = repo_dir or os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        self._local_dirs: Dict[str, str] = {}  # per-host staged copy (local mode)
        self._local_ports: Dict[int, int] = {}  # local mode: OS-probed ports

    def describe(self) -> Dict:
        return {"kind": "hosts", "hosts": self.hosts, "ssh": self.use_ssh}

    def prepare(self, exp_dir: str) -> None:
        pass  # artifacts live in the per-host workdirs until pull()

    def __enter__(self) -> "HostsTestbed":
        return self

    def __exit__(self, *_exc) -> None:
        self.cleanup()

    # --- addressing ---

    def addr(self, index: int) -> str:
        """The TCP address peers/clients dial for host ``index``."""
        if not self.use_ssh:
            return "127.0.0.1"
        host = self.hosts[index % len(self.hosts)]
        return host.split("@", 1)[-1]

    def peer_port(self, pid: int) -> int:
        return self._derived_port(pid)

    def client_port(self, pid: int) -> int:
        return self._derived_port(1000 + pid)

    def _derived_port(self, slot: int) -> int:
        """Over ssh the ports must be predictable on the remote (base +
        offset).  In local mode all servers share this machine, where
        ``base + offset`` arithmetic can collide with any concurrently
        bound socket (base_port usually comes from free_port(), i.e. the
        ephemeral range a loaded test suite is actively allocating from) —
        probe each port from the OS instead, memoized per slot."""
        if self.use_ssh:
            return self.base_port + slot
        if slot not in self._local_ports:
            from fantoch_tpu.run.harness import free_port

            self._local_ports[slot] = free_port()
        return self._local_ports[slot]

    # --- staging (baremetal.rs setup: clone/sync the tree per machine) ---

    def stage(self) -> None:
        if self.use_ssh:
            for host in dict.fromkeys(self.hosts):
                subprocess.run(
                    [
                        "rsync", "-az", "--delete",
                        *[f"--exclude={e}" for e in _STAGE_EXCLUDES],
                        "-e", "ssh " + " ".join(_SSH_OPTS),
                        f"{self.repo_dir}/",
                        f"{host}:{self.remote_dir}/",
                    ],
                    check=True,
                    capture_output=True,
                    timeout=300,
                )
            return
        # local mode: one staged copy per distinct host entry, so the
        # launched processes genuinely run out of the staged tree
        import tempfile

        for host in dict.fromkeys(self.hosts):
            if host in self._local_dirs:
                continue
            dst = tempfile.mkdtemp(prefix=f"fantoch_stage_{host.replace('@', '_')}_")
            shutil.copytree(
                self.repo_dir,
                dst,
                dirs_exist_ok=True,
                ignore=shutil.ignore_patterns(*_STAGE_EXCLUDES),
            )
            self._local_dirs[host] = dst

    def _workdir(self, index: int) -> str:
        host = self.hosts[index % len(self.hosts)]
        if self.use_ssh:
            return self.remote_dir
        return self._local_dirs[host]

    # --- launch / pull ---

    def _remote_command(
        self,
        index: int,
        module: str,
        args: List[str],
        pre_dirs: Optional[List[str]] = None,
        profile_artifact: Optional[str] = None,
        pidfile: Optional[str] = None,
        profile_kind: str = "cprofile",
    ) -> str:
        """The command string a remote shell runs (identical in both
        transports — that's the point of the local mode)."""
        argv = " ".join(shlex.quote(a) for a in args)
        mkdirs = "".join(
            f"mkdir -p {shlex.quote(d)} && " for d in (pre_dirs or [])
        )
        profile_mod = (
            "cProfile" if profile_kind == "cprofile" else "fantoch_tpu.exp.memprof"
        )
        profile = (
            f"-m {profile_mod} -o {shlex.quote(profile_artifact)} "
            if profile_artifact is not None
            else ""
        )
        # $$ is the shell's pid, which exec turns into the python's pid:
        # the pidfile gives interrupt() an in-band target over ssh (a
        # plain ssh client exit only SIGHUPs the remote, which skips
        # Python's KeyboardInterrupt path and any profiler dump)
        pidf = (
            f"echo $$ > {shlex.quote(pidfile)} && " if pidfile is not None else ""
        )
        # exec: the launched python replaces the shell, so teardown signals
        # (SIGINT locally, kill -INT via the pidfile over ssh) reach it.
        # -u JAX_PLATFORMS: a caller's backend override must not leak into
        # the staged servers (the localhost testbed scrubs it the same way)
        return (
            f"cd {self._workdir(index)} && {mkdirs}{pidf}"
            f"exec env -u JAX_PLATFORMS PYTHONPATH=. "
            f"FANTOCH_PLATFORM={shlex.quote(self.platform)} "
            f"{shlex.quote(self._python_for(index))} {profile}-m {module} {argv}"
        )

    def _python_for(self, index: int) -> str:
        # local mode must use THIS interpreter (the remote default python3
        # may not carry the deps)
        return self.python if self.use_ssh else sys.executable

    def spawn(
        self,
        index: int,
        module: str,
        args: List[str],
        stdout,
        pre_dirs: Optional[List[str]] = None,
        profile_artifact: Optional[str] = None,
        pidfile: Optional[str] = None,
        profile_kind: str = "cprofile",
    ) -> subprocess.Popen:
        command = self._remote_command(
            index, module, args, pre_dirs, profile_artifact, pidfile,
            profile_kind,
        )
        if self.use_ssh:
            host = self.hosts[index % len(self.hosts)]
            argv = ["ssh", *_SSH_OPTS, host, command]
        else:
            argv = ["bash", "-c", command]
        return subprocess.Popen(
            argv, stdout=stdout, stderr=subprocess.STDOUT
        )

    def interrupt(self, proc: subprocess.Popen, index: int, pidfile_rel: str) -> None:
        """Deliver SIGINT to the server behind ``proc``: locally the
        exec'd python IS the child; over ssh, in-band via the pidfile
        (connection teardown alone would SIGHUP-kill the remote python
        without raising KeyboardInterrupt, losing profiler artifacts and
        final metrics snapshots)."""
        if not self.use_ssh:
            proc.send_signal(signal.SIGINT)
            return
        host = self.hosts[index % len(self.hosts)]
        pidpath = f"{self.remote_dir}/{pidfile_rel}"
        subprocess.run(
            [
                "ssh", *_SSH_OPTS, host,
                f"kill -INT $(cat {shlex.quote(pidpath)}) 2>/dev/null || true",
            ],
            capture_output=True,
            timeout=30,
        )

    def pull(self, index: int, remote_rel: str, local_path: str) -> bool:
        """Copy one artifact back from host ``index``; False if absent."""
        if self.use_ssh:
            host = self.hosts[index % len(self.hosts)]
            out = subprocess.run(
                [
                    "scp", *_SSH_OPTS,
                    f"{host}:{self.remote_dir}/{remote_rel}",
                    local_path,
                ],
                capture_output=True,
                timeout=120,
            )
            return out.returncode == 0
        src = os.path.join(self._workdir(index), remote_rel)
        if not os.path.exists(src):
            return False
        shutil.copyfile(src, local_path)
        return True

    def cleanup(self) -> None:
        for path in self._local_dirs.values():
            shutil.rmtree(path, ignore_errors=True)
        self._local_dirs.clear()
