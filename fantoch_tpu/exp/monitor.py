"""Machine resource monitoring during experiments — the dstat analog.

Reference: fantoch_exp runs ``dstat`` on every machine and ships the CSVs
into the experiment directory (fantoch_exp/src/bench.rs:22,203-258); the
plot layer renders them as resource tables (fantoch_plot/src/lib.rs
dstat tables).  No dstat binary here: sample ``/proc`` directly — cpu
jiffies from /proc/stat, memory from /proc/meminfo, network byte counts
from /proc/net/dev — into the same kind of per-interval CSV.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

_CSV_HEADER = "epoch_s,cpu_pct,mem_used_mb,mem_total_mb,net_rx_kbps,net_tx_kbps"


def _read_cpu() -> tuple:
    """(busy, total) jiffies across all cpus."""
    with open("/proc/stat") as fh:
        fields = fh.readline().split()[1:]
    vals = [int(v) for v in fields]
    idle = vals[3] + (vals[4] if len(vals) > 4 else 0)  # idle + iowait
    total = sum(vals)
    return total - idle, total


def _read_mem() -> tuple:
    """(used_mb, total_mb) like dstat's mem usage (total - available)."""
    info: Dict[str, int] = {}
    with open("/proc/meminfo") as fh:
        for line in fh:
            name, value, *_ = line.split()
            info[name.rstrip(":")] = int(value)  # kB
    total = info.get("MemTotal", 0)
    avail = info.get("MemAvailable", info.get("MemFree", 0))
    return (total - avail) / 1024.0, total / 1024.0


def _read_net() -> tuple:
    """(rx_bytes, tx_bytes) summed over non-loopback interfaces."""
    rx = tx = 0
    with open("/proc/net/dev") as fh:
        for line in fh.readlines()[2:]:
            name, data = line.split(":", 1)
            if name.strip() == "lo":
                continue
            vals = data.split()
            rx += int(vals[0])
            tx += int(vals[8])
    return rx, tx


class ResourceMonitor:
    """Samples cpu/mem/net into ``path`` every ``interval_s`` until stopped.

    Thread-based (the experiment driver is synchronous subprocess
    orchestration); sampling reads three procfs files per tick.
    """

    def __init__(self, path: str, interval_s: float = 1.0):
        self._path = path
        self._interval_s = interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def __enter__(self) -> "ResourceMonitor":
        self.start()
        return self

    def __exit__(self, *_exc) -> None:
        self.stop()

    def start(self) -> None:
        self._stop.clear()  # support restart after stop()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self._interval_s + 2)
            self._thread = None

    def _run(self) -> None:
        try:
            self._run_inner()
        except Exception:  # noqa: BLE001 — sampling is best-effort by design
            # no procfs (non-Linux host) or an unexpected /proc line format:
            # stop sampling quietly rather than killing the daemon thread
            # with a traceback mid-run.  Samples flushed so far stay on
            # disk; only write the header when nothing was ever written
            # (so resource_table always finds a parsable CSV).
            import os

            try:
                if not os.path.exists(self._path) or os.path.getsize(self._path) == 0:
                    with open(self._path, "w") as fh:
                        fh.write(_CSV_HEADER + "\n")
            except OSError:
                pass

    def _run_inner(self) -> None:
        busy0, total0 = _read_cpu()
        rx0, tx0 = _read_net()
        t0 = time.time()
        with open(self._path, "w") as fh:
            fh.write(_CSV_HEADER + "\n")
            while not self._stop.wait(self._interval_s):
                busy1, total1 = _read_cpu()
                rx1, tx1 = _read_net()
                t1 = time.time()
                dt = max(t1 - t0, 1e-6)
                cpu = 100.0 * (busy1 - busy0) / max(total1 - total0, 1)
                used_mb, total_mb = _read_mem()
                fh.write(
                    f"{t1:.3f},{cpu:.1f},{used_mb:.1f},{total_mb:.1f},"
                    f"{(rx1 - rx0) / dt / 1024.0:.1f},"
                    f"{(tx1 - tx0) / dt / 1024.0:.1f}\n"
                )
                fh.flush()
                busy0, total0, rx0, tx0, t0 = busy1, total1, rx1, tx1, t1


def load_samples(path: str) -> List[Dict[str, float]]:
    """Parse a monitor CSV back into row dicts."""
    out: List[Dict[str, float]] = []
    if not os.path.exists(path):
        return out
    with open(path) as fh:
        header = fh.readline().strip().split(",")
        for line in fh:
            vals = line.strip().split(",")
            if len(vals) == len(header):
                out.append({k: float(v) for k, v in zip(header, vals)})
    return out
