"""Machine resource monitoring during experiments — the dstat analog.

Reference: fantoch_exp runs ``dstat`` on every machine and ships the CSVs
into the experiment directory (fantoch_exp/src/bench.rs:22,203-258); the
plot layer renders them as resource tables (fantoch_plot/src/lib.rs
dstat tables).  No dstat binary here: sample ``/proc`` directly — cpu
jiffies from /proc/stat, memory from /proc/meminfo, network byte counts
from /proc/net/dev.

Since the live-telemetry plane landed, host resources are just another
*series source*: the monitor emits the same windowed JSONL schema
(observability/timeseries.py, ``src="host"``) every other telemetry
writer uses — cumulative jiffy/byte counters (the writer rates them per
window) plus memory gauges — so ``obs watch`` renders host load next to
a cluster's submit/reply rates, and the bespoke CSV format is gone.
``load_samples`` still returns the dstat-shaped row dicts the plot layer
tables (and one release of old ``resources.csv`` files) expect.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional

# the experiment artifact name (exp/bench.py writes it per run dir)
RESOURCES_FILE = "resources.jsonl"

_CSV_HEADER = "epoch_s,cpu_pct,mem_used_mb,mem_total_mb,net_rx_kbps,net_tx_kbps"


def _read_cpu() -> tuple:
    """(busy, total) jiffies across all cpus."""
    with open("/proc/stat") as fh:
        fields = fh.readline().split()[1:]
    vals = [int(v) for v in fields]
    idle = vals[3] + (vals[4] if len(vals) > 4 else 0)  # idle + iowait
    total = sum(vals)
    return total - idle, total


def _read_mem() -> tuple:
    """(used_mb, total_mb) like dstat's mem usage (total - available)."""
    info: Dict[str, int] = {}
    with open("/proc/meminfo") as fh:
        for line in fh:
            name, value, *_ = line.split()
            info[name.rstrip(":")] = int(value)  # kB
    total = info.get("MemTotal", 0)
    avail = info.get("MemAvailable", info.get("MemFree", 0))
    return (total - avail) / 1024.0, total / 1024.0


def _read_net() -> tuple:
    """(rx_bytes, tx_bytes) summed over non-loopback interfaces."""
    rx = tx = 0
    with open("/proc/net/dev") as fh:
        for line in fh.readlines()[2:]:
            name, data = line.split(":", 1)
            if name.strip() == "lo":
                continue
            vals = data.split()
            rx += int(vals[0])
            tx += int(vals[8])
    return rx, tx


class ResourceMonitor:
    """Samples cpu/mem/net into ``path`` (telemetry-series JSONL,
    ``src="host"``) every ``interval_s`` until stopped.

    Thread-based (the experiment driver is synchronous subprocess
    orchestration); sampling reads three procfs files per tick.
    """

    def __init__(self, path: str, interval_s: float = 1.0):
        self._path = path
        self._interval_s = interval_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def __enter__(self) -> "ResourceMonitor":
        self.start()
        return self

    def __exit__(self, *_exc) -> None:
        self.stop()

    def start(self) -> None:
        self._stop.clear()  # support restart after stop()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self._interval_s + 2)
            self._thread = None

    def _run(self) -> None:
        try:
            self._run_inner()
        except Exception:  # noqa: BLE001 — sampling is best-effort by design
            # no procfs (non-Linux host) or an unexpected /proc line format:
            # stop sampling quietly rather than killing the daemon thread
            # with a traceback mid-run.  Windows flushed so far stay on
            # disk; ensure an (empty but parsable) file always exists so
            # resource_table finds one.
            try:
                if not os.path.exists(self._path):
                    with open(self._path, "w"):
                        pass
            except OSError:
                pass

    def _run_inner(self) -> None:
        from fantoch_tpu.core.timing import RunTime
        from fantoch_tpu.observability.timeseries import SeriesWriter

        writer = SeriesWriter(
            self._path,
            RunTime(),
            window_ms=max(1, int(self._interval_s * 1000)),
        )
        try:
            while not self._stop.wait(self._interval_s):
                busy, total = _read_cpu()
                rx, tx = _read_net()
                used_mb, total_mb = _read_mem()
                # cumulative counters in, per-second rates out (the
                # writer owns the delta arithmetic); memory is a gauge
                writer.emit(
                    "host",
                    counters={
                        "cpu_busy_jiffies": busy,
                        "cpu_total_jiffies": total,
                        "net_rx_bytes": rx,
                        "net_tx_bytes": tx,
                    },
                    gauges={
                        "mem_used_mb": round(used_mb, 1),
                        "mem_total_mb": round(total_mb, 1),
                    },
                )
                writer.flush()
        finally:
            writer.close()


def _rows_from_windows(windows: List[dict]) -> List[Dict[str, float]]:
    """Telemetry windows -> the dstat-shaped rows the plot tables eat."""
    out: List[Dict[str, float]] = []
    for window in windows:
        if window.get("k") != "win" or window.get("src") != "host":
            continue
        if window.get("seq", 0) == 0:
            # the first window rates against the writer's construction
            # instant, before the first /proc sample — skip it like the
            # CSV sampler skipped its baseline read
            continue
        rate = window.get("rate", {})
        gauges = window.get("g", {})
        total_rate = rate.get("cpu_total_jiffies", 0.0)
        out.append({
            "epoch_s": window["t"] / 1e6,
            "cpu_pct": round(
                100.0 * rate.get("cpu_busy_jiffies", 0.0) / total_rate, 1
            ) if total_rate else 0.0,
            "mem_used_mb": gauges.get("mem_used_mb", 0.0),
            "mem_total_mb": gauges.get("mem_total_mb", 0.0),
            "net_rx_kbps": round(rate.get("net_rx_bytes", 0.0) / 1024.0, 1),
            "net_tx_kbps": round(rate.get("net_tx_bytes", 0.0) / 1024.0, 1),
        })
    return out


def _rows_from_csv(path: str) -> List[Dict[str, float]]:
    """Pre-telemetry ``resources.csv`` compatibility (one release)."""
    out: List[Dict[str, float]] = []
    with open(path) as fh:
        header = fh.readline().strip().split(",")
        for line in fh:
            vals = line.strip().split(",")
            if len(vals) == len(header):
                out.append({k: float(v) for k, v in zip(header, vals)})
    return out


def load_samples(path: str) -> List[Dict[str, float]]:
    """Parse a monitor artifact back into dstat-shaped row dicts.

    Reads the telemetry-series JSONL (``resources.jsonl``); old
    experiment dirs holding the retired CSV format (or a ``.jsonl`` path
    whose sibling ``resources.csv`` exists) still load for one release.
    """
    if os.path.exists(path):
        if path.endswith(".csv"):
            return _rows_from_csv(path)
        from fantoch_tpu.observability.timeseries import read_series

        return _rows_from_windows(read_series(path))
    legacy = os.path.join(os.path.dirname(path), "resources.csv")
    if os.path.exists(legacy):
        return _rows_from_csv(legacy)
    return []
