"""Scenario observatory: declarative sweep factory + saturation curves.

The fantoch_exp/fantoch_plot multiplier (PAPER.md L7): every protocol,
nemesis, plane, and knob already in the repo becomes *comparable* only
when it rides a swept throughput-latency curve, not a single point.  A
:class:`ScenarioSpec` declares the whole cross product once — protocol
x (n, f) x fault plan (incl. device faults) x key skew x read/write mix
x multi-key txn mix x offered open-loop rate x Config knobs (pipeline /
ingest / pallas / planes) x placement — and :func:`expand` turns it into
a deterministic run matrix:

  * same spec + seed => byte-identical expansion
    (:func:`canonical_expansion`), and on the sim timeline byte-identical
    per-cell traces (every cell seed is a stable hash of the spec seed
    and the cell name — never Python's randomized ``hash``);
  * placement is a config *output*: ``{"mode": "search"}`` runs the
    planner (:meth:`fantoch_tpu.planner.Search.best_placement`) under the
    scenario's latency objective and records the chosen regions (plus the
    identity-placement baseline it beat) in the expansion manifest;
  * zipf specs report the expected multi-shard / multi-key command
    fraction (``bin/shard_distribution.compute_distribution``) as the
    partial-replication planner input.

:func:`run_scenario` executes each cell through the existing harnesses —
the deterministic sim runner (virtual-time open-loop Poisson arrivals,
trace + telemetry capture into the per-cell obs dir) or the localhost
TCP ``run_overload_phase`` — then sweeps the offered-rate axis into full
throughput-latency CURVES: p50/p95/p99 vs goodput per point, saturation
knee detection (:func:`detect_knee`), shed/degraded annotations from the
overload (PR 8) and accelerator-fault (PR 17) counters, and typed
per-cell SLO verdicts (target p99 / min goodput declared in the spec).
Results land as ``plot/db.py``-indexable per-cell manifests plus one
machine-readable ``curves.json`` (``plot.db.save_curves``) rendered by
``plot.plots.saturation_curves``.

Saturation on the sim timeline is real, not simulated noise: goodput is
measured over the client-reconstructed serving span (first submit ->
last completion), and as the offered rate grows the arrival window
compresses below the fixed commit-latency tail, capping goodput at
``total_commands / completion_span`` — a deterministic knee.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

# spec protocol name -> lazy export in fantoch_tpu.protocol
_PROTOCOLS = {
    "basic": "Basic",
    "epaxos": "EPaxos",
    "atlas": "Atlas",
    "newt": "Newt",
    "fpaxos": "FPaxos",
    "caesar": "Caesar",
}


def protocol_class(name: str):
    import fantoch_tpu.protocol as protocol

    if name not in _PROTOCOLS:
        raise ValueError(
            f"unknown protocol {name!r} (know {sorted(_PROTOCOLS)})"
        )
    return getattr(protocol, _PROTOCOLS[name])


@dataclass(frozen=True)
class ScenarioSpec:
    """One declarative scenario: the full sweep cross product + SLO.

    JSON round-trips via :meth:`to_dict` / :meth:`from_dict` (and
    :func:`load_spec` for files), so a spec file IS the experiment."""

    name: str
    protocols: Tuple[str, ...] = ("epaxos",)
    # (n, f) pairs
    sites: Tuple[Tuple[int, int], ...] = ((3, 1),)
    timeline: str = "sim"  # "sim" (virtual time) | "run" (localhost TCP)
    seed: int = 0
    planet: str = "gcp"
    # workload axes
    clients_per_process: int = 2
    commands_per_client: int = 20
    key_gen: str = "conflict_rate"  # or "zipf"
    conflict_rate: int = 50
    zipf_coefficient: float = 1.0
    keys_per_shard: int = 1_000_000
    keys_per_command: int = 1
    payload_size: int = 0
    read_only_percentage: int = 0
    # partial-replication planner input (ROADMAP item 2 prep): the shard
    # count the zipf multi-shard fraction is *reported* for in the
    # expansion manifest; execution stays single-shard
    planner_shard_count: int = 1
    # offered open-loop rate axis (cluster cmds/s).  Explicit points, or
    # a geometric ladder {"start_cmds_per_s", "factor", "points"} swept
    # toward saturation; both empty = one closed-loop cell
    rates: Tuple[float, ...] = ()
    rate_sweep: Optional[Dict[str, Any]] = None
    # sim-only fault schedule (sim/faults.FaultPlan.to_dict shape,
    # device faults included)
    fault_plan: Optional[Dict[str, Any]] = None
    # Config.with_ overrides (pipeline depth, ingest deadline, pallas,
    # device planes, admission limit, trace/telemetry knobs, ...)
    knobs: Dict[str, Any] = field(default_factory=dict)
    # placement: {"mode": "regions", "regions": [...], "clients": [...]}
    # pins it; {"mode": "search", "candidates": [...], "clients": [...],
    # "objective": "mean"|"p95"|"p99"|"max", "colocated": bool} makes it
    # a planner OUTPUT; {"mode": "closest"} (default) takes the planet's
    # first n regions (sorted)
    placement: Dict[str, Any] = field(
        default_factory=lambda: {"mode": "closest"}
    )
    # {"p99_ms": float, "min_goodput_cmds_per_s": float} — either key
    # optional; verdicts are typed pass/fail per cell
    slo: Optional[Dict[str, Any]] = None
    extra_sim_time_ms: int = 0

    def __post_init__(self):
        if self.timeline not in ("sim", "run"):
            raise ValueError(f"timeline must be sim|run, got {self.timeline!r}")
        if self.key_gen not in ("conflict_rate", "zipf"):
            raise ValueError(f"unknown key_gen {self.key_gen!r}")
        for name in self.protocols:
            if name not in _PROTOCOLS:
                raise ValueError(f"unknown protocol {name!r}")
        if self.timeline == "run" and self.fault_plan is not None:
            raise ValueError(
                "fault_plan is sim-only (the run timeline has no nemesis "
                "hook in run_overload_phase)"
            )

    def to_dict(self) -> Dict[str, Any]:
        out = dataclasses.asdict(self)
        out["protocols"] = list(self.protocols)
        out["sites"] = [list(site) for site in self.sites]
        out["rates"] = list(self.rates)
        return out

    @staticmethod
    def from_dict(data: Dict[str, Any]) -> "ScenarioSpec":
        data = dict(data)
        data["protocols"] = tuple(data.get("protocols", ("epaxos",)))
        data["sites"] = tuple(
            tuple(site) for site in data.get("sites", ((3, 1),))
        )
        data["rates"] = tuple(data.get("rates", ()))
        known = {f.name for f in dataclasses.fields(ScenarioSpec)}
        unknown = set(data) - known
        if unknown:
            raise ValueError(f"unknown spec field(s): {sorted(unknown)}")
        return ScenarioSpec(**data)


def load_spec(path: str) -> ScenarioSpec:
    with open(path) as fh:
        return ScenarioSpec.from_dict(json.load(fh))


# --- deterministic expansion ---


def cell_seed(spec_seed: int, cell_name: str) -> int:
    """Stable per-cell seed: sha256 over ``"<seed>:<cell>"`` — never
    Python's per-process-randomized ``hash`` (same spec + seed must
    derive the same seeds on every machine, every run)."""
    digest = hashlib.sha256(f"{spec_seed}:{cell_name}".encode()).digest()
    return int.from_bytes(digest[:4], "big")


def resolve_rates(spec: ScenarioSpec) -> List[Optional[float]]:
    """The offered-rate axis: explicit points win; else the geometric
    ladder; else one closed-loop cell (rate None)."""
    if spec.rates:
        return [float(r) for r in spec.rates]
    if spec.rate_sweep:
        start = float(spec.rate_sweep["start_cmds_per_s"])
        factor = float(spec.rate_sweep.get("factor", 2.0))
        points = int(spec.rate_sweep.get("points", 4))
        assert start > 0 and factor > 1 and points >= 1, spec.rate_sweep
        return [start * factor**i for i in range(points)]
    return [None]


def _rate_tag(rate: Optional[float]) -> str:
    if rate is None:
        return "closed"
    text = f"{rate:g}".replace(".", "_")
    return f"r{text}"


def _planet(spec: ScenarioSpec, planet=None):
    if planet is not None:
        return planet
    from fantoch_tpu.core.planet import Planet

    return Planet.new(spec.planet)


def _region_names(regions) -> List[str]:
    return [r.name for r in regions]


def _resolve_placement(
    spec: ScenarioSpec, protocol: str, n: int, f: int, planet
) -> Dict[str, Any]:
    """Server + client regions for one (protocol, n, f) — searched under
    the scenario's latency objective when the spec asks for it, so
    placement is an expansion OUTPUT recorded in the manifest."""
    from fantoch_tpu.core.planet import Region

    mode = spec.placement.get("mode", "closest")
    if mode == "regions":
        servers = [Region(name) for name in spec.placement["regions"][:n]]
        assert len(servers) == n, (
            f"placement pins {len(servers)} regions, cell needs n={n}"
        )
        clients = [
            Region(name) for name in spec.placement.get("clients", [])
        ] or list(servers)
        return {
            "mode": "regions",
            "regions": _region_names(servers),
            "clients": _region_names(clients),
        }
    if mode == "closest":
        servers = sorted(planet.regions())[:n]
        return {
            "mode": "closest",
            "regions": _region_names(servers),
            "clients": _region_names(servers),
        }
    if mode == "search":
        from fantoch_tpu.planner import Search

        names = spec.placement.get("candidates")
        candidates = (
            [Region(name) for name in names]
            if names
            else sorted(planet.regions())
        )
        client_names = spec.placement.get("clients")
        clients = (
            [Region(name) for name in client_names]
            if client_names
            else list(candidates)
        )
        objective = spec.placement.get("objective", "mean")
        colocated = bool(spec.placement.get("colocated", False))
        search = Search(planet, candidates, clients)
        best = search.best_placement(
            protocol, n, f, objective=objective, colocated=colocated
        )
        identity = search.placement_objective(
            candidates[:n], protocol, f, objective=objective,
            colocated=colocated,
        )
        return {
            "mode": "search",
            "objective": objective,
            "objective_ms": best.value,
            "identity_regions": _region_names(candidates[:n]),
            "identity_objective_ms": identity,
            "regions": _region_names(best.regions),
            "clients": _region_names(clients) if not colocated
            else _region_names(best.regions),
        }
    raise ValueError(f"unknown placement mode {mode!r}")


def _workload_report(spec: ScenarioSpec) -> Dict[str, Any]:
    """The expansion manifest's workload section.  Zipf specs carry the
    expected multi-shard / multi-key fraction at the spec's planner
    shard count (bin/shard_distribution) — the partial-replication
    planner input the sweep exists to feed."""
    out: Dict[str, Any] = {
        "key_gen": spec.key_gen,
        "keys_per_command": spec.keys_per_command,
        "read_only_percentage": spec.read_only_percentage,
        "payload_size": spec.payload_size,
    }
    if spec.key_gen == "zipf":
        from fantoch_tpu.bin.shard_distribution import compute_distribution

        out["zipf_coefficient"] = spec.zipf_coefficient
        out.update(
            compute_distribution(
                shard_count=spec.planner_shard_count,
                keys_per_command=spec.keys_per_command,
                coefficient=spec.zipf_coefficient,
                keys_per_shard=spec.keys_per_shard,
                commands=2000,
                seed=spec.seed,
            )
        )
    else:
        out["conflict_rate"] = spec.conflict_rate
    return out


def expand(spec: ScenarioSpec, planet=None) -> Dict[str, Any]:
    """Spec -> run matrix.  Pure of wall clock and process state: the
    manifest depends only on (spec, planet dataset), so re-expansion is
    byte-identical (:func:`canonical_expansion`)."""
    planet = _planet(spec, planet)
    rates = resolve_rates(spec)
    placements: Dict[str, Dict[str, Any]] = {}
    cells: List[Dict[str, Any]] = []
    for protocol in spec.protocols:
        for n, f in spec.sites:
            site_key = f"{protocol}_n{n}_f{f}"
            placement = _resolve_placement(spec, protocol, n, f, planet)
            placements[site_key] = placement
            for rate in rates:
                name = f"{site_key}_{_rate_tag(rate)}"
                cells.append(
                    {
                        "index": len(cells),
                        "name": name,
                        "protocol": protocol,
                        "n": n,
                        "f": f,
                        "rate_cmds_per_s": rate,
                        "seed": cell_seed(spec.seed, name),
                        "regions": placement["regions"],
                        "client_regions": placement["clients"],
                    }
                )
    return {
        "scenario": spec.name,
        "spec": spec.to_dict(),
        "workload": _workload_report(spec),
        "placements": placements,
        "cells": cells,
    }


def canonical_expansion(spec: ScenarioSpec, planet=None) -> str:
    """The byte-identity contract: canonical JSON (sorted keys, fixed
    separators) of :func:`expand` — same spec + seed => same bytes."""
    return json.dumps(
        expand(spec, planet), sort_keys=True, separators=(",", ":")
    )


# --- cell execution ---


def _build_config(spec: ScenarioSpec, n: int, f: int):
    from fantoch_tpu.core.config import Config

    config = Config(
        n=n,
        f=f,
        shard_count=1,
        gc_interval_ms=100,
        executor_executed_notification_interval_ms=100,
    )
    if spec.knobs:
        config = config.with_(**spec.knobs)
    return config


def _build_workload(spec: ScenarioSpec):
    from fantoch_tpu.client.key_gen import ZipfKeyGen
    from fantoch_tpu.client.workload import Workload
    from fantoch_tpu.client import ConflictRateKeyGen

    if spec.key_gen == "zipf":
        key_gen = ZipfKeyGen(spec.zipf_coefficient, spec.keys_per_shard)
    else:
        key_gen = ConflictRateKeyGen(spec.conflict_rate)
    return Workload(
        shard_count=1,
        key_gen=key_gen,
        keys_per_command=spec.keys_per_command,
        commands_per_client=spec.commands_per_client,
        payload_size=spec.payload_size,
        read_only_percentage=spec.read_only_percentage,
    )


def _percentile_ms(latencies_us: Sequence[int], q: float) -> Optional[float]:
    if not latencies_us:
        return None
    index = min(len(latencies_us) - 1, int(len(latencies_us) * q))
    return round(latencies_us[index] / 1000.0, 3)


def _run_sim_cell(
    spec: ScenarioSpec, cell: Dict[str, Any], cell_dir: str, planet
) -> Dict[str, Any]:
    from fantoch_tpu.core.planet import Region
    from fantoch_tpu.sim.faults import FaultPlan
    from fantoch_tpu.sim.runner import Runner

    config = _build_config(spec, cell["n"], cell["f"])
    regions = [Region(name) for name in cell["regions"]]
    client_regions = [Region(name) for name in cell["client_regions"]]
    rate = cell["rate_cmds_per_s"]
    client_count = spec.clients_per_process * len(client_regions)
    per_client = rate / client_count if rate is not None else None
    fault_plan = (
        FaultPlan.from_dict(spec.fault_plan)
        if spec.fault_plan is not None
        else None
    )
    trace_path = (
        os.path.join(cell_dir, "trace.jsonl")
        if config.trace_sample_rate > 0
        else None
    )
    runner = Runner(
        protocol_class(cell["protocol"]),
        planet,
        config,
        _build_workload(spec),
        spec.clients_per_process,
        process_regions=regions,
        client_regions=client_regions,
        seed=cell["seed"],
        fault_plan=fault_plan,
        trace_path=trace_path,
        open_loop_rate_per_s=per_client,
        telemetry_path=os.path.join(cell_dir, "telemetry.jsonl"),
    )
    runner.run(spec.extra_sim_time_ms or None)
    summary = runner.serving_summary()
    latencies = summary["latencies_us"]
    span_s = summary["span_ms"] / 1000.0
    goodput = (
        round(summary["completed"] / span_s, 2) if span_s > 0 else 0.0
    )
    device = summary["device"]
    return {
        "commands": summary["completed"],
        "offered_cmds_per_s": rate,
        "goodput_cmds_per_s": goodput,
        # plots.heatmap/throughput_latency compatibility key
        "throughput_cmds_per_s": goodput,
        "span_s": round(span_s, 4),
        "latency_ms": {
            "p50": _percentile_ms(latencies, 0.50),
            "p95": _percentile_ms(latencies, 0.95),
            "p99": _percentile_ms(latencies, 0.99),
        },
        # overload/degraded annotations: the sim has no admission plane
        # (sheds live in the run layer), the device-fault counters fold
        # across every process's planes
        "sheds": 0,
        "queue_depth_hwm": 0,
        "degraded_ms": round(device.get("degraded_ms", 0.0), 3),
        "failovers": int(device.get("failovers", 0)),
    }


def _run_tcp_cell(
    spec: ScenarioSpec, cell: Dict[str, Any], cell_dir: str
) -> Dict[str, Any]:
    from fantoch_tpu.run.harness import run_overload_phase

    config = _build_config(spec, cell["n"], cell["f"])
    rate = cell["rate_cmds_per_s"]
    client_count = spec.clients_per_process * cell["n"]
    row = run_overload_phase(
        protocol_class(cell["protocol"]),
        config,
        _build_workload(spec),
        spec.clients_per_process,
        arrival_rate_per_s=(
            rate / client_count if rate is not None else None
        ),
        arrival_seed=cell["seed"],
    )
    device = row["device"] or {}
    return {
        "commands": row["completed"],
        "offered_cmds_per_s": rate,
        "goodput_cmds_per_s": row["goodput_cmds_per_s"],
        "throughput_cmds_per_s": row["goodput_cmds_per_s"],
        "latency_ms": {
            "p50": row["p50_ms"],
            "p95": row["p95_ms"],
            "p99": row["p99_ms"],
        },
        "sheds": row["sheds"] + row["shed_commands"],
        "queue_depth_hwm": row["queue_depth_hwm"],
        "degraded_ms": round(device.get("degraded_ms", 0.0), 3),
        "failovers": int(device.get("failovers", 0)),
    }


def run_cell(
    spec: ScenarioSpec, cell: Dict[str, Any], out_dir: str, planet=None
) -> Dict[str, Any]:
    """Execute one cell into ``<out_dir>/<cell name>/``: telemetry +
    trace capture (sim), and a ``plot.db.ResultsDB``-indexable
    ``manifest.json``.  Returns the outcome dict."""
    cell_dir = os.path.join(out_dir, cell["name"])
    os.makedirs(cell_dir, exist_ok=True)
    if spec.timeline == "sim":
        outcome = _run_sim_cell(spec, cell, cell_dir, _planet(spec, planet))
    else:
        outcome = _run_tcp_cell(spec, cell, cell_dir)
    manifest = {
        "config": {
            "scenario": spec.name,
            "timeline": spec.timeline,
            "protocol": cell["protocol"],
            "n": cell["n"],
            "f": cell["f"],
            "clients_per_process": spec.clients_per_process,
            "key_gen": spec.key_gen,
            "conflict_rate": spec.conflict_rate,
            "zipf_coefficient": spec.zipf_coefficient,
            "keys_per_command": spec.keys_per_command,
            "read_only_percentage": spec.read_only_percentage,
            "rate_cmds_per_s": cell["rate_cmds_per_s"],
            "seed": cell["seed"],
        },
        "outcome": outcome,
    }
    with open(os.path.join(cell_dir, "manifest.json"), "w") as fh:
        json.dump(manifest, fh, sort_keys=True, indent=1)
        fh.write("\n")
    return outcome


# --- saturation-knee detection ---


def detect_knee(
    points: Sequence[Dict[str, Any]],
    efficiency: float = 0.75,
    min_gain: float = 0.05,
    min_offered_growth: float = 0.2,
) -> Optional[int]:
    """Index (into offered-rate order) of the first saturated point, or
    None for an unsaturated curve.  A point is saturated when either

      * its serving efficiency (goodput / offered) fell below
        ``efficiency`` x the FIRST point's efficiency (capped at 1) —
        relative, because a finite open-loop run's serving span always
        carries a fixed straggler-arrival + commit-latency tail, so even
        an unsaturated point sits below offered by a workload-dependent
        constant the lightest point calibrates out; or
      * the offered rate grew by ``min_offered_growth`` over the previous
        point while goodput gained less than ``min_gain`` (the curve went
        flat: extra offered load buys nothing).

    The calibration point itself can never trip the efficiency rule (a
    one-point curve carries no saturation evidence).  Pure and
    deterministic — callers sort points by offered rate; points without
    an offered rate (closed loop) never saturate."""
    prev = None
    reference_eff = None
    for index, point in enumerate(points):
        offered = point.get("offered_cmds_per_s")
        goodput = point.get("goodput_cmds_per_s") or 0.0
        if offered is None or offered <= 0:
            prev = None
            continue
        eff = goodput / offered
        if reference_eff is None:
            reference_eff = min(1.0, eff)
        elif eff < efficiency * reference_eff:
            return index
        if prev is not None:
            prev_offered, prev_goodput = prev
            if prev_goodput > 0 and prev_offered > 0:
                growth = (offered - prev_offered) / prev_offered
                gain = (goodput - prev_goodput) / prev_goodput
                if growth >= min_offered_growth and gain < min_gain:
                    return index
        prev = (offered, goodput)
    return None


def _slo_verdict(
    spec: ScenarioSpec, cell_name: str, point: Dict[str, Any]
) -> Dict[str, Any]:
    """Typed pass/fail for one cell against the spec's SLO block."""
    checks: Dict[str, Any] = {}
    slo = spec.slo or {}
    if "p99_ms" in slo:
        actual = point["p99_ms"]
        checks["p99_ms"] = {
            "target": slo["p99_ms"],
            "actual": actual,
            "pass": actual is not None and actual <= slo["p99_ms"],
        }
    if "min_goodput_cmds_per_s" in slo:
        actual = point["goodput_cmds_per_s"]
        checks["min_goodput_cmds_per_s"] = {
            "target": slo["min_goodput_cmds_per_s"],
            "actual": actual,
            "pass": actual >= slo["min_goodput_cmds_per_s"],
        }
    return {
        "cell": cell_name,
        "checks": checks,
        "pass": all(c["pass"] for c in checks.values()),
    }


def build_curves(
    spec: ScenarioSpec,
    expansion: Dict[str, Any],
    outcomes: Dict[str, Dict[str, Any]],
) -> Dict[str, Any]:
    """Assemble the per-(protocol, n, f) throughput-latency curves from
    executed cells: points sorted by offered rate, knee detection, SLO
    verdicts.  This document IS ``curves.json``."""
    groups: Dict[Tuple[str, int, int], List[Dict[str, Any]]] = {}
    for cell in expansion["cells"]:
        outcome = outcomes.get(cell["name"])
        if outcome is None:
            continue
        point = {
            "cell": cell["name"],
            "offered_cmds_per_s": cell["rate_cmds_per_s"],
            "goodput_cmds_per_s": outcome["goodput_cmds_per_s"],
            "commands": outcome["commands"],
            "p50_ms": outcome["latency_ms"]["p50"],
            "p95_ms": outcome["latency_ms"]["p95"],
            "p99_ms": outcome["latency_ms"]["p99"],
            "sheds": outcome["sheds"],
            "queue_depth_hwm": outcome["queue_depth_hwm"],
            "degraded_ms": outcome["degraded_ms"],
            "failovers": outcome["failovers"],
        }
        key = (cell["protocol"], cell["n"], cell["f"])
        groups.setdefault(key, []).append(point)
    curves = []
    for (protocol, n, f), points in sorted(groups.items()):
        points.sort(
            key=lambda p: (
                p["offered_cmds_per_s"] is not None,
                p["offered_cmds_per_s"] or 0.0,
            )
        )
        knee_index = detect_knee(points)
        verdicts = [
            _slo_verdict(spec, p["cell"], p) for p in points
        ]
        curves.append(
            {
                "protocol": protocol,
                "n": n,
                "f": f,
                "points": points,
                "knee_index": knee_index,
                "knee": points[knee_index] if knee_index is not None else None,
                "slo": verdicts,
            }
        )
    return {
        "scenario": spec.name,
        "timeline": spec.timeline,
        "seed": spec.seed,
        "slo": spec.slo,
        "workload": expansion["workload"],
        "placements": expansion["placements"],
        "curves": curves,
    }


def run_scenario(
    spec: ScenarioSpec, out_dir: str, planet=None, render: bool = True
) -> Dict[str, Any]:
    """Expand, execute every cell, assemble + persist the curves.

    Writes ``expansion.json`` (canonical bytes), per-cell obs dirs, and
    ``curves.json`` under ``out_dir``; renders ``curves.png`` through
    ``plot.plots.saturation_curves`` unless ``render=False``.  Returns
    the curves document."""
    from fantoch_tpu.plot.db import save_curves

    planet = _planet(spec, planet)
    os.makedirs(out_dir, exist_ok=True)
    canonical = canonical_expansion(spec, planet)
    with open(os.path.join(out_dir, "expansion.json"), "w") as fh:
        fh.write(canonical)
        fh.write("\n")
    expansion = json.loads(canonical)
    outcomes: Dict[str, Dict[str, Any]] = {}
    for cell in expansion["cells"]:
        outcomes[cell["name"]] = run_cell(spec, cell, out_dir, planet)
    doc = build_curves(spec, expansion, outcomes)
    save_curves(doc, os.path.join(out_dir, "curves.json"))
    if render:
        from fantoch_tpu.plot import plots

        plots.saturation_curves(doc, os.path.join(out_dir, "curves.png"))
    return doc
