"""Closed-loop client state machine.

Reference: fantoch/src/client/mod.rs:27-170.  A client generates commands
from its workload, targets the closest process of the target shard, and
records end-to-end latency per command.
"""

from __future__ import annotations

import dataclasses
import random
from typing import Dict, List, Optional, Set, Tuple

from fantoch_tpu.client.data import ClientData
from fantoch_tpu.client.pending import Pending
from fantoch_tpu.client.workload import Workload
from fantoch_tpu.core.command import Command, CommandResult
from fantoch_tpu.core.ids import ClientId, ProcessId, RiflGen, ShardId
from fantoch_tpu.core.timing import SysTime
from fantoch_tpu.utils import logger


class Client:
    def __init__(
        self,
        client_id: ClientId,
        workload: Workload,
        status_frequency: Optional[int] = None,
        rng: Optional[random.Random] = None,
    ):
        self._client_id = client_id
        self._processes: Dict[ShardId, ProcessId] = {}
        self._rifl_gen = RiflGen(client_id)
        # each client gets its own copy of the workload progress counter
        self._workload = dataclasses.replace(workload)
        self._key_gen_state = workload.initial_key_gen_state(client_id, rng)
        self._pending = Pending()
        self._data = ClientData()
        self._status_frequency = status_frequency
        # overload-plane tallies (run/backpressure.py): submissions the
        # server shed (typed Overloaded replies retried with backoff)
        # and commands this client itself abandoned past their deadline
        # budget — goodput accounting for the latency-under-load plots
        self.overload_retries = 0
        self.shed_commands = 0
        # live-telemetry seam (observability/timeseries.py): an optional
        # per-completion callback fed each latency sample (µs) as it is
        # recorded, so the telemetry writers maintain their cumulative
        # latency histogram at O(1) per reply instead of re-walking
        # every recorded sample per window
        self._latency_observer = None

    @property
    def id(self) -> ClientId:
        return self._client_id

    def connect(self, processes: Dict[ShardId, ProcessId]) -> None:
        """Learn the closest process of each shard."""
        self._processes = processes

    def shard_process(self, shard_id: ShardId) -> ProcessId:
        return self._processes[shard_id]

    def targets(self) -> Set[ProcessId]:
        """Every process this client submits to (one per shard) — the sim's
        nemesis abandons clients whose target crashed."""
        return set(self._processes.values())

    def next_cmd(self, time: SysTime) -> Optional[Tuple[ShardId, Command]]:
        nxt = self._workload.next_cmd(self._rifl_gen, self._key_gen_state)
        if nxt is not None:
            _, cmd = nxt
            self._pending.start(cmd.rifl, time)
        return nxt

    def handle(self, cmd_results: List[CommandResult], time: SysTime) -> bool:
        """Record completion of one command (possibly split over shards);
        returns True once the whole workload is generated and completed."""
        rifls = {r.rifl for r in cmd_results}
        assert len(rifls) == 1, "all results must belong to the same rifl"
        rifl = rifls.pop()
        latency, end_time = self._pending.end(rifl, time)
        self._data.record(latency, end_time)
        if self._latency_observer is not None:
            self._latency_observer(latency)
        if self._status_frequency and self._workload.issued_commands % self._status_frequency == 0:
            logger.info(
                "c%s: %s of %s",
                self._client_id,
                self._workload.issued_commands,
                self._workload.commands_per_client,
            )
        return self.done

    def shed(self, rifl) -> None:
        """Abandon an in-flight command (deadline budget expired while
        the server kept shedding it): no latency sample is recorded —
        shed work is *not* executed late — and the shed is tallied for
        the goodput accounting."""
        self._pending.cancel(rifl)
        self.shed_commands += 1

    @property
    def done(self) -> bool:
        """Workload fully generated and nothing in flight (completed or
        shed) — the drivers' shared termination predicate."""
        return self._workload.finished() and self._pending.is_empty()

    def set_latency_observer(self, observer) -> None:
        """``observer(latency_micros)`` fires on every completion
        (telemetry's incremental latency fold); None disables."""
        self._latency_observer = observer

    def data(self) -> ClientData:
        return self._data

    @property
    def issued_commands(self) -> int:
        return self._workload.issued_commands
