"""Per-client latency/throughput records.

Reference: fantoch/src/client/data.rs:6-157 — a map from end-time (ms) to
the latencies (µs) of commands that finished then, with merge/prune and
latency & throughput iterators.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Tuple


class ClientData:
    def __init__(self) -> None:
        # end time (ms) -> list of latencies (µs)
        self._data: Dict[int, List[int]] = {}

    def record(self, latency_micros: int, end_time_millis: int) -> None:
        self._data.setdefault(end_time_millis, []).append(latency_micros)

    def merge(self, other: "ClientData") -> None:
        for end_time, latencies in other._data.items():
            self._data.setdefault(end_time, []).extend(latencies)

    def prune(self, start_millis: int, end_millis: int) -> None:
        """Keep only commands that ended within [start, end] (warmup/cooldown
        trimming in experiments)."""
        self._data = {
            t: ls for t, ls in self._data.items() if start_millis <= t <= end_millis
        }

    def latency_data(self) -> Iterator[int]:
        """All latencies in µs."""
        for latencies in self._data.values():
            yield from latencies

    def throughput_data(self) -> Iterator[Tuple[int, int]]:
        """(end_time_ms, commands finished at that ms)."""
        for end_time in sorted(self._data):
            yield end_time, len(self._data[end_time])

    def start_and_end(self) -> Tuple[int, int]:
        assert self._data, "no data recorded"
        times = self._data.keys()
        return min(times), max(times)

    def span_millis(self) -> Tuple[float, int]:
        """(first command's submit time, last command's end time), ms —
        the client's actual serving span reconstructed from the records
        (submit = end - latency), so throughput accounting can exclude
        harness boot/teardown wall it never served through."""
        assert self._data, "no data recorded"
        first = min(
            end - max(latencies) / 1000.0
            for end, latencies in self._data.items()
        )
        return first, max(self._data)

    def command_count(self) -> int:
        return sum(len(ls) for ls in self._data.values())
