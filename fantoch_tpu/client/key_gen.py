"""Key generators: conflict-rate (single hot key) and zipfian.

Reference: fantoch/src/client/key_gen.rs:8-117.
"""

from __future__ import annotations

import random
from dataclasses import dataclass
from typing import Optional, Union

import numpy as np

from fantoch_tpu.core.ids import ClientId
from fantoch_tpu.core.kvs import Key

# single color accessed by all conflicting operations
CONFLICT_COLOR = "CONFLICT"


@dataclass(frozen=True)
class ConflictRateKeyGen:
    """With probability `conflict_rate`% produce the shared hot key, else a
    client-private key."""

    conflict_rate: int

    def __str__(self) -> str:
        return f"conflict{self.conflict_rate}"


@dataclass(frozen=True)
class ZipfKeyGen:
    coefficient: float
    keys_per_shard: int

    def __str__(self) -> str:
        return f"zipf{self.coefficient:.2f}".replace(".", "-")


KeyGen = Union[ConflictRateKeyGen, ZipfKeyGen]


class KeyGenState:
    """Per-client sampling state (key_gen.rs:46-108)."""

    def __init__(self, key_gen: KeyGen, shard_count: int, client_id: ClientId,
                 rng: Optional[random.Random] = None):
        self._key_gen = key_gen
        self._client_id = client_id
        self._rng = rng or random.Random()
        self._zipf_cdf: Optional[np.ndarray] = None
        if isinstance(key_gen, ZipfKeyGen):
            key_count = key_gen.keys_per_shard * shard_count
            # zipf pmf over ranks 1..key_count with exponent `coefficient`
            ranks = np.arange(1, key_count + 1, dtype=np.float64)
            weights = ranks ** (-key_gen.coefficient)
            self._zipf_cdf = np.cumsum(weights / weights.sum())

    @property
    def rng(self) -> random.Random:
        return self._rng

    def gen_cmd_key(self) -> Key:
        if isinstance(self._key_gen, ConflictRateKeyGen):
            if true_if_random_is_less_than(self._key_gen.conflict_rate, self._rng):
                return CONFLICT_COLOR
            return str(self._client_id)
        # zipf: sample a rank from the precomputed cdf
        assert self._zipf_cdf is not None
        u = self._rng.random()
        rank = int(np.searchsorted(self._zipf_cdf, u)) + 1
        return str(rank)


def true_if_random_is_less_than(percentage: int, rng: Optional[random.Random] = None) -> bool:
    """Reference: key_gen.rs:111-117 (0 and 100 are deterministic)."""
    if percentage == 0:
        return False
    if percentage == 100:
        return True
    rng = rng or random
    return rng.randrange(100) < percentage
