from fantoch_tpu.client.client import Client
from fantoch_tpu.client.data import ClientData
from fantoch_tpu.client.key_gen import CONFLICT_COLOR, ConflictRateKeyGen, KeyGen, KeyGenState, ZipfKeyGen
from fantoch_tpu.client.pending import Pending
from fantoch_tpu.client.workload import Workload
