"""Client-side latency tracking of in-flight commands.

Reference: fantoch/src/client/pending.rs:6-51.  Times are microseconds.
"""

from __future__ import annotations

from typing import Dict, Tuple

from fantoch_tpu.core.ids import Rifl
from fantoch_tpu.core.timing import SysTime


class Pending:
    def __init__(self) -> None:
        self._pending: Dict[Rifl, int] = {}

    def start(self, rifl: Rifl, time: SysTime) -> None:
        assert rifl not in self._pending, "the same rifl can't be started twice"
        self._pending[rifl] = time.micros()

    def end(self, rifl: Rifl, time: SysTime) -> Tuple[int, int]:
        """Returns (latency_micros, end_time_millis)."""
        start_time = self._pending.pop(rifl, None)
        assert start_time is not None, "can't end a command that has not started"
        end_time = time.micros()
        assert start_time <= end_time, "time must be monotonic"
        return end_time - start_time, end_time // 1000

    def cancel(self, rifl: Rifl) -> None:
        """Drop an in-flight command without recording a latency — the
        shed path of the overload plane (a command abandoned past its
        deadline budget must not pollute the latency data)."""
        self._pending.pop(rifl, None)

    def is_empty(self) -> bool:
        return not self._pending
