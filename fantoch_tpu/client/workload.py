"""Workload: command generation for clients.

Reference: fantoch/src/client/workload.rs:12-230.
"""

from __future__ import annotations

import random
import string
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from fantoch_tpu.client.key_gen import (
    ConflictRateKeyGen,
    KeyGen,
    KeyGenState,
    true_if_random_is_less_than,
)
from fantoch_tpu.core.command import Command
from fantoch_tpu.core.ids import RiflGen, ShardId
from fantoch_tpu.core.kvs import KVOp, Key, Value
from fantoch_tpu.utils import key_hash

_PAYLOAD_ALPHABET = string.ascii_letters + string.digits


@dataclass
class Workload:
    shard_count: int
    key_gen: KeyGen
    keys_per_command: int
    commands_per_client: int
    payload_size: int
    read_only_percentage: int = 0
    command_count: int = 0  # commands already issued

    def __post_init__(self) -> None:
        # valid-workload checks (workload.rs:37-49)
        if isinstance(self.key_gen, ConflictRateKeyGen):
            assert self.key_gen.conflict_rate <= 100, "conflict rate must be <= 100"
            if self.key_gen.conflict_rate == 100 and self.keys_per_command > 1:
                raise ValueError(
                    "can't generate more than one key when the conflict_rate is 100"
                )
            if self.key_gen.conflict_rate == 0 and self.keys_per_command > 1:
                raise ValueError(
                    "conflict_rate 0 yields a single distinct key per client; "
                    "keys_per_command > 1 would loop forever"
                )
            if self.keys_per_command > 2:
                raise ValueError(
                    "can't generate more than two keys with the conflict_rate key generator"
                )
        assert 0 <= self.read_only_percentage <= 100

    def initial_key_gen_state(self, client_id: int, rng: Optional[random.Random] = None) -> KeyGenState:
        return KeyGenState(self.key_gen, self.shard_count, client_id, rng)

    def next_cmd(
        self, rifl_gen: RiflGen, key_gen_state: KeyGenState
    ) -> Optional[Tuple[ShardId, Command]]:
        if self.command_count >= self.commands_per_client:
            return None
        self.command_count += 1
        return self._gen_cmd(rifl_gen, key_gen_state)

    @property
    def issued_commands(self) -> int:
        return self.command_count

    def finished(self) -> bool:
        return self.command_count == self.commands_per_client

    def _gen_cmd(self, rifl_gen: RiflGen, key_gen_state: KeyGenState) -> Tuple[ShardId, Command]:
        """Generate one command; the target shard is the shard of the first
        key generated (workload.rs:136-177)."""
        rifl = rifl_gen.next_id()
        keys = self._gen_unique_keys(key_gen_state)
        read_only = true_if_random_is_less_than(self.read_only_percentage, key_gen_state.rng)
        ops: Dict[ShardId, Dict[Key, tuple]] = {}
        target_shard: Optional[ShardId] = None
        for key in keys:
            op = KVOp.get() if read_only else KVOp.put(self._gen_cmd_value(key_gen_state.rng))
            shard_id = self.shard_id(key)
            ops.setdefault(shard_id, {})[key] = (op,)
            if target_shard is None:
                target_shard = shard_id
        assert target_shard is not None
        return target_shard, Command(rifl, ops)

    def _gen_unique_keys(self, key_gen_state: KeyGenState) -> List[Key]:
        keys: List[Key] = []
        while len(keys) != self.keys_per_command:
            key = key_gen_state.gen_cmd_key()
            if key not in keys:
                keys.append(key)
        return keys

    def _gen_cmd_value(self, rng: random.Random) -> Value:
        return "".join(rng.choices(_PAYLOAD_ALPHABET, k=self.payload_size))

    def shard_id(self, key: Key) -> ShardId:
        """Key -> shard by stable hash (workload.rs:203)."""
        return key_hash(key) % self.shard_count
