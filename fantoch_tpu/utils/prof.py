"""Function-latency profiling: the fantoch_prof analog.

Reference: fantoch_prof/src/lib.rs:78-186 — a tracing Subscriber that
turns span enter/exit into per-function latency histograms, printed
periodically by the tracer task (fantoch/src/run/task/tracer.rs:16-44).

Here the span surface is explicit: wrap hot functions with ``@profiled``
or time a region with ``elapsed("name")``; latencies land in a global
``Metrics`` histogram registry keyed by name (microseconds).  The runner's
tracer task (``ProcessRuntime`` with ``tracer_show_interval_ms``) prints
``snapshot()`` on an interval.  For device work, prefer
``jax.profiler.TraceAnnotation`` (wired in executor/graph/batched.py) —
this module covers the host side.
"""

from __future__ import annotations

import contextlib
import functools
import threading
import time
from typing import Callable, Dict, Iterator

from fantoch_tpu.core.metrics import Histogram, Metrics

_metrics: Metrics = Metrics()
_lock = threading.Lock()


@contextlib.contextmanager
def elapsed(name: str) -> Iterator[None]:
    """Time a region into the global histogram for `name` (microseconds)."""
    start = time.perf_counter()
    try:
        yield
    finally:
        micros = int((time.perf_counter() - start) * 1e6)
        with _lock:
            _metrics.collect(name, micros)


def profiled(fn: Callable) -> Callable:
    """Decorator: record every call's latency under the function's name."""
    name = fn.__qualname__

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        with elapsed(name):
            return fn(*args, **kwargs)

    return wrapper


def snapshot() -> Dict[str, Histogram]:
    """Copy of the collected histograms (name -> Histogram)."""
    with _lock:
        out: Metrics = Metrics()
        out.merge(_metrics)
        return dict(out.collected)


def reset() -> None:
    global _metrics
    with _lock:
        _metrics = Metrics()


def format_snapshot() -> str:
    """One line per profiled function (tracer.rs:16-44 output analog)."""
    lines = []
    for name, hist in sorted(snapshot().items()):
        lines.append(
            f"{name}: n={hist.count} mean={hist.mean():.0f}us "
            f"p95={hist.percentile(0.95):.0f}us p99={hist.percentile(0.99):.0f}us"
        )
    return "\n".join(lines)
