"""Function-latency profiling: the fantoch_prof analog.

Reference: fantoch_prof/src/lib.rs:78-186 — a tracing Subscriber that
turns span enter/exit into per-function latency histograms, printed
periodically by the tracer task (fantoch/src/run/task/tracer.rs:16-44).

Here the span surface is explicit: wrap hot functions with ``@profiled``
or time a region with ``elapsed("name")``; latencies land in a ``Metrics``
histogram registry keyed by name (microseconds).  The runner's
tracer task (``ProcessRuntime`` with ``tracer_show_interval_ms``) prints
``snapshot()`` on an interval.  For device work, prefer
``jax.profiler.TraceAnnotation`` (wired in executor/graph/batched.py) —
this module covers the host side.

Registry scoping: the registry is a *contextvar*, defaulting to one
process-global ``Metrics``.  A runner that wants its samples isolated
(several ``ProcessRuntime``s share one Python process in the localhost
harness — a module global would blend their latencies) calls
``set_registry(Metrics())`` before spawning its tasks: every task created
afterwards snapshots that context and records into the runner's own
registry, while other runners (and the default scope) stay untouched.
"""

from __future__ import annotations

import contextlib
import contextvars
import functools
import threading
import time
from typing import Callable, Dict, Iterator, Optional

from fantoch_tpu.core.metrics import Histogram, Metrics

_default_metrics: Metrics = Metrics()
_registry: "contextvars.ContextVar[Metrics]" = contextvars.ContextVar(
    "fantoch_prof_registry", default=_default_metrics
)
_lock = threading.Lock()


def get_registry() -> Metrics:
    """The registry of the current context (the process-global default
    unless a runner installed its own)."""
    return _registry.get()


def set_registry(metrics: Optional[Metrics] = None) -> Metrics:
    """Install ``metrics`` (or a fresh ``Metrics``) as the current
    context's registry; returns it.  Tasks spawned after this call record
    into it (asyncio tasks snapshot the context at creation)."""
    metrics = metrics if metrics is not None else Metrics()
    _registry.set(metrics)
    return metrics


@contextlib.contextmanager
def scoped_registry(metrics: Optional[Metrics] = None) -> Iterator[Metrics]:
    """Context manager: a private registry for the enclosed region."""
    metrics = metrics if metrics is not None else Metrics()
    token = _registry.set(metrics)
    try:
        yield metrics
    finally:
        _registry.reset(token)


@contextlib.contextmanager
def elapsed(name: str) -> Iterator[None]:
    """Time a region into the current registry's histogram for `name`
    (microseconds)."""
    start = time.perf_counter()
    try:
        yield
    finally:
        micros = int((time.perf_counter() - start) * 1e6)
        with _lock:
            _registry.get().collect(name, micros)


def profiled(fn: Callable) -> Callable:
    """Decorator: record every call's latency under the function's name."""
    name = fn.__qualname__

    @functools.wraps(fn)
    def wrapper(*args, **kwargs):
        with elapsed(name):
            return fn(*args, **kwargs)

    return wrapper


# --- auto-instrumentation (the span-subscriber analog) ---------------------
#
# The reference annotates hot functions with #[instrument] and the
# ProfSubscriber aggregates every span automatically
# (fantoch_prof/src/lib.rs:78-136).  Python's analog: install wrappers
# over the framework's hot-path methods at runtime — no call-site edits,
# one switch to turn the tripwire on.

# (class path, method) pairs covering the reference's instrumented set
# (fantoch's #[instrument] spans sit on the protocol handlers and the
# executor entry points)
_HOT_PATHS = [
    ("fantoch_tpu.protocol.base:Protocol", ("submit", "handle", "handle_event")),
    ("fantoch_tpu.executor.base:Executor", ("handle", "handle_batch")),
    (
        "fantoch_tpu.executor.graph.deps_graph:DependencyGraph",
        ("handle_add", "commands_to_execute"),
    ),
]
_instrumented: list = []


def _wrap_method(cls, name: str) -> None:
    fn = cls.__dict__.get(name)
    if fn is None or getattr(fn, "_prof_wrapped", False):
        return
    wrapped = profiled(fn)
    wrapped._prof_wrapped = True  # type: ignore[attr-defined]
    setattr(cls, name, wrapped)
    _instrumented.append((cls, name, fn))


def auto_instrument(extra: Iterator = ()) -> int:
    """Install latency spans over the framework's hot paths (and any
    ``extra`` (cls, method-names) pairs): every subclass handler inherits
    the span through the base class unless it overrides the method, in
    which case the override is wrapped too.  Returns the number of
    methods instrumented; ``uninstrument()`` restores them."""
    import importlib

    count = 0
    specs = list(_HOT_PATHS)
    for spec in specs:
        path, methods = spec
        module_name, cls_name = path.split(":")
        cls = getattr(importlib.import_module(module_name), cls_name)
        targets = [cls] + [c for c in _all_subclasses(cls)]
        for target in targets:
            for method in methods:
                before = len(_instrumented)
                _wrap_method(target, method)
                count += len(_instrumented) - before
    for cls, methods in extra:
        for method in methods:
            before = len(_instrumented)
            _wrap_method(cls, method)
            count += len(_instrumented) - before
    return count


def _all_subclasses(cls) -> set:
    out = set()
    for sub in cls.__subclasses__():
        out.add(sub)
        out |= _all_subclasses(sub)
    return out


def uninstrument() -> None:
    """Undo auto_instrument (restores the original methods)."""
    while _instrumented:
        cls, name, fn = _instrumented.pop()
        setattr(cls, name, fn)


def snapshot() -> Dict[str, Histogram]:
    """Copy of the current registry's histograms (name -> Histogram)."""
    with _lock:
        out: Metrics = Metrics()
        out.merge(_registry.get())
        return dict(out.collected)


def reset() -> None:
    """Clear the current registry in place (in place, not a rebind: tasks
    that captured this registry at spawn keep recording into it)."""
    with _lock:
        reg = _registry.get()
        reg.collected.clear()
        reg.aggregated.clear()


def format_snapshot() -> str:
    """One line per profiled function (tracer.rs:16-44 output analog)."""
    lines = []
    for name, hist in sorted(snapshot().items()):
        lines.append(
            f"{name}: n={hist.count} mean={hist.mean():.0f}us "
            f"p95={hist.percentile(0.95):.0f}us p99={hist.percentile(0.99):.0f}us"
        )
    return "\n".join(lines)
