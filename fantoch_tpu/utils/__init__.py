"""Shared utilities: key hashing, distance-based process sorting, logging.

Reference: fantoch/src/util.rs.
"""

from __future__ import annotations

import logging
from typing import Dict, Iterable, Iterator, List, Tuple

from fantoch_tpu.core.ids import Dot, ProcessId, ShardId
from fantoch_tpu.core.kvs import Key
from fantoch_tpu.core.planet import Planet, Region

logger = logging.getLogger("fantoch_tpu")

# 64-bit FNV-1a: a stable, fast, dependency-free key hash.  The reference uses
# ahash (fantoch/src/util.rs:107-111); any stable 64-bit hash works as long as
# every process agrees on it, so we pick one that is reproducible across runs
# (Python's builtin hash() is salted per-process and therefore unusable here).
_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_MASK = (1 << 64) - 1


def key_hash(key: Key) -> int:
    h = _FNV_OFFSET
    for b in key.encode():
        h = ((h ^ b) * _FNV_PRIME) & _MASK
    return h


def dots(repr_: Iterable[Tuple[ProcessId, int, int]]) -> Iterator[Dot]:
    """Expand (process, start, end) ranges into dots (fantoch/src/util.rs:135-140)."""
    for process_id, start, end in repr_:
        for seq in range(start, end + 1):
            yield Dot(process_id, seq)


def sort_processes_by_distance(
    region: Region,
    planet: Planet,
    processes: List[Tuple[ProcessId, ShardId, Region]],
) -> List[Tuple[ProcessId, ShardId]]:
    """Sort processes by the distance of their region from `region`; ties
    (same region) break by process id.  Reference: fantoch/src/util.rs:142-176.
    """
    sorted_regions = planet.sorted_by_distance(region)
    assert sorted_regions is not None, f"{region} should be part of planet"
    index_of = {reg: i for i, (_dist, reg) in enumerate(sorted_regions)}
    ordered = sorted(processes, key=lambda p: (index_of[p[2]], p[0]))
    return [(pid, shard) for pid, shard, _ in ordered]


def closest_process_per_shard(
    region: Region,
    planet: Planet,
    processes: List[Tuple[ProcessId, ShardId, Region]],
) -> Dict[ShardId, ProcessId]:
    """Closest process of each shard (fantoch/src/util.rs:178-192)."""
    out: Dict[ShardId, ProcessId] = {}
    for process_id, shard_id in sort_processes_by_distance(region, planet, processes):
        out.setdefault(shard_id, process_id)
    return out
