"""fantoch_tpu: a TPU-native framework for specifying, simulating and running
planet-scale consensus/SMR protocols.

Capabilities mirror the reference Rust framework (fantoch): leaderless and
leader-based protocols (EPaxos, Atlas, Newt/Tempo, Caesar, FPaxos, Basic) as
pure state machines over a shared ``Protocol`` interface, pluggable
``Executor`` ordering engines, a deterministic discrete-event simulator, and
an asyncio TCP runner — with the hot execution data plane (dependency-graph
SCC/topological resolution, key-clock proposals, vote-range stability)
re-designed as batched JAX/Pallas computations instead of serial pointer
walks, and multi-chip scaling expressed as jax.sharding over a device Mesh.
"""

__version__ = "0.1.0"
