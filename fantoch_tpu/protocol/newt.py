"""Newt (Tempo): timestamp consensus with per-key clock votes.

Reference: fantoch_ps/src/protocol/newt.rs (1535 LoC).  Every command gets a
timestamp; the coordinator proposes ``max`` over its key clocks + 1, fast-
quorum members counter-propose considering the remote clock as a minimum,
and the command commits at the max reported clock — on the fast path iff
that max was reported by at least ``f`` quorum members (newt.rs:527-546),
else through a Synod round on the clock value (``ConsensusValue = u64``,
newt.rs:1107).  Execution is delegated to the TableExecutor: votes consumed
while proposing prove that no lower timestamp can ever be assigned, making
timestamps *stable* once enough frontiers pass them.

Extras mirrored here:
- tiny quorums (fast quorum ``2f``, stability ``n - f``) and
  ``skip_fast_ack`` (fast-quorum members commit directly when ``q == 2``,
  newt.rs:95-97,313,451);
- real-time clock bump: a periodic event votes all keys up to
  ``max(max_commit_clock, time.micros())`` so stability tracks wall time
  under low load (newt.rs:983-1006);
- detached-vote batching via the periodic ``SendDetached`` event.

Partial replication (newt.rs:1025-1100 + 680-730): the target shard
forwards submits (MForwardSubmit); every acking fast-quorum member also
MBumps the closest process of each other shard so their key clocks chase
the command's likely timestamp with detached votes; each shard's decided
clock travels to the dot owner via MShardCommit, the owner aggregates the
*max* over shards, and the final MCommit at each participant carries the
aggregated clock with the shard's locally-held Votes (Votes never cross
shards — the data2 channel of partial.rs).
"""

from __future__ import annotations

import copy

from collections import deque
from dataclasses import dataclass
from typing import Any, Deque, Dict, Optional, Set, Tuple

from fantoch_tpu.core.command import Command
from fantoch_tpu.core.config import Config
from fantoch_tpu.core.ids import Dot, ProcessId, ShardId
from fantoch_tpu.core.timing import SysTime
from fantoch_tpu.executor.table import (
    TableDetachedVotes,
    TableExecutor,
    TableVotes,
    TableVotesArraysBuilder,
)
from fantoch_tpu.protocol.base import (
    Action,
    BaseProcess,
    Protocol,
    ProtocolMetrics,
    ToForward,
    ToSend,
)
from fantoch_tpu.protocol.commit_gc import (
    CommitGCMixin,
    GarbageCollectionEvent,
    MCommitDot,
)
from fantoch_tpu.protocol.common.synod import (
    MAccept,
    MAccepted as SynodMAccepted,
    MChosen,
    Synod,
)
from fantoch_tpu.protocol.common.table_clocks import (
    KeyClocks,
    QuorumClocks,
    VoteRange,
    Votes,
)
from fantoch_tpu.protocol.gc import GCTrack
from fantoch_tpu.protocol.partial import (
    MForwardSubmit,
    MShardAggregatedCommit,
    MShardCommit,
    PartialCommitMixin,
)
from fantoch_tpu.protocol.info import CommandsInfo
from fantoch_tpu.protocol.recovery import (
    MRecoveryPrepare,
    MRecoveryPromise,
    RecoveryEvent,
    RecoveryMixin,
)
from fantoch_tpu.protocol.sync import (
    MSync,
    MSyncBackfill,
    MSyncReply,
    SyncMixin,
)
from fantoch_tpu.run.routing import (
    worker_dot_index_shift,
    worker_index_no_shift,
)


# --- messages (newt.rs:1173-1233) ---


@dataclass
class MCollect:
    dot: Dot
    cmd: Command
    quorum: Set[ProcessId]
    clock: int
    coordinator_votes: Votes


@dataclass
class MCollectAck:
    dot: Dot
    clock: int
    process_votes: Votes


@dataclass
class MCommit:
    dot: Dot
    clock: int
    votes: Votes
    # True when the commit was decided by recovery consensus rather than
    # the coordinator's aggregation: the carried votes then lack the fast
    # quorum's consumed ranges, and each member re-broadcasts its held
    # copy commit-coupled (see _handle_mcommit) so vote frontiers heal
    # without ever overtaking the ops they stabilize
    recovered: bool = False
    # payload piggyback on recovery chosen-replies: a rejoined replica can
    # hold a buffered commit for a dot whose MCollect it missed while
    # down AND that was still in flight when the MSync records were cut —
    # without the payload here the prepare/chosen exchange loops
    # payload-less forever and the dot's (subtracted-from-backfill) votes
    # never fold (fuzzer-found rejoin stall)
    cmd: Optional[Command] = None


@dataclass
class MCommitClock:
    """Notify the clock-bump worker of a commit clock (newt.rs:660-676)."""

    clock: int


@dataclass
class MBump:
    """Cross-shard key-clock priming: a fast-quorum member of the target
    shard tells the closest process of every other shard the clock it
    acked, so that shard's keys chase the likely final timestamp with
    detached votes (newt.rs:1045-1060, handler :680-708)."""

    dot: Dot
    clock: int


@dataclass
class MDetached:
    detached: Votes


@dataclass
class MConsensus:
    dot: Dot
    ballot: int
    clock: int
    # payload piggyback on recovery rounds, so a recovered clock can commit
    # at processes the original MCollect broadcast never reached
    cmd: Optional[Command] = None


@dataclass
class MConsensusAck:
    dot: Dot
    ballot: int


# --- periodic events ---


@dataclass
class ClockBumpEvent:
    pass


@dataclass
class SendDetachedEvent:
    pass


class Status:
    START = "start"
    PAYLOAD = "payload"
    COLLECT = "collect"
    COMMIT = "commit"


def _recovery_proposal_gen(values):
    """Recovery clock selection over the ballot-0 reports of an n-f promise
    quorum (protocol/recovery.py; the reference's todo!() at
    newt.rs:1110-1112).  Reports are the clocks fast-quorum members
    proposed when acking the MCollect; 0 marks acceptors that never did.
    All-zero -> the dot is recovered as a committed noop (clock 0, nothing
    executes); otherwise the max reported clock — agreement alone is what
    per-key order needs, and survivors' detached votes fill their own
    frontiers up to any committed clock."""
    return max(values.values(), default=0)


def _subtract_pending(votes: Votes, pending: Dict[str, list], by: ProcessId) -> Votes:
    """Remove ``pending`` intervals (per key) from backfill ``votes``
    (each key holds contiguous [1, clock] ranges by ``by``) — the
    consumed-for-pending-dots exclusion of the rejoin backfill."""
    out = Votes()
    for key, key_votes in votes:
        holes = sorted(pending.get(key, ()))
        for vote in key_votes:
            cursor = vote.start
            for hole_start, hole_end in holes:
                if hole_end < cursor or hole_start > vote.end:
                    continue
                if hole_start > cursor:
                    out.add(key, VoteRange(by, cursor, hole_start - 1))
                cursor = max(cursor, hole_end + 1)
            if cursor <= vote.end:
                out.add(key, VoteRange(by, cursor, vote.end))
    return out


def _newt_info_factory(pid, _sid, cfg, fq, _wq) -> "NewtInfo":
    """Picklable per-dot info factory (the model checker pickles state)."""
    return NewtInfo(pid, cfg.n, cfg.f, fq)


class NewtInfo:
    """Per-dot lifecycle info (newt.rs:1117-1170)."""

    __slots__ = (
        "status", "quorum", "synod", "cmd", "votes", "quorum_clocks",
        "recovery_consumed",
    )

    def __init__(self, process_id: ProcessId, n: int, f: int, fast_quorum_size: int):
        self.status = Status.START
        self.quorum: Set[ProcessId] = set()
        self.synod: Synod[int] = Synod(process_id, n, f, _recovery_proposal_gen, 0)
        self.cmd: Optional[Command] = None
        # coordinator-side aggregation of fast-quorum votes
        self.votes = Votes()
        self.quorum_clocks = QuorumClocks(fast_quorum_size)
        # True once a recovery PROMISE consumed votes for this dot
        # (_recovery_promise_floor): those ranges exist nowhere else, so
        # the commit handler must re-broadcast the held votes
        # commit-coupled even when the commit was decided by the normal
        # (non-recovery) path racing the prepare
        self.recovery_consumed = False


# --- mutation self-test hook (tests/test_fuzz.py) ---
# When True, every GC-straggler guard below is bypassed, reintroducing the
# PR 7 latent bug: a late retransmit for a dot that already went stable
# and was GC'd resurrects a fresh START info via `_cmds.get`, and a later
# payload adoption can REPLAY the commit — double-adding the ops to the
# vote table (same-(clock,dot) collision, duplicate execution).  The
# chaos fuzzer's mutation self-test flips this to prove the
# auditor+fuzzer detect the real historical violation, not just
# synthetic ones.  Never set outside tests.
_GC_STRAGGLER_GUARD_DISABLED = False


def _set_gc_straggler_guard(enabled: bool) -> None:
    """Test hook: disable (enabled=False) or restore the GC-straggler
    guards.  Pair with try/finally — a leaked disable corrupts every
    subsequent Newt run in the process."""
    global _GC_STRAGGLER_GUARD_DISABLED
    _GC_STRAGGLER_GUARD_DISABLED = not enabled


# the clock-bump worker owns all key clocks under worker parallelism
# (newt.rs:1236 CLOCK_BUMP_WORKER_INDEX)
CLOCK_BUMP_WORKER_INDEX = 1

# cap on MBump clocks buffered before their MCollect arrives; comfortably
# above any realistic in-flight multi-shard window (bumps are hints, so
# eviction never affects correctness)
_MBUMP_BUFFER_CAP = 4096


class Newt(PartialCommitMixin, RecoveryMixin, SyncMixin, CommitGCMixin, Protocol):
    Executor = TableExecutor

    def __init__(self, process_id: ProcessId, shard_id: ShardId, config: Config):
        fast_quorum_size, write_quorum_size, _ = config.newt_quorum_sizes()
        self.bp = BaseProcess(
            process_id, shard_id, config, fast_quorum_size, write_quorum_size
        )
        if config.batched_table_executor:
            from fantoch_tpu.protocol.common.table_batched import BatchedKeyClocks

            self.key_clocks = BatchedKeyClocks(process_id, shard_id)
        else:
            self.key_clocks = KeyClocks(process_id, shard_id)
        self._cmds: CommandsInfo[NewtInfo] = CommandsInfo(
            process_id,
            shard_id,
            config,
            fast_quorum_size,
            write_quorum_size,
            _newt_info_factory,
        )
        self._gc_track = GCTrack(process_id, shard_id, config.n)
        self._to_processes: Deque[Action] = deque()
        self._to_executors: Deque[Any] = deque()
        # batched commit seam: committed rows and detached votes accumulate
        # as columns and drain as ONE TableVotesArrays per to_executors
        # sweep — no per-command TableVotes objects on the batched path.
        # Requires all of a process's table infos to reach one executor
        # (the runner disables it via set_commit_arrays when the executor
        # pool routes per key)
        self._commit_arrays: Optional[TableVotesArraysBuilder] = (
            TableVotesArraysBuilder() if config.batched_table_executor else None
        )
        # accumulated detached votes, flushed by SendDetachedEvent
        self._detached = Votes()
        # MBump clocks that arrived before the MCollect (newt.rs:45,699-708).
        # Bounded: a bump is a clock-priming *hint*, so evicting the oldest
        # entry is always safe — this caps the stale residue of bumps that
        # trail a GC'd commit (get_existing cannot distinguish "never seen"
        # from "GC'd", and no later message would ever pop such an entry)
        self._buffered_mbumps: Dict[Dot, int] = {}
        self._init_partial()
        self._init_recovery()
        # MCommit before MCollect (multiplexing reorders): buffer
        # (from, clock, merged votes, recovered)
        self._buffered_mcommits: Dict[Dot, Tuple[ProcessId, int, Votes, bool]] = {}
        # highest committed clock: the floor for real-time clock bumps
        # (traceical clocks can run ahead of a simulated wall clock)
        self._max_commit_clock = 0
        self._skip_fast_ack = config.skip_fast_ack and fast_quorum_size == 2
        # liveness requires flushing detached votes: proposals consume vote
        # ranges beyond a command's final clock, and if those never reach the
        # other replicas' vote tables, their frontiers stall below the gap
        # and stability stops advancing.  The reference leaves this implicit
        # (its test macro "always set newt_detached_send_interval",
        # fantoch_ps/src/protocol/mod.rs:65); we make it explicit.
        assert config.newt_detached_send_interval_ms is not None, (
            "Newt requires newt_detached_send_interval_ms: without it, "
            "detached votes are never sent and timestamp stability stalls"
        )

    def periodic_events(self):
        events = list(self.gc_periodic_events())
        if self.bp.config.newt_clock_bump_interval_ms is not None:
            events.append((ClockBumpEvent(), self.bp.config.newt_clock_bump_interval_ms))
        if self.bp.config.newt_detached_send_interval_ms is not None:
            events.append(
                (SendDetachedEvent(), self.bp.config.newt_detached_send_interval_ms)
            )
        events.extend(self.recovery_periodic_events())
        return events

    @property
    def id(self) -> ProcessId:
        return self.bp.process_id

    @property
    def shard_id(self) -> ShardId:
        return self.bp.shard_id

    def discover(self, processes):
        connect_ok = self.bp.discover(processes)
        return connect_ok, dict(self.bp.closest_shard_process())

    def submit(self, dot: Optional[Dot], cmd: Command, time: SysTime) -> None:
        dot = self._handle_submit(dot, cmd, target_shard=True)
        # trace: dot assigned + payload owned at the coordinator
        self.bp.trace_span("payload", cmd.rifl, dot=dot)

    def submit_batch(self, pairs, time: SysTime) -> None:
        """Batched submit seam: one kernel-batched clock proposal covers
        every command (BatchedKeyClocks.proposal_batch), then the per-dot
        MCollect fan-out proceeds as usual.  Falls back to per-command
        submits when the clocks are not array-backed."""
        proposal_batch = getattr(self.key_clocks, "proposal_batch", None)
        if proposal_batch is None:
            for dot, cmd in pairs:
                self.submit(dot, cmd, time)
            return
        dots = [
            dot if dot is not None else self.bp.next_dot() for dot, _ in pairs
        ]
        cmds = [cmd for _, cmd in pairs]
        for dot, cmd in zip(dots, cmds):
            self.partial_submit_actions(dot, cmd, target_shard=True)
        results = proposal_batch(cmds, [0] * len(cmds))
        for dot, cmd, (clock, process_votes) in zip(dots, cmds, results):
            self._emit_mcollect(dot, cmd, clock, process_votes)
        if self.bp.tracer.enabled:
            for dot, cmd in zip(dots, cmds):
                self.bp.trace_span("payload", cmd.rifl, dot=dot)

    def handle(self, from_, from_shard_id, msg, time):
        if isinstance(msg, MCollect):
            self._handle_mcollect(
                from_, msg.dot, msg.cmd, msg.quorum, msg.clock, msg.coordinator_votes, time
            )
        elif isinstance(msg, MCollectAck):
            self._handle_mcollectack(from_, msg.dot, msg.clock, msg.process_votes)
        elif isinstance(msg, MCommit):
            self._handle_mcommit(
                from_, msg.dot, msg.clock, msg.votes, msg.recovered,
                getattr(msg, "cmd", None), time,
            )
        elif isinstance(msg, MCommitClock):
            assert from_ == self.bp.process_id
            self._max_commit_clock = max(self._max_commit_clock, msg.clock)
        elif isinstance(msg, MDetached):
            self._handle_mdetached(msg.detached)
        elif isinstance(msg, MConsensus):
            self._handle_mconsensus(from_, msg.dot, msg.ballot, msg.clock, msg.cmd, time)
        elif isinstance(msg, MConsensusAck):
            self._handle_mconsensusack(from_, msg.dot, msg.ballot)
        elif isinstance(msg, MBump):
            self._handle_mbump(msg.dot, msg.clock)
        elif self.handle_recovery_message(from_, msg, time):
            pass
        elif self.handle_sync_message(from_, msg, time):
            pass
        elif self.handle_partial_message(from_, msg):
            pass
        elif not self.handle_gc_message(from_, msg):
            raise AssertionError(f"unknown message {msg}")

    def handle_event(self, event, time):
        if isinstance(event, GarbageCollectionEvent):
            self.handle_gc_event()
        elif isinstance(event, ClockBumpEvent):
            self._handle_event_clock_bump(time)
        elif isinstance(event, SendDetachedEvent):
            self._handle_event_send_detached()
        elif isinstance(event, RecoveryEvent):
            self.handle_recovery_event(time)
        else:
            raise AssertionError(f"unknown event {event}")

    def to_processes(self) -> Optional[Action]:
        return self._to_processes.popleft() if self._to_processes else None

    def to_executors(self):
        if self._commit_arrays is not None and len(self._commit_arrays):
            return self._commit_arrays.take()
        return self._to_executors.popleft() if self._to_executors else None

    def set_commit_arrays(self, enabled: bool) -> None:
        """Runner hook: the arrays commit seam assumes a single table
        executor consumes this process's infos; per-key executor pools
        must turn it off (falls back to per-command TableVotes)."""
        if enabled and self._commit_arrays is None:
            self._commit_arrays = TableVotesArraysBuilder()
        elif not enabled and self._commit_arrays is not None:
            # flush anything accumulated so no commit is lost
            pending = self._commit_arrays.take()
            if pending is not None:
                self._to_executors.append(pending)
            self._commit_arrays = None

    @classmethod
    def parallel(cls) -> bool:
        return KeyClocks.parallel()

    @classmethod
    def leaderless(cls) -> bool:
        return True

    def metrics(self) -> ProtocolMetrics:
        return self.bp.metrics()

    # --- handlers ---

    def _gc_straggler(self, dot: Dot) -> bool:
        """True when ``dot``'s commit already went stable-everywhere and
        was GC'd here, so the message is a straggler that must not
        resurrect a fresh info (PR 7 safety fix).  The mutation self-test
        bypasses this via the module flag to prove the fuzzer catches
        the historical commit-replay bug."""
        return (not _GC_STRAGGLER_GUARD_DISABLED) and self._gc_track.contains(dot)

    def _handle_submit(
        self, dot: Optional[Dot], cmd: Command, target_shard: bool
    ) -> Dot:
        dot = dot if dot is not None else self.bp.next_dot()
        self.partial_submit_actions(dot, cmd, target_shard)
        # propose: bump key clocks, consuming votes; those votes are either
        # shipped in the MCollect (skip_fast_ack: quorum members can commit
        # without the ack round) or kept for the MCollectAck aggregation
        clock, process_votes = self.key_clocks.proposal(cmd, 0)
        self._emit_mcollect(dot, cmd, clock, process_votes)
        return dot

    def _emit_mcollect(
        self, dot: Dot, cmd: Command, clock: int, process_votes: Votes
    ) -> None:
        if self._skip_fast_ack:
            coordinator_votes = process_votes
        else:
            info = self._cmds.get(dot)
            info.votes = process_votes
            coordinator_votes = Votes()
        mcollect = MCollect(dot, cmd, self.bp.fast_quorum(), clock, coordinator_votes)
        self._to_processes.append(ToSend(self.bp.all(), mcollect))

    def _handle_mcollect(self, from_, dot, cmd, quorum, remote_clock, votes, time) -> None:
        if self._gc_straggler(dot):
            return  # straggler for a GC'd dot: do not resurrect its info
        info = self._cmds.get(dot)
        if info.status != Status.START:
            return
        self._recovery_track(dot, time)

        if self.bp.process_id not in quorum:
            # not in the fast quorum: store the payload only; pre-create the
            # key clocks so periodic bumps cover these keys too
            if self.bp.config.newt_clock_bump_interval_ms is not None:
                self.key_clocks.init_clocks(cmd)
            info.status = Status.PAYLOAD
            info.cmd = cmd
            buffered_bump = self._buffered_mbumps.pop(dot, None)
            if buffered_bump is not None:
                self.key_clocks.detached(cmd, buffered_bump, self._detached)
            self._replay_buffered_mcommit(dot)
            return

        message_from_self = from_ == self.bp.process_id
        if message_from_self:
            # votes already consumed at submit time; don't double-vote
            clock, process_votes = remote_clock, Votes()
        else:
            clock, process_votes = self.key_clocks.proposal(cmd, remote_clock)

        info.cmd = cmd
        if not info.synod.set_if_not_accepted(lambda: clock):
            # a recovery prepare already owns a higher ballot: our promise
            # forbids the ballot-0 ack.  The proposal above consumed votes
            # from our key clocks, though — hold them (plus any coordinator
            # votes the MCollect carried) with the dot; the commit handler
            # releases them commit-coupled so our vote frontier never gains
            # a gap and never advances ahead of the dot's ops either
            info.votes.merge(votes)
            info.votes.merge(process_votes)
            info.status = Status.PAYLOAD
            self._replay_buffered_mcommit(dot)
            return
        info.status = Status.COLLECT
        info.quorum = set(quorum)

        if not message_from_self and self._skip_fast_ack:
            # tiny-quorums shortcut (q=2): this quorum member holds both the
            # coordinator's votes and its own — commit directly.  Count the
            # fast path here: exactly one non-coordinator member exists, so
            # commands are counted once (the reference skips accounting on
            # this path entirely, newt.rs:451-462, leaving commit totals
            # unverifiable under skip_fast_ack).
            self.bp.fast_path(dot, cmd)
            votes.merge(process_votes)
            self._mcommit_actions(info, dot, clock, votes)
        else:
            if self._recovery_enabled():
                # keep a copy of the votes we ship: if the coordinator dies
                # with the ack in flight, these consumed ranges exist
                # nowhere else and the resulting gap in our own vote
                # frontier would stall timestamp stability forever — the
                # commit handler re-flushes them through the detached
                # channel (ranges dedup, so double delivery is harmless)
                info.votes.merge(copy.deepcopy(process_votes))
            self._to_processes.append(
                ToSend({from_}, MCollectAck(dot, clock, process_votes))
            )
            # prime the other shards' key clocks with the acked clock
            # (newt.rs:1045-1060): each acking member bumps the closest
            # process of every other shard the command touches
            for shard_id in cmd.shards():
                if shard_id != self.bp.shard_id:
                    self._to_processes.append(
                        ToSend({self.bp.closest_process(shard_id)}, MBump(dot, clock))
                    )
        # a buffered MBump from another shard can now generate detached
        # votes (newt.rs:434-440)
        buffered_bump = self._buffered_mbumps.pop(dot, None)
        if buffered_bump is not None:
            self.key_clocks.detached(cmd, buffered_bump, self._detached)
        # with recovery in play a commit can be decided without this
        # member's ack and thus arrive before its MCollect — replay it
        self._replay_buffered_mcommit(dot)

    def _replay_buffered_mcommit(self, dot: Dot) -> None:
        buffered = self._buffered_mcommits.pop(dot, None)
        if buffered is not None:
            buf_from, buf_clock, buf_votes, buf_recovered = buffered
            self._handle_mcommit(buf_from, dot, buf_clock, buf_votes, buf_recovered)

    def _handle_mcollectack(self, from_, dot, clock, remote_votes) -> None:
        if self._gc_straggler(dot):
            return  # straggler for a GC'd dot: do not resurrect its info
        info = self._cmds.get(dot)
        if info.status != Status.COLLECT:
            return
        if info.quorum_clocks.contains(from_):
            # duplicate ack (at-least-once delivery): counting it again
            # would double-count max_clock_count — an unsound fast path —
            # and a late duplicate after the quorum completed (slow path /
            # recovery join keep status COLLECT) would trip the size
            # assert.  Votes were merged on the first copy; ranges dedup
            # anyway, so dropping the whole message is safe
            return
        info.votes.merge(remote_votes)
        max_clock, max_count = info.quorum_clocks.add(from_, clock)

        # detached-bump optimization (newt.rs:506-521): raise our own key
        # clocks to the highest clock seen so far, so later proposals can't
        # undercut this command's likely final timestamp.  When the ack is
        # from self the votes would never ride an MCommit — skip.
        cmd = info.cmd
        assert cmd is not None
        if from_ != self.bp.process_id:
            self.key_clocks.detached(cmd, max_clock, self._detached)

        if not info.quorum_clocks.all():
            return
        if not info.synod.can_skip_prepare():
            # a recovery proposer owns a higher ballot: neither the
            # unilateral fast-path commit nor the first-ballot shortcut is
            # sound anymore — join recovery with a full prepare; the
            # aggregated votes stay in info.votes for the eventual commit
            prepare = info.synod.new_prepare()
            self._to_processes.append(
                ToSend(
                    self.bp.all(), MRecoveryPrepare(dot, prepare.ballot, info.cmd)
                )
            )
            return
        if max_count >= self.bp.config.f:
            self.bp.fast_path(dot, cmd)
            votes, info.votes = info.votes, Votes()
            self._mcommit_actions(info, dot, max_clock, votes)
        else:
            self.bp.slow_path(dot, cmd)
            ballot = info.synod.skip_prepare()
            self._to_processes.append(
                ToSend(self.bp.write_quorum(), MConsensus(dot, ballot, max_clock))
            )

    def _handle_mbump(self, dot: Dot, clock: int) -> None:
        """Another shard's acked clock: chase it with detached votes, or
        buffer (keeping the max) until the MCollect delivers the payload
        (newt.rs:680-708).

        get_existing, not get: a bump racing behind the commit (the bump is
        one hop, the commit path is four) must not resurrect a GC'd info —
        the reference's `cmds.get` here re-creates it and leaks.  A bump
        for a dot with no info either precedes the MCollect (buffer; the
        MCollect handler drains it) or trails the commit (the commit
        handler drops the buffered entry, see _handle_mcommit)."""
        info = self._cmds.get_existing(dot)
        if info is not None and info.cmd is not None:
            if info.status != Status.COMMIT:
                self.key_clocks.detached(info.cmd, clock, self._detached)
            return
        prev = self._buffered_mbumps.get(dot, 0)
        if prev == 0 and len(self._buffered_mbumps) >= _MBUMP_BUFFER_CAP:
            # evict the oldest entry (dict = insertion order): either a
            # stale post-GC straggler or, at worst, a lost priming hint
            self._buffered_mbumps.pop(next(iter(self._buffered_mbumps)))
        self._buffered_mbumps[dot] = max(prev, clock)

    def _mcommit_actions(
        self, info: NewtInfo, dot: Dot, clock: int, votes: Votes, recovered: bool = False
    ) -> None:
        """Single-shard: broadcast MCommit.  Multi-shard: clock-max shard
        aggregation; the Votes stay here and rejoin the final MCommit
        (newt.rs:1063-1093)."""
        cmd = info.cmd
        if cmd is None or not self.partial_mcommit_actions(dot, cmd, clock, local=votes):
            self._to_processes.append(
                ToSend(self.bp.all(), MCommit(dot, clock, votes, recovered))
            )

    # --- recovery hooks (protocol/recovery.py) ---

    def _adopt_recovered_payload(self, dot, info, cmd, time) -> None:
        info.cmd = cmd
        if info.status == Status.START:
            info.status = Status.PAYLOAD
            buffered_bump = self._buffered_mbumps.pop(dot, None)
            if buffered_bump is not None:
                self.key_clocks.detached(cmd, buffered_bump, self._detached)
            self._replay_buffered_mcommit(dot)

    def _recovery_commit_known(self, dot) -> bool:
        return dot in self._buffered_mcommits

    def _recovery_consensus_msg(self, dot, ballot, value, cmd):
        return MConsensus(dot, ballot, value, cmd)

    def _recovery_promise_floor(self, dot, info) -> int:
        # Tempo-style promise: CONSUME votes through clock+1 (a full
        # proposal) and hold them with the dot, reporting the consumed
        # clock as the floor.  A floor merely *sampled* from the key
        # clocks is only an upper bound at promise time — between the
        # promise and the recovery commit, other commands keep voting and
        # stability can pass the recovered timestamp, so the late commit
        # executes out of (clock, dot) order (divergence; the fuzzer's
        # restart+hold schedules hit exactly this).  Consuming instead
        # leaves a GAP in this acceptor's vote column that only the
        # commit-coupled release fills: any stability set intersects the
        # promise quorum (stability threshold + n-f > n), so no
        # timestamp at or below the recovered clock can stabilize before
        # the dot's ops arrive.  Held votes for a dot that recovers as a
        # noop flush detached (the noop commit branch), so nothing leaks.
        if info.cmd is None or info.status == Status.COMMIT:
            return 0
        clock, votes = self.key_clocks.proposal(info.cmd, 0)
        info.votes.merge(votes)
        info.recovery_consumed = True
        return clock

    def _recovery_adjust_value(self, dot, info, value, floor: int):
        # free-choice clocks lift to the quorum's max consumed floor: the
        # floor reporter consumed votes through it, so the lifted clock is
        # covered by held ranges (no +1 — a clock above the consumed
        # region would reopen the stability-overtakes-commit gap).  Equal-
        # clock ties with already-executed commands are safe because the
        # floor is a *consumed* clock+1 proposal, strictly above every
        # vote its reporter ever issued.  Noop (0) stays noop.
        if value == 0:
            return value
        return max(value, floor)

    def _recovery_chosen_reply(self, to, dot, info, value) -> None:
        # same single-shard guard as the late-MConsensus reply; recovered
        # so the receiver re-broadcasts any votes it still holds.  The
        # payload rides along: the asker may hold a payload-less
        # buffered commit (rejoin gap)
        if info.cmd is None or info.cmd.shard_count == 1:
            self._to_processes.append(
                ToSend(
                    {to},
                    MCommit(dot, value, info.votes, recovered=True, cmd=info.cmd),
                )
            )

    # --- rejoin sync hooks (protocol/sync.py) ---

    def _sync_record(self, dot, info):
        # clock 0 == recovered noop; the commit's quorum votes were
        # consumed into tables long ago, so the record carries none — the
        # backfill re-statement below supplies the frontier coverage
        return (dot, info.cmd, info.synod.value())

    def _apply_sync_record(self, from_, record, time) -> None:
        dot, cmd, clock = record
        if self._gc_track.contains(dot):
            return  # committed (and possibly executed + GC'd) here already
        info = self._cmds.get(dot)
        if info.status == Status.COMMIT:
            return
        if cmd is not None and info.cmd is None:
            self._adopt_recovered_payload(dot, info, cmd, time)
        # recovered=True: if we held consumed-but-unshipped votes for the
        # dot across the crash, the commit handler re-broadcasts them
        # commit-coupled so no peer's frontier keeps our gap
        self._handle_mcommit(from_, dot, clock, Votes(), recovered=True)

    def _sync_backfill_votes(self) -> Optional[Votes]:
        """Vote-frontier healing payload: our issued votes are exactly
        [1, clock] per key (see KeyClocks.backfill_votes), MINUS the
        ranges consumed for still-pending dots.  Those must only ever
        travel commit-coupled: a table that sees them detached before
        the dot's ops would let stability overtake the commit and
        execute around it (the order-divergence hazard the commit
        handler's held-vote discipline exists to prevent).  The pending
        copies the recovery plane keeps (``info.votes``) are exactly
        that exclusion set, so backfill requires recovery enabled."""
        if not self._recovery_enabled():
            return None
        votes = self.key_clocks.backfill_votes()
        if votes.is_empty():
            return None
        me = self.bp.process_id
        pending: Dict[str, list] = {}
        for _dot, info in self._cmds.items():
            if info.status == Status.COMMIT or info.votes.is_empty():
                continue
            for key, key_votes in info.votes:
                for vote in key_votes:
                    if vote.by == me:
                        pending.setdefault(key, []).append((vote.start, vote.end))
        if pending:
            votes = _subtract_pending(votes, pending, me)
        return None if votes.is_empty() else votes

    def _sync_backfill_payload(self):
        # the record-serving side: barrier-gated (MSyncBackfill) — the
        # pending subtraction covers OUR unfinished dots, but ranges we
        # consumed for commits the REQUESTER has not applied yet are only
        # safe once it has folded every streamed record in, and delivery
        # under fault plans can reorder a plain detached message ahead of
        # the record chunks (fuzzer-found restart order divergence)
        return self._sync_backfill_votes()

    def _apply_sync_backfill(self, from_, votes, time) -> None:
        self._handle_mdetached(votes)

    def _sync_backfill_blocked(self) -> bool:
        # a payload-less buffered commit here means some dot's ops are
        # still in flight to us: an incoming backfill can carry the
        # ranges its quorum consumed for exactly that dot, and applying
        # them first lets stability overtake the commit (fuzzer-found:
        # a rejoiner's column reached a live peer ahead of the peer's
        # lost-behind-retransmits MCollect)
        return bool(self._buffered_mcommits)

    def _sync_backfill_actions(self, targets) -> None:
        """The REJOINER's own frontier re-statement toward live peers —
        sent through the same gated MSyncBackfill envelope (records=0:
        there is no record stream in this direction, but the receiver's
        buffered-commit gate must still hold it while any of its
        in-flight commits could own the covered ranges)."""
        votes = self._sync_backfill_votes()
        if votes is not None:
            self._to_processes.append(
                ToSend(set(targets), MSyncBackfill(votes, 0))
            )

    # --- partial-replication adapters (clock max; newt.rs:825-895) ---

    def _partial_initial_data(self):
        return 0

    def _partial_join(self, acc, data):
        return max(acc, data)

    def _partial_final_mcommit(self, dot: Dot, data, local):
        return MCommit(dot, data, local if local is not None else Votes())

    def _handle_mcommit(
        self, from_, dot, clock, votes: Votes, recovered=False,
        cmd=None, time=None,
    ) -> None:
        if self._gc_straggler(dot):
            # straggler for a dot already committed-everywhere and GC'd
            # (late retransmit, held-vote re-broadcast, rejoin traffic):
            # `_cmds.get` would resurrect a fresh START info and a later
            # payload adoption could REPLAY the commit — double-adding
            # the ops to the table.  The ops executed long ago; only the
            # carried vote ranges still matter (fold them detached)
            if not votes.is_empty():
                if self._commit_arrays is not None:
                    for key, key_votes in votes:
                        self._commit_arrays.add_detached(key, key_votes)
                else:
                    for key, key_votes in votes:
                        self._to_executors.append(TableDetachedVotes(key, key_votes))
            return
        info = self._cmds.get(dot)
        if cmd is not None and info.cmd is None and info.status == Status.START:
            # recovery chosen-reply piggyback: adopt so the commit below
            # proceeds instead of buffering payload-less
            self._adopt_recovered_payload(dot, info, cmd, time)
        if info.status == Status.COMMIT:
            # duplicate commit — typically a member re-broadcasting its
            # held votes after a recovered commit: the ops are already in
            # our table, so the ranges can join it directly
            if not votes.is_empty():
                if self._commit_arrays is not None:
                    for key, key_votes in votes:
                        self._commit_arrays.add_detached(key, key_votes)
                else:
                    for key, key_votes in votes:
                        self._to_executors.append(
                            TableDetachedVotes(key, key_votes)
                        )
            return
        if clock == 0:
            # recovered noop (the dot never got a clock proposal anywhere
            # the promise quorum could see): nothing executes and nothing
            # stabilizes — settle the synod and stop recovery.  Votes held
            # for a noop dot couple to no ops, so they flush as detached —
            # including the CARRIED votes: the recovery proposer's own
            # held ranges (promise-consumed, or shipped-ack copies) ride
            # the MCommit broadcast, and dropping them here would leave a
            # permanent hole in that process's vote column at every
            # receiver (frontiers stall below it forever)
            info.status = Status.COMMIT
            # audit plane: a noop commit executes nothing — rifl None
            self.bp.audit_commit(dot, None, 0)
            self._buffered_mbumps.pop(dot, None)
            if not votes.is_empty():
                self._detached.merge(votes)
            if not info.votes.is_empty():
                held, info.votes = info.votes, Votes()
                self._detached.merge(held)
            out = info.synod.handle(from_, MChosen(clock))
            assert out is None
            self._recovery_untrack(dot)
            if self._gc_running() and self._dot_in_my_shard(dot):
                self._to_processes.append(ToForward(MCommitDot(dot)))
            else:
                self._cmds.gc_single(dot)
            return
        if info.status == Status.START:
            buffered = self._buffered_mcommits.get(dot)
            if buffered is not None:
                # merge (not overwrite): a recovered commit and a member's
                # held-vote re-broadcast may both arrive pre-payload
                _bf, _bc, buf_votes, buf_rec = buffered
                votes.merge(buf_votes)
                recovered = recovered or buf_rec
            self._buffered_mcommits[dot] = (from_, clock, votes, recovered)
            if time is not None:
                # track for recovery: if the MCollect never comes (it was
                # broadcast while this replica was down and the commit
                # missed the rejoin records), only the recovery
                # chosen-reply exchange can fetch the payload
                self._recovery_track(dot, time)
            return

        cmd = info.cmd
        assert cmd is not None, "there should be a command payload"
        if not info.votes.is_empty():
            # votes this process consumed for the dot (shipped toward a
            # possibly-dead coordinator, or held on the no-ack/interrupted
            # paths).  They may exist nowhere else, and they must reach
            # every table *with* the dot's ops — releasing them detached
            # would let stability overtake the commit on slower replicas.
            # So: join them to the local table add below, and when the
            # commit was recovery-decided (its votes lack the quorum's
            # consumed ranges) — or a recovery PROMISE consumed ranges
            # here that no aggregation ever saw — re-broadcast them
            # commit-coupled; receivers fold them in post-ops via the
            # duplicate-commit branch above
            held, info.votes = info.votes, Votes()
            if recovered or info.recovery_consumed:
                self._to_processes.append(
                    ToSend(
                        self.bp.all_but_me(),
                        MCommit(dot, clock, copy.deepcopy(held), recovered=True),
                    )
                )
            votes.merge(held)
        if self._commit_arrays is not None:
            # batched commit seam: rows go out as columns, not objects
            for key, ops in cmd.iter_ops(self.bp.shard_id):
                self._commit_arrays.add_row(
                    dot, clock, cmd.rifl, key, ops, votes.remove(key)
                )
        else:
            for key, ops in cmd.iter_ops(self.bp.shard_id):
                key_votes = votes.remove(key)
                self._to_executors.append(
                    TableVotes(dot, clock, cmd.rifl, key, ops, key_votes)
                )

        info.status = Status.COMMIT
        # audit plane: timestamp-order agreement = same dot, same clock
        self.bp.audit_commit(dot, cmd.rifl, clock)
        self.bp.trace_span(
            "commit", cmd.rifl, dot=dot,
            meta={"recovered": True} if recovered else None,
        )
        # a bump buffered between our commit and its own delivery is moot
        # (detached votes already cover the commit clock); one trailing the
        # GC'd commit ages out of the bounded buffer instead
        self._buffered_mbumps.pop(dot, None)
        out = info.synod.handle(from_, MChosen(clock))
        assert out is None
        self._recovery_untrack(dot)

        if self.bp.config.newt_clock_bump_interval_ms is not None:
            # real-time mode: the clock-bump worker generates detached votes
            # periodically; just teach it the commit clock
            self._to_processes.append(ToForward(MCommitClock(clock)))
        else:
            self.key_clocks.detached(cmd, clock, self._detached)

        if self._gc_running() and self._dot_in_my_shard(dot):
            self._to_processes.append(ToForward(MCommitDot(dot)))
        else:
            self._cmds.gc_single(dot)

    def _handle_mdetached(self, detached: Votes) -> None:
        if self._commit_arrays is not None:
            for key, key_votes in detached:
                self._commit_arrays.add_detached(key, key_votes)
            return
        for key, key_votes in detached:
            self._to_executors.append(TableDetachedVotes(key, key_votes))

    def _handle_mconsensus(self, from_, dot, ballot, clock, cmd=None, time=None) -> None:
        if self._gc_straggler(dot):
            return  # straggler for a GC'd dot: do not resurrect its info
        info = self._cmds.get(dot)
        if cmd is not None and info.cmd is None:
            self._adopt_recovered_payload(dot, info, cmd, time)
        out = info.synod.handle(from_, MAccept(ballot, clock))
        if out is None:
            return
        if isinstance(out, SynodMAccepted):
            self._to_processes.append(ToSend({from_}, MConsensusAck(dot, out.ballot)))
        elif isinstance(out, MChosen):
            # already chosen: answer with a commit carrying our local votes
            # (a recovery proposer re-running consensus against
            # already-chosen acceptors lands here).  Multi-shard commands
            # must not: the local clock lacks the cross-shard max, which
            # only travels via MShardAggregatedCommit
            if info.cmd is None or info.cmd.shard_count == 1:
                self._to_processes.append(
                    ToSend(
                        {from_},
                        MCommit(
                            dot, out.value, info.votes,
                            recovered=True, cmd=info.cmd,
                        ),
                    )
                )
        else:
            raise AssertionError(f"unexpected synod output {out}")

    def _handle_mconsensusack(self, from_, dot, ballot) -> None:
        if self._gc_straggler(dot):
            return  # straggler for a GC'd dot: do not resurrect its info
        info = self._cmds.get(dot)
        out = info.synod.handle(from_, SynodMAccepted(ballot))
        if out is None:
            return
        assert isinstance(out, MChosen), f"unexpected synod output {out}"
        votes, info.votes = info.votes, Votes()
        # first-round slow-path ballots are process ids (<= n); anything
        # above means this choice came from recovery prepare/promise and
        # the commit must carry the recovered flag (vote re-broadcasts)
        recovered = info.synod.current_ballot() > self.bp.config.n
        self._mcommit_actions(info, dot, out.value, votes, recovered)

    # --- periodic events ---

    def _handle_event_clock_bump(self, time: SysTime) -> None:
        # vote every key up to max(highest committed clock, now): stability
        # then tracks real time even for idle keys (newt.rs:983-1006; micros
        # because millis lack precision at high client counts)
        min_clock = max(self._max_commit_clock, time.micros())
        self.key_clocks.detached_all(min_clock, self._detached)

    def _handle_event_send_detached(self) -> None:
        if not self._detached.is_empty():
            detached, self._detached = self._detached, Votes()
            self._to_processes.append(ToSend(self.bp.all(), MDetached(detached)))
        # held rejoin backfills re-check on this cadence: the
        # buffered-commit gate clears as in-flight commits resolve, and
        # no single message reliably anchors that release
        self._sync_release_backfills(None)

    def _dot_in_my_shard(self, dot: Dot) -> bool:
        return dot.target_shard(self.bp.config.n) == self.bp.shard_id

    # --- worker routing (newt.rs:1236-1284) ---

    @staticmethod
    def message_index(msg):
        if isinstance(
            msg,
            (
                MCollect,
                MCollectAck,
                MCommit,
                MConsensus,
                MConsensusAck,
                MForwardSubmit,
                MBump,
                MShardCommit,
                MShardAggregatedCommit,
                MRecoveryPrepare,
                MRecoveryPromise,
            ),
        ):
            return worker_dot_index_shift(msg.dot)
        if isinstance(msg, MCommitClock):
            return worker_index_no_shift(CLOCK_BUMP_WORKER_INDEX)
        if isinstance(msg, MDetached):
            # any worker may feed detached votes to the executors
            return worker_index_no_shift(0)
        if isinstance(msg, (MSync, MSyncReply, MSyncBackfill)):
            # dotless rejoin traffic: serialized on the GC worker (whose
            # committed clock it reads and whose retention it rides)
            return worker_index_no_shift(0)
        gc_index = CommitGCMixin.gc_message_index(msg)
        if gc_index is not None:
            return gc_index[0]
        raise AssertionError(f"unknown message {msg}")

    @staticmethod
    def event_index(event):
        if isinstance(event, (ClockBumpEvent, SendDetachedEvent)):
            return worker_index_no_shift(CLOCK_BUMP_WORKER_INDEX)
        return CommitGCMixin.event_index(event)
