"""Partial-replication (multi-shard) commit glue.

Reference: fantoch_ps/src/protocol/partial.rs:8-246.  A multi-shard command
runs the protocol *independently in each shard it touches* under the same
dot; commits are then aggregated:

  1. the shard the client targeted forwards the submit to the closest
     process of every other shard the command touches (MForwardSubmit);
  2. when a shard's instance decides (fast or slow path), instead of
     broadcasting MCommit it sends its decided data to the dot owner (the
     coordinator process in the target shard) as MShardCommit;
  3. the owner aggregates one MShardCommit per shard; once all shards
     reported it answers every participant with MShardAggregatedCommit;
  4. each participant then broadcasts the final MCommit *within its own
     shard* (BaseProcess.all() is shard-local).

``PartialCommitMixin`` owns the per-dot aggregation state and exposes the
four hooks; the protocol supplies three small adapters describing what its
commit data looks like (join for the aggregate, message constructors).
Used by Atlas (deps union) and Newt (clock max, with the Votes riding the
``local`` channel); EPaxos does not support partial replication (mirroring
the reference, fantoch_ps/src/protocol/epaxos.rs).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, Dict, Set

from fantoch_tpu.core.command import Command
from fantoch_tpu.core.ids import Dot, ProcessId
from fantoch_tpu.protocol.base import ToSend


@dataclass
class MForwardSubmit:
    """Submit forwarded to the closest process of a non-target shard."""

    dot: Dot
    cmd: Command


@dataclass
class MShardCommit:
    """One shard's decided data, sent to the dot owner for aggregation."""

    dot: Dot
    data: Any


@dataclass
class MShardAggregatedCommit:
    """The joined decision, sent back to every participant shard."""

    dot: Dot
    data: Any


class ShardsCommits:
    """Aggregation of one commit notification per shard (partial.rs:206-246)."""

    __slots__ = ("shard_count", "participants", "data")

    def __init__(self, shard_count: int, initial: Any):
        self.shard_count = shard_count
        self.participants: Set[ProcessId] = set()
        self.data = initial

    def add(self, from_: ProcessId, data: Any, join: Callable[[Any, Any], Any]) -> bool:
        assert from_ not in self.participants, (
            f"duplicate MShardCommit from {from_}"
        )
        self.participants.add(from_)
        self.data = join(self.data, data)
        return len(self.participants) == self.shard_count


class PartialCommitMixin:
    """Protocol mixin owning the multi-shard commit aggregation.

    Requirements on the host protocol class:
      * ``self.bp`` — a BaseProcess (shard-local all(), closest_process);
      * ``self._to_processes`` — the action deque;
      * ``_partial_initial_data()`` — bottom element of the commit-data
        join (e.g. an empty Dependency set for Atlas);
      * ``_partial_join(acc, data)`` — commutative join of per-shard data
        (deps union for Atlas; max clock for a timestamp protocol);
      * ``_partial_final_mcommit(dot, data, local)`` — the protocol's
        MCommit message carrying the aggregated data plus whatever the
        participant stashed as ``local`` at ``partial_mcommit_actions``
        time (the reference's data2 channel — e.g. Newt's Votes, which
        never cross shards; None when nothing was stashed).
    """

    _shards_commits: Dict[Dot, ShardsCommits]

    def _init_partial(self) -> None:
        self._shards_commits = {}
        # per-dot data that stays at the participant and rejoins the final
        # MCommit after aggregation (the reference's D2 / set_votes channel:
        # Newt's Votes never cross shards, partial.rs:37-102 data2)
        self._partial_local: Dict[Dot, Any] = {}

    # --- hook 1: submit-side forwarding (partial.rs:8-35) ---

    def partial_submit_actions(self, dot: Dot, cmd: Command, target_shard: bool) -> None:
        if not target_shard:
            return
        for shard_id in cmd.shards():
            if shard_id != self.bp.shard_id:
                self._to_processes.append(
                    ToSend(
                        {self.bp.closest_process(shard_id)},
                        MForwardSubmit(dot, cmd),
                    )
                )

    # --- hook 2: at a shard's commit decision (partial.rs:37-102) ---

    def partial_mcommit_actions(
        self, dot: Dot, cmd: Command, data: Any, local: Any = None
    ) -> bool:
        """Returns True if the commit was routed through shard aggregation
        (multi-shard); False means the caller should broadcast its own
        MCommit (single-shard command).  ``local`` stays here and is handed
        back to ``_partial_final_mcommit`` when the aggregate returns."""
        shard_count = cmd.shard_count
        if shard_count == 1:
            return False
        if local is not None:
            self._partial_local[dot] = local
        # our own data flows through the MShardCommit to the owner (which
        # may be ourselves — self-delivery) and comes back aggregated
        self._to_processes.append(ToSend({dot.source}, MShardCommit(dot, data)))
        return True

    # --- hook 3: at the dot owner (partial.rs:104-142) ---

    def partial_handle_mshard_commit(
        self, from_: ProcessId, dot: Dot, data: Any, shard_count: int
    ) -> None:
        agg = self._shards_commits.get(dot)
        if agg is None:
            agg = ShardsCommits(shard_count, self._partial_initial_data())
            self._shards_commits[dot] = agg
        done = agg.add(from_, data, self._partial_join)
        if done:
            self._to_processes.append(
                ToSend(
                    set(agg.participants),
                    MShardAggregatedCommit(dot, agg.data),
                )
            )
            del self._shards_commits[dot]

    # --- hook 4: back at each participant (partial.rs:144-177) ---

    def partial_handle_mshard_aggregated_commit(self, dot: Dot, data: Any) -> None:
        local = self._partial_local.pop(dot, None)
        self._to_processes.append(
            ToSend(self.bp.all(), self._partial_final_mcommit(dot, data, local))
        )

    # --- shared message dispatch (the handle() tail both protocols share) ---

    def handle_partial_message(self, from_: ProcessId, msg) -> bool:
        """Dispatch the partial-replication message set; False when ``msg``
        is none of them (caller continues its chain)."""
        if isinstance(msg, MForwardSubmit):
            self._handle_submit(msg.dot, msg.cmd, target_shard=False)
        elif isinstance(msg, MShardCommit):
            info = self._cmds.get(msg.dot)
            assert info.cmd is not None, (
                "the dot owner submits before any shard can commit"
            )
            self.partial_handle_mshard_commit(
                from_, msg.dot, msg.data, info.cmd.shard_count
            )
        elif isinstance(msg, MShardAggregatedCommit):
            self.partial_handle_mshard_aggregated_commit(msg.dot, msg.data)
        else:
            return False
        return True

    # --- adapters the protocol must provide ---

    def _partial_initial_data(self) -> Any:
        raise NotImplementedError

    def _partial_join(self, acc: Any, data: Any) -> Any:
        raise NotImplementedError

    def _partial_final_mcommit(self, dot: Dot, data: Any, local: Any):
        raise NotImplementedError
