"""Partial-replication (multi-shard) commit glue.

Reference: fantoch_ps/src/protocol/partial.rs.  A multi-shard command runs
the protocol *independently in each shard it touches*; commits are then
aggregated: every shard sends an MShardCommit to the dot owner (the process
in the client's target shard), which replies MShardAggregatedCommit with
the joined data once all shards reported, and each shard then broadcasts
the final MCommit internally.  Used by Atlas (deps union) and Newt (max
clock + votes); EPaxos does not support partial replication.
"""

from __future__ import annotations

from typing import Callable, Dict, Generic, Optional, Set, TypeVar

from fantoch_tpu.core.command import Command
from fantoch_tpu.core.ids import Dot, ProcessId
from fantoch_tpu.protocol.base import BaseProcess, ToSend

I = TypeVar("I")


class ShardsCommits(Generic[I]):
    """Aggregation of one commit notification per shard (partial.rs:206-246)."""

    __slots__ = ("process_id", "shard_count", "participants", "info")

    def __init__(self, process_id: ProcessId, shard_count: int, info: I):
        self.process_id = process_id
        self.shard_count = shard_count
        self.participants: Set[ProcessId] = set()
        self.info = info

    def add(self, from_: ProcessId, add: Callable[[I], None]) -> bool:
        assert from_ not in self.participants
        self.participants.add(from_)
        add(self.info)
        return len(self.participants) == self.shard_count

    def update(self, update: Callable[[I], None]) -> None:
        update(self.info)


def submit_actions(
    bp: BaseProcess,
    dot: Dot,
    cmd: Command,
    target_shard: bool,
    create_mforward_submit,
    to_processes,
) -> None:
    """Forward the submit to the closest process of every other shard the
    command touches — only from the shard the client targeted
    (partial.rs:8-35)."""
    if not target_shard:
        return
    for shard_id in cmd.shards():
        if shard_id != bp.shard_id:
            to_processes.append(
                ToSend({bp.closest_process(shard_id)}, create_mforward_submit(dot, cmd))
            )


def mcommit_actions(
    bp: BaseProcess,
    get_shards_commits: Callable[[], Optional[ShardsCommits]],
    set_shards_commits: Callable[[ShardsCommits], None],
    info_factory: Callable[[], I],
    shard_count: int,
    dot: Dot,
    data1,
    data2,
    create_mcommit,
    create_mshard_commit,
    update_shards_commits_info: Callable[[I, object], None],
    to_processes,
) -> None:
    """Single shard: broadcast the MCommit.  Multi-shard: record our own
    data and send an MShardCommit to the dot owner (partial.rs:37-102)."""
    if shard_count == 1:
        to_processes.append(ToSend(bp.all(), create_mcommit(dot, data1, data2)))
        return
    shards_commits = _init(get_shards_commits, set_shards_commits, bp, shard_count, info_factory)
    shards_commits.update(lambda info: update_shards_commits_info(info, data2))
    to_processes.append(ToSend({dot.source}, create_mshard_commit(dot, data1)))


def handle_mshard_commit(
    bp: BaseProcess,
    get_shards_commits: Callable[[], Optional[ShardsCommits]],
    set_shards_commits: Callable[[ShardsCommits], None],
    info_factory: Callable[[], I],
    shard_count: int,
    from_: ProcessId,
    dot: Dot,
    data,
    add_shards_commits_info: Callable[[I, object], None],
    create_mshard_aggregated_commit,
    to_processes,
) -> None:
    """At the dot owner: aggregate per-shard commits; once all shards
    reported, answer every participant (partial.rs:104-142)."""
    shards_commits = _init(get_shards_commits, set_shards_commits, bp, shard_count, info_factory)
    done = shards_commits.add(from_, lambda info: add_shards_commits_info(info, data))
    if done:
        to_processes.append(
            ToSend(
                set(shards_commits.participants),
                create_mshard_aggregated_commit(dot, shards_commits.info),
            )
        )


def handle_mshard_aggregated_commit(
    bp: BaseProcess,
    take_shards_commits: Callable[[], Optional[ShardsCommits]],
    dot: Dot,
    data1,
    extract_mcommit_extra_data,
    create_mcommit,
    to_processes,
) -> None:
    """Back at each participant: broadcast the final MCommit within the
    shard (partial.rs:144-177)."""
    shards_commits = take_shards_commits()
    assert shards_commits is not None, (
        f"no shards commit info when handling MShardAggregatedCommit for {dot}"
    )
    data2 = extract_mcommit_extra_data(shards_commits.info)
    to_processes.append(ToSend(bp.all(), create_mcommit(dot, data1, data2)))


def _init(get, set_, bp: BaseProcess, shard_count: int, info_factory) -> ShardsCommits:
    shards_commits = get()
    if shards_commits is None:
        shards_commits = ShardsCommits(bp.process_id, shard_count, info_factory())
        set_(shards_commits)
    return shards_commits
