"""Shared commit-tracking / garbage-collection machinery.

Every leaderless protocol carries the same GC message set
(MCommitDot -> GC worker; periodic MGarbageCollection broadcast of the
committed clock; MStable forwarded to all workers once the meet advances).
The reference duplicates these handlers in each protocol file
(e.g. fantoch/src/protocol/basic.rs:261-315,
fantoch_ps/src/protocol/epaxos.rs:520-600); here they live once as a mixin
over ``self.bp``/``self._gc_track``/``self._cmds``/``self._to_processes``.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Tuple

from fantoch_tpu.core.clocks import VClock
from fantoch_tpu.core.ids import Dot, ProcessId
from fantoch_tpu.protocol.base import ToForward, ToSend
from fantoch_tpu.run.routing import GC_WORKER_INDEX, worker_index_no_shift


@dataclass
class MCommitDot:
    dot: Dot


@dataclass
class MGarbageCollection:
    committed: VClock


@dataclass
class MStable:
    stable: List[Tuple[ProcessId, int, int]]


@dataclass
class GarbageCollectionEvent:
    """Periodic event triggering a GC round."""


class CommitGCMixin:
    """Requires: self.bp (BaseProcess), self._gc_track (GCTrack),
    self._cmds (CommandsInfo), self._to_processes (deque)."""

    def gc_periodic_events(self):
        if self.bp.config.gc_interval_ms is not None:
            return [(GarbageCollectionEvent(), self.bp.config.gc_interval_ms)]
        return []

    def handle_gc_message(self, from_: ProcessId, msg) -> bool:
        """Dispatch a GC message; returns False if `msg` is not one."""
        if isinstance(msg, MCommitDot):
            assert from_ == self.bp.process_id
            self._gc_track.add_to_clock(msg.dot)
        elif isinstance(msg, MGarbageCollection):
            self._gc_track.update_clock_of(from_, msg.committed)
            stable = self._gc_track.stable()
            if stable:
                self._to_processes.append(ToForward(MStable(stable)))
        elif isinstance(msg, MStable):
            assert from_ == self.bp.process_id
            self.bp.stable(self._cmds.gc(msg.stable))
        else:
            return False
        return True

    def note_durable_commits(self, dots) -> None:
        """Restart-replay hook (run/wal.py): fold WAL-tail commit dots
        into the committed clock so the rejoin horizon (MSync) covers
        them — peers must not re-stream commits whose effects the
        executor tail replay already applied (re-applying would execute
        them twice).  Single-shard only, like the sync plane."""
        if self.bp.config.shard_count != 1:
            return
        for dot in dots:
            self._gc_track.add_to_clock(dot)

    def handle_gc_event(self) -> None:
        """Periodic: broadcast our committed clock."""
        committed = self._gc_track.clock()
        self._to_processes.append(
            ToSend(self.bp.all_but_me(), MGarbageCollection(committed))
        )

    def _gc_running(self) -> bool:
        return self.bp.config.gc_interval_ms is not None

    @staticmethod
    def event_index(event):
        """Periodic events (GC rounds) run on the reserved GC worker
        (fantoch/src/run/prelude.rs:18)."""
        return worker_index_no_shift(GC_WORKER_INDEX)

    @staticmethod
    def gc_message_index(msg):
        """Worker routing for GC messages; None if `msg` is not one, and the
        MStable broadcast-to-all-workers is represented as (None,)."""
        if isinstance(msg, (MCommitDot, MGarbageCollection)):
            return (worker_index_no_shift(GC_WORKER_INDEX),)
        if isinstance(msg, MStable):
            return (None,)
        return None
