"""Caesar: timestamp + predecessors consensus with a wait condition.

Reference: fantoch_ps/src/protocol/caesar.rs (1399 LoC).  The coordinator
assigns a globally-unique lexicographic timestamp ``Clock(seq, pid)`` to
each command and proposes it to everyone; each replica computes the
conflicting commands with lower timestamps (the predecessors) and replies:

* ACCEPT (ok) — no conflicting command with a *higher* timestamp blocks it;
* WAIT — blocked by higher-timestamp conflicts whose fate is unknown: the
  reply is delayed until they commit/accept (the wait condition,
  caesar.rs:266-451);
* REJECT (not ok) — some higher-timestamp conflict does not include this
  command in its deps, so the proposed timestamp is too low; the replica
  counter-proposes a higher one.

Fast path iff the whole fast quorum (3n/4 + 1) said ok; otherwise the
coordinator retries with the aggregated (clock, deps) through MRetry on the
write quorum (majority), which yields extended deps and then commits.
Execution is the PredecessorsExecutor: conflicts execute in timestamp
order.  GC is driven by the *executed* clock reported back by the executor
(handle_executed, caesar.rs:177-179).

Crash recovery (beyond the reference, whose wait-condition TODO at
caesar.rs:840-842 is where its recovery story ends): every per-dot info
embeds a :class:`~fantoch_tpu.protocol.common.synod.Synod` over the
``(clock, predecessors)`` pair.  Each replica stages its MProposeAck
report — including reject counter-proposals and retry refreshes — as the
synod's ballot-0 value, so a surviving process can drive the shared
per-dot recovery consensus (protocol/recovery.py) when a coordinator dies
mid-flight:

* a promise carries the acceptor's staged ``(clock, deps)`` report plus a
  ``clock_floor`` — the highest timestamp sequence indexed on the dot's
  keys (executed-everywhere GC keeps every non-globally-executed conflict
  indexed, so the floor upper-bounds anything survivors executed past);
* on the free-choice path the proposer takes the max reported clock and
  the union of reported predecessor sets; if the quorum floor reaches the
  chosen clock it issues a FRESH unique timestamp above the floor
  (``clock_next`` after joining the floor) and re-extends the
  predecessors under it — a recovered commit can therefore neither
  deadlock a waiting proposal (its commit resolves the wait condition
  like any other) nor land below timestamps survivors executed past (the
  floor-consumption class PR 7/9 closed for Newt);
* a dot payloaded at no live process commits as a NOOP: nothing executes,
  the executor's noop seam resolves dependents, and commands it was
  blocking unblock unconditionally (a command that never existed cannot
  reject anyone).

Restart & rejoin ride the shared :class:`SyncMixin`: commit records carry
the decided ``(clock, deps)`` value (the synod's chosen value), and the
key-clock index rebuilds from applied records — Caesar has no detached
vote channel, so unlike Newt there is no separate frontier backfill: the
predecessor index travels entirely inside the commit records, and the
timestamp sequence floor rides ``clock_join`` on each applied clock.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Deque, Dict, Optional, Set, Tuple

from fantoch_tpu.core.command import Command
from fantoch_tpu.core.config import Config
from fantoch_tpu.core.ids import Dot, ProcessId, ShardId
from fantoch_tpu.core.timing import SysTime
from fantoch_tpu.executor.pred import (
    PredArraysBuilder,
    PredecessorsExecutionInfo,
    PredecessorsExecutor,
    PredecessorsNoop,
)
from fantoch_tpu.protocol.base import (
    Action,
    BaseProcess,
    Executed,
    Protocol,
    ProtocolMetrics,
    ToSend,
)
from fantoch_tpu.protocol.commit_gc import MGarbageCollection
from fantoch_tpu.protocol.common.pred_clocks import (
    Clock,
    KeyClocks,
    QuorumClocks,
    QuorumRetries,
)
from fantoch_tpu.protocol.common.synod import (
    MAccept as SynodMAccept,
    MAccepted as SynodMAccepted,
    MChosen as SynodMChosen,
    Synod,
)
from fantoch_tpu.protocol.gc import GCTrack
from fantoch_tpu.protocol.info import CommandsInfo
from fantoch_tpu.protocol.recovery import (
    MRecoveryPrepare,
    MRecoveryPromise,
    RecoveryEvent,
    RecoveryMixin,
)
from fantoch_tpu.protocol.sync import (
    MSync,
    MSyncBackfill,
    MSyncReply,
    SyncMixin,
)
from fantoch_tpu.run.routing import (
    GC_WORKER_INDEX,
    worker_dot_index_shift,
    worker_index_no_shift,
)


# --- messages (caesar.rs:1088-1117) ---


@dataclass
class MPropose:
    dot: Dot
    cmd: Command
    clock: Clock


@dataclass
class MProposeAck:
    dot: Dot
    clock: Clock
    deps: Set[Dot]
    ok: bool


@dataclass
class MCommit:
    dot: Dot
    # None == recovered noop: the dot was payloaded at no live process,
    # nothing executes, dependents resolve through the executor noop seam
    clock: Optional[Clock]
    deps: Set[Dot]
    # payload piggyback on recovery chosen-replies and consensus-decided
    # commits: a recovering (or rejoining) replica can hold a buffered
    # commit for a dot whose MPropose it never saw — without the payload
    # the prepare/chosen exchange would loop payload-less forever
    cmd: Optional[Command] = None


@dataclass
class MRetry:
    dot: Dot
    clock: Clock
    deps: Set[Dot]


@dataclass
class MRetryAck:
    dot: Dot
    deps: Set[Dot]


@dataclass
class MConsensus:
    """Recovery phase-2: a recovery proposer's ``(clock, deps)`` accept at
    its ballot (the Caesar analog of newt.MConsensus — the normal slow
    path keeps the reference's ballot-less MRetry round; only recovery
    runs through the synod)."""

    dot: Dot
    ballot: int
    value: "CaesarConsensusValue"
    # payload piggyback so a recovered pair can commit at processes the
    # original MPropose broadcast never reached
    cmd: Optional[Command] = None


@dataclass
class MConsensusAck:
    dot: Dot
    ballot: int


@dataclass
class GarbageCollectionEvent:
    pass


class Status:
    START = "start"
    PROPOSE = "propose"
    REJECT = "reject"
    ACCEPT = "accept"
    COMMIT = "commit"


@dataclass(frozen=True)
class CaesarConsensusValue:
    """The pair agreed on per dot: the final timestamp and predecessor
    set.  ``clock None`` is the *noop* bottom: a recovery promise carrying
    it means "this acceptor never computed a report for the dot", which is
    what distinguishes a never-payloaded dot (recovered as a committed
    noop) from a real report with empty predecessors.  ``deps`` is a
    sorted tuple so equal values fingerprint identically in the model
    checker."""

    clock: Optional[Clock]
    deps: Tuple[Dot, ...]

    @property
    def is_noop(self) -> bool:
        return self.clock is None

    @staticmethod
    def bottom() -> "CaesarConsensusValue":
        return CaesarConsensusValue(None, ())


def _caesar_recovery_proposal_gen(values):
    """Recovery pair selection over the ballot-0 reports of the promise
    quorum (protocol/recovery.py): the highest reported clock with the
    union of reported predecessor sets; all-noop -> the dot is recovered
    as a committed noop.  The union may still be free-choice-adjusted
    (clock lift + predecessor re-extension) by ``_recovery_adjust_value``
    before it is proposed."""
    clock: Optional[Clock] = None
    deps: Set[Dot] = set()
    for value in values.values():
        if value.is_noop:
            continue
        deps |= set(value.deps)
        if clock is None or value.clock > clock:
            clock = value.clock
    if clock is None:
        return CaesarConsensusValue.bottom()
    return CaesarConsensusValue(clock, tuple(sorted(deps)))


def _caesar_info_factory(pid, _sid, cfg, fq, wq) -> "CaesarInfo":
    """Picklable per-dot info factory (the model checker pickles state)."""
    return CaesarInfo(pid, cfg.n, cfg.f, fq, wq)


class CaesarInfo:
    """Per-dot lifecycle info (caesar.rs:1039-1086)."""

    __slots__ = (
        "status",
        "cmd",
        "clock",
        "deps",
        "blocking",
        "blocked_by",
        "quorum_clocks",
        "quorum_retries",
        "synod",
    )

    def __init__(
        self,
        process_id: ProcessId,
        n: int,
        f: int,
        fast_quorum_size: int,
        write_quorum_size: int,
    ):
        self.status = Status.START
        self.cmd: Optional[Command] = None
        self.clock = Clock.zero(process_id)
        self.deps: Set[Dot] = set()
        # commands this command is blocking / blocked by (the wait condition)
        self.blocking: Set[Dot] = set()
        self.blocked_by: Set[Dot] = set()
        self.quorum_clocks = QuorumClocks(process_id, fast_quorum_size, write_quorum_size)
        self.quorum_retries = QuorumRetries(write_quorum_size)
        # per-dot recovery consensus over the (clock, deps) pair; ballot-0
        # holds this replica's staged MProposeAck report
        self.synod: Synod[CaesarConsensusValue] = Synod(
            process_id, n, f, _caesar_recovery_proposal_gen,
            CaesarConsensusValue.bottom(),
        )


class Caesar(RecoveryMixin, SyncMixin, Protocol):
    Executor = PredecessorsExecutor

    @classmethod
    def allowed_faults(cls, n: int) -> int:
        return n // 2

    def __init__(self, process_id: ProcessId, shard_id: ShardId, config: Config):
        fast_quorum_size, write_quorum_size = config.caesar_quorum_sizes()
        self.bp = BaseProcess(process_id, shard_id, config, fast_quorum_size, write_quorum_size)
        self.key_clocks = KeyClocks(process_id, shard_id)
        self._cmds: CommandsInfo[CaesarInfo] = CommandsInfo(
            process_id,
            shard_id,
            config,
            fast_quorum_size,
            write_quorum_size,
            _caesar_info_factory,
        )
        self._gc_track = GCTrack(process_id, shard_id, config.n)
        self._to_processes: Deque[Action] = deque()
        self._to_executors: Deque[PredecessorsExecutionInfo] = deque()
        # column-borne commit seam (the PR 4 TableVotesArraysBuilder
        # move): with the device pred plane on, commits/noops accumulate
        # as columns and drain ONE PredExecutionArrays per to_executors
        # sweep — no per-command info objects on the plane path (the
        # runner disables it via set_commit_arrays for executor pools)
        self._commit_arrays: Optional[PredArraysBuilder] = (
            PredArraysBuilder() if config.device_pred_plane else None
        )
        # MRetry/MCommit that arrived before the MPropose (multiplexing)
        self._buffered_retries: Dict[Dot, Tuple[ProcessId, Clock, Set[Dot]]] = {}
        self._buffered_commits: Dict[
            Dot, Tuple[ProcessId, Optional[Clock], Set[Dot]]
        ] = {}
        self._wait_condition = config.caesar_wait_condition
        # WAL-tail replayed commit dots not yet re-executed here: the
        # straggler/horizon overlay (see note_durable_commits) — they
        # cannot live in _gc_track because handle_executed REPLACES its
        # clock with the executor's executed clock, which excludes a
        # replayed commit still pending on a dependency
        self._durable_tail: set = set()
        self._init_recovery()
        # safety requires executed-everywhere GC: removing a command from the
        # key-clock index at commit time (the reference's no-GC shortcut,
        # caesar.rs:616-620, flagged unsafe by its own TODO at :840-842)
        # lets later proposals miss it as a predecessor, so conflicting
        # commands can execute in different orders on different replicas
        assert config.gc_interval_ms is not None, (
            "Caesar requires gc_interval_ms: commands may only leave the "
            "key-clock index once executed everywhere"
        )

    def periodic_events(self):
        # gc_interval_ms is mandatory (asserted in __init__)
        events = [(GarbageCollectionEvent(), self.bp.config.gc_interval_ms)]
        events.extend(self.recovery_periodic_events())
        return events

    @property
    def id(self) -> ProcessId:
        return self.bp.process_id

    @property
    def shard_id(self) -> ShardId:
        return self.bp.shard_id

    def discover(self, processes):
        connect_ok = self.bp.discover(processes)
        return connect_ok, dict(self.bp.closest_shard_process())

    def submit(self, dot: Optional[Dot], cmd: Command, time: SysTime) -> None:
        dot = dot if dot is not None else self.bp.next_dot()
        clock = self.key_clocks.clock_next()
        # send to everyone: due to the wait condition the fastest ok-quorum
        # may not be the closest one
        self._to_processes.append(ToSend(self.bp.all(), MPropose(dot, cmd, clock)))

    def handle(self, from_, from_shard_id, msg, time):
        if isinstance(msg, MPropose):
            self._handle_mpropose(from_, msg.dot, msg.cmd, msg.clock, time)
        elif isinstance(msg, MProposeAck):
            self._handle_mproposeack(from_, msg.dot, msg.clock, msg.deps, msg.ok)
        elif isinstance(msg, MCommit):
            self._handle_mcommit(
                from_, msg.dot, msg.clock, msg.deps, time, getattr(msg, "cmd", None)
            )
        elif isinstance(msg, MRetry):
            self._handle_mretry(from_, msg.dot, msg.clock, msg.deps, time)
        elif isinstance(msg, MRetryAck):
            self._handle_mretryack(from_, msg.dot, msg.deps)
        elif isinstance(msg, MConsensus):
            self._handle_mconsensus(from_, msg.dot, msg.ballot, msg.value, msg.cmd, time)
        elif isinstance(msg, MConsensusAck):
            self._handle_mconsensusack(from_, msg.dot, msg.ballot)
        elif isinstance(msg, MGarbageCollection):
            self._handle_mgc(from_, msg.committed)
        elif self.handle_recovery_message(from_, msg, time):
            pass
        elif self.handle_sync_message(from_, msg, time):
            pass
        else:
            raise AssertionError(f"unknown message {msg}")

    def handle_event(self, event, time):
        if isinstance(event, RecoveryEvent):
            self.handle_recovery_event(time)
            return
        assert isinstance(event, GarbageCollectionEvent)
        self._to_processes.append(
            ToSend(self.bp.all_but_me(), MGarbageCollection(self._gc_track.clock()))
        )

    def handle_executed(self, executed: Executed, time: SysTime) -> None:
        # GC is driven by the executor: a dot is collectable once *executed*
        # everywhere (not just committed — the key-clock index must keep
        # commands until no proposal can conflict with them)
        if self._durable_tail:
            # replayed-commit overlay dots age out once truly executed
            self._durable_tail = {
                dot
                for dot in self._durable_tail
                if not executed.contains(dot.source, dot.sequence)
            }
        self._gc_track.update_clock(executed)

    def note_durable_commits(self, dots) -> None:
        """Restart-replay hook (run/wal.py): remember WAL-tail commit dots
        so the straggler guards and the rejoin horizon cover them.  They
        go into an OVERLAY, not the GC clock: handle_executed replaces
        that clock wholesale with the executor's executed clock, which
        would silently drop a replayed commit still pending on a
        dependency — a later duplicate/re-streamed commit would then
        resurrect a fresh info and re-feed the executor, tripping its
        exactly-once assert."""
        if self.bp.config.shard_count != 1:
            return
        self._durable_tail.update(dots)

    def _gc_straggler(self, dot: Dot) -> bool:
        """True when ``dot``'s commit is already settled here — executed
        (the GC clock) or replayed from the WAL tail (the overlay) — so
        an incoming message for it is a straggler that must not
        resurrect a fresh info."""
        return self._gc_track.contains(dot) or dot in self._durable_tail

    def _recovery_settled(self, dot: Dot) -> bool:
        # recovery-plane guard (RecoveryMixin): WAL-tail replayed dots
        # are committed, never recovery candidates
        return self._gc_straggler(dot)

    def to_processes(self) -> Optional[Action]:
        return self._to_processes.popleft() if self._to_processes else None

    def to_executors(self):
        if self._commit_arrays is not None and len(self._commit_arrays):
            return self._commit_arrays.take()
        return self._to_executors.popleft() if self._to_executors else None

    def set_commit_arrays(self, enabled: bool) -> None:
        """Runner hook (the Newt seam's twin): the arrays commit seam
        assumes a single predecessors executor consumes this process's
        infos; executor pools must turn it off (falls back to
        per-command infos)."""
        if enabled and self._commit_arrays is None:
            self._commit_arrays = PredArraysBuilder()
        elif not enabled and self._commit_arrays is not None:
            # flush anything accumulated so no commit is lost
            pending = self._commit_arrays.take()
            if pending is not None:
                self._to_executors.append(pending)
            self._commit_arrays = None

    def _emit_commit(self, dot: Dot, cmd: Command, clock: Clock, deps: Set[Dot]) -> None:
        if self._commit_arrays is not None:
            self._commit_arrays.add_commit(dot, cmd, clock, deps)
        else:
            self._to_executors.append(
                PredecessorsExecutionInfo(dot, cmd, clock, deps)
            )

    def _emit_noop(self, dot: Dot) -> None:
        if self._commit_arrays is not None:
            self._commit_arrays.add_noop(dot)
        else:
            self._to_executors.append(PredecessorsNoop(dot))

    @classmethod
    def parallel(cls) -> bool:
        return KeyClocks.parallel()

    @classmethod
    def leaderless(cls) -> bool:
        return True

    def metrics(self) -> ProtocolMetrics:
        return self.bp.metrics()

    # --- handlers ---

    def _handle_mpropose(self, from_, dot, cmd, remote_clock: Clock, time) -> None:
        assert dot.source == from_, "the coordinator is the dot source"
        self.key_clocks.clock_join(remote_clock)

        if self._gc_straggler(dot):
            # straggler (late duplicate) for a dot already committed
            # everywhere and GC'd (or replayed from the WAL tail):
            # `_cmds.get` would resurrect a fresh START info, and a
            # trailing MCommit duplicate could then RE-feed the executor
            # (its exactly-once assert catches the replay) — the PR 7
            # GC-straggler class, Caesar edition
            return
        info = self._cmds.get(dot)
        if info.status != Status.START:
            return

        # predecessors under the proposed timestamp; higher-timestamp
        # conflicts block the reply (the wait condition's input)
        blocked_by: Set[Dot] = set()
        deps = self.key_clocks.predecessors(dot, cmd, remote_clock, blocked_by)

        info.status = Status.PROPOSE
        info.cmd = cmd
        info.deps = deps
        self._update_clock(dot, info, remote_clock)
        info.blocked_by = set(blocked_by)
        self._recovery_track(dot, time)

        # stage the ballot-0 recovery report NOW (the proposed pair as
        # computed here): a WAITING command's ack may never be sent, but
        # its promise must still carry the conflict edges this replica
        # knows about.  Failure means a recovery prepare already owns a
        # higher ballot — the ballot-0 ack is forbidden (our promise is a
        # contract); the command stays indexed (it must appear as a
        # predecessor of later proposals) and recovery drives the commit
        staged = info.synod.set_if_not_accepted(
            lambda: CaesarConsensusValue(remote_clock, tuple(sorted(deps)))
        )
        if not staged:
            self._replay_buffered(dot, time)
            return

        if not blocked_by:
            self._accept_command(dot, info)
        elif not self._wait_condition:
            self._reject_command(dot, info)
        else:
            # check each blocker: ACCEPT/COMMIT blockers with a good-enough
            # clock+deps can be ignored; an un-ignorable one rejects us right
            # away; unknown-fate ones register us in their blocking set
            reject = False
            not_blocked_by: Set[Dot] = set()
            for blocker in blocked_by:
                blocker_info = self._cmds.get_existing(blocker)
                if blocker_info is None:
                    # GCed = executed everywhere: can be ignored
                    not_blocked_by.add(blocker)
                    continue
                if blocker_info.status in (Status.ACCEPT, Status.COMMIT):
                    if self._safe_to_ignore(
                        dot, info.clock, blocker_info.clock, blocker_info.deps
                    ):
                        not_blocked_by.add(blocker)
                    else:
                        reject = True
                        break
                else:
                    blocker_info.blocking.add(dot)
            if reject:
                self._reject_command(dot, info)
            elif len(not_blocked_by) == len(blocked_by):
                self._accept_command(dot, info)
            else:
                info.blocked_by -= not_blocked_by
                assert info.blocked_by, "a waiting command must have blockers"

        # replay any buffered retry/commit now that we have the payload
        self._replay_buffered(dot, time)

    def _replay_buffered(self, dot, time) -> None:
        buffered = self._buffered_retries.pop(dot, None)
        if buffered is not None:
            self._handle_mretry(buffered[0], dot, buffered[1], buffered[2], time)
        buffered = self._buffered_commits.pop(dot, None)
        if buffered is not None:
            self._handle_mcommit(buffered[0], dot, buffered[1], buffered[2], time)

    def _handle_mproposeack(self, from_, dot, clock: Clock, deps, ok: bool) -> None:
        # get_existing: a straggler ack (MPropose went to all n, only the
        # fast quorum's replies matter) must not recreate a GCed info
        info = self._cmds.get_existing(dot)
        if info is None:
            return
        # the coordinator can end up rejecting its own command, hence REJECT
        if info.status not in (Status.PROPOSE, Status.REJECT):
            return
        if info.quorum_clocks.contains(from_):
            # duplicate ack (at-least-once delivery): double-counting a
            # participant would complete the quorum with fewer distinct
            # reports — an unsound fast path (the dedup class PR 9 fixed
            # in both mcollectack handlers)
            return
        if info.quorum_clocks.all():
            # straggler ack: MPropose goes to all n but the quorum (< n for
            # n>=5) completes first, and the commit/retry that flips the
            # status travels through the message queue — so a late ack can
            # legitimately arrive while the status is still PROPOSE/REJECT
            # (the reference panics here, reachable in our runner's
            # reader-task queueing; see ADVICE r1)
            return

        info.quorum_clocks.add(from_, clock, deps, ok)
        if not info.quorum_clocks.all():
            return

        if not info.synod.can_skip_prepare():
            # a recovery proposer owns a higher ballot for this dot: a
            # unilateral commit/retry is no longer sound — join recovery
            # with a full prepare instead (the Newt mcollectack pattern)
            prepare = info.synod.new_prepare()
            self._to_processes.append(
                ToSend(self.bp.all(), MRecoveryPrepare(dot, prepare.ballot, info.cmd))
            )
            return

        agg_clock, agg_deps, agg_ok = info.quorum_clocks.aggregated()
        if agg_ok:
            # everyone accepted the coordinator's proposal as-is
            assert agg_clock == info.clock
            self.bp.fast_path()
            self._to_processes.append(
                ToSend(self.bp.all(), MCommit(dot, agg_clock, agg_deps))
            )
        else:
            self.bp.slow_path()
            # sent to everyone: the new clock may unblock waiting commands
            self._to_processes.append(
                ToSend(self.bp.all(), MRetry(dot, agg_clock, agg_deps))
            )

    def _handle_mcommit(
        self, from_, dot, clock: Optional[Clock], deps, time=None, cmd=None
    ) -> None:
        if clock is not None:
            self.key_clocks.clock_join(clock)
        if self._gc_straggler(dot):
            return  # straggler for a settled dot: do not resurrect its info
        info = self._cmds.get(dot)
        if info.status == Status.COMMIT:
            return
        if cmd is not None and info.cmd is None:
            # recovery chosen-reply / sync-record piggyback: adopt so the
            # commit below proceeds instead of buffering payload-less.  A
            # commit buffered earlier is superseded by this one (consensus
            # decided the same value) — pop it or it leaks
            self._buffered_commits.pop(dot, None)
            self._adopt_recovered_payload(dot, info, cmd, time)
            if info.status == Status.COMMIT:
                return  # adoption replayed a buffered retry chain to commit

        if clock is None:
            # recovered noop: the dot was payloaded at no live process.
            # Nothing executes and nothing is indexed — the executor noop
            # seam resolves dependents, and commands this dot was blocking
            # unblock unconditionally (a command that never existed cannot
            # reject anyone)
            info.status = Status.COMMIT
            # audit plane: a noop commit executes nothing — rifl None
            self.bp.audit_commit(dot, None, "noop")
            if info.cmd is not None and not info.clock.is_zero():
                # un-index: a noop must stop being reported as a
                # predecessor (and _gc_command must not try to remove it
                # again — the zero clock marks it)
                self.key_clocks.remove(info.cmd, info.clock)
                info.clock = Clock.zero(self.bp.process_id)
            self._emit_noop(dot)
            blocking, info.blocking = info.blocking, set()
            for blocked in blocking:
                blocked_info = self._cmds.get_existing(blocked)
                if blocked_info is None or blocked_info.status != Status.PROPOSE:
                    continue
                blocked_info.blocked_by.discard(dot)
                if not blocked_info.blocked_by:
                    self._accept_command(blocked, blocked_info)
            out = info.synod.handle(from_, SynodMChosen(CaesarConsensusValue.bottom()))
            assert out is None
            self._recovery_untrack(dot)
            return

        if info.status == Status.START:
            self._buffered_commits[dot] = (from_, clock, set(deps))
            if time is not None:
                # track for recovery: if the MPropose never comes (it was
                # broadcast while this replica was down and the commit
                # missed the rejoin records), only the recovery
                # chosen-reply exchange can fetch the payload
                self._recovery_track(dot, time)
            return

        cmd = info.cmd
        assert cmd is not None, "there should be a command payload"
        self._emit_commit(dot, cmd, clock, set(deps))

        info.status = Status.COMMIT
        # audit plane: agreement = same dot, same (clock, predecessors)
        self.bp.audit_commit(dot, cmd.rifl, (clock, tuple(sorted(deps))))
        info.deps = set(deps)
        self._update_clock(dot, info, clock)
        # settle the per-dot synod so recovery prepares short-circuit with
        # this decided pair, and stop any recovery retries for the dot
        out = info.synod.handle(
            from_, SynodMChosen(CaesarConsensusValue(clock, tuple(sorted(deps))))
        )
        assert out is None
        self._recovery_untrack(dot)

        blocking, info.blocking = info.blocking, set()
        self._try_to_unblock(dot, clock, info.deps, blocking)

    def _handle_mretry(self, from_, dot, clock: Clock, deps, time=None) -> None:
        self.key_clocks.clock_join(clock)
        if self._gc_straggler(dot):
            return  # straggler for a settled dot: do not resurrect its info
        info = self._cmds.get(dot)
        if info.status == Status.START:
            self._buffered_retries[dot] = (from_, clock, set(deps))
            return
        if info.status == Status.COMMIT:
            return

        info.status = Status.ACCEPT
        info.deps = set(deps)
        self._update_clock(dot, info, clock)
        # refresh the staged ballot-0 report to the retry pair: a recovery
        # promise must report the freshest knowledge (no-op once a
        # recovery prepare froze the report by bumping the ballot)
        info.synod.set_if_not_accepted(
            lambda: CaesarConsensusValue(clock, tuple(sorted(deps)))
        )

        # reply with deps extended by our own lower-timestamp conflicts
        cmd = info.cmd
        assert cmd is not None
        new_deps = self.key_clocks.predecessors(dot, cmd, clock)
        new_deps.update(deps)
        self._to_processes.append(ToSend({from_}, MRetryAck(dot, new_deps)))

        blocking, info.blocking = info.blocking, set()
        self._try_to_unblock(dot, clock, info.deps, blocking)

    def _handle_mretryack(self, from_, dot, deps) -> None:
        info = self._cmds.get_existing(dot)
        if info is None or info.status != Status.ACCEPT:
            return
        if info.quorum_retries.contains(from_):
            return  # duplicate ack (at-least-once delivery)
        if info.quorum_retries.all():
            # straggler MRetryAck past write-quorum completion (see the
            # matching guard in _handle_mproposeack)
            return

        info.quorum_retries.add(from_, deps)
        if not info.quorum_retries.all():
            return
        if not info.synod.can_skip_prepare():
            # a recovery proposer owns a higher ballot: join recovery
            # instead of committing unilaterally
            prepare = info.synod.new_prepare()
            self._to_processes.append(
                ToSend(self.bp.all(), MRecoveryPrepare(dot, prepare.ballot, info.cmd))
            )
            return
        agg_deps = info.quorum_retries.aggregated()
        self._to_processes.append(
            ToSend(self.bp.all(), MCommit(dot, info.clock, agg_deps))
        )

    def _handle_mgc(self, from_: ProcessId, committed) -> None:
        self._gc_track.update_clock_of(from_, committed)
        stable = self._gc_track.stable()
        count = 0
        for process_id, start, end in stable:
            for seq in range(start, end + 1):
                self._gc_command(Dot(process_id, seq))
                count += 1
        if count:
            self.bp.stable(count)

    # --- recovery consensus (protocol/recovery.py + the synod phase-2) ---

    def _handle_mconsensus(self, from_, dot, ballot, value, cmd=None, time=None) -> None:
        if self._gc_straggler(dot):
            return  # straggler for a settled dot: do not resurrect its info
        info = self._cmds.get(dot)
        if cmd is not None and info.cmd is None:
            self._adopt_recovered_payload(dot, info, cmd, time)
        out = info.synod.handle(from_, SynodMAccept(ballot, value))
        if out is None:
            return  # ballot too low
        if isinstance(out, SynodMAccepted):
            self._to_processes.append(ToSend({from_}, MConsensusAck(dot, out.ballot)))
        elif isinstance(out, SynodMChosen):
            # already decided here: short-circuit with the commit
            self._recovery_chosen_reply(from_, dot, info, out.value)
        else:
            raise AssertionError(f"unexpected synod output {out}")

    def _handle_mconsensusack(self, from_, dot, ballot) -> None:
        if self._gc_straggler(dot):
            return  # straggler for a settled dot: do not resurrect its info
        info = self._cmds.get(dot)
        out = info.synod.handle(from_, SynodMAccepted(ballot))
        if out is None:
            return
        assert isinstance(out, SynodMChosen), f"unexpected synod output {out}"
        value = out.value
        self._to_processes.append(
            ToSend(
                self.bp.all(),
                MCommit(dot, value.clock, set(value.deps), cmd=info.cmd),
            )
        )

    # --- recovery hooks (protocol/recovery.py) ---

    def _adopt_recovered_payload(self, dot, info, cmd, time) -> None:
        info.cmd = cmd
        if info.status != Status.START:
            return
        # index the payload like a REJECT-style counter-report: a fresh
        # unique timestamp above everything seen here plus its
        # predecessors under it.  The dot must appear as a predecessor of
        # later conflicting proposals, and the staged ballot-0 report must
        # carry the conflict edges this replica knows about (the graph
        # protocols' "late report" idiom)
        clock = self.key_clocks.clock_next()
        deps = self.key_clocks.predecessors(dot, cmd, clock)
        info.status = Status.PROPOSE
        info.deps = deps
        self._update_clock(dot, info, clock)
        info.synod.set_if_not_accepted(
            lambda: CaesarConsensusValue(clock, tuple(sorted(deps)))
        )
        self._replay_buffered(dot, time)

    def _recovery_commit_known(self, dot) -> bool:
        return dot in self._buffered_commits

    def _recovery_consensus_msg(self, dot, ballot, value, cmd):
        return MConsensus(dot, ballot, value, cmd)

    def _recovery_chosen_reply(self, to, dot, info, value) -> None:
        # the payload rides along: the asker may hold a payload-less
        # buffered commit (rejoin gap); noop values carry clock None
        self._to_processes.append(
            ToSend(
                {to},
                MCommit(dot, value.clock, set(value.deps), cmd=info.cmd),
            )
        )

    def _recovery_promise_floor(self, dot, info) -> int:
        # the highest timestamp sequence indexed on the dot's keys
        # (excluding the dot itself): executed-everywhere GC keeps every
        # conflict indexed until globally executed, so the promise
        # quorum's max floor upper-bounds any timestamp survivors may
        # already have executed past — the free choice lifts above it
        if info.cmd is None or info.status == Status.COMMIT:
            return 0
        return self.key_clocks.max_seq(info.cmd, exclude=dot)

    def _recovery_adjust_value(self, dot, info, value, floor: int):
        # free-choice pairs lift above the quorum's floor with a FRESH
        # unique timestamp (clock_next after joining the floor — Caesar
        # clocks are (seq, pid) pairs, so reusing a seq under our own pid
        # could collide with a timestamp we already issued), and the
        # predecessor union re-extends under the lifted clock so every
        # conflict this proposer knows about orders below it.  Noop stays
        # noop.
        if value.is_noop:
            return value
        clock = value.clock
        deps = set(value.deps)
        if info.cmd is not None and floor >= clock.seq:
            self.key_clocks.clock_join(Clock(floor, 0))
            clock = self.key_clocks.clock_next()
            deps |= self.key_clocks.predecessors(dot, info.cmd, clock)
        deps.discard(dot)
        return CaesarConsensusValue(clock, tuple(sorted(deps)))

    # --- rejoin sync hooks (protocol/sync.py) ---

    def _sync_record(self, dot, info):
        # the decided (clock, deps) pair lives in the per-dot synod once
        # MChosen ran (commit bookkeeping); cmd is None for recovered
        # noops that were never payloaded here
        return (dot, info.cmd, info.synod.value())

    def _apply_sync_record(self, from_, record, time) -> None:
        dot, cmd, value = record
        if self._gc_straggler(dot):
            return  # executed (or WAL-tail replayed) here already
        info = self._cmds.get(dot)
        if info.status == Status.COMMIT:
            return
        self._handle_mcommit(from_, dot, value.clock, set(value.deps), time, cmd)

    # _sync_backfill_actions: the SyncMixin default (no-op) is correct for
    # Caesar — unlike Newt there is no detached vote channel to re-state:
    # the predecessor index rebuilds entirely from applied commit records,
    # and the timestamp floor rides clock_join on each applied clock.
    # Ranges "held by pending dots" have no Caesar analog because nothing
    # is consumed at propose time; pending dots heal through the recovery
    # plane instead (every MPropose/buffered commit is _recovery_track'd).

    # --- wait-condition helpers (caesar.rs:826-1035) ---

    def _safe_to_ignore(
        self, my_dot: Dot, my_clock: Clock, their_clock: Clock, their_deps: Set[Dot]
    ) -> bool:
        # clocks only increase: the blocker's (ACCEPT/COMMIT) clock must
        # still be higher than ours.  Ignoring it is safe only if it depends
        # on us — then it executes after us despite the higher timestamp
        assert my_clock < their_clock
        return my_dot in their_deps

    def _try_to_unblock(
        self, dot: Dot, clock: Clock, deps: Set[Dot], blocking: Set[Dot]
    ) -> None:
        """`dot` gained a final-enough (clock, deps): re-examine every
        command it was blocking."""
        for blocked in blocking:
            blocked_info = self._cmds.get_existing(blocked)
            if blocked_info is None or blocked_info.status != Status.PROPOSE:
                continue
            if self._safe_to_ignore(blocked, blocked_info.clock, clock, deps):
                blocked_info.blocked_by.discard(dot)
                if not blocked_info.blocked_by:
                    self._accept_command(blocked, blocked_info)
            else:
                # reject ASAP — no point waiting for the other blockers
                self._reject_command(blocked, blocked_info)

    def _accept_command(self, dot: Dot, info: CaesarInfo) -> None:
        self._send_mpropose_ack(dot, info, info.clock, set(info.deps), True)

    def _reject_command(self, dot: Dot, info: CaesarInfo) -> None:
        info.status = Status.REJECT
        # counter-propose: a fresh higher timestamp and its predecessors
        new_clock = self.key_clocks.clock_next()
        cmd = info.cmd
        assert cmd is not None
        new_deps = self.key_clocks.predecessors(dot, cmd, new_clock)
        self._send_mpropose_ack(dot, info, new_clock, new_deps, False)

    def _send_mpropose_ack(
        self, dot: Dot, info: CaesarInfo, clock: Clock, deps: Set[Dot], ok: bool
    ) -> None:
        # refresh the staged ballot-0 report to the pair actually acked
        # (a reject counter-proposal supersedes the propose-time report;
        # no-op once a recovery prepare froze the report)
        info.synod.set_if_not_accepted(
            lambda: CaesarConsensusValue(clock, tuple(sorted(deps)))
        )
        self._to_processes.append(ToSend({dot.source}, MProposeAck(dot, clock, deps, ok)))

    # --- clock index maintenance (caesar.rs:786-838) ---

    def _update_clock(self, dot: Dot, info: CaesarInfo, new_clock: Clock) -> None:
        cmd = info.cmd
        assert cmd is not None
        if not info.clock.is_zero():
            self.key_clocks.remove(cmd, info.clock)
        self.key_clocks.add(dot, cmd, new_clock)
        info.clock = new_clock

    def _gc_command(self, dot: Dot) -> None:
        info = self._cmds.gc_single(dot)
        assert info is not None, "the GC worker sees every command"
        # recovered noops may carry no payload (never payloaded here) and
        # always carry a zero clock (un-indexed at commit)
        if info.cmd is not None and not info.clock.is_zero():
            self.key_clocks.remove(info.cmd, info.clock)

    # --- worker routing (caesar.rs:1119-1160) ---

    @staticmethod
    def message_index(msg):
        if isinstance(
            msg,
            (
                MPropose,
                MProposeAck,
                MCommit,
                MRetry,
                MRetryAck,
                MConsensus,
                MConsensusAck,
                MRecoveryPrepare,
                MRecoveryPromise,
            ),
        ):
            return worker_dot_index_shift(msg.dot)
        if isinstance(msg, MGarbageCollection):
            return worker_index_no_shift(GC_WORKER_INDEX)
        if isinstance(msg, (MSync, MSyncReply, MSyncBackfill)):
            # dotless rejoin traffic: serialized on the GC worker (whose
            # committed clock it reads and whose retention it rides)
            return worker_index_no_shift(GC_WORKER_INDEX)
        raise AssertionError(f"unknown message {msg}")

    @staticmethod
    def event_index(event):
        return worker_index_no_shift(GC_WORKER_INDEX)
