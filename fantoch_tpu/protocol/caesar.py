"""Caesar: timestamp + predecessors consensus with a wait condition.

Reference: fantoch_ps/src/protocol/caesar.rs (1399 LoC).  The coordinator
assigns a globally-unique lexicographic timestamp ``Clock(seq, pid)`` to
each command and proposes it to everyone; each replica computes the
conflicting commands with lower timestamps (the predecessors) and replies:

* ACCEPT (ok) — no conflicting command with a *higher* timestamp blocks it;
* WAIT — blocked by higher-timestamp conflicts whose fate is unknown: the
  reply is delayed until they commit/accept (the wait condition,
  caesar.rs:266-451);
* REJECT (not ok) — some higher-timestamp conflict does not include this
  command in its deps, so the proposed timestamp is too low; the replica
  counter-proposes a higher one.

Fast path iff the whole fast quorum (3n/4 + 1) said ok; otherwise the
coordinator retries with the aggregated (clock, deps) through MRetry on the
write quorum (majority), which yields extended deps and then commits.
Execution is the PredecessorsExecutor: conflicts execute in timestamp
order.  GC is driven by the *executed* clock reported back by the executor
(handle_executed, caesar.rs:177-179).
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Deque, Dict, Optional, Set, Tuple

from fantoch_tpu.core.command import Command
from fantoch_tpu.core.config import Config
from fantoch_tpu.core.ids import Dot, ProcessId, ShardId
from fantoch_tpu.core.timing import SysTime
from fantoch_tpu.executor.pred import PredecessorsExecutionInfo, PredecessorsExecutor
from fantoch_tpu.protocol.base import (
    Action,
    BaseProcess,
    Executed,
    Protocol,
    ProtocolMetrics,
    ToSend,
)
from fantoch_tpu.protocol.commit_gc import MGarbageCollection
from fantoch_tpu.protocol.common.pred_clocks import (
    Clock,
    KeyClocks,
    QuorumClocks,
    QuorumRetries,
)
from fantoch_tpu.protocol.gc import GCTrack
from fantoch_tpu.protocol.info import CommandsInfo
from fantoch_tpu.run.routing import (
    GC_WORKER_INDEX,
    worker_dot_index_shift,
    worker_index_no_shift,
)


# --- messages (caesar.rs:1088-1117) ---


@dataclass
class MPropose:
    dot: Dot
    cmd: Command
    clock: Clock


@dataclass
class MProposeAck:
    dot: Dot
    clock: Clock
    deps: Set[Dot]
    ok: bool


@dataclass
class MCommit:
    dot: Dot
    clock: Clock
    deps: Set[Dot]


@dataclass
class MRetry:
    dot: Dot
    clock: Clock
    deps: Set[Dot]


@dataclass
class MRetryAck:
    dot: Dot
    deps: Set[Dot]


@dataclass
class GarbageCollectionEvent:
    pass


class Status:
    START = "start"
    PROPOSE = "propose"
    REJECT = "reject"
    ACCEPT = "accept"
    COMMIT = "commit"


def _caesar_info_factory(pid, _sid, _cfg, fq, wq) -> "CaesarInfo":
    """Picklable per-dot info factory (the model checker pickles state)."""
    return CaesarInfo(pid, fq, wq)


class CaesarInfo:
    """Per-dot lifecycle info (caesar.rs:1039-1086)."""

    __slots__ = (
        "status",
        "cmd",
        "clock",
        "deps",
        "blocking",
        "blocked_by",
        "quorum_clocks",
        "quorum_retries",
    )

    def __init__(self, process_id: ProcessId, fast_quorum_size: int, write_quorum_size: int):
        self.status = Status.START
        self.cmd: Optional[Command] = None
        self.clock = Clock.zero(process_id)
        self.deps: Set[Dot] = set()
        # commands this command is blocking / blocked by (the wait condition)
        self.blocking: Set[Dot] = set()
        self.blocked_by: Set[Dot] = set()
        self.quorum_clocks = QuorumClocks(process_id, fast_quorum_size, write_quorum_size)
        self.quorum_retries = QuorumRetries(write_quorum_size)


class Caesar(Protocol):
    Executor = PredecessorsExecutor

    @classmethod
    def allowed_faults(cls, n: int) -> int:
        return n // 2

    def __init__(self, process_id: ProcessId, shard_id: ShardId, config: Config):
        fast_quorum_size, write_quorum_size = config.caesar_quorum_sizes()
        self.bp = BaseProcess(process_id, shard_id, config, fast_quorum_size, write_quorum_size)
        self.key_clocks = KeyClocks(process_id, shard_id)
        self._cmds: CommandsInfo[CaesarInfo] = CommandsInfo(
            process_id,
            shard_id,
            config,
            fast_quorum_size,
            write_quorum_size,
            _caesar_info_factory,
        )
        self._gc_track = GCTrack(process_id, shard_id, config.n)
        self._to_processes: Deque[Action] = deque()
        self._to_executors: Deque[PredecessorsExecutionInfo] = deque()
        # MRetry/MCommit that arrived before the MPropose (multiplexing)
        self._buffered_retries: Dict[Dot, Tuple[ProcessId, Clock, Set[Dot]]] = {}
        self._buffered_commits: Dict[Dot, Tuple[ProcessId, Clock, Set[Dot]]] = {}
        self._wait_condition = config.caesar_wait_condition
        # safety requires executed-everywhere GC: removing a command from the
        # key-clock index at commit time (the reference's no-GC shortcut,
        # caesar.rs:616-620, flagged unsafe by its own TODO at :840-842)
        # lets later proposals miss it as a predecessor, so conflicting
        # commands can execute in different orders on different replicas
        assert config.gc_interval_ms is not None, (
            "Caesar requires gc_interval_ms: commands may only leave the "
            "key-clock index once executed everywhere"
        )

    def periodic_events(self):
        # gc_interval_ms is mandatory (asserted in __init__)
        return [(GarbageCollectionEvent(), self.bp.config.gc_interval_ms)]

    @property
    def id(self) -> ProcessId:
        return self.bp.process_id

    @property
    def shard_id(self) -> ShardId:
        return self.bp.shard_id

    def discover(self, processes):
        connect_ok = self.bp.discover(processes)
        return connect_ok, dict(self.bp.closest_shard_process())

    def submit(self, dot: Optional[Dot], cmd: Command, time: SysTime) -> None:
        dot = dot if dot is not None else self.bp.next_dot()
        clock = self.key_clocks.clock_next()
        # send to everyone: due to the wait condition the fastest ok-quorum
        # may not be the closest one
        self._to_processes.append(ToSend(self.bp.all(), MPropose(dot, cmd, clock)))

    def handle(self, from_, from_shard_id, msg, time):
        if isinstance(msg, MPropose):
            self._handle_mpropose(from_, msg.dot, msg.cmd, msg.clock, time)
        elif isinstance(msg, MProposeAck):
            self._handle_mproposeack(from_, msg.dot, msg.clock, msg.deps, msg.ok)
        elif isinstance(msg, MCommit):
            self._handle_mcommit(from_, msg.dot, msg.clock, msg.deps, time)
        elif isinstance(msg, MRetry):
            self._handle_mretry(from_, msg.dot, msg.clock, msg.deps, time)
        elif isinstance(msg, MRetryAck):
            self._handle_mretryack(from_, msg.dot, msg.deps)
        elif isinstance(msg, MGarbageCollection):
            self._handle_mgc(from_, msg.committed)
        else:
            raise AssertionError(f"unknown message {msg}")

    def handle_event(self, event, time):
        assert isinstance(event, GarbageCollectionEvent)
        self._to_processes.append(
            ToSend(self.bp.all_but_me(), MGarbageCollection(self._gc_track.clock()))
        )

    def handle_executed(self, executed: Executed, time: SysTime) -> None:
        # GC is driven by the executor: a dot is collectable once *executed*
        # everywhere (not just committed — the key-clock index must keep
        # commands until no proposal can conflict with them)
        self._gc_track.update_clock(executed)

    def to_processes(self) -> Optional[Action]:
        return self._to_processes.popleft() if self._to_processes else None

    def to_executors(self):
        return self._to_executors.popleft() if self._to_executors else None

    @classmethod
    def parallel(cls) -> bool:
        return KeyClocks.parallel()

    @classmethod
    def leaderless(cls) -> bool:
        return True

    def metrics(self) -> ProtocolMetrics:
        return self.bp.metrics()

    # --- handlers ---

    def _handle_mpropose(self, from_, dot, cmd, remote_clock: Clock, time) -> None:
        assert dot.source == from_, "the coordinator is the dot source"
        self.key_clocks.clock_join(remote_clock)

        if self._gc_track.contains(dot):
            # straggler (late duplicate) for a dot already committed
            # everywhere and GC'd: `_cmds.get` would resurrect a fresh
            # START info, and a trailing MCommit duplicate could then
            # RE-feed the executor (its exactly-once assert catches the
            # replay) — the PR 7 GC-straggler class, Caesar edition
            return
        info = self._cmds.get(dot)
        if info.status != Status.START:
            return

        # predecessors under the proposed timestamp; higher-timestamp
        # conflicts block the reply (the wait condition's input)
        blocked_by: Set[Dot] = set()
        deps = self.key_clocks.predecessors(dot, cmd, remote_clock, blocked_by)

        info.status = Status.PROPOSE
        info.cmd = cmd
        info.deps = deps
        self._update_clock(dot, info, remote_clock)
        info.blocked_by = set(blocked_by)

        if not blocked_by:
            self._accept_command(dot, info)
        elif not self._wait_condition:
            self._reject_command(dot, info)
        else:
            # check each blocker: ACCEPT/COMMIT blockers with a good-enough
            # clock+deps can be ignored; an un-ignorable one rejects us right
            # away; unknown-fate ones register us in their blocking set
            reject = False
            not_blocked_by: Set[Dot] = set()
            for blocker in blocked_by:
                blocker_info = self._cmds.get_existing(blocker)
                if blocker_info is None:
                    # GCed = executed everywhere: can be ignored
                    not_blocked_by.add(blocker)
                    continue
                if blocker_info.status in (Status.ACCEPT, Status.COMMIT):
                    if self._safe_to_ignore(
                        dot, info.clock, blocker_info.clock, blocker_info.deps
                    ):
                        not_blocked_by.add(blocker)
                    else:
                        reject = True
                        break
                else:
                    blocker_info.blocking.add(dot)
            if reject:
                self._reject_command(dot, info)
            elif len(not_blocked_by) == len(blocked_by):
                self._accept_command(dot, info)
            else:
                info.blocked_by -= not_blocked_by
                assert info.blocked_by, "a waiting command must have blockers"

        # replay any buffered retry/commit now that we have the payload
        buffered = self._buffered_retries.pop(dot, None)
        if buffered is not None:
            self._handle_mretry(buffered[0], dot, buffered[1], buffered[2], time)
        buffered = self._buffered_commits.pop(dot, None)
        if buffered is not None:
            self._handle_mcommit(buffered[0], dot, buffered[1], buffered[2], time)

    def _handle_mproposeack(self, from_, dot, clock: Clock, deps, ok: bool) -> None:
        # get_existing: a straggler ack (MPropose went to all n, only the
        # fast quorum's replies matter) must not recreate a GCed info
        info = self._cmds.get_existing(dot)
        if info is None:
            return
        # the coordinator can end up rejecting its own command, hence REJECT
        if info.status not in (Status.PROPOSE, Status.REJECT):
            return
        if info.quorum_clocks.all():
            # straggler ack: MPropose goes to all n but the quorum (< n for
            # n>=5) completes first, and the commit/retry that flips the
            # status travels through the message queue — so a late ack can
            # legitimately arrive while the status is still PROPOSE/REJECT
            # (the reference panics here, reachable in our runner's
            # reader-task queueing; see ADVICE r1)
            return

        info.quorum_clocks.add(from_, clock, deps, ok)
        if not info.quorum_clocks.all():
            return

        agg_clock, agg_deps, agg_ok = info.quorum_clocks.aggregated()
        if agg_ok:
            # everyone accepted the coordinator's proposal as-is
            assert agg_clock == info.clock
            self.bp.fast_path()
            self._to_processes.append(
                ToSend(self.bp.all(), MCommit(dot, agg_clock, agg_deps))
            )
        else:
            self.bp.slow_path()
            # sent to everyone: the new clock may unblock waiting commands
            self._to_processes.append(
                ToSend(self.bp.all(), MRetry(dot, agg_clock, agg_deps))
            )

    def _handle_mcommit(self, from_, dot, clock: Clock, deps, time) -> None:
        self.key_clocks.clock_join(clock)
        if self._gc_track.contains(dot):
            return  # straggler for a GC'd dot: do not resurrect its info
        info = self._cmds.get(dot)
        if info.status == Status.START:
            self._buffered_commits[dot] = (from_, clock, deps)
            return
        if info.status == Status.COMMIT:
            return

        cmd = info.cmd
        assert cmd is not None, "there should be a command payload"
        self._to_executors.append(
            PredecessorsExecutionInfo(dot, cmd, clock, set(deps))
        )

        info.status = Status.COMMIT
        # audit plane: agreement = same dot, same (clock, predecessors)
        self.bp.audit_commit(dot, cmd.rifl, (clock, tuple(sorted(deps))))
        info.deps = set(deps)
        self._update_clock(dot, info, clock)

        blocking, info.blocking = info.blocking, set()
        self._try_to_unblock(dot, clock, info.deps, blocking)

    def _handle_mretry(self, from_, dot, clock: Clock, deps, time) -> None:
        self.key_clocks.clock_join(clock)
        if self._gc_track.contains(dot):
            return  # straggler for a GC'd dot: do not resurrect its info
        info = self._cmds.get(dot)
        if info.status == Status.START:
            self._buffered_retries[dot] = (from_, clock, deps)
            return
        if info.status == Status.COMMIT:
            return

        info.status = Status.ACCEPT
        info.deps = set(deps)
        self._update_clock(dot, info, clock)

        # reply with deps extended by our own lower-timestamp conflicts
        cmd = info.cmd
        assert cmd is not None
        new_deps = self.key_clocks.predecessors(dot, cmd, clock)
        new_deps.update(deps)
        self._to_processes.append(ToSend({from_}, MRetryAck(dot, new_deps)))

        blocking, info.blocking = info.blocking, set()
        self._try_to_unblock(dot, clock, info.deps, blocking)

    def _handle_mretryack(self, from_, dot, deps) -> None:
        info = self._cmds.get_existing(dot)
        if info is None or info.status != Status.ACCEPT:
            return
        if info.quorum_retries.all():
            # straggler MRetryAck past write-quorum completion (see the
            # matching guard in _handle_mproposeack)
            return

        info.quorum_retries.add(from_, deps)
        if not info.quorum_retries.all():
            return
        agg_deps = info.quorum_retries.aggregated()
        self._to_processes.append(
            ToSend(self.bp.all(), MCommit(dot, info.clock, agg_deps))
        )

    def _handle_mgc(self, from_: ProcessId, committed) -> None:
        self._gc_track.update_clock_of(from_, committed)
        stable = self._gc_track.stable()
        count = 0
        for process_id, start, end in stable:
            for seq in range(start, end + 1):
                self._gc_command(Dot(process_id, seq))
                count += 1
        if count:
            self.bp.stable(count)

    # --- wait-condition helpers (caesar.rs:826-1035) ---

    def _safe_to_ignore(
        self, my_dot: Dot, my_clock: Clock, their_clock: Clock, their_deps: Set[Dot]
    ) -> bool:
        # clocks only increase: the blocker's (ACCEPT/COMMIT) clock must
        # still be higher than ours.  Ignoring it is safe only if it depends
        # on us — then it executes after us despite the higher timestamp
        assert my_clock < their_clock
        return my_dot in their_deps

    def _try_to_unblock(
        self, dot: Dot, clock: Clock, deps: Set[Dot], blocking: Set[Dot]
    ) -> None:
        """`dot` gained a final-enough (clock, deps): re-examine every
        command it was blocking."""
        for blocked in blocking:
            blocked_info = self._cmds.get_existing(blocked)
            if blocked_info is None or blocked_info.status != Status.PROPOSE:
                continue
            if self._safe_to_ignore(blocked, blocked_info.clock, clock, deps):
                blocked_info.blocked_by.discard(dot)
                if not blocked_info.blocked_by:
                    self._accept_command(blocked, blocked_info)
            else:
                # reject ASAP — no point waiting for the other blockers
                self._reject_command(blocked, blocked_info)

    def _accept_command(self, dot: Dot, info: CaesarInfo) -> None:
        self._send_mpropose_ack(dot, info.clock, set(info.deps), True)

    def _reject_command(self, dot: Dot, info: CaesarInfo) -> None:
        info.status = Status.REJECT
        # counter-propose: a fresh higher timestamp and its predecessors
        new_clock = self.key_clocks.clock_next()
        cmd = info.cmd
        assert cmd is not None
        new_deps = self.key_clocks.predecessors(dot, cmd, new_clock)
        self._send_mpropose_ack(dot, new_clock, new_deps, False)

    def _send_mpropose_ack(self, dot: Dot, clock: Clock, deps: Set[Dot], ok: bool) -> None:
        self._to_processes.append(ToSend({dot.source}, MProposeAck(dot, clock, deps, ok)))

    # --- clock index maintenance (caesar.rs:786-838) ---

    def _update_clock(self, dot: Dot, info: CaesarInfo, new_clock: Clock) -> None:
        cmd = info.cmd
        assert cmd is not None
        if not info.clock.is_zero():
            self.key_clocks.remove(cmd, info.clock)
        self.key_clocks.add(dot, cmd, new_clock)
        info.clock = new_clock

    def _gc_command(self, dot: Dot) -> None:
        info = self._cmds.gc_single(dot)
        assert info is not None, "the GC worker sees every command"
        cmd = info.cmd
        assert cmd is not None
        if not info.clock.is_zero():
            self.key_clocks.remove(cmd, info.clock)

    # --- worker routing (caesar.rs:1119-1160) ---

    @staticmethod
    def message_index(msg):
        if isinstance(msg, (MPropose, MProposeAck, MCommit, MRetry, MRetryAck)):
            return worker_dot_index_shift(msg.dot)
        if isinstance(msg, MGarbageCollection):
            return worker_index_no_shift(GC_WORKER_INDEX)
        raise AssertionError(f"unknown message {msg}")

    @staticmethod
    def event_index(event):
        return worker_index_no_shift(GC_WORKER_INDEX)
