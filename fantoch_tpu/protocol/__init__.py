from fantoch_tpu.protocol.base import (
    Action,
    BaseProcess,
    Executed,
    Protocol,
    ProtocolMetricsKind,
    ToForward,
    ToSend,
)
from fantoch_tpu.protocol.basic import Basic
from fantoch_tpu.protocol.gc import GCTrack
from fantoch_tpu.protocol.info import CommandsInfo
