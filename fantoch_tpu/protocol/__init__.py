from fantoch_tpu.protocol.base import (
    Action,
    BaseProcess,
    Executed,
    Protocol,
    ProtocolMetricsKind,
    ToForward,
    ToSend,
)
from fantoch_tpu.protocol.gc import GCTrack
from fantoch_tpu.protocol.info import CommandsInfo

_LAZY = {
    "Basic": "fantoch_tpu.protocol.basic",
    "EPaxos": "fantoch_tpu.protocol.graph_protocol",
    "Atlas": "fantoch_tpu.protocol.graph_protocol",
    "Newt": "fantoch_tpu.protocol.newt",
    "FPaxos": "fantoch_tpu.protocol.fpaxos",
    "Caesar": "fantoch_tpu.protocol.caesar",
}


def __getattr__(name):
    # lazy protocol exports (PEP 562): protocols import executors, which
    # import protocol commons — eager imports here would be circular
    module = _LAZY.get(name)
    if module is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(module), name)
