"""Dot-based garbage-collection tracking.

Reference: fantoch/src/protocol/gc.rs:8-143.  The GC worker of each process
tracks (a) its own committed clock (an AEClock) and (b) the committed
VClocks received from every peer; the *stable* frontier is the meet of all
clocks — dots below it are committed everywhere and safe to GC.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from fantoch_tpu.core.clocks import AEClock, VClock
from fantoch_tpu.core.ids import Dot, ProcessId, ShardId, process_ids


class GCTrack:
    def __init__(self, process_id: ProcessId, shard_id: ShardId, n: int):
        self._process_id = process_id
        self._shard_id = shard_id
        self._n = n
        self._my_clock: AEClock[ProcessId] = AEClock(process_ids(shard_id, n))
        self._all_but_me: Dict[ProcessId, VClock[ProcessId]] = {}
        self._previous_stable: VClock[ProcessId] = VClock(process_ids(shard_id, n))

    def clock(self) -> VClock[ProcessId]:
        """Contiguous frontier of locally committed dots."""
        return self._my_clock.frontier()

    def my_clock(self) -> AEClock[ProcessId]:
        """Copy of the full committed clock (frontier + above-exceptions):
        the horizon a restarted replica sends with MSync.  Unlike
        ``_cmds`` this is never trimmed by GC, so it also covers commits
        whose info was already collected locally."""
        return self._my_clock.copy()

    def contains(self, dot: Dot) -> bool:
        """Whether ``dot`` was ever committed here (GC'd or not)."""
        events = self._my_clock.get(dot.source)
        return events is not None and events.contains(dot.sequence)

    def add_to_clock(self, dot: Dot) -> None:
        self._my_clock.add(dot.source, dot.sequence)
        assert len(self._my_clock) == self._n, "dots must belong to this shard"

    def update_clock(self, clock: AEClock[ProcessId]) -> None:
        """Replace the local clock (used when the executor drives GC)."""
        self._my_clock = clock
        assert len(self._my_clock) == self._n

    def update_clock_of(self, from_: ProcessId, clock: VClock[ProcessId]) -> None:
        """Join knowledge about `from_`'s committed clock (messages can be
        reordered, so replacing would not be monotone)."""
        current = self._all_but_me.get(from_)
        if current is None:
            # copy: the same message object may be delivered to many simulated
            # processes; aliasing it would leak commit knowledge across them
            self._all_but_me[from_] = clock.copy()
        else:
            current.join(clock)

    def stable(self) -> List[Tuple[ProcessId, int, int]]:
        """Newly-stable dot ranges [(process, start, end)] since last call
        (gc.rs:72-116)."""
        new_stable = self._stable_clock()
        dots: List[Tuple[ProcessId, int, int]] = []
        for process_id, previous in self._previous_stable.items():
            current = new_stable.get(process_id)
            start, end = previous + 1, current
            # never go backwards (reordered/multiplexed messages)
            new_stable.add(process_id, previous)
            if start <= end:
                dots.append((process_id, start, end))
        self._previous_stable = new_stable
        return dots

    def _stable_clock(self) -> VClock[ProcessId]:
        """Meet of all processes' committed clocks (gc.rs:120-137)."""
        if len(self._all_but_me) != self._n - 1:
            # no stable dots until we have info from every process
            return VClock(process_ids(self._shard_id, self._n))
        stable = self._my_clock.frontier()
        for clock in self._all_but_me.values():
            stable.meet(clock)
        return stable
