"""Per-dot recovery consensus shared by the leaderless protocols.

The reference leaves coordinator-crash recovery unimplemented (``todo!()``
at fantoch_ps epaxos.rs:627-629 and newt.rs:1110-1112); this module goes
beyond it: when a dot's commit is overdue (``Config.recovery_delay_ms``), a
surviving process drives the dot's embedded :class:`Synod` through the
full prepare/promise path that ``protocol/common/synod.py`` always carried
but nothing called.

Protocol flow (per overdue dot):

1. **Trigger** — a periodic :class:`RecoveryEvent` scans the protocol's
   pending-dot ledger.  The dot's owner (``dot.source``) retries first;
   ring successors stagger in at ``recovery_delay_ms`` increments so a
   dead owner's dots are picked up by exactly one process at a time
   (deterministic: no randomness, so fault traces stay byte-identical).
2. **Prepare** — ``synod.new_prepare()`` allocates a ballot above anything
   seen (``id + n * round``) and broadcasts :class:`MRecoveryPrepare`.
3. **Promise** — every acceptor answers with its ballot-0 value (the deps
   or clock it reported when it acked the original MCollect; the
   protocol's *bottom* when it never did) or its highest accepted value,
   plus the command payload when it holds one — so a recovering value can
   commit even at processes the original payload broadcast missed.  An
   acceptor that already learned the decision short-circuits with a
   commit reply instead.
4. **Select** — with ``n - f`` promises the synod proposer picks the
   highest-ballot accepted value; if nothing was ever accepted the
   protocol's ``proposal_gen`` runs over the ballot-0 reports: the union
   of reported deps (graph family) / the max reported clock (Newt) / the
   max reported clock with the union of reported predecessor sets
   (Caesar), or the protocol's *noop* bottom for dots never payloaded
   anywhere visible (owner crashed before its MCollect got out).  On
   that free-choice path the value is also passed through the protocol's
   ``_recovery_adjust_value`` with the max ``clock_floor`` the promises
   carried: Newt lifts recovered clocks strictly above the quorum's
   current key clocks, and Caesar re-issues a fresh unique timestamp
   above the quorum's max indexed sequence (re-extending the predecessor
   union under it), so a recovery-decided timestamp can never land at or
   below timestamps the survivors may already have executed past (the
   live-vs-reconstructed order divergence a *restarted* replica would
   otherwise expose).
5. **Phase 2** — the chosen value flows through the protocols' existing
   MConsensus/MConsensusAck handlers (broadcast rather than
   write-quorum-only, since quorum members may be the dead ones) and
   commits through the normal MCommit path; noop commits resolve
   dependents through the executor's noop seam without executing
   anything.

Safety note: ballots make concurrent recoveries and recovery-vs-slow-path
races safe (classic synod).  The one residual window is recovery racing a
*fast-path* commit that the (live or crashed) coordinator decided but that
no promiser has seen: the recovered value can then differ from the
decided one — the graph protocols' union includes non-quorum "late
reports" (extra conflict edges a fast-path value never saw) and can at
the same time miss deps/clock-maxima known only to reporters outside the
promise quorum.  With the all-at-once fan-out both the simulator and the
TCP writer perform, a decided commit either reaches every live process or
none, so the window requires an in-flight commit surviving past the
recovery trigger: ``recovery_delay_ms`` MUST exceed the maximum delivery
delay, retransmit tails included — size the knob accordingly (and the
model checker covers the message-driven interleavings exhaustively at
small scope).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

from fantoch_tpu.core.command import Command
from fantoch_tpu.core.ids import Dot, ProcessId
from fantoch_tpu.core.timing import SysTime
from fantoch_tpu.protocol.base import ToSend
from fantoch_tpu.protocol.common.synod import (
    MAccept as SynodMAccept,
    MChosen as SynodMChosen,
    MPrepare as SynodMPrepare,
    MPromise as SynodMPromise,
)


@dataclass
class MRecoveryPrepare:
    dot: Dot
    ballot: int
    # payload piggyback (symmetric to MRecoveryPromise.cmd): an acceptor
    # that never saw the MCollect adopts it so its promise can CONSUME
    # key-clock votes (Newt's _recovery_promise_floor).  Without it, a
    # payload-less promiser reports floor 0 and its vote column keeps
    # advancing — a stability set avoiding the consuming promisers can
    # then pass the recovered clock before the commit lands (the
    # fuzzer-found crash-restart order divergence)
    cmd: Optional[Command] = None


@dataclass
class MRecoveryPromise:
    dot: Dot
    ballot: int
    accepted: Tuple[int, Any]  # (accepted ballot, value)
    cmd: Optional[Command]  # payload piggyback for processes that miss it
    # the acceptor's current clock floor for the dot's keys (Newt: max
    # key clock; 0 when the payload is unknown or the protocol has no
    # clocks).  When the recovered value is a FREE choice (no promise
    # carried an accepted ballot), the proposer lifts the chosen clock
    # above the quorum's max floor: an n-f promise quorum intersects
    # every stability-threshold set, so the max floor upper-bounds any
    # timestamp that may already be stable — without the lift, a
    # recovered clock can land BELOW timestamps the survivors already
    # executed past, and a replica that later reconstructs order from
    # table state (a restarted one) diverges from the live history
    clock_floor: int = 0


@dataclass
class RecoveryEvent:
    """Periodic overdue-dot scan (interval = Config.recovery_delay_ms)."""


# free-choice selections wait for ALL n promises during the first
# this-many recovery rounds (ballot = id + n * round); later rounds fire
# at n - f so a crashed process cannot block recovery forever — by then
# its silence has outlived several recovery_delay_ms intervals, which
# the knob's contract already sizes well above any delivery delay.
# Waiting for every live report matters because the one ballot-0 report
# carrying a conflict edge may live ANYWHERE: at a fast-quorum member
# whose promise trails the first n - f (the fuzzer-found Atlas
# divergence — a dep known only to the straggling member), or at a
# NON-member whose late report (staged when the MCollect reached it) is
# the only place the edge was ever recorded.  A dep/clock union missing
# that report commits a value that orders the dot against nothing.
FREE_CHOICE_HOLD_ROUNDS = 2


class RecoveryMixin:
    """Requires from the host protocol: ``self.bp`` (BaseProcess),
    ``self._cmds`` (CommandsInfo over infos with ``.status``/``.synod``/
    ``.cmd``), ``self._to_processes`` (deque), a ``Status`` class with
    ``COMMIT``, and two hooks:

    * ``_recovery_consensus_msg(dot, ballot, value, cmd)`` — the protocol's
      MConsensus carrying a recovered value (and the payload piggyback);
    * ``_recovery_chosen_reply(to, dot, info, value)`` — answer a prepare
      for an already-decided dot with the protocol's commit message.
    """

    _STATUS_COMMIT = "commit"

    def _init_recovery(self) -> None:
        # dot -> virtual ms when it became pending (or last recovery try)
        self._pending_since: Dict[Dot, int] = {}
        # prepares issued for never-payloaded dots (tracer counters are
        # running totals)
        self._unpayloaded_prepares = 0
        # dot -> (ballot, max promise clock_floor) for the free-choice
        # clock lift (see MRecoveryPromise.clock_floor)
        self._promise_floors: Dict[Dot, Tuple[int, int]] = {}

    def _recovery_enabled(self) -> bool:
        cfg = self.bp.config
        # single-shard only: the partial-replication commit aggregation has
        # no recovery story yet (cross-shard MShardCommit state dies with
        # the dot owner)
        return cfg.recovery_delay_ms is not None and cfg.shard_count == 1

    def recovery_periodic_events(self):
        if self._recovery_enabled():
            return [(RecoveryEvent(), self.bp.config.recovery_delay_ms)]
        return []

    def _recovery_track(self, dot: Dot, time: SysTime) -> None:
        if not self._recovery_enabled() or dot in self._pending_since:
            return
        if self._recovery_settled(dot):
            # straggler for a dot already committed everywhere and GC'd
            # (a late duplicate prepare/commit), or settled by a WAL-tail
            # replay: enrolling it would pin a resurrected info in
            # permanent recovery churn — its noop commit is dropped by
            # every receiver's own straggler guard, so the round ladder
            # would never terminate
            return
        self._pending_since[dot] = time.millis()

    def _recovery_untrack(self, dot: Dot) -> None:
        if self._recovery_enabled():
            self._pending_since.pop(dot, None)
            # floor bookkeeping for an abandoned/committed round must not
            # outlive the dot (it is not GC'd with the per-dot info)
            self._promise_floors.pop(dot, None)

    # --- triggers ---

    def nudge_recovery(self, dots, time: SysTime) -> None:
        """Executor-watchdog hint (Protocol.nudge_recovery): track missing
        dependency dots so the periodic scan recovers them — the only path
        by which a dot payloaded at no live process (its owner crashed
        before the broadcast got out) heals, as a committed noop."""
        if not self._recovery_enabled():
            return
        for dot in sorted(dots):
            self._recovery_track(dot, time)

    def handle_recovery_event(self, time: SysTime) -> None:
        if not self._recovery_enabled():
            return
        now = time.millis()
        delay = self.bp.config.recovery_delay_ms
        n = self.bp.config.n
        me = self.bp.process_id
        gc_track = getattr(self, "_gc_track", None)
        for dot in list(self._pending_since):
            if gc_track is not None and gc_track.contains(dot):
                # committed everywhere and GC'd since it was tracked:
                # done — `_cmds.get` below would resurrect a fresh info
                # and re-run recovery for a dead dot forever
                self._pending_since.pop(dot, None)
                self._promise_floors.pop(dot, None)
                continue
            # get (not get_existing): a nudged dot may have no info yet —
            # recovery then runs on the fresh bottom synod and, with no
            # payload anywhere, commits it as a noop
            info = self._cmds.get(dot)
            if info.status == self._STATUS_COMMIT:
                self._pending_since.pop(dot, None)
                continue
            # stagger: the owner retries after one delay, its ring
            # successor after two, and so on — one new proposer per
            # interval.  For a dot whose DECISION this process already
            # holds (a payload-less buffered commit: the rejoin-gap
            # class), the full ring stagger only delays a heal that any
            # committed peer answers with an instant chosen reply — so
            # those dots compress the stagger to quarter-delay strides
            # (still distinct per process, so concurrent recoverers stay
            # phase-disjoint; fuzzer-found: a rejoiner's buffered commit
            # at ring distance 3 healed delay*4 late, past the run tail)
            stride = delay
            if self._recovery_commit_known(dot):
                stride = max(1, delay // 4)
            wait = delay + stride * ((me - dot.source) % n)
            if now - self._pending_since[dot] < wait:
                continue
            # rebase so this proposer retries once per n*delay, keeping
            # its ring phase: proposers sharing one retry cadence duel
            # forever (each prepare preempts the other's accept phase —
            # deterministically so in the sim), so the ring offsets must
            # stay disjoint across retries, not just on the first join
            self._pending_since[dot] = now + delay * n - wait
            prepare = info.synod.new_prepare()
            # trace: the dot entered recovery consensus (out-of-chain
            # stage when the payload is known here, else a counter — a
            # never-payloaded dot has no rifl to span against)
            tracer = self.bp.tracer
            if tracer.enabled:
                if info.cmd is not None:
                    tracer.span(
                        "recovery", info.cmd.rifl, dot=dot, pid=me,
                        meta={"ballot": prepare.ballot},
                    )
                else:
                    self._unpayloaded_prepares += 1
                    tracer.counter(
                        "recovery_unpayloaded_prepares",
                        self._unpayloaded_prepares, pid=me,
                    )
            self._to_processes.append(
                ToSend(
                    self.bp.all(),
                    MRecoveryPrepare(dot, prepare.ballot, info.cmd),
                )
            )

    # --- wire handlers ---

    def handle_recovery_message(self, from_: ProcessId, msg: Any, time: SysTime) -> bool:
        """Dispatch a recovery message; returns False if ``msg`` is not
        one."""
        if isinstance(msg, MRecoveryPrepare):
            self._handle_recovery_prepare(
                from_, msg.dot, msg.ballot, getattr(msg, "cmd", None), time
            )
        elif isinstance(msg, MRecoveryPromise):
            self._handle_recovery_promise(
                from_, msg.dot, msg.ballot, msg.accepted, msg.cmd, time,
                getattr(msg, "clock_floor", 0),
            )
        else:
            return False
        return True

    def _recovery_gc_straggler(self, dot: Dot) -> bool:
        """True when ``dot`` already committed here and its info was (or
        can be) GC'd: a LATE DUPLICATE recovery message for it must be
        dropped outright.  ``_cmds.get`` would resurrect a fresh info,
        and the promise-floor hook would then CONSUME key-clock votes for
        a dot whose commit — the only thing that ever releases them —
        already happened: the consumed ranges leak forever, the
        acceptor's vote column keeps a permanent hole, and timestamp
        stability stalls mesh-wide (fuzzer-found under the
        late-retransmit nemesis, soak seed 99).

        Committed-but-still-live dots (info present) are NOT stragglers:
        their synod short-circuits the prepare with a chosen reply — the
        payload-fetch heal path rejoin-gap buffered commits depend on."""
        return (
            self._recovery_settled(dot)
            and self._cmds.get_existing(dot) is None
        )

    def _handle_recovery_prepare(
        self,
        from_: ProcessId,
        dot: Dot,
        ballot: int,
        cmd: Optional[Command] = None,
        time: Optional[SysTime] = None,
    ) -> None:
        if self._recovery_gc_straggler(dot):
            # committed here already: a live proposer cannot exist for a
            # stable-everywhere dot (it would have committed it too), so
            # this is a late duplicate — do not resurrect, do not consume
            return
        info = self._cmds.get(dot)
        if cmd is not None and info.cmd is None:
            # adopt the piggybacked payload BEFORE promising: the promise
            # floor consumes key-clock votes, which needs the keys
            self._adopt_recovered_payload(dot, info, cmd, time)
        if time is not None and info.status != self._STATUS_COMMIT:
            # a prepare names a dot someone considers overdue — track it
            # HERE too: the promise may adopt a payload and consume votes
            # (state that must eventually release commit-coupled), and if
            # the proposer dies mid-round, this process must be able to
            # finish the recovery itself (ring stagger) instead of
            # holding a permanent gap in its vote column
            self._recovery_track(dot, time)
        out = info.synod.handle(from_, SynodMPrepare(ballot))
        if out is None:
            return  # stale ballot
        if isinstance(out, SynodMPromise):
            self._to_processes.append(
                ToSend(
                    {from_},
                    MRecoveryPromise(
                        dot, out.ballot, out.accepted, info.cmd,
                        self._recovery_promise_floor(dot, info),
                    ),
                )
            )
        elif isinstance(out, SynodMChosen):
            # already decided here: short-circuit the proposer with a commit
            self._recovery_chosen_reply(from_, dot, info, out.value)
        else:  # pragma: no cover
            raise AssertionError(f"unexpected synod output {out}")

    def _handle_recovery_promise(
        self,
        from_: ProcessId,
        dot: Dot,
        ballot: int,
        accepted: Tuple[int, Any],
        cmd: Optional[Command],
        time: SysTime,
        clock_floor: int = 0,
    ) -> None:
        if self._recovery_gc_straggler(dot):
            return  # late duplicate for a GC'd dot: do not resurrect
        info = self._cmds.get(dot)
        if cmd is not None and info.cmd is None:
            # adopt the piggybacked payload so a later commit can execute
            # even if the original MCollect never reached us
            self._adopt_recovered_payload(dot, info, cmd, time)
        # floor bookkeeping for the free-choice clock lift: track the max
        # reported floor per (dot, ballot) round; the synod applies the
        # adjuster ONLY when the value is a free choice (no promise
        # carried an accepted ballot), so a bound value is never touched
        state = self._promise_floors.get(dot)
        if state is None or state[0] != ballot:
            state = (ballot, 0)
        state = (ballot, max(state[1], clock_floor))
        self._promise_floors[dot] = state
        floor = state[1]

        def adjust(value):
            return self._recovery_adjust_value(dot, info, value, floor)

        # free-choice hold (see FREE_CHOICE_HOLD_ROUNDS): during the
        # first rounds, wait for ALL n ballot-0 reports — the one report
        # carrying a conflict edge (or the highest consumed clock floor)
        # can live at ANY process, quorum member or not, and a union
        # missing it commits a value that orders the dot against nothing.
        # The synod only consults the hold below n promises, so an
        # all-alive mesh fires after one delivery round-trip; a crashed
        # process blocks only until the round cap
        hold = None
        round_ = (ballot - 1) // self.bp.config.n
        if round_ <= FREE_CHOICE_HOLD_ROUNDS:
            def hold(_promisers):
                return True

        out = info.synod.handle(
            from_, SynodMPromise(ballot, accepted),
            free_choice_adjust=adjust, free_choice_hold=hold,
        )
        if out is None:
            return  # not this ballot, or still below n - f promises
        assert isinstance(out, SynodMAccept), f"unexpected synod output {out}"
        self._promise_floors.pop(dot, None)
        # broadcast (not write-quorum-only): the write quorum was sized for
        # the failure-free path and may contain the dead processes recovery
        # is routing around; phase-2 still only needs f + 1 accepts
        self._to_processes.append(
            ToSend(
                self.bp.all(),
                self._recovery_consensus_msg(dot, out.ballot, out.value, info.cmd),
            )
        )

    # --- hooks for the host protocol ---

    def _recovery_settled(self, dot) -> bool:
        """Whether ``dot``'s commit is already settled at this process —
        the shared guard behind straggler drops and scan eviction.
        Default: the GC clock; Caesar adds its WAL-tail replay overlay
        (its executed-driven clock cannot absorb durable folds)."""
        gc_track = getattr(self, "_gc_track", None)
        return gc_track is not None and gc_track.contains(dot)

    def _recovery_commit_known(self, dot) -> bool:
        """Whether this process already holds the dot's decided commit
        (buffered payload-less — the rejoin-gap class): recovery then
        only needs to fetch the payload via a chosen reply, so the scan
        compresses its ring stagger.  Default False."""
        return False

    def _recovery_promise_floor(self, dot, info) -> int:
        """The acceptor's clock floor for the dot's keys (see
        MRecoveryPromise.clock_floor).  Default 0 — clockless protocols
        (the graph family) never lift.  Newt CONSUMES votes through the
        floor it reports; Caesar reports the max indexed timestamp
        sequence on the dot's keys (excluding the dot itself)."""
        return 0

    def _recovery_adjust_value(self, dot, info, value, floor: int):
        """Lift a FREE-choice recovered value to the promise quorum's max
        clock floor.  Default identity; Newt lifts non-noop clocks to
        ``max(value, floor)`` — the floor is a clock the reporting
        acceptor CONSUMED votes through (see ``_recovery_promise_floor``),
        so the lifted clock is covered by held ranges released
        commit-coupled; lifting ABOVE it (a +1) would land on a clock
        nobody consumed and reopen the stability-overtakes-commit gap.
        Caesar instead issues a FRESH unique timestamp above the floor
        and re-extends the predecessor union under it."""
        return value

    def _adopt_recovered_payload(self, dot: Dot, info, cmd: Command, time: SysTime) -> None:
        info.cmd = cmd

    def _recovery_consensus_msg(self, dot: Dot, ballot: int, value, cmd):
        raise NotImplementedError

    def _recovery_chosen_reply(self, to: ProcessId, dot: Dot, info, value) -> None:
        raise NotImplementedError
