"""FPaxos: leader-based Flexible Paxos over the MultiSynod agents.

Reference: fantoch_ps/src/protocol/fpaxos.rs.  Clients submit anywhere;
non-leaders forward to the leader, which allocates (ballot, slot) pairs and
drives phase-2 through per-slot commanders (spawned via a self-forward so
they can run slot-sharded across workers); acceptors sit at a fixed worker.
Chosen commands broadcast as MChosen and execute in slot order.  GC is
slot-watermark based — no MStable round: the acceptor worker both tracks
watermarks and holds the slots to collect (fpaxos.rs:419-447).

Leader failover (beyond the reference, whose acceptor carries a todo!()
for it at multi.rs:97-99): with ``Config.fpaxos_leader_timeout_ms`` set,
the leader heartbeats every quarter-timeout and followers watch for
silence — the ring successor suspects first (one timeout), the next one a
timeout later, and so on, so elections are staggered and deterministic.
A candidate runs MultiSynod prepare/promise over the accepted-slot maps of
an n-f quorum, carries every possibly-chosen value forward through fresh
commanders at its ballot, resumes allocation above every slot seen, and
announces itself via the heartbeat.  Followers re-forward their pending
(not-yet-chosen) submissions to the new leader, which dedups by rifl.
The run layer's heartbeat failure detector feeds ``on_peer_down`` so a
TCP cluster elects as soon as the detector fires rather than waiting out
the protocol timeout.
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Deque, Dict, Optional, Set

from fantoch_tpu.core.command import Command
from fantoch_tpu.core.config import Config
from fantoch_tpu.core.ids import Dot, ProcessId, Rifl, ShardId
from fantoch_tpu.core.timing import SysTime
from fantoch_tpu.executor.slot import SlotExecutionInfo, SlotExecutor
from fantoch_tpu.protocol.base import (
    Action,
    BaseProcess,
    Protocol,
    ProtocolMetrics,
    ToForward,
    ToSend,
)
from fantoch_tpu.protocol.common.multi_synod import (
    MAccept as SynodMAccept,
    MAccepted as SynodMAccepted,
    MChosen as SynodMChosen,
    MForwardSubmit as SynodMForwardSubmit,
    MPrepare as SynodMPrepare,
    MSpawnCommander as SynodMSpawnCommander,
    MultiSynod,
    SlotGCTrack,
)
from fantoch_tpu.protocol.sync import MSlotSync, MSlotSyncReply, SlotSyncMixin
from fantoch_tpu.run.routing import (
    LEADER_WORKER_INDEX,
    worker_index_no_shift,
    worker_index_shift,
)

# the acceptor owns ballot/accepted state and must be a single worker
# (fpaxos.rs:417)
ACCEPTOR_WORKER_INDEX = 1


# --- messages (fpaxos.rs:389-414) ---


@dataclass
class MForwardSubmit:
    cmd: Command


@dataclass
class MSpawnCommander:
    ballot: int
    slot: int
    cmd: Command


@dataclass
class MAccept:
    ballot: int
    slot: int
    cmd: Command


@dataclass
class MAccepted:
    ballot: int
    slot: int


@dataclass
class MChosen:
    slot: int
    cmd: Command


@dataclass
class MGarbageCollection:
    committed: int


@dataclass
class MPrepare:
    """Leader-election phase 1 (candidate ballot)."""

    ballot: int


@dataclass
class MPromise:
    """Phase-1 answer: the acceptor's accepted-slot map (slot -> (ballot,
    cmd)) for value carry-forward."""

    ballot: int
    accepted: Dict[int, tuple]


@dataclass
class MLeaderHeartbeat:
    """Periodic leadership announcement; also how a freshly-elected leader
    tells followers where to (re-)forward."""

    ballot: int


@dataclass
class GarbageCollectionEvent:
    pass


@dataclass
class LeaderCheckEvent:
    """Periodic failover tick: the leader heartbeats, followers judge
    silence (interval = fpaxos_leader_timeout_ms // 4)."""


class FPaxos(SlotSyncMixin, Protocol):
    Executor = SlotExecutor

    def __init__(self, process_id: ProcessId, shard_id: ShardId, config: Config):
        # no fast quorum — there are no fast paths
        self.bp = BaseProcess(process_id, shard_id, config, 0, config.fpaxos_quorum_size())
        initial_leader = config.leader
        assert initial_leader is not None, (
            "in a leader-based protocol, the initial leader should be defined"
        )
        self._leader = initial_leader
        # ballot backing the current leadership belief (heartbeats carry
        # it; higher ballot wins)
        self._leader_ballot = initial_leader
        self._multi_synod: MultiSynod[Command] = MultiSynod(
            process_id, initial_leader, config.n, config.f
        )
        self._gc_track = SlotGCTrack(process_id, config.n)
        self._to_processes: Deque[Action] = deque()
        self._to_executors: Deque[SlotExecutionInfo] = deque()
        # failover state
        self._failover = config.fpaxos_leader_timeout_ms is not None
        if self._failover:
            # the acceptor must retain accepted slots until globally stable
            # (the gc-track path); gc_single-at-choose would let a new
            # leader resume allocation below a chosen slot it cannot see
            assert config.gc_interval_ms is not None, (
                "fpaxos_leader_timeout_ms requires gc_interval_ms: leader "
                "failover carries values forward from acceptor state, which "
                "must be retained until slots are globally stable"
            )
        # last virtual ms any message arrived from the current leader
        self._leader_heard: Optional[int] = None
        # elections started here (tracer counters are running totals)
        self._elections = 0
        # submissions forwarded but not yet chosen: re-forwarded on leader
        # change (Rifl -> Command); the leader dedups re-forwards below
        self._pending_forwards: Dict[Rifl, Command] = {}
        # rifls this process knows are allocated-or-chosen — the dedup set
        # that keeps a re-forward from executing a command twice.  Bounded
        # by the same stability horizon as the acceptor maps (pruning in
        # _handle_mgc keeps only the un-stable tail)
        self._seen_rifls: Set[Rifl] = set()
        self._rifl_slot: Dict[Rifl, int] = {}
        # chosen log: slot -> command for every chosen slot not yet
        # globally stable.  Doubles as (a) the re-chosen/duplicate dedup
        # set at takeover and under at-least-once delivery, and (b) the
        # retained record stream a rejoining replica pulls via MSlotSync
        # (retention argument: the dead replica's GC watermark froze, so
        # stability — and this log's pruning — stalled at its last
        # report).  Pruned by GC at the stability-minus-window horizon
        self._chosen_slots: Dict[int, Command] = {}
        # last virtual ms pending forwards were (re-)sent: lost forwards
        # (message loss; a leader crash-restart window with no election)
        # retry on a timeout cadence — the leader's rifl dedup makes
        # re-forwards exactly-once
        self._last_reforward_ms: Optional[int] = None
        # last virtual ms the leader re-drove its in-flight accept
        # rounds: an MAccept toward a crash-RESTARTING write-quorum
        # member evaporates during the downtime (no detector fires for a
        # restarting peer), and nothing else retries phase 2 — the slot,
        # and everything ordered after it, would stall forever
        # (fuzzer-found follower crash-restart stall)
        self._last_redrive_ms: Optional[int] = None
        # peers the run layer's failure detector declared dead
        self._down: Set[ProcessId] = set()

    def periodic_events(self):
        events = []
        if self.bp.config.gc_interval_ms is not None:
            events.append((GarbageCollectionEvent(), self.bp.config.gc_interval_ms))
        if self._failover:
            interval = max(1, self.bp.config.fpaxos_leader_timeout_ms // 4)
            events.append((LeaderCheckEvent(), interval))
        return events

    @property
    def id(self) -> ProcessId:
        return self.bp.process_id

    @property
    def shard_id(self) -> ShardId:
        return self.bp.shard_id

    def discover(self, processes):
        connect_ok = self.bp.discover(processes)
        return connect_ok, dict(self.bp.closest_shard_process())

    def submit(self, dot: Optional[Dot], cmd: Command, time: SysTime) -> None:
        self._handle_submit(cmd)

    def handle(self, from_, from_shard_id, msg, time):
        if self._failover and from_ == self._leader and from_ != self.id:
            self._leader_heard = time.millis()
        if isinstance(msg, MForwardSubmit):
            self._handle_submit(msg.cmd)
        elif isinstance(msg, MSpawnCommander):
            self._handle_mspawn_commander(from_, msg.ballot, msg.slot, msg.cmd)
        elif isinstance(msg, MAccept):
            self._handle_maccept(from_, msg.ballot, msg.slot, msg.cmd)
        elif isinstance(msg, MAccepted):
            self._handle_maccepted(from_, msg.ballot, msg.slot)
        elif isinstance(msg, MChosen):
            self._handle_mchosen(msg.slot, msg.cmd)
        elif isinstance(msg, MGarbageCollection):
            self._handle_mgc(from_, msg.committed)
        elif isinstance(msg, MPrepare):
            self._handle_mprepare(from_, msg.ballot)
        elif isinstance(msg, MPromise):
            self._handle_mpromise(from_, msg.ballot, msg.accepted, time)
        elif isinstance(msg, MLeaderHeartbeat):
            self._handle_leader_heartbeat(from_, msg.ballot, time)
        elif self.handle_slot_sync_message(from_, msg, time):
            pass
        else:
            raise AssertionError(f"unknown message {msg}")

    def handle_event(self, event, time):
        if isinstance(event, LeaderCheckEvent):
            self._handle_leader_check(time)
            return
        assert isinstance(event, GarbageCollectionEvent)
        self._to_processes.append(
            ToSend(self.bp.all_but_me(), MGarbageCollection(self._gc_track.committed()))
        )

    def to_processes(self) -> Optional[Action]:
        return self._to_processes.popleft() if self._to_processes else None

    def to_executors(self):
        return self._to_executors.popleft() if self._to_executors else None

    @classmethod
    def parallel(cls) -> bool:
        return True

    @classmethod
    def leaderless(cls) -> bool:
        return False

    def metrics(self) -> ProtocolMetrics:
        return self.bp.metrics()

    # --- handlers ---

    def _handle_submit(self, cmd: Command) -> None:
        if self._multi_synod.is_leader and cmd.rifl in self._seen_rifls:
            # already allocated (carried forward) or chosen: a follower's
            # post-failover re-forward OR a plain duplicated
            # MForwardSubmit delivery (at-least-once links) — allocating
            # a second slot would execute the command twice (fuzzer-found
            # without failover), so the rifl dedup always runs
            return
        out = self._multi_synod.submit(cmd)
        if isinstance(out, SynodMSpawnCommander):
            # we're the leader: spawn the commander via a self-forward so it
            # can land on a slot-sharded worker
            # trace: the leader allocating the slot is the dotless analog
            # of the coordinator's payload stage
            if self.bp.tracer.enabled:
                self.bp.trace_span("payload", cmd.rifl, meta={"slot": out.slot})
            self._register_allocation(out.value.rifl, out.slot)
            self._to_processes.append(
                ToForward(MSpawnCommander(out.ballot, out.slot, out.value))
            )
        elif isinstance(out, SynodMForwardSubmit):
            if self._failover:
                self._pending_forwards[cmd.rifl] = cmd
            self._to_processes.append(ToSend({self._leader}, MForwardSubmit(out.value)))
        else:
            raise AssertionError(f"can't handle {out} in submit")

    # without GC, the delivery-dedup sets are pruned to this many recent
    # slots (with GC, global stability prunes them exactly)
    _DEDUP_WINDOW = 4096

    def _prune_dedup_window(self) -> None:
        if len(self._chosen_slots) <= 2 * self._DEDUP_WINDOW:
            return
        floor = max(self._chosen_slots) - self._DEDUP_WINDOW
        self._chosen_slots = {
            s: cmd for s, cmd in self._chosen_slots.items() if s > floor
        }
        for rifl, slot in list(self._rifl_slot.items()):
            if slot <= floor:
                self._rifl_slot.pop(rifl, None)
                self._seen_rifls.discard(rifl)

    def _register_allocation(self, rifl: Rifl, slot: int) -> None:
        self._seen_rifls.add(rifl)
        self._rifl_slot[rifl] = slot

    def _handle_mspawn_commander(self, from_, ballot, slot, cmd) -> None:
        assert from_ == self.id, "spawn commander messages come from self"
        out = self._multi_synod.handle(from_, SynodMSpawnCommander(ballot, slot, cmd))
        assert isinstance(out, SynodMAccept)
        # steady state accepts go to the write quorum; a post-takeover
        # leader (ballot > n: initial-leader ballots are process ids) or a
        # known-dead quorum member means the quorum was sized for the
        # failure-free path and may contain dead processes — broadcast
        # then (still only f+1 accepts needed), without paying the n-fold
        # amplification on every failure-free command
        targets = self.bp.write_quorum()
        if self._failover and (ballot > self.bp.config.n or self._down & targets):
            targets = self.bp.all()
        self._to_processes.append(
            ToSend(targets, MAccept(out.ballot, out.slot, out.value))
        )

    def _handle_maccept(self, from_, ballot, slot, cmd) -> None:
        out = self._multi_synod.handle(from_, SynodMAccept(ballot, slot, cmd))
        if out is None:
            return  # ballot too low: this leader was superseded
        assert isinstance(out, SynodMAccepted)
        self._to_processes.append(ToSend({from_}, MAccepted(out.ballot, out.slot)))

    def _handle_maccepted(self, from_, ballot, slot) -> None:
        out = self._multi_synod.handle(from_, SynodMAccepted(ballot, slot))
        if out is None:
            return
        assert isinstance(out, SynodMChosen)
        self._to_processes.append(ToSend(self.bp.all(), MChosen(out.slot, out.value)))

    def _handle_mchosen(self, slot: int, cmd: Command) -> None:
        # exactly-once per slot under at-least-once delivery: a duplicated
        # MChosen (the sim's duplication nemesis; a resend tail in the run
        # layer) must not reach the executor twice — the takeover
        # carry-forward dedup doubles as the delivery dedup, so it runs
        # with or without failover (fuzzer-found: a duplicated MChosen
        # without failover tripped the slot executor's exactly-once
        # assert).  Pruned by GC at the global stability horizon; a
        # duplicate trailing even THAT is caught by the stable floor (the
        # GC-straggler guard: its slot executed everywhere long ago)
        if slot in self._chosen_slots or slot <= self._gc_track.stable_floor:
            return
        self._chosen_slots[slot] = cmd
        if self.bp.config.gc_interval_ms is None:
            # without GC nothing ever prunes the dedup state — keep a
            # bounded recent-slot window instead of growing forever (a
            # duplicate older than the window is ancient history; the
            # slot executor's next_slot floor also rejects it)
            self._prune_dedup_window()
        if self._failover:
            self._seen_rifls.add(cmd.rifl)
            self._pending_forwards.pop(cmd.rifl, None)
        if self.bp.tracer.enabled:
            self.bp.trace_span("commit", cmd.rifl, meta={"slot": slot})
        # audit plane: slot-order agreement = same slot, same command
        self.bp.audit_commit(slot, cmd.rifl, None)
        self._to_executors.append(SlotExecutionInfo(slot, cmd))
        if self.bp.config.gc_interval_ms is not None:
            self._gc_track.commit(slot)
        else:
            self._multi_synod.gc_single(slot)

    def _handle_mgc(self, from_: ProcessId, committed: int) -> None:
        self._gc_track.committed_by(from_, committed)
        start, end = self._gc_track.stable()
        if start <= end:
            self.bp.stable(self._multi_synod.gc(start, end))
            # stable slots can never be re-proposed (no acceptor still
            # holds them): prune the exactly-once bookkeeping — which now
            # runs with or without failover (delivery dedup).  Pruning
            # LAGS stability by the dedup window: a late duplicate of an
            # already-stable MChosen is caught by the stable floor, but a
            # late duplicate MForwardSubmit carries only a rifl — with
            # its entry pruned exactly at stability, the leader would
            # allocate a SECOND slot for an executed command
            # (fuzzer-found duplicate execution)
            cut = end - self._DEDUP_WINDOW
            self._chosen_slots = {
                s: cmd for s, cmd in self._chosen_slots.items() if s > cut
            }
            for rifl, slot in list(self._rifl_slot.items()):
                if slot <= cut:
                    self._rifl_slot.pop(rifl, None)
                    self._seen_rifls.discard(rifl)

    # --- rejoin catch-up (protocol/sync.py SlotSyncMixin) ---

    def rejoin(self, time: SysTime) -> None:
        """Restart hook: pull the chosen slots this replica missed while
        down, and restart the leader-silence clock (the restored
        ``_leader_heard`` is a pre-crash timestamp — judging the current
        leader by it would fire a spurious election on the first tick)."""
        if self._failover:
            self._leader_heard = time.millis()
            self._last_reforward_ms = time.millis()
        SlotSyncMixin.rejoin(self, time)

    def _slot_sync_floor(self) -> int:
        return self._gc_track.committed()

    def _slot_sync_records(self, floor: int):
        # sorted: chunk contents are a pure function of protocol state,
        # so same-seed traces stay identical
        return sorted(
            (slot, cmd)
            for slot, cmd in self._chosen_slots.items()
            if slot > floor
        )

    def _apply_slot_sync_record(self, from_: ProcessId, record, time: SysTime) -> None:
        slot, cmd = record
        # the normal chosen path: chosen-slot dedup + the stable floor
        # make overlapping peer replies exactly-once
        self._handle_mchosen(slot, cmd)

    def note_durable_chosen(self, records) -> None:
        """Restart-replay hook (run/wal.py): fold WAL-tail ``(slot, cmd)``
        records into the chosen log + committed watermark so the rejoin
        MSlotSync floor covers them — peers must not re-stream slots whose
        effects the executor tail replay already applied."""
        for slot, cmd in records:
            if slot in self._chosen_slots or slot <= self._gc_track.stable_floor:
                continue
            self._chosen_slots[slot] = cmd
            self._gc_track.commit(slot)
            if self._failover:
                self._seen_rifls.add(cmd.rifl)
                self._rifl_slot[cmd.rifl] = slot

    # --- leader failover ---

    def _ring_distance(self, candidate: ProcessId) -> int:
        return (candidate - self._leader) % self.bp.config.n

    def _handle_leader_check(self, time: SysTime) -> None:
        now = time.millis()
        if self._leader == self.id:
            if self._multi_synod.is_leader:
                self._to_processes.append(
                    ToSend(
                        self.bp.all_but_me(), MLeaderHeartbeat(self._leader_ballot)
                    )
                )
                # re-drive accept rounds stuck past a timeout: the
                # original fan-out may have been lost to a write-quorum
                # member's crash-restart window (frames to a down process
                # evaporate; a RESTARTING peer never trips the failure
                # detector, so on_peer_down's re-drive cannot cover this).
                # Broadcast is idempotent: acceptors re-accepting the same
                # (ballot, slot, value) are no-ops and the chosen-slot
                # dedup swallows re-chosen duplicates
                inflight = self._multi_synod.inflight()
                if inflight:
                    if self._last_redrive_ms is None:
                        self._last_redrive_ms = now
                    elif (
                        now - self._last_redrive_ms
                        >= self.bp.config.fpaxos_leader_timeout_ms
                    ):
                        self._last_redrive_ms = now
                        for ballot, slot, cmd in inflight:
                            self._to_processes.append(
                                ToSend(self.bp.all(), MAccept(ballot, slot, cmd))
                            )
                else:
                    self._last_redrive_ms = None
            return
        # pending-forward retry: a forward toward the leader can be lost
        # (message loss; a leader that crash-restarted inside the timeout
        # window — no election, so no heartbeat-change re-forward fires).
        # Retry on a timeout cadence; the leader's unconditional rifl
        # dedup makes re-forwards exactly-once, and dedup entries are
        # retained until global stability (which cannot pass a slot this
        # follower never saw chosen)
        if self._pending_forwards:
            if self._last_reforward_ms is None:
                self._last_reforward_ms = now
            elif now - self._last_reforward_ms >= self.bp.config.fpaxos_leader_timeout_ms:
                self._last_reforward_ms = now
                for cmd in self._pending_forwards.values():
                    self._to_processes.append(
                        ToSend({self._leader}, MForwardSubmit(cmd))
                    )
        else:
            self._last_reforward_ms = None
        if self._leader_heard is None:
            self._leader_heard = now  # start the clock at the first tick
            return
        # staggered suspicion: the ring successor campaigns after one
        # timeout, the next after two, ... — deterministic, collision-free
        timeout = self.bp.config.fpaxos_leader_timeout_ms
        wait = timeout * max(1, self._ring_distance(self.id))
        if now - self._leader_heard >= wait:
            self._leader_heard = now  # re-campaign only after another wait
            self._start_election()

    def _start_election(self) -> None:
        prepare = self._multi_synod.new_prepare()
        # trace: leader failover is the recovery trigger of the
        # leader-based world (a counter, not a span — no single dot
        # heals); counters are running totals, last observation wins
        self._elections += 1
        if self.bp.tracer.enabled:
            self.bp.tracer.counter(
                "fpaxos_elections", self._elections, pid=self.id,
                meta={"ballot": prepare.ballot},
            )
        # broadcast (self included: our own acceptor's promise counts)
        self._to_processes.append(ToSend(self.bp.all(), MPrepare(prepare.ballot)))

    def _handle_mprepare(self, from_: ProcessId, ballot: int) -> None:
        out = self._multi_synod.handle(from_, SynodMPrepare(ballot))
        if out is not None:
            self._to_processes.append(
                ToSend({from_}, MPromise(out.ballot, out.accepted))
            )

    def _handle_mpromise(self, from_: ProcessId, ballot: int, accepted, time) -> None:
        carry = self._multi_synod.handle_promise(from_, ballot, accepted)
        if carry is None:
            return
        # won the election: adopt leadership, resume allocation above the
        # chosen/stable horizon — the carry map covers accepted-but-
        # unstable slots only, and once GC pruned the acceptor maps a
        # winner trusting it alone re-allocates STABLE slots, whose
        # re-chosen events every stable-floor guard drops (the command is
        # lost, its client hangs; found by the FPaxos leader-kill WAL
        # restart row).  Any chosen-but-unstable slot is in some
        # promiser's accepted map (quorum intersection), and any stable
        # slot is at or below our own committed frontier (stability is a
        # min that includes us), so the max of the two is a sound floor
        self._multi_synod.resume_above(
            max(self._gc_track.committed(), max(self._chosen_slots, default=0))
        )
        # then re-propose every possibly-chosen slot at our ballot,
        # re-submit our own pending forwards, and announce
        self._leader = self.id
        self._leader_ballot = ballot
        for slot, cmd in carry.items():
            if slot in self._chosen_slots:
                continue  # already decided and known here
            self._register_allocation(cmd.rifl, slot)
            self._pending_forwards.pop(cmd.rifl, None)
            self._to_processes.append(ToForward(MSpawnCommander(ballot, slot, cmd)))
        pending, self._pending_forwards = self._pending_forwards, {}
        for cmd in pending.values():
            slot = self._rifl_slot.get(cmd.rifl)
            if slot is not None:
                # a stale own allocation from a superseded leadership
                # (pre-crash commander whose accept landed nowhere): the
                # dedup entry must clear or the re-submission below is
                # dropped and the command lost.  Stale means the slot is
                # occupied by NOBODY in the n-f promise view (unchosen —
                # it would have appeared in the carry map) OR by a
                # DIFFERENT command (an intervening leader reused the
                # slot number); only a same-rifl occupant proves our
                # allocation survived and the dedup should hold
                occupant = carry.get(slot)
                if occupant is None:
                    occupant = self._chosen_slots.get(slot)
                if occupant is None or occupant.rifl != cmd.rifl:
                    self._seen_rifls.discard(cmd.rifl)
                    self._rifl_slot.pop(cmd.rifl, None)
            self._handle_submit(cmd)
        self._to_processes.append(
            ToSend(self.bp.all_but_me(), MLeaderHeartbeat(ballot))
        )

    def _handle_leader_heartbeat(self, from_: ProcessId, ballot: int, time) -> None:
        if ballot < self._leader_ballot:
            return  # stale leader
        # a higher-ballot heartbeat proves an election this process never
        # voted in (it was crashed during the campaign and restored a
        # stale is_leader): stop allocating, and hand the values stranded
        # in superseded commanders to the real leader — those rounds can
        # never complete, and nothing else would retry them
        stale = self._multi_synod.demote_if_superseded(ballot)
        for _b, _slot, cmd in stale:
            self._pending_forwards[cmd.rifl] = cmd
        changed = from_ != self._leader
        self._leader = from_
        self._leader_ballot = ballot
        self._leader_heard = time.millis()
        if (changed or stale) and self._pending_forwards:
            # our earlier forwards may have died with the old leader:
            # re-forward everything not yet chosen (the leader dedups)
            for cmd in self._pending_forwards.values():
                self._to_processes.append(ToSend({from_}, MForwardSubmit(cmd)))

    def on_peer_down(self, peer_id: ProcessId, time: SysTime) -> None:
        """Run-layer failure-detector hook: elect immediately when the
        dead peer is the leader and we are the first live ring successor
        (the sim path relies on the staggered timeouts instead)."""
        if not self._failover:
            return
        self._down.add(peer_id)
        if self._multi_synod.is_leader and peer_id != self.id:
            # re-drive phase 2 for every in-flight slot: the original
            # accept fan-out was the f+1 write quorum and may have
            # included the dead peer — nothing else retries those rounds,
            # so their slots (and everything ordered after them) would
            # stall forever.  Broadcast: acceptors re-accepting the same
            # (ballot, slot, value) are idempotent and the chosen-slot
            # dedup swallows a re-chosen duplicate
            for ballot, slot, cmd in self._multi_synod.inflight():
                self._to_processes.append(
                    ToSend(self.bp.all(), MAccept(ballot, slot, cmd))
                )
        if peer_id != self._leader or self._leader == self.id:
            return
        candidates = sorted(
            (pid for pid in self.bp.all() if pid != self._leader and pid not in self._down),
            key=self._ring_distance,
        )
        if candidates and candidates[0] == self.id:
            self._leader_heard = time.millis()
            self._start_election()

    def on_peer_up(self, peer_id: ProcessId, time: SysTime) -> None:
        """Detector hook symmetric to :meth:`on_peer_down`: the peer is
        reachable again (restarted, or a false positive).  It re-enters
        the election candidate ring — a later failover may elect it —
        and our pending forwards are re-sent toward the current leader:
        frames queued while the peer was declared dead were dropped, and
        the leader's rifl dedup makes the re-forward exactly-once."""
        if not self._failover:
            return
        self._down.discard(peer_id)
        if self._pending_forwards and self._leader != self.id:
            for cmd in self._pending_forwards.values():
                self._to_processes.append(
                    ToSend({self._leader}, MForwardSubmit(cmd))
                )

    # --- worker routing (fpaxos.rs:416-465) ---

    @staticmethod
    def message_index(msg):
        if isinstance(msg, (MForwardSubmit, MPromise, MLeaderHeartbeat)):
            # leadership state (election, pending re-forwards) lives with
            # the submit path on the leader worker
            return worker_index_no_shift(LEADER_WORKER_INDEX)
        if isinstance(
            msg, (MAccept, MChosen, MGarbageCollection, MPrepare, MSlotSync, MSlotSyncReply)
        ):
            # the acceptor also learns chosen slots, runs gc tracking,
            # answers election prepares (its accepted map is the promise),
            # and serves/applies the rejoin slot-sync stream (the chosen
            # log lives with the MChosen handler)
            return worker_index_no_shift(ACCEPTOR_WORKER_INDEX)
        if isinstance(msg, (MSpawnCommander, MAccepted)):
            return worker_index_shift(msg.slot)
        raise AssertionError(f"unknown message {msg}")

    @staticmethod
    def event_index(event):
        if isinstance(event, LeaderCheckEvent):
            return worker_index_no_shift(LEADER_WORKER_INDEX)
        return worker_index_no_shift(ACCEPTOR_WORKER_INDEX)
