"""FPaxos: leader-based Flexible Paxos over the MultiSynod agents.

Reference: fantoch_ps/src/protocol/fpaxos.rs.  Clients submit anywhere;
non-leaders forward to the leader, which allocates (ballot, slot) pairs and
drives phase-2 through per-slot commanders (spawned via a self-forward so
they can run slot-sharded across workers); acceptors sit at a fixed worker.
Chosen commands broadcast as MChosen and execute in slot order.  GC is
slot-watermark based — no MStable round: the acceptor worker both tracks
watermarks and holds the slots to collect (fpaxos.rs:419-447).
"""

from __future__ import annotations

from collections import deque
from dataclasses import dataclass
from typing import Deque, Optional

from fantoch_tpu.core.command import Command
from fantoch_tpu.core.config import Config
from fantoch_tpu.core.ids import Dot, ProcessId, ShardId
from fantoch_tpu.core.timing import SysTime
from fantoch_tpu.executor.slot import SlotExecutionInfo, SlotExecutor
from fantoch_tpu.protocol.base import (
    Action,
    BaseProcess,
    Protocol,
    ProtocolMetrics,
    ToForward,
    ToSend,
)
from fantoch_tpu.protocol.common.multi_synod import (
    MAccept as SynodMAccept,
    MAccepted as SynodMAccepted,
    MChosen as SynodMChosen,
    MForwardSubmit as SynodMForwardSubmit,
    MSpawnCommander as SynodMSpawnCommander,
    MultiSynod,
    SlotGCTrack,
)
from fantoch_tpu.run.routing import (
    LEADER_WORKER_INDEX,
    worker_index_no_shift,
    worker_index_shift,
)

# the acceptor owns ballot/accepted state and must be a single worker
# (fpaxos.rs:417)
ACCEPTOR_WORKER_INDEX = 1


# --- messages (fpaxos.rs:389-414) ---


@dataclass
class MForwardSubmit:
    cmd: Command


@dataclass
class MSpawnCommander:
    ballot: int
    slot: int
    cmd: Command


@dataclass
class MAccept:
    ballot: int
    slot: int
    cmd: Command


@dataclass
class MAccepted:
    ballot: int
    slot: int


@dataclass
class MChosen:
    slot: int
    cmd: Command


@dataclass
class MGarbageCollection:
    committed: int


@dataclass
class GarbageCollectionEvent:
    pass


class FPaxos(Protocol):
    Executor = SlotExecutor

    def __init__(self, process_id: ProcessId, shard_id: ShardId, config: Config):
        # no fast quorum — there are no fast paths
        self.bp = BaseProcess(process_id, shard_id, config, 0, config.fpaxos_quorum_size())
        initial_leader = config.leader
        assert initial_leader is not None, (
            "in a leader-based protocol, the initial leader should be defined"
        )
        self._leader = initial_leader
        self._multi_synod: MultiSynod[Command] = MultiSynod(
            process_id, initial_leader, config.n, config.f
        )
        self._gc_track = SlotGCTrack(process_id, config.n)
        self._to_processes: Deque[Action] = deque()
        self._to_executors: Deque[SlotExecutionInfo] = deque()

    def periodic_events(self):
        if self.bp.config.gc_interval_ms is not None:
            return [(GarbageCollectionEvent(), self.bp.config.gc_interval_ms)]
        return []

    @property
    def id(self) -> ProcessId:
        return self.bp.process_id

    @property
    def shard_id(self) -> ShardId:
        return self.bp.shard_id

    def discover(self, processes):
        connect_ok = self.bp.discover(processes)
        return connect_ok, dict(self.bp.closest_shard_process())

    def submit(self, dot: Optional[Dot], cmd: Command, time: SysTime) -> None:
        self._handle_submit(cmd)

    def handle(self, from_, from_shard_id, msg, time):
        if isinstance(msg, MForwardSubmit):
            self._handle_submit(msg.cmd)
        elif isinstance(msg, MSpawnCommander):
            self._handle_mspawn_commander(from_, msg.ballot, msg.slot, msg.cmd)
        elif isinstance(msg, MAccept):
            self._handle_maccept(from_, msg.ballot, msg.slot, msg.cmd)
        elif isinstance(msg, MAccepted):
            self._handle_maccepted(from_, msg.ballot, msg.slot)
        elif isinstance(msg, MChosen):
            self._handle_mchosen(msg.slot, msg.cmd)
        elif isinstance(msg, MGarbageCollection):
            self._handle_mgc(from_, msg.committed)
        else:
            raise AssertionError(f"unknown message {msg}")

    def handle_event(self, event, time):
        assert isinstance(event, GarbageCollectionEvent)
        self._to_processes.append(
            ToSend(self.bp.all_but_me(), MGarbageCollection(self._gc_track.committed()))
        )

    def to_processes(self) -> Optional[Action]:
        return self._to_processes.popleft() if self._to_processes else None

    def to_executors(self):
        return self._to_executors.popleft() if self._to_executors else None

    @classmethod
    def parallel(cls) -> bool:
        return True

    @classmethod
    def leaderless(cls) -> bool:
        return False

    def metrics(self) -> ProtocolMetrics:
        return self.bp.metrics()

    # --- handlers ---

    def _handle_submit(self, cmd: Command) -> None:
        out = self._multi_synod.submit(cmd)
        if isinstance(out, SynodMSpawnCommander):
            # we're the leader: spawn the commander via a self-forward so it
            # can land on a slot-sharded worker
            self._to_processes.append(
                ToForward(MSpawnCommander(out.ballot, out.slot, out.value))
            )
        elif isinstance(out, SynodMForwardSubmit):
            self._to_processes.append(ToSend({self._leader}, MForwardSubmit(out.value)))
        else:
            raise AssertionError(f"can't handle {out} in submit")

    def _handle_mspawn_commander(self, from_, ballot, slot, cmd) -> None:
        assert from_ == self.id, "spawn commander messages come from self"
        out = self._multi_synod.handle(from_, SynodMSpawnCommander(ballot, slot, cmd))
        assert isinstance(out, SynodMAccept)
        self._to_processes.append(
            ToSend(self.bp.write_quorum(), MAccept(out.ballot, out.slot, out.value))
        )

    def _handle_maccept(self, from_, ballot, slot, cmd) -> None:
        out = self._multi_synod.handle(from_, SynodMAccept(ballot, slot, cmd))
        if out is None:
            return  # ballot too low: this leader was superseded
        assert isinstance(out, SynodMAccepted)
        self._to_processes.append(ToSend({from_}, MAccepted(out.ballot, out.slot)))

    def _handle_maccepted(self, from_, ballot, slot) -> None:
        out = self._multi_synod.handle(from_, SynodMAccepted(ballot, slot))
        if out is None:
            return
        assert isinstance(out, SynodMChosen)
        self._to_processes.append(ToSend(self.bp.all(), MChosen(out.slot, out.value)))

    def _handle_mchosen(self, slot: int, cmd: Command) -> None:
        self._to_executors.append(SlotExecutionInfo(slot, cmd))
        if self.bp.config.gc_interval_ms is not None:
            self._gc_track.commit(slot)
        else:
            self._multi_synod.gc_single(slot)

    def _handle_mgc(self, from_: ProcessId, committed: int) -> None:
        self._gc_track.committed_by(from_, committed)
        start, end = self._gc_track.stable()
        if start <= end:
            self.bp.stable(self._multi_synod.gc(start, end))

    # --- worker routing (fpaxos.rs:416-465) ---

    @staticmethod
    def message_index(msg):
        if isinstance(msg, MForwardSubmit):
            return worker_index_no_shift(LEADER_WORKER_INDEX)
        if isinstance(msg, (MAccept, MChosen, MGarbageCollection)):
            # the acceptor also learns chosen slots and runs gc tracking
            return worker_index_no_shift(ACCEPTOR_WORKER_INDEX)
        if isinstance(msg, (MSpawnCommander, MAccepted)):
            return worker_index_shift(msg.slot)
        raise AssertionError(f"unknown message {msg}")

    @staticmethod
    def event_index(event):
        return worker_index_no_shift(ACCEPTOR_WORKER_INDEX)
