"""Timestamp-with-predecessors commons for Caesar.

Reference: fantoch_ps/src/protocol/common/pred/clocks/{mod,quorum}.rs and
.../keys/sequential.rs.  Caesar timestamps are lexicographic
``(seq, process_id)`` pairs — globally unique, totally ordered.  Key clocks
store *which command* sits at each timestamp per key, so a proposal can
split conflicting commands into predecessors (lower timestamp) and
``blocked_by`` (higher timestamp — the wait condition's input).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Set, Tuple

from fantoch_tpu.core.command import Command
from fantoch_tpu.core.ids import Dot, ProcessId, ShardId
from fantoch_tpu.core.kvs import Key


@dataclass(frozen=True, order=True)
class Clock:
    """Lexicographic (seq, process_id) timestamp (mod.rs:27-62)."""

    seq: int
    process_id: ProcessId

    @staticmethod
    def zero(process_id: ProcessId) -> "Clock":
        return Clock(0, process_id)

    def is_zero(self) -> bool:
        return self.seq == 0

    def join(self, other: "Clock") -> "Clock":
        """Lexicographic max (mod.rs:41-57)."""
        return max(self, other)


class SequentialKeyClocks:
    """Per-key timestamp->dot maps + a monotone local sequence
    (keys/sequential.rs:13-140)."""

    __slots__ = ("process_id", "shard_id", "_seq", "_clocks")

    def __init__(self, process_id: ProcessId, shard_id: ShardId):
        self.process_id = process_id
        self.shard_id = shard_id
        self._seq = 0
        self._clocks: Dict[Key, Dict[Clock, Dot]] = {}

    def clock_next(self) -> Clock:
        self._seq += 1
        return Clock(self._seq, self.process_id)

    def clock_join(self, other: Clock) -> None:
        self._seq = max(self._seq, other.seq)

    def add(self, dot: Dot, cmd: Command, clock: Clock) -> None:
        """Index `dot` at `clock` on every key of the command; it then gets
        reported as a predecessor of higher-timestamp conflicts."""
        for key in cmd.keys(self.shard_id):
            commands = self._clocks.setdefault(key, {})
            assert clock not in commands, (
                "can't add a timestamp belonging to a command already added"
            )
            commands[clock] = dot

    def remove(self, cmd: Command, clock: Clock) -> None:
        for key in cmd.keys(self.shard_id):
            removed = self._clocks.get(key, {}).pop(clock, None)
            assert removed is not None, (
                "can't remove a timestamp belonging to a command never added"
            )

    def predecessors(
        self,
        dot: Dot,
        cmd: Command,
        clock: Clock,
        higher: Optional[Set[Dot]] = None,
    ) -> Set[Dot]:
        """Conflicting commands with a lower timestamp; fills `higher` with
        the higher-timestamp ones when provided (keys/sequential.rs:77-119)."""
        predecessors: Set[Dot] = set()
        for key in cmd.keys(self.shard_id):
            for cmd_clock, cmd_dot in self._clocks.get(key, {}).items():
                if cmd_clock < clock:
                    predecessors.add(cmd_dot)
                elif cmd_clock > clock:
                    if higher is not None:
                        higher.add(cmd_dot)
                else:
                    assert cmd_dot == dot, (
                        "found different command with the same timestamp"
                    )
        return predecessors

    def max_seq(self, cmd: Command, exclude: Optional[Dot] = None) -> int:
        """Highest timestamp sequence indexed on any of the command's keys
        (0 when none), excluding ``exclude`` (the dot under recovery must
        not floor itself).  The recovery plane's clock floor: every
        conflicting command this replica knows about — committed,
        accepted, executed-but-not-yet-GC'd — sits at or below it, so a
        free-choice recovered clock lifted strictly above the quorum max
        can never land below a timestamp survivors executed past."""
        floor = 0
        for key in cmd.keys(self.shard_id):
            for clock, dot in self._clocks.get(key, {}).items():
                if dot != exclude and clock.seq > floor:
                    floor = clock.seq
        return floor

    @classmethod
    def parallel(cls) -> bool:
        return False


KeyClocks = SequentialKeyClocks


class QuorumClocks:
    """Fast-quorum MProposeAck aggregation: max clock, dep union, AND of oks;
    complete either when the whole fast quorum replied or as soon as a
    majority replied with some not-ok (early slow path, quorum.rs:6-77)."""

    __slots__ = ("fast_quorum_size", "write_quorum_size", "_participants", "clock", "deps", "ok")

    def __init__(self, process_id: ProcessId, fast_quorum_size: int, write_quorum_size: int):
        self.fast_quorum_size = fast_quorum_size
        self.write_quorum_size = write_quorum_size
        self._participants: Set[ProcessId] = set()
        self.clock = Clock.zero(process_id)
        self.deps: Set[Dot] = set()
        self.ok = True

    def contains(self, process_id: ProcessId) -> bool:
        """Duplicate-delivery dedup (the PR 9 mcollectack class): counting
        one participant twice would complete the quorum with fewer
        distinct reports — an unsound fast path."""
        return process_id in self._participants

    def add(self, process_id: ProcessId, clock: Clock, deps: Set[Dot], ok: bool) -> None:
        assert len(self._participants) < self.fast_quorum_size
        self._participants.add(process_id)
        self.clock = self.clock.join(clock)
        self.deps.update(deps)
        self.ok = self.ok and ok

    def all(self) -> bool:
        replied = len(self._participants)
        some_not_ok_after_majority = not self.ok and replied >= self.write_quorum_size
        return some_not_ok_after_majority or replied == self.fast_quorum_size

    def aggregated(self) -> Tuple[Clock, Set[Dot], bool]:
        deps, self.deps = self.deps, set()
        return self.clock, deps, self.ok


class QuorumRetries:
    """MRetryAck aggregation: dep union over the write quorum
    (quorum.rs:80-120)."""

    __slots__ = ("write_quorum_size", "_participants", "deps")

    def __init__(self, write_quorum_size: int):
        self.write_quorum_size = write_quorum_size
        self._participants: Set[ProcessId] = set()
        self.deps: Set[Dot] = set()

    def contains(self, process_id: ProcessId) -> bool:
        """Duplicate-delivery dedup (see QuorumClocks.contains)."""
        return process_id in self._participants

    def add(self, process_id: ProcessId, deps: Set[Dot]) -> None:
        assert len(self._participants) < self.write_quorum_size
        self._participants.add(process_id)
        self.deps.update(deps)

    def all(self) -> bool:
        return len(self._participants) == self.write_quorum_size

    def aggregated(self) -> Set[Dot]:
        deps, self.deps = self.deps, set()
        return deps
