"""Timestamp-vote commons for Newt/Tempo: vote ranges, per-key clocks, and
quorum clock aggregation.

Reference: fantoch_ps/src/protocol/common/table/votes.rs (Votes/VoteRange
with adjacent-range compression), .../table/clocks/keys/sequential.rs
(proposal = bump each key clock to max(min_clock, clock+1) and vote the
consumed range), .../table/clocks/quorum.rs (max clock + count-of-max).

The tensor analog of ``proposal`` is a scatter-max over key-hash buckets
(see fantoch_tpu/ops): each committed batch bumps ``clock[key]`` with one
``.at[keys].max`` and the consumed ranges fall out as
``(old_clock+1, new_clock)`` per key — vote ranges are born compressed.
This module is the host control-plane twin used by the protocol state
machine and the simulator tests.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterator, List, Tuple

from fantoch_tpu.core.command import Command
from fantoch_tpu.core.ids import ProcessId, ShardId
from fantoch_tpu.core.kvs import Key


@dataclass
class VoteRange:
    """Votes ``start..=end`` on some key by process ``by``
    (votes.rs:103-155)."""

    by: ProcessId
    start: int
    end: int

    def __post_init__(self) -> None:
        assert self.start <= self.end

    def try_compress(self, other: "VoteRange") -> bool:
        """Extend self with `other` if contiguous; True on success
        (votes.rs:133-148)."""
        assert self.by == other.by
        if self.end + 1 == other.start:
            self.end = other.end
            return True
        return False

    def votes(self) -> List[int]:
        return list(range(self.start, self.end + 1))

    def __repr__(self) -> str:
        if self.start == self.end:
            return f"<{self.by}: {self.start}>"
        return f"<{self.by}: {self.start}-{self.end}>"


class Votes:
    """All votes on some command: key -> list of VoteRange (votes.rs:8-100)."""

    __slots__ = ("_votes",)

    def __init__(self) -> None:
        self._votes: Dict[Key, List[VoteRange]] = {}

    def add(self, key: Key, vote: VoteRange) -> None:
        """Append, compressing with the last range when contiguous and by
        the same voter (a detached accumulator can interleave voters: a
        recovered noop's carried votes merge foreign ranges in before the
        next own-clock bump appends)."""
        current = self._votes.setdefault(key, [])
        if current and current[-1].by == vote.by and current[-1].try_compress(vote):
            return
        current.append(vote)

    def set(self, key: Key, key_votes: List[VoteRange]) -> None:
        assert key not in self._votes
        self._votes[key] = key_votes

    def merge(self, remote: "Votes") -> None:
        for key, key_votes in remote._votes.items():
            self._votes.setdefault(key, []).extend(key_votes)

    def get(self, key: Key) -> List[VoteRange]:
        return self._votes.get(key, [])

    def remove(self, key: Key) -> List[VoteRange]:
        return self._votes.pop(key, [])

    def __len__(self) -> int:
        return len(self._votes)

    def is_empty(self) -> bool:
        return not self._votes

    def __iter__(self) -> Iterator[Tuple[Key, List[VoteRange]]]:
        return iter(self._votes.items())

    def __eq__(self, other: object) -> bool:
        return isinstance(other, Votes) and self._votes == other._votes

    def __repr__(self) -> str:
        return f"Votes({self._votes})"


class SequentialKeyClocks:
    """Per-key timestamp clocks with vote generation (sequential.rs:9-105).

    ``proposal`` bumps every key of the command to
    ``max(min_clock, highest-key-clock + 1)`` and returns the consumed vote
    ranges; ``detached``/``detached_all`` vote up to a target clock without
    proposing (used by clock-bump and commit notifications).
    """

    __slots__ = ("process_id", "shard_id", "_clocks")

    def __init__(self, process_id: ProcessId, shard_id: ShardId):
        self.process_id = process_id
        self.shard_id = shard_id
        self._clocks: Dict[Key, int] = {}

    def init_clocks(self, cmd: Command) -> None:
        """Ensure a clock exists per key so periodic bumps cover it."""
        for key in cmd.keys(self.shard_id):
            self._clocks.setdefault(key, 0)

    def proposal(self, cmd: Command, min_clock: int) -> Tuple[int, Votes]:
        clock = max(min_clock, self._cmd_clock(cmd) + 1)
        votes = Votes()
        self.detached(cmd, clock, votes)
        return clock, votes

    def detached(self, cmd: Command, up_to: int, votes: Votes) -> None:
        for key in cmd.keys(self.shard_id):
            self._maybe_bump(key, up_to, votes)

    def detached_all(self, up_to: int, votes: Votes) -> None:
        for key in self._clocks:
            self._maybe_bump(key, up_to, votes)

    def backfill_votes(self) -> Votes:
        """Re-statement of every vote this process ever issued: one
        ``[1, clock]`` range per known key.  Proposals and detached bumps
        both advance ``_clocks`` by exactly the ranges they vote, so a
        process's issued votes on a key are always the contiguous prefix
        up to its clock.  Safe to re-send wholesale (ranges dedup in the
        vote tables) — the rejoin plane (protocol/sync.py) uses it to
        heal the vote-frontier gaps a restarted replica would otherwise
        stall below forever."""
        votes = Votes()
        for key, clock in self._clocks.items():
            if clock > 0:
                votes.add(key, VoteRange(self.process_id, 1, clock))
        return votes

    @classmethod
    def parallel(cls) -> bool:
        return False

    def _cmd_clock(self, cmd: Command) -> int:
        return max(
            (self._clocks.get(key, 0) for key in cmd.keys(self.shard_id)),
            default=0,
        )

    def _maybe_bump(self, key: Key, up_to: int, votes: Votes) -> None:
        current = self._clocks.get(key, 0)
        if current < up_to:
            votes.add(key, VoteRange(self.process_id, current + 1, up_to))
            self._clocks[key] = up_to


# the default key-clocks; an Atomic/Locked split is unnecessary here — worker
# parallelism in the TPU runner batches proposals through one device step
# instead of sharing mutable clock maps across threads (see ops/)
KeyClocks = SequentialKeyClocks


class QuorumClocks:
    """Aggregates clocks reported by the fast quorum: tracks the max and how
    many times it was reported (quorum.rs:6-60)."""

    __slots__ = ("fast_quorum_size", "_participants", "max_clock", "max_clock_count")

    def __init__(self, fast_quorum_size: int):
        self.fast_quorum_size = fast_quorum_size
        self._participants: set = set()
        self.max_clock = 0
        self.max_clock_count = 0

    def contains(self, process_id: ProcessId) -> bool:
        """Already counted?  Handlers drop duplicate acks BEFORE add: a
        duplicated delivery (the sim's at-least-once nemesis) would
        double-count ``max_clock_count`` — and a spuriously-met ``>= f``
        max-count is an unsound fast-path commit (fuzzer-found)."""
        return process_id in self._participants

    def add(self, process_id: ProcessId, clock: int) -> Tuple[int, int]:
        assert process_id not in self._participants, "duplicate ack"
        assert len(self._participants) < self.fast_quorum_size
        self._participants.add(process_id)
        if clock > self.max_clock:
            self.max_clock = clock
            self.max_clock_count = 1
        elif clock == self.max_clock:
            self.max_clock_count += 1
        return self.max_clock, self.max_clock_count

    def all(self) -> bool:
        return len(self._participants) == self.fast_quorum_size
