"""Dependency tracking commons for graph-based protocols (EPaxos, Atlas).

Reference: fantoch_ps/src/protocol/common/graph/deps/keys/{mod,sequential}.rs
and .../deps/quorum.rs.  ``KeyDeps`` tracks, per key, the latest command that
touched it — a new command's dependencies are those latest conflicting
commands.  ``QuorumDeps`` aggregates dependency sets reported by fast-quorum
processes with per-dependency report counts, deciding the fast-path
condition (union == reported-by-all for EPaxos, threshold union for Atlas).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, FrozenSet, Optional, Set, Tuple

from fantoch_tpu.core.command import Command
from fantoch_tpu.core.ids import Dot, ProcessId, ShardId
from fantoch_tpu.core.kvs import Key


@dataclass(frozen=True)
class Dependency:
    """A dependency: the dot plus the shards that replicate it (None for
    noops).  Reference: deps/keys/mod.rs:18-35."""

    dot: Dot
    shards: Optional[FrozenSet[ShardId]]

    @staticmethod
    def from_cmd(dot: Dot, cmd: Command) -> "Dependency":
        return Dependency(dot, frozenset(cmd.shards()))

    @staticmethod
    def from_noop(dot: Dot) -> "Dependency":
        return Dependency(dot, None)


class _LatestRW:
    """Per-key (latest read, latest write) slots (locked.rs:10-15)."""

    __slots__ = ("read", "write")

    def __init__(self) -> None:
        self.read: Optional[Dependency] = None
        self.write: Optional[Dependency] = None


class KeyDeps:
    """Latest-per-key conflict index with the read/write split
    (deps/keys/locked.rs:10-122): a read-only command depends only on the
    latest *write* on each key (reads commute) and becomes the latest
    read; a write depends on the latest read AND write and becomes the
    latest write.  Read-heavy workloads thus commit with far fewer
    dependencies than the latest-*access* index of sequential.rs.

    The reference has Sequential (plain map, no split) and Locked (per-key
    RwLock, with the split) variants for worker parallelism; here one
    implementation serves both (see fantoch_tpu/protocol/info.py for the
    rationale) and adopts the Locked variant's sharper conflict relation.
    The batched device counterpart — the intra-batch latest-per-key chain
    — lives in fantoch_tpu/parallel/mesh_step.py (_intra_batch_chain) and
    fantoch_tpu/ops/table_ops.py (scatter-max key clocks).
    """

    def __init__(self, shard_id: ShardId):
        self._shard_id = shard_id
        self._latest: Dict[Key, _LatestRW] = {}
        self._noop_latest: Optional[Dependency] = None

    def add_cmd(
        self, dot: Dot, cmd: Command, past: Optional[Set[Dependency]] = None
    ) -> Set[Dependency]:
        """Record `dot` on each of `cmd`'s keys; returns its dependencies,
        seeded with `past` (remote deps) if given (locked.rs:84-128)."""
        deps: Set[Dependency] = set(past) if past else set()
        new_dep = Dependency.from_cmd(dot, cmd)
        read_only = cmd.read_only
        for key in cmd.keys(self._shard_id):
            entry = self._latest.get(key)
            if entry is None:
                entry = _LatestRW()
                self._latest[key] = entry
            if read_only:
                if entry.write is not None:
                    deps.add(entry.write)
                entry.read = new_dep
            else:
                if entry.read is not None:
                    deps.add(entry.read)
                    # clear the read slot: this write now depends on it, so
                    # later writes are ordered after it transitively — the
                    # reference keeps it (locked.rs:108-110) and ships one
                    # permanently redundant dep per subsequent write
                    entry.read = None
                if entry.write is not None:
                    deps.add(entry.write)
                entry.write = new_dep
        if self._noop_latest is not None:
            deps.add(self._noop_latest)
        return deps

    def add_noop(self, dot: Dot) -> Set[Dependency]:
        """A noop conflicts with everything: depends on every key's latest
        read and write plus the previous noop (locked.rs:130-170)."""
        deps: Set[Dependency] = set()
        prev = self._noop_latest
        self._noop_latest = Dependency.from_noop(dot)
        if prev is not None:
            deps.add(prev)
        for entry in self._latest.values():
            if entry.read is not None:
                deps.add(entry.read)
            if entry.write is not None:
                deps.add(entry.write)
        return deps

    # test-only queries (locked.rs:172-187)
    def cmd_deps(self, cmd: Command) -> Set[Dot]:
        deps: Set[Dot] = set()
        if self._noop_latest is not None:
            deps.add(self._noop_latest.dot)
        for key in cmd.keys(self._shard_id):
            entry = self._latest.get(key)
            if entry is not None:
                if entry.read is not None:
                    deps.add(entry.read.dot)
                if entry.write is not None:
                    deps.add(entry.write.dot)
        return deps

    def noop_deps(self) -> Set[Dot]:
        deps: Set[Dot] = set()
        for entry in self._latest.values():
            if entry.read is not None:
                deps.add(entry.read.dot)
            if entry.write is not None:
                deps.add(entry.write.dot)
        if self._noop_latest is not None:
            deps.add(self._noop_latest.dot)
        return deps

    @classmethod
    def parallel(cls) -> bool:
        return True


class QuorumDeps:
    """Per-dependency report counts over a fast quorum (deps/quorum.rs:8-100)."""

    def __init__(self, fast_quorum_size: int):
        self._fast_quorum_size = fast_quorum_size
        self._participants: Set[ProcessId] = set()
        self._threshold_deps: Dict[Dependency, int] = {}

    def contains(self, process_id: ProcessId) -> bool:
        """Already counted?  Handlers drop duplicate acks BEFORE add: a
        duplicated delivery (the sim's at-least-once nemesis) would
        double-count threshold reports — and a spuriously-met Atlas
        threshold is an unsound fast-path commit (fuzzer-found)."""
        return process_id in self._participants

    def add(self, process_id: ProcessId, deps: Set[Dependency]) -> None:
        assert process_id not in self._participants, "duplicate ack"
        assert len(self._participants) < self._fast_quorum_size
        self._participants.add(process_id)
        for dep in deps:
            self._threshold_deps[dep] = self._threshold_deps.get(dep, 0) + 1

    def all(self) -> bool:
        return len(self._participants) == self._fast_quorum_size

    def check_threshold_union(self, threshold: int) -> Tuple[Set[Dependency], bool]:
        """(union, every dep reported >= threshold times) — Atlas fast path."""
        assert self.all()
        equal = all(count >= threshold for count in self._threshold_deps.values())
        return set(self._threshold_deps), equal

    def check_union(self) -> Tuple[Set[Dependency], bool]:
        """(union, all quorum processes reported identical deps) — EPaxos
        fast path."""
        assert self.all()
        counts = set(self._threshold_deps.values())
        if not counts:
            equal = True  # no deps reported: trivially all equal
        elif len(counts) == 1:
            equal = counts.pop() == self._fast_quorum_size
        else:
            equal = False
        return set(self._threshold_deps), equal
