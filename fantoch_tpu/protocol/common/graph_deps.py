"""Dependency tracking commons for graph-based protocols (EPaxos, Atlas).

Reference: fantoch_ps/src/protocol/common/graph/deps/keys/{mod,sequential}.rs
and .../deps/quorum.rs.  ``KeyDeps`` tracks, per key, the latest command that
touched it — a new command's dependencies are those latest conflicting
commands.  ``QuorumDeps`` aggregates dependency sets reported by fast-quorum
processes with per-dependency report counts, deciding the fast-path
condition (union == reported-by-all for EPaxos, threshold union for Atlas).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, FrozenSet, Optional, Set, Tuple

from fantoch_tpu.core.command import Command
from fantoch_tpu.core.ids import Dot, ProcessId, ShardId
from fantoch_tpu.core.kvs import Key


@dataclass(frozen=True)
class Dependency:
    """A dependency: the dot plus the shards that replicate it (None for
    noops).  Reference: deps/keys/mod.rs:18-35."""

    dot: Dot
    shards: Optional[FrozenSet[ShardId]]

    @staticmethod
    def from_cmd(dot: Dot, cmd: Command) -> "Dependency":
        return Dependency(dot, frozenset(cmd.shards()))

    @staticmethod
    def from_noop(dot: Dot) -> "Dependency":
        return Dependency(dot, None)


class KeyDeps:
    """Latest-per-key conflict index (deps/keys/sequential.rs:8-145).

    The reference has Sequential (plain map) and Locked (per-key RwLock)
    variants for worker parallelism; here one implementation serves both
    (see fantoch_tpu/protocol/info.py for the rationale).  The batched
    device counterpart — the intra-batch latest-per-key chain — lives in
    fantoch_tpu/parallel/mesh_step.py (_intra_batch_chain) and
    fantoch_tpu/ops/table_ops.py (scatter-max key clocks).
    """

    def __init__(self, shard_id: ShardId):
        self._shard_id = shard_id
        self._latest: Dict[Key, Dependency] = {}
        self._noop_latest: Optional[Dependency] = None

    def add_cmd(
        self, dot: Dot, cmd: Command, past: Optional[Set[Dependency]] = None
    ) -> Set[Dependency]:
        """Record `dot` as the latest on each of `cmd`'s keys; returns its
        dependencies (latest prior commands on those keys + latest noop),
        seeded with `past` (remote deps) if given."""
        deps: Set[Dependency] = set(past) if past else set()
        new_dep = Dependency.from_cmd(dot, cmd)
        for key in cmd.keys(self._shard_id):
            prev = self._latest.get(key)
            if prev is not None:
                deps.add(prev)
            self._latest[key] = new_dep
        if self._noop_latest is not None:
            deps.add(self._noop_latest)
        return deps

    def add_noop(self, dot: Dot) -> Set[Dependency]:
        """A noop conflicts with everything: depends on every key's latest
        plus the previous noop."""
        deps: Set[Dependency] = set()
        prev = self._noop_latest
        self._noop_latest = Dependency.from_noop(dot)
        if prev is not None:
            deps.add(prev)
        deps.update(self._latest.values())
        return deps

    # test-only queries (deps/keys/sequential.rs:44-58)
    def cmd_deps(self, cmd: Command) -> Set[Dot]:
        deps: Set[Dot] = set()
        if self._noop_latest is not None:
            deps.add(self._noop_latest.dot)
        for key in cmd.keys(self._shard_id):
            dep = self._latest.get(key)
            if dep is not None:
                deps.add(dep.dot)
        return deps

    def noop_deps(self) -> Set[Dot]:
        deps = {d.dot for d in self._latest.values()}
        if self._noop_latest is not None:
            deps.add(self._noop_latest.dot)
        return deps

    @classmethod
    def parallel(cls) -> bool:
        return True


class QuorumDeps:
    """Per-dependency report counts over a fast quorum (deps/quorum.rs:8-100)."""

    def __init__(self, fast_quorum_size: int):
        self._fast_quorum_size = fast_quorum_size
        self._participants: Set[ProcessId] = set()
        self._threshold_deps: Dict[Dependency, int] = {}

    def add(self, process_id: ProcessId, deps: Set[Dependency]) -> None:
        assert len(self._participants) < self._fast_quorum_size
        self._participants.add(process_id)
        for dep in deps:
            self._threshold_deps[dep] = self._threshold_deps.get(dep, 0) + 1

    def all(self) -> bool:
        return len(self._participants) == self._fast_quorum_size

    def check_threshold_union(self, threshold: int) -> Tuple[Set[Dependency], bool]:
        """(union, every dep reported >= threshold times) — Atlas fast path."""
        assert self.all()
        equal = all(count >= threshold for count in self._threshold_deps.values())
        return set(self._threshold_deps), equal

    def check_union(self) -> Tuple[Set[Dependency], bool]:
        """(union, all quorum processes reported identical deps) — EPaxos
        fast path."""
        assert self.all()
        counts = set(self._threshold_deps.values())
        if not counts:
            equal = True  # no deps reported: trivially all equal
        elif len(counts) == 1:
            equal = counts.pop() == self._fast_quorum_size
        else:
            equal = False
        return set(self._threshold_deps), equal
