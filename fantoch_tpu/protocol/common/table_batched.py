"""Array-backed key clocks for the Newt/Tempo proposal path.

The host twin (``SequentialKeyClocks``, table_clocks.py) bumps one dict
entry per key per command — the per-command Python the reference pays per
``SequentialKeyClocks::proposal`` call
(fantoch_ps/src/protocol/common/table/clocks/keys/sequential.rs:36-47).
``BatchedKeyClocks`` holds the clock table as a dense int64 array over a
key registry and adds ``proposal_batch``: one
:func:`fantoch_tpu.ops.table_ops.batched_clock_proposal` kernel call
assigns clocks + consumed vote ranges to a whole batch of single-key
commands (commands on the same key receive consecutive clocks in batch
order, exactly the sequential semantics).  Scalar ``proposal`` /
``detached`` / ``detached_all`` keep the full SequentialKeyClocks
interface, so this is a drop-in replacement selected by
``Config.batched_table_executor``.

Clock width: the kernel works in int32; ``proposal_batch`` rebases int64
host clocks when they fit a 31-bit window above zero and falls back to the
sequential loop otherwise (real-time microsecond clocks — the window
machinery of ops/table_ops.ClockWindow belongs to the device-resident
serving path, not this host seam).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from fantoch_tpu.core.command import Command
from fantoch_tpu.core.ids import ProcessId, ShardId
from fantoch_tpu.core.kvs import Key
from fantoch_tpu.ops.table_ops import next_pow2 as _pow2
from fantoch_tpu.protocol.common.table_clocks import VoteRange, Votes

_INT32_MAX = (1 << 31) - 1


class BatchedKeyClocks:
    """SequentialKeyClocks semantics over a dense clock array.

    The batched proposal path keeps the clock table DEVICE-RESIDENT
    across batches (``ops/table_ops.resident_clock_proposal`` with a
    donated prior): successive ``proposal_batch_arrays`` calls never
    re-upload or re-download the table.  The host ``_clocks`` mirror goes
    stale while the device copy leads; scalar-path accesses
    (``proposal``/``detached``/``detached_all``) re-sync the host view
    but KEEP the device table resident — their bumps are recorded and
    folded into the next batch dispatch as one O(bumps) scatter-max
    (``ops/table_ops.resident_clock_bump``, donated), so live Newt's
    scalar detached-bumps between submit batches no longer degrade the
    proposal path to upload-per-batch (the pre-r07 regression BENCH_DEV
    round 6 documented).  The device copy is dropped only when the key
    registry outgrows its capacity, on pickling, and when a genuine
    31-bit overflow forces the sequential fallback.
    """

    __slots__ = (
        "process_id", "shard_id", "_key_index", "_keys", "_clocks", "_count",
        "_dev_prior", "_dev_kcap", "_host_stale", "_host_max",
        "_pending_bumps", "resident_uploads",
    )

    def __init__(self, process_id: ProcessId, shard_id: ShardId):
        self.process_id = process_id
        self.shard_id = shard_id
        self._key_index: Dict[Key, int] = {}
        self._keys: List[Key] = []
        self._clocks = np.zeros(64, dtype=np.int64)
        self._count = 0
        self._dev_prior = None  # resident int32[kcap] clock table
        self._dev_kcap = 0
        self._host_stale = False
        # upper bound on any clock in the table (host or device): the
        # window guard must not read the device table, so the bound is
        # maintained incrementally and tightened at materialize time
        self._host_max = 0
        # scalar bumps applied to the host mirror while the device table
        # stays resident: bucket -> bumped-to clock, folded into the next
        # batch dispatch (scatter-max) and cleared
        self._pending_bumps: Dict[int, int] = {}
        # full-table uploads (first build + capacity regrows + rebuilds
        # after a drop) — the residency regression instrument: steady
        # state holds this at 1 however many scalar bumps interleave
        self.resident_uploads = 0

    def _sync_host(self) -> None:
        """Refresh the host mirror from the resident device table WITHOUT
        dropping it (scalar paths need current clock values; their bumps
        ride ``_pending_bumps`` back to the device).  Buckets registered
        after the last batch hold 0 on both sides; the device table's
        last slot is the pad bucket and is never copied.  ``_host_max``
        is NOT tightened here: it still bounds the resident pad bucket's
        accumulated garbage."""
        if self._dev_prior is not None and self._host_stale:
            import jax

            dev = np.asarray(jax.device_get(self._dev_prior)).astype(np.int64)
            # never copy the device table's LAST slot: it is the pad
            # bucket, whose clock accumulates garbage from pad rows.
            # A real key at that index can only have registered after
            # the last dispatch (dispatch guarantees real indices
            # <= len(dev) - 2), so its live clock is the host's 0
            take = min(self._count, len(dev) - 1)
            self._clocks[:take] = dev[:take]
            self._host_stale = False

    def _materialize_host(self) -> None:
        """Sync the host mirror and DROP the device copy (the caller is
        about to rebuild it, pickle, or fall back to the sequential
        path).  Pending scalar bumps are already in the host mirror, so
        they die with the device copy; the window bound tightens to the
        actual table max (pad-bucket garbage is dropped here)."""
        self._sync_host()
        if self._dev_prior is not None:
            self._dev_prior = None
            self._dev_kcap = 0
            if self._count:
                self._host_max = int(self._clocks[: self._count].max())
            else:
                self._host_max = 0
        self._pending_bumps.clear()

    def __getstate__(self):
        # device buffers don't pickle (sim snapshots / the model checker):
        # materialize the host view and ship that
        self._materialize_host()
        return {
            s: getattr(self, s)
            for s in self.__slots__
            if s not in ("_dev_prior",)
        }

    def __setstate__(self, state):
        # pre-r07 pickles lack the residency-fix fields
        self._pending_bumps = {}
        self.resident_uploads = 0
        for k, v in state.items():
            setattr(self, k, v)
        self._dev_prior = None
        self._dev_kcap = 0
        self._host_stale = False

    # --- registry ---

    def _index(self, key: Key) -> int:
        idx = self._key_index.get(key)
        if idx is None:
            idx = self._count
            self._key_index[key] = idx
            self._keys.append(key)
            self._count += 1
            if idx >= len(self._clocks):
                grown = np.zeros(len(self._clocks) * 2, dtype=np.int64)
                grown[: len(self._clocks)] = self._clocks
                self._clocks = grown
        return idx

    def init_clocks(self, cmd: Command) -> None:
        for key in cmd.keys(self.shard_id):
            self._index(key)

    # --- scalar SequentialKeyClocks interface ---

    def proposal(self, cmd: Command, min_clock: int) -> Tuple[int, Votes]:
        self._sync_host()
        clock = max(min_clock, self._cmd_clock(cmd) + 1)
        votes = Votes()
        self.detached(cmd, clock, votes)
        return clock, votes

    def detached(self, cmd: Command, up_to: int, votes: Votes) -> None:
        self._sync_host()
        for key in cmd.keys(self.shard_id):
            self._maybe_bump(key, up_to, votes)

    def detached_all(self, up_to: int, votes: Votes) -> None:
        # vectorized sweep over every registered key (the clock-bump event
        # touches the whole table, newt.rs:983-1006)
        self._sync_host()
        self._host_max = max(self._host_max, up_to)
        count = self._count
        current = self._clocks[:count]
        behind = np.nonzero(current < up_to)[0]
        resident = self._dev_prior is not None
        for idx in behind.tolist():
            votes.add(
                self._keys[idx],
                VoteRange(self.process_id, int(current[idx]) + 1, up_to),
            )
            if resident:
                self._pending_bumps[idx] = up_to
        current[behind] = up_to

    def backfill_votes(self) -> Votes:
        """Array twin of ``SequentialKeyClocks.backfill_votes``: one
        ``[1, clock]`` range per known key — the contiguous prefix of
        every vote this process ever issued (see the host twin for why
        that invariant holds).  Used by the rejoin plane
        (protocol/sync.py); does not disturb device residency."""
        self._sync_host()
        votes = Votes()
        count = self._count
        clocks = self._clocks[:count]
        for idx in np.nonzero(clocks > 0)[0].tolist():
            votes.add(
                self._keys[idx],
                VoteRange(self.process_id, 1, int(clocks[idx])),
            )
        return votes

    @classmethod
    def parallel(cls) -> bool:
        return False

    def _cmd_clock(self, cmd: Command) -> int:
        return max(
            (int(self._clocks[self._index(key)]) for key in cmd.keys(self.shard_id)),
            default=0,
        )

    def _maybe_bump(self, key: Key, up_to: int, votes: Votes) -> None:
        idx = self._index(key)
        current = int(self._clocks[idx])
        if current < up_to:
            votes.add(key, VoteRange(self.process_id, current + 1, up_to))
            self._clocks[idx] = up_to
            if up_to > self._host_max:
                self._host_max = up_to
            if self._dev_prior is not None:
                # the resident device table still holds `current`; the
                # next batch dispatch folds this bump in (scatter-max)
                self._pending_bumps[idx] = up_to

    # --- the batched proposal seam ---

    def proposal_batch(
        self, cmds: List[Command], min_clocks: List[int]
    ) -> List[Tuple[int, Votes]]:
        """Clocks + votes for a whole batch, preserving batch order within
        each key (== running ``proposal`` sequentially).  Single-key
        commands with window-sized clocks go through the device kernel;
        anything else falls back to the sequential loop."""
        assert len(cmds) == len(min_clocks)
        batch = len(cmds)
        if batch == 0:
            return []
        keys: List[Key] = []
        single = True
        for cmd in cmds:
            if cmd.key_count(self.shard_id) != 1:
                single = False
                break
            keys.append(next(iter(cmd.keys(self.shard_id))))
        if single:
            out = self._proposal_batch_kernel(keys, min_clocks)
            if out is not None:
                return out
        return [self.proposal(cmd, mc) for cmd, mc in zip(cmds, min_clocks)]

    def proposal_batch_arrays(
        self, keys: List[Key], min_clocks
    ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """Array-native proposal seam (VERDICT r4 #4): clocks + consumed
        vote-range starts as int64 columns, NO Votes/VoteRange objects —
        the per-command object building that floors the host path at
        ~4.5 us/cmd happens only at whatever boundary actually needs
        objects.  The vote consumed by row ``i`` is
        ``[vote_start[i], clock[i]]`` by this process.

        Returns None when clocks exceed the 31-bit kernel window
        (real-time micros; callers fall back to the sequential loop).
        Semantics: identical to running ``proposal`` sequentially —
        same-key commands get consecutive clocks in batch order
        (fantoch_ps/src/protocol/common/table/votes.rs:133 ranges).

        Residency: the clock table stays ON DEVICE between calls
        (``resident_clock_proposal`` donates it back to itself); only the
        per-row clock/vote_start columns cross the host boundary.  The
        table is rebuilt from the host mirror when the key registry
        outgrows the device capacity (pow2 schedule) or after a scalar
        access dropped the device copy."""
        import jax
        import jax.numpy as jnp

        from fantoch_tpu.ops.table_ops import resident_clock_proposal

        batch = len(keys)
        ki = self._key_index
        try:
            idx_list = [ki[k] for k in keys]
        except KeyError:
            for k in keys:
                self._index(k)
            idx_list = [ki[k] for k in keys]
        mins = np.asarray(min_clocks, dtype=np.int64)
        # pad the key table to pow2 so XLA compiles O(log) programs as the
        # registry grows; pad the batch with private pad-bucket rows
        kcap = _pow2(max(self._count, 1) + 1)
        bcap = _pow2(batch)
        # 31-bit window guard without reading the device table: no bucket
        # (pad included) can exceed max(previous bound, batch mins) plus
        # the padded batch size, so the bound threads through batches
        hi = max(self._host_max, int(mins.max()) if batch else 0)
        if hi + bcap + 1 > _INT32_MAX:
            # the incrementally-grown bound includes pad-bucket drift
            # (+bcap per resident batch): materializing tightens it to
            # the true table max, so only genuine real-time-micros
            # clocks still overflow and pay the sequential fallback
            self._materialize_host()
            hi = max(self._host_max, int(mins.max()) if batch else 0)
            if hi + bcap + 1 > _INT32_MAX:
                return None
        if self._dev_prior is None or self._dev_kcap < kcap:
            # first batch, or the registry outgrew the device capacity:
            # (re)build the resident table from the host mirror
            self._materialize_host()
            prior = np.zeros(kcap, dtype=np.int32)
            prior[: self._count] = self._clocks[: self._count]
            # jnp.array COPIES into an XLA-owned buffer.  device_put /
            # jnp.asarray of a numpy array zero-copy ALIASES its host
            # memory on the CPU backend, and resident_clock_proposal
            # donates this buffer — donating the alias hands numpy-owned
            # memory to XLA (use-after-free, segfaults under the
            # persistent compile cache)
            self._dev_prior = jnp.array(prior)
            self._dev_kcap = kcap
            self.resident_uploads += 1
        elif self._pending_bumps:
            # scalar bumps interleaved since the last batch: fold them
            # into the resident table as ONE donated scatter-max —
            # O(bumps) host->device traffic instead of the full-table
            # re-upload the pre-r07 scalar path paid.  No rebuild above
            # means every bumped bucket is < _dev_kcap - 1 (the pad
            # slot), so the scatter stays inside the real region.
            from fantoch_tpu.ops.table_ops import resident_clock_bump

            items = sorted(self._pending_bumps.items())
            m = len(items)
            mcap = _pow2(m)
            bidx = np.full(mcap, self._dev_kcap - 1, dtype=np.int32)
            bval = np.zeros(mcap, dtype=np.int32)
            bidx[:m] = [i for i, _ in items]
            bval[:m] = [v for _, v in items]
            self._dev_prior = resident_clock_bump(
                self._dev_prior, jnp.asarray(bidx), jnp.asarray(bval)
            )
            self._pending_bumps.clear()
        pk = np.full(bcap, self._dev_kcap - 1, dtype=np.int32)  # pad bucket
        pm = np.zeros(bcap, dtype=np.int32)
        pk[:batch] = idx_list
        pm[:batch] = mins.astype(np.int32)
        clock_d, start_d, new_prior = resident_clock_proposal(
            self._dev_prior, jnp.asarray(pk), jnp.asarray(pm)
        )
        self._dev_prior = new_prior  # stays resident; donated next call
        self._host_stale = True
        self._host_max = hi + bcap
        # one blocking transfer for the two row outputs (per-array
        # np.asarray would pay a device round trip each on a
        # remote-dispatch rig); the clock table never crosses
        clock, vote_start = jax.device_get((clock_d, start_d))
        return (
            clock[:batch].astype(np.int64),
            vote_start[:batch].astype(np.int64),
        )

    def _proposal_batch_kernel(
        self, keys: List[Key], min_clocks: List[int]
    ) -> Optional[List[Tuple[int, Votes]]]:
        arrays = self.proposal_batch_arrays(keys, min_clocks)
        if arrays is None:
            return None
        clock, vote_start = arrays
        out: List[Tuple[int, Votes]] = []
        for i in range(len(keys)):
            votes = Votes()
            votes.set(
                keys[i],
                [VoteRange(self.process_id, int(vote_start[i]), int(clock[i]))],
            )
            out.append((int(clock[i]), votes))
        return out

