"""Array-backed key clocks for the Newt/Tempo proposal path.

The host twin (``SequentialKeyClocks``, table_clocks.py) bumps one dict
entry per key per command — the per-command Python the reference pays per
``SequentialKeyClocks::proposal`` call
(fantoch_ps/src/protocol/common/table/clocks/keys/sequential.rs:36-47).
``BatchedKeyClocks`` holds the clock table as a dense int64 array over a
key registry and adds ``proposal_batch``: one
:func:`fantoch_tpu.ops.table_ops.batched_clock_proposal` kernel call
assigns clocks + consumed vote ranges to a whole batch of single-key
commands (commands on the same key receive consecutive clocks in batch
order, exactly the sequential semantics).  Scalar ``proposal`` /
``detached`` / ``detached_all`` keep the full SequentialKeyClocks
interface, so this is a drop-in replacement selected by
``Config.batched_table_executor``.

Clock width: the kernel works in int32; ``proposal_batch`` rebases int64
host clocks when they fit a 31-bit window above zero and falls back to the
sequential loop otherwise (real-time microsecond clocks — the window
machinery of ops/table_ops.ClockWindow belongs to the device-resident
serving path, not this host seam).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from fantoch_tpu.core.command import Command
from fantoch_tpu.core.ids import ProcessId, ShardId
from fantoch_tpu.core.kvs import Key
from fantoch_tpu.protocol.common.table_clocks import VoteRange, Votes

_INT32_MAX = (1 << 31) - 1


class BatchedKeyClocks:
    """SequentialKeyClocks semantics over a dense clock array."""

    __slots__ = ("process_id", "shard_id", "_key_index", "_keys", "_clocks", "_count")

    def __init__(self, process_id: ProcessId, shard_id: ShardId):
        self.process_id = process_id
        self.shard_id = shard_id
        self._key_index: Dict[Key, int] = {}
        self._keys: List[Key] = []
        self._clocks = np.zeros(64, dtype=np.int64)
        self._count = 0

    # --- registry ---

    def _index(self, key: Key) -> int:
        idx = self._key_index.get(key)
        if idx is None:
            idx = self._count
            self._key_index[key] = idx
            self._keys.append(key)
            self._count += 1
            if idx >= len(self._clocks):
                grown = np.zeros(len(self._clocks) * 2, dtype=np.int64)
                grown[: len(self._clocks)] = self._clocks
                self._clocks = grown
        return idx

    def init_clocks(self, cmd: Command) -> None:
        for key in cmd.keys(self.shard_id):
            self._index(key)

    # --- scalar SequentialKeyClocks interface ---

    def proposal(self, cmd: Command, min_clock: int) -> Tuple[int, Votes]:
        clock = max(min_clock, self._cmd_clock(cmd) + 1)
        votes = Votes()
        self.detached(cmd, clock, votes)
        return clock, votes

    def detached(self, cmd: Command, up_to: int, votes: Votes) -> None:
        for key in cmd.keys(self.shard_id):
            self._maybe_bump(key, up_to, votes)

    def detached_all(self, up_to: int, votes: Votes) -> None:
        # vectorized sweep over every registered key (the clock-bump event
        # touches the whole table, newt.rs:983-1006)
        count = self._count
        current = self._clocks[:count]
        behind = np.nonzero(current < up_to)[0]
        for idx in behind.tolist():
            votes.add(
                self._keys[idx],
                VoteRange(self.process_id, int(current[idx]) + 1, up_to),
            )
        current[behind] = up_to

    @classmethod
    def parallel(cls) -> bool:
        return False

    def _cmd_clock(self, cmd: Command) -> int:
        return max(
            (int(self._clocks[self._index(key)]) for key in cmd.keys(self.shard_id)),
            default=0,
        )

    def _maybe_bump(self, key: Key, up_to: int, votes: Votes) -> None:
        idx = self._index(key)
        current = int(self._clocks[idx])
        if current < up_to:
            votes.add(key, VoteRange(self.process_id, current + 1, up_to))
            self._clocks[idx] = up_to

    # --- the batched proposal seam ---

    def proposal_batch(
        self, cmds: List[Command], min_clocks: List[int]
    ) -> List[Tuple[int, Votes]]:
        """Clocks + votes for a whole batch, preserving batch order within
        each key (== running ``proposal`` sequentially).  Single-key
        commands with window-sized clocks go through the device kernel;
        anything else falls back to the sequential loop."""
        assert len(cmds) == len(min_clocks)
        batch = len(cmds)
        if batch == 0:
            return []
        keys: List[Key] = []
        single = True
        for cmd in cmds:
            if cmd.key_count(self.shard_id) != 1:
                single = False
                break
            keys.append(next(iter(cmd.keys(self.shard_id))))
        if single:
            out = self._proposal_batch_kernel(keys, min_clocks)
            if out is not None:
                return out
        return [self.proposal(cmd, mc) for cmd, mc in zip(cmds, min_clocks)]

    def proposal_batch_arrays(
        self, keys: List[Key], min_clocks
    ) -> Optional[Tuple[np.ndarray, np.ndarray]]:
        """Array-native proposal seam (VERDICT r4 #4): clocks + consumed
        vote-range starts as int64 columns, NO Votes/VoteRange objects —
        the per-command object building that floors the host path at
        ~4.5 us/cmd happens only at whatever boundary actually needs
        objects.  The vote consumed by row ``i`` is
        ``[vote_start[i], clock[i]]`` by this process.

        Returns None when clocks exceed the 31-bit kernel window
        (real-time micros; callers fall back to the sequential loop).
        Semantics: identical to running ``proposal`` sequentially —
        same-key commands get consecutive clocks in batch order
        (fantoch_ps/src/protocol/common/table/votes.rs:133 ranges)."""
        import jax
        import jax.numpy as jnp

        from fantoch_tpu.ops.table_ops import batched_clock_proposal

        batch = len(keys)
        key_idx = np.fromiter(
            (self._index(k) for k in keys), np.int32, batch
        )
        mins = np.asarray(min_clocks, dtype=np.int64)
        # pad the key table to pow2 so XLA compiles O(log) programs as the
        # registry grows; pad the batch with private pad-bucket rows
        kcap = _pow2(max(self._count, 1) + 1)
        bcap = _pow2(batch)
        prior = np.zeros(kcap, dtype=np.int64)
        prior[: self._count] = self._clocks[: self._count]
        hi = max(int(prior.max()), int(mins.max()) if batch else 0)
        if hi + bcap + 1 > _INT32_MAX:
            return None  # real-time micros clocks: sequential fallback
        pk = np.full(bcap, kcap - 1, dtype=np.int32)  # pad bucket
        pm = np.zeros(bcap, dtype=np.int32)
        pk[:batch] = key_idx
        pm[:batch] = mins.astype(np.int32)
        out = batched_clock_proposal(
            jnp.asarray(prior.astype(np.int32)), jnp.asarray(pk), jnp.asarray(pm)
        )
        # one blocking transfer for all three outputs (per-array np.asarray
        # would pay a device round trip each on a remote-dispatch rig)
        clock, vote_start, new_prior = jax.device_get(out)
        clock = clock[:batch].astype(np.int64)
        vote_start = vote_start[:batch].astype(np.int64)
        new_prior = new_prior.astype(np.int64)
        self._clocks[: self._count] = new_prior[: self._count]
        return clock, vote_start

    def _proposal_batch_kernel(
        self, keys: List[Key], min_clocks: List[int]
    ) -> Optional[List[Tuple[int, Votes]]]:
        arrays = self.proposal_batch_arrays(keys, min_clocks)
        if arrays is None:
            return None
        clock, vote_start = arrays
        out: List[Tuple[int, Votes]] = []
        for i in range(len(keys)):
            votes = Votes()
            votes.set(
                keys[i],
                [VoteRange(self.process_id, int(vote_start[i]), int(clock[i]))],
            )
            out.append((int(clock[i]), votes))
        return out


def _pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p
