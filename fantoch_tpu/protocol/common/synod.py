"""Single-decree Flexible Paxos (Synod): phase-1 waits n-f promises, phase-2
waits f+1 accepts.  Embedded in every per-dot info for slow paths.

Reference: fantoch_ps/src/protocol/common/synod/single.rs.  The coordinator
ballot trick: ballot 0 means "never accepted"; the original coordinator can
skip the prepare phase with ballot = its own id, because any prepared ballot
is > n and thus nothing can have been accepted below it.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, Generic, Optional, Set, Tuple, TypeVar

from fantoch_tpu.core.ids import ProcessId

V = TypeVar("V")
Ballot = int


# Synod messages (single.rs:10-20)
@dataclass
class MChosen(Generic[V]):
    value: V


@dataclass
class MPrepare:
    ballot: Ballot


@dataclass
class MAccept(Generic[V]):
    ballot: Ballot
    value: V


@dataclass
class MPromise(Generic[V]):
    ballot: Ballot
    accepted: Tuple[Ballot, V]


@dataclass
class MAccepted:
    ballot: Ballot


SynodMessage = object  # union of the above


class Synod(Generic[V]):
    def __init__(
        self,
        process_id: ProcessId,
        n: int,
        f: int,
        proposal_gen: Callable[[Dict[ProcessId, V]], V],
        initial_value: V,
    ):
        self._proposer = _Proposer(process_id, n, f, proposal_gen)
        self._acceptor = _Acceptor(initial_value)
        self._chosen = False

    def set_if_not_accepted(self, value_gen: Callable[[], V]) -> bool:
        """Set the consensus value if nothing has been accepted yet (ballot
        still 0)."""
        return self._acceptor.set_if_not_accepted(value_gen)

    def value(self) -> V:
        return self._acceptor.value()

    def new_prepare(self) -> MPrepare:
        return self._proposer.new_prepare(self._acceptor)

    def skip_prepare(self) -> Ballot:
        """First-ballot shortcut for the original coordinator (single.rs:86-92)."""
        return self._proposer.skip_prepare(self._acceptor)

    def can_skip_prepare(self) -> bool:
        """The first-ballot shortcut is sound only while no prepare has
        touched the acceptor: once a recovery proposer owns a higher
        ballot, the original coordinator must go through prepare too."""
        return self._acceptor.ballot == 0

    def chosen(self) -> bool:
        return self._chosen

    def current_ballot(self) -> Ballot:
        """The proposer's active ballot: <= n on the first-ballot shortcut,
        > n once a recovery prepare ran (ballot = id + n * round)."""
        return self._proposer._ballot

    def handle(
        self,
        from_: ProcessId,
        msg,
        free_choice_adjust=None,
        free_choice_hold=None,
    ) -> Optional[SynodMessage]:
        """``free_choice_adjust`` (optional, transient — callers pass it
        per call so nothing unpicklable sticks to consensus state) maps
        the proposal-generator's value right before it is proposed.  It
        applies ONLY on the free-choice path (no promise carried an
        accepted ballot); a value bound by a prior accept is never
        touched.  The recovery plane uses it to lift recovered clocks
        above the promise quorum's stability floor.

        ``free_choice_hold`` (optional, transient like the adjuster) is
        consulted when the free-choice path has its n-f promises but not
        yet all n: ``hold(promisers)`` returning True keeps the proposer
        collecting instead of firing, so ballot-0 reports from live
        stragglers still join the union.  The recovery plane holds until
        every *known fast-quorum member* has reported: firing at the
        first n-f can drop the one report carrying a conflict edge (the
        fuzzer-found Atlas divergence — a dep known only to the second
        fast-quorum member, whose promise arrived 29ms after the quorum),
        and a dep/clock union missing such a report commits a value that
        orders the dot against nothing.  Holding is bounded by the
        caller (recovery releases after FREE_CHOICE_HOLD_ROUNDS rounds)
        so a genuinely dead member cannot block liveness."""
        if isinstance(msg, MChosen):
            self._chosen = True
            self._acceptor.set_value(msg.value)
            return None
        if isinstance(msg, MPrepare):
            return self._chosen_msg() or self._acceptor.handle_prepare(msg.ballot)
        if isinstance(msg, MAccept):
            return self._chosen_msg() or self._acceptor.handle_accept(msg.ballot, msg.value)
        if isinstance(msg, MPromise):
            if self._chosen:
                # post-decision latch: a duplicated promise (at-least-once
                # delivery) must not re-run the selection — a second
                # free choice could adjust to a NEWER clock floor and
                # emit a conflicting MAccept at the same ballot
                return None
            return self._proposer.handle_promise(
                from_, msg.ballot, msg.accepted, free_choice_adjust,
                free_choice_hold,
            )
        if isinstance(msg, MAccepted):
            if self._chosen:
                # duplicated accepteds after the choice would refill the
                # accept set from its post-choice reset and re-fire with
                # no proposal (the first-ballot-shortcut assert)
                return None
            return self._proposer.handle_accepted(from_, msg.ballot, self._acceptor)
        raise AssertionError(f"unknown synod message {msg}")

    def _chosen_msg(self) -> Optional[MChosen]:
        if self._chosen:
            return MChosen(self._acceptor.value())
        return None


class _Proposer(Generic[V]):
    def __init__(self, process_id, n, f, proposal_gen):
        self._process_id = process_id
        self._n = n
        self._f = f
        self._ballot: Ballot = 0
        self._proposal_gen = proposal_gen
        self._promises: Dict[ProcessId, Tuple[Ballot, V]] = {}
        self._accepts: Set[ProcessId] = set()
        self._proposal: Optional[V] = None

    def new_prepare(self, acceptor: "_Acceptor[V]") -> MPrepare:
        assert acceptor.ballot >= self._ballot
        # ballot owned by this process in the next round: id + n * round
        round_ = acceptor.ballot // self._n
        self._ballot = self._process_id + self._n * (round_ + 1)
        assert acceptor.ballot < self._ballot
        self._reset_state()
        return MPrepare(self._ballot)

    def skip_prepare(self, acceptor: "_Acceptor[V]") -> Ballot:
        assert acceptor.ballot == 0
        self._ballot = self._process_id
        return self._ballot

    def _reset_state(self) -> Tuple[Dict[ProcessId, Tuple[Ballot, V]], Optional[V]]:
        promises, self._promises = self._promises, {}
        self._accepts = set()
        proposal, self._proposal = self._proposal, None
        return promises, proposal

    def handle_promise(
        self, from_, ballot, accepted, free_choice_adjust=None,
        free_choice_hold=None,
    ) -> Optional[MAccept]:
        if ballot != self._ballot:
            return None
        if self._proposal is not None:
            # already proposed at this ballot: a late promise must not
            # re-run the selection (a second MAccept with a different
            # union would race the first)
            return None
        self._promises[from_] = accepted
        if len(self._promises) < self._n - self._f:
            return None
        # pick the value accepted at the highest ballot; if none was accepted
        # (all ballot 0), ask the proposal generator — the one point where
        # the value is a free (therefore adjustable) choice
        promises = self._promises
        highest_from = max(promises, key=lambda p: promises[p][0])
        highest_ballot = promises[highest_from][0]
        if highest_ballot == 0:
            if (
                free_choice_hold is not None
                and len(promises) < self._n
                and free_choice_hold(frozenset(promises))
            ):
                # keep collecting ballot-0 reports (see Synod.handle):
                # promises accumulate until the hold releases — by the
                # awaited report arriving (this path re-runs with >= n-f
                # promises) or by the caller's round bound
                return None
            values = {p: v for p, (_b, v) in promises.items()}
            proposal = self._proposal_gen(values)
            if free_choice_adjust is not None:
                proposal = free_choice_adjust(proposal)
        else:
            proposal = promises[highest_from][1]
        self._reset_state()
        self._proposal = proposal
        return MAccept(ballot, proposal)

    def handle_accepted(self, from_, ballot, acceptor: "_Acceptor[V]") -> Optional[MChosen]:
        if ballot != self._ballot:
            return None
        self._accepts.add(from_)
        if len(self._accepts) != self._f + 1:
            return None
        _, proposal = self._reset_state()
        if proposal is None:
            # first-ballot shortcut: the accepted value at our own ballot
            acc_ballot, acc_value = acceptor.accepted
            assert acc_ballot == self._process_id, (
                "there should have been a proposal before a value can be "
                "chosen (or we should still be at the first ballot)"
            )
            proposal = acc_value
        return MChosen(proposal)


class _Acceptor(Generic[V]):
    def __init__(self, initial_value: V):
        self.ballot: Ballot = 0
        self.accepted: Tuple[Ballot, V] = (0, initial_value)

    def set_if_not_accepted(self, value_gen: Callable[[], V]) -> bool:
        if self.ballot == 0:
            self.accepted = (0, value_gen())
            return True
        return False

    def set_value(self, value: V) -> None:
        self.accepted = (0, value)

    def value(self) -> V:
        return self.accepted[1]

    def handle_prepare(self, ballot: Ballot) -> Optional[MPromise]:
        if ballot > self.ballot:
            self.ballot = ballot
            return MPromise(ballot, self.accepted)
        return None

    def handle_accept(self, ballot: Ballot, value: V) -> Optional[MAccepted]:
        if ballot >= self.ballot:
            self.ballot = ballot
            self.accepted = (ballot, value)
            return MAccepted(ballot)
        return None
