"""Flexible multi-decree Paxos (MultiSynod): leader, acceptor and per-slot
commanders, modeled after "Paxos Made Moderately Complex".

Reference: fantoch_ps/src/protocol/common/synod/multi.rs (agents) and
.../synod/gc.rs (slot-watermark GC track).  Phase-1 waits n-f promises,
phase-2 waits f+1 accepts; the initial leader's first ballot (its own id)
is implicitly joined by every acceptor at bootstrap, so steady-state
commands skip the prepare phase entirely.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Generic, Optional, Set, Tuple, TypeVar

from fantoch_tpu.core.ids import ProcessId

V = TypeVar("V")
Ballot = int
Slot = int


# MultiSynod messages (multi.rs:18-31); MChosen/MForwardSubmit are handled
# by the protocol layer, the rest route between the agents
@dataclass
class MPrepare:
    """Leader-election phase 1: a candidate's ballot (the reference's
    todo!() at multi.rs:97-99, implemented here)."""

    ballot: Ballot


@dataclass
class MPromise(Generic[V]):
    """Phase-1 answer: the acceptor's whole accepted-slot map, so the new
    leader can carry forward every value that may have been chosen."""

    ballot: Ballot
    accepted: Dict[Slot, Tuple[Ballot, V]]


@dataclass
class MSpawnCommander(Generic[V]):
    ballot: Ballot
    slot: Slot
    value: V


@dataclass
class MAccept(Generic[V]):
    ballot: Ballot
    slot: Slot
    value: V


@dataclass
class MAccepted:
    ballot: Ballot
    slot: Slot


@dataclass
class MChosen(Generic[V]):
    slot: Slot
    value: V


@dataclass
class MForwardSubmit(Generic[V]):
    value: V


class _Leader:
    """Ballot + slot allocator; only the leader allocates (multi.rs:170-210)."""

    __slots__ = ("process_id", "is_leader", "ballot", "last_slot")

    def __init__(self, process_id: ProcessId, initial_leader: ProcessId):
        self.process_id = process_id
        self.is_leader = process_id == initial_leader
        self.ballot: Ballot = process_id if self.is_leader else 0
        self.last_slot: Slot = 0

    def try_submit(self) -> Optional[Tuple[Ballot, Slot]]:
        if not self.is_leader:
            return None
        self.last_slot += 1
        return self.ballot, self.last_slot


class _Commander(Generic[V]):
    """Watches accepts for one slot until f+1 arrive (multi.rs:212-260)."""

    __slots__ = ("f", "ballot", "value", "accepts")

    def __init__(self, f: int, ballot: Ballot, value: V):
        self.f = f
        self.ballot = ballot
        self.value = value
        self.accepts: Set[ProcessId] = set()

    def handle_accepted(self, from_: ProcessId, ballot: Ballot) -> bool:
        if self.ballot != ballot:
            return False
        self.accepts.add(from_)
        return len(self.accepts) == self.f + 1


class _Acceptor(Generic[V]):
    """Ballot-guarded accepted-slot store (multi.rs:262-340).  Boots already
    joined to the initial leader's ballot."""

    __slots__ = ("ballot", "accepted")

    def __init__(self, initial_leader: ProcessId):
        self.ballot: Ballot = initial_leader
        self.accepted: Dict[Slot, Tuple[Ballot, V]] = {}

    def handle_prepare(self, ballot: Ballot) -> Optional[MPromise]:
        """Leader-election phase 1 (the reference's todo!() at
        multi.rs:97-99): join a higher ballot and promise the full
        accepted-slot map for value carry-forward."""
        if ballot <= self.ballot:
            return None
        self.ballot = ballot
        return MPromise(ballot, dict(self.accepted))

    def handle_accept(self, ballot: Ballot, slot: Slot, value: V) -> Optional[MAccepted]:
        if ballot < self.ballot:
            return None
        self.ballot = ballot
        self.accepted[slot] = (ballot, value)
        return MAccepted(ballot, slot)

    def gc(self, start: Slot, end: Slot) -> int:
        """Remove stable slots; counts only slots actually held (acceptors
        outside the leader's write quorum never saw them)."""
        return sum(1 for slot in range(start, end + 1) if self.accepted.pop(slot, None) is not None)

    def gc_single(self, slot: Slot) -> None:
        self.accepted.pop(slot, None)


class MultiSynod(Generic[V]):
    def __init__(self, process_id: ProcessId, initial_leader: ProcessId, n: int, f: int):
        self.n = n
        self.f = f
        self._leader = _Leader(process_id, initial_leader)
        self._acceptor: _Acceptor[V] = _Acceptor(initial_leader)
        self._commanders: Dict[Slot, _Commander[V]] = {}
        # election state: the ballot we're campaigning on + its promises
        self._campaign_ballot: Optional[Ballot] = None
        self._promises: Dict[ProcessId, Dict[Slot, Tuple[Ballot, V]]] = {}

    @property
    def is_leader(self) -> bool:
        return self._leader.is_leader

    def inflight(self):
        """(ballot, slot, value) of every allocated-but-unchosen slot —
        the accept rounds a leader must RE-DRIVE (broadcast) when a
        write-quorum member dies: the original f+1-sized accept fan-out
        may have included the corpse, and nothing else retries phase 2
        (fuzzer-found FPaxos stall)."""
        return sorted(
            (commander.ballot, slot, commander.value)
            for slot, commander in self._commanders.items()
        )

    def resume_above(self, slot: Slot) -> None:
        """Floor the slot allocator: a freshly-elected leader resumes
        above every slot it can PROVE allocated — the promise carry map
        alone is not enough once GC pruned globally-stable slots from the
        acceptor maps (the winner would re-allocate stable slots, whose
        re-chosen events every replica's stable-floor guard then drops:
        the command is lost and its client hangs forever)."""
        self._leader.last_slot = max(self._leader.last_slot, slot)

    def demote_if_superseded(self, ballot: Ballot):
        """A higher-ballot leadership proof arrived (an election heartbeat
        this process never voted in — e.g. it was crashed during the
        campaign and restored a stale ``is_leader``): stop allocating and
        pop every commander at a superseded ballot.  Those rounds can
        never complete (n - f acceptors joined the higher ballot, so at
        most f could still accept — below the f + 1 choose threshold);
        the protocol re-forwards their values to the real leader.
        Returns the popped (ballot, slot, value) triples, sorted."""
        if not self._leader.is_leader or ballot <= self._leader.ballot:
            return []
        self._leader.is_leader = False
        stale = sorted(
            (commander.ballot, slot, commander.value)
            for slot, commander in self._commanders.items()
            if commander.ballot < ballot
        )
        for _b, slot, _v in stale:
            del self._commanders[slot]
        return stale

    def submit(self, value: V):
        """MSpawnCommander if we're the leader, else MForwardSubmit."""
        allocated = self._leader.try_submit()
        if allocated is None:
            return MForwardSubmit(value)
        ballot, slot = allocated
        return MSpawnCommander(ballot, slot, value)

    def new_prepare(self) -> MPrepare:
        """Start (or restart) a leadership campaign: a fresh ballot owned
        by this process, above anything the local acceptor has joined."""
        round_ = self._acceptor.ballot // self.n
        self._campaign_ballot = self._leader.process_id + self.n * (round_ + 1)
        assert self._campaign_ballot > self._acceptor.ballot
        self._promises = {}
        self._leader.is_leader = False  # a superseded leader must re-win
        return MPrepare(self._campaign_ballot)

    def handle_promise(
        self, from_: ProcessId, ballot: Ballot, accepted: Dict[Slot, Tuple[Ballot, V]]
    ) -> Optional[Dict[Slot, V]]:
        """Count campaign promises; with n - f of them, take over: adopt
        the ballot, resume slot allocation above every slot seen, and
        return the carry-forward map (slot -> highest-ballot accepted
        value) the protocol must re-propose through fresh commanders."""
        if ballot != self._campaign_ballot or self._leader.is_leader:
            return None
        self._promises[from_] = accepted
        if len(self._promises) != self.n - self.f:
            return None
        carry: Dict[Slot, Tuple[Ballot, V]] = {}
        for acc in self._promises.values():
            for slot, (b, value) in acc.items():
                if slot not in carry or b > carry[slot][0]:
                    carry[slot] = (b, value)
        self._leader.is_leader = True
        self._leader.ballot = ballot
        self._leader.last_slot = max(
            self._leader.last_slot, max(carry, default=0)
        )
        self._promises = {}
        return {slot: value for slot, (_b, value) in sorted(carry.items())}

    def handle(self, from_: ProcessId, msg):
        if isinstance(msg, MPrepare):
            return self._handle_prepare(msg.ballot)
        if isinstance(msg, MSpawnCommander):
            return self._handle_spawn_commander(msg.ballot, msg.slot, msg.value)
        if isinstance(msg, MAccept):
            return self._acceptor.handle_accept(msg.ballot, msg.slot, msg.value)
        if isinstance(msg, MAccepted):
            return self._handle_maccepted(from_, msg.ballot, msg.slot)
        raise AssertionError(f"unexpected multi-synod message {msg}")

    def _handle_prepare(self, ballot: Ballot) -> Optional[MPromise]:
        out = self._acceptor.handle_prepare(ballot)
        if out is not None and self._leader.is_leader and ballot > self._leader.ballot:
            # superseded: stop allocating; live commanders die with their
            # ballot (their accepts are rejected at the joined acceptors)
            self._leader.is_leader = False
        return out

    def gc(self, start: Slot, end: Slot) -> int:
        return self._acceptor.gc(start, end)

    def gc_single(self, slot: Slot) -> None:
        self._acceptor.gc_single(slot)

    def _handle_spawn_commander(self, ballot: Ballot, slot: Slot, value: V) -> MAccept:
        prev = self._commanders.get(slot)
        # one commander per slot in steady state; a takeover re-proposes a
        # carried-forward slot at a strictly higher ballot, superseding any
        # commander a dethroned leader left behind
        assert prev is None or prev.ballot < ballot, "one commander per slot"
        self._commanders[slot] = _Commander(self.f, ballot, value)
        return MAccept(ballot, slot, value)

    def _handle_maccepted(self, from_: ProcessId, ballot: Ballot, slot: Slot):
        commander = self._commanders.get(slot)
        if commander is None:
            # commander already satisfied (or never existed here)
            return None
        if commander.handle_accepted(from_, ballot):
            del self._commanders[slot]
            return MChosen(slot, commander.value)
        return None


class SlotGCTrack:
    """Slot-watermark GC: local committed frontier + everyone else's
    watermarks; stable = the minimum (synod/gc.rs:7-77)."""

    __slots__ = ("process_id", "n", "_committed", "_all_but_me", "_previous_stable")

    def __init__(self, process_id: ProcessId, n: int):
        from fantoch_tpu.core.clocks import AboveExSet

        self.process_id = process_id
        self.n = n
        self._committed = AboveExSet()
        self._all_but_me: Dict[ProcessId, int] = {}
        self._previous_stable = 0

    def commit(self, slot: Slot) -> None:
        self._committed.add(slot)

    def committed(self) -> int:
        return self._committed.frontier

    def committed_by(self, from_: ProcessId, committed: int) -> None:
        self._all_but_me[from_] = committed

    def stable(self) -> Tuple[int, int]:
        """Newly-stable slot range (start > end when nothing is new)."""
        new_stable = self._stable_slot()
        slot_range = (self._previous_stable + 1, new_stable)
        self._previous_stable = new_stable
        return slot_range

    @property
    def stable_floor(self) -> int:
        """Highest slot already handed to GC: a chosen/duplicate message
        at or below it is a straggler for pruned state and must not
        re-enter the pipeline (the FPaxos analog of the dot protocols'
        GC-straggler guards)."""
        return self._previous_stable

    def _stable_slot(self) -> int:
        if len(self._all_but_me) != self.n - 1:
            return 0
        return min(self._committed.frontier, min(self._all_but_me.values()))
