"""Protocol interface: pure state machines with pulled outputs.

Reference: fantoch/src/protocol/mod.rs:42-186.  A protocol handles submits,
messages and periodic events, and exposes two output queues that drivers
pull: ``to_processes`` (network actions) and ``to_executors`` (execution
info for the ordering engine).  ``BaseProcess``
(fantoch/src/protocol/base.rs) carries the plumbing shared by all
protocols: quorums from a distance-sorted process list, dot generation, and
fast/slow/stable metrics.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from dataclasses import dataclass
from enum import Enum
from typing import (
    Any,
    Dict,
    Generic,
    Iterator,
    List,
    Optional,
    Set,
    Tuple,
    TypeVar,
)

from fantoch_tpu.core.clocks import AEClock
from fantoch_tpu.core.command import Command
from fantoch_tpu.core.config import Config
from fantoch_tpu.core.ids import Dot, IdGen, ProcessId, ShardId
from fantoch_tpu.core.metrics import Metrics
from fantoch_tpu.core.timing import SysTime
from fantoch_tpu.observability.tracer import NOOP_TRACER

# Compact representation of which dots have been executed
# (fantoch/src/protocol/mod.rs:40).
Executed = AEClock[ProcessId]


class ProtocolMetricsKind(Enum):
    """Reference: fantoch/src/protocol/mod.rs:147-161."""

    FAST_PATH = "fast_path"
    SLOW_PATH = "slow_path"
    STABLE = "stable"


ProtocolMetrics = Metrics  # keyed by ProtocolMetricsKind

Msg = TypeVar("Msg")


@dataclass
class ToSend(Generic[Msg]):
    """Send `msg` to every process in `target`
    (fantoch/src/protocol/mod.rs:177-182)."""

    target: Set[ProcessId]
    msg: Msg


@dataclass
class ToForward(Generic[Msg]):
    """Forward `msg` to another worker of the same process
    (fantoch/src/protocol/mod.rs:183-185)."""

    msg: Msg


Action = Any  # ToSend | ToForward


class Protocol(ABC):
    """Protocol state-machine interface (fantoch/src/protocol/mod.rs:42-112).

    Subclasses must also define, for the runner's worker routing, a
    ``message_index(msg)`` / ``event_index(event)`` pair returning
    :data:`fantoch_tpu.run.routing.WorkerIndex` values.
    """

    # Executor class used by this protocol
    Executor: type

    @abstractmethod
    def __init__(self, process_id: ProcessId, shard_id: ShardId, config: Config): ...

    @classmethod
    def new(
        cls, process_id: ProcessId, shard_id: ShardId, config: Config
    ) -> Tuple["Protocol", List[Tuple[Any, int]]]:
        """Create a protocol instance plus its periodic events
        ``[(event, interval_ms)]``."""
        protocol = cls(process_id, shard_id, config)
        return protocol, protocol.periodic_events()

    def periodic_events(self) -> List[Tuple[Any, int]]:
        return []

    @property
    @abstractmethod
    def id(self) -> ProcessId: ...

    @property
    @abstractmethod
    def shard_id(self) -> ShardId: ...

    @abstractmethod
    def discover(
        self, processes: List[Tuple[ProcessId, ShardId]]
    ) -> Tuple[bool, Dict[ShardId, ProcessId]]: ...

    @abstractmethod
    def submit(self, dot: Optional[Dot], cmd: Command, time: SysTime) -> None: ...

    @abstractmethod
    def handle(
        self, from_: ProcessId, from_shard_id: ShardId, msg: Any, time: SysTime
    ) -> None: ...

    def handle_event(self, event: Any, time: SysTime) -> None:
        raise NotImplementedError(f"unhandled periodic event {event}")

    def handle_executed(self, executed: Executed, time: SysTime) -> None:
        """Notification of executed dots (GC worker only); default no-op."""

    def on_peer_down(self, peer_id: ProcessId, time: SysTime) -> None:
        """Run-layer failure-detector notification (a peer stayed silent
        past the heartbeat budget).  Default no-op; leader-based protocols
        use it to trigger failover without waiting out their own
        protocol-level timeout."""

    def on_peer_up(self, peer_id: ProcessId, time: SysTime) -> None:
        """Run-layer detector notification symmetric to
        :meth:`on_peer_down`: a peer declared dead is reachable again (it
        restarted, or the silence was a false positive).  Default no-op;
        protocols that route around dead peers (FPaxos pending-forwards,
        election candidate sets) refresh those targets here so a returned
        replica stops being routed around."""

    def rejoin(self, time: SysTime) -> None:
        """Restart hook: queue catch-up actions after this (restored)
        process re-enters the mesh.  Default no-op; protocols with the
        sync plane (protocol/sync.py) broadcast ``MSync`` with their
        committed horizon so live peers stream the commits this process
        missed while down."""

    def note_durable_chosen(self, records) -> None:
        """Restart-replay hook for slot-ordered protocols: ``(slot, cmd)``
        records whose effects the WAL tail replay already applied.
        Default no-op; FPaxos folds them into its chosen log + committed
        watermark so the rejoin MSlotSync floor covers them."""

    def note_durable_commits(self, dots) -> None:
        """Restart-replay hook: commit dots whose effects the WAL tail
        replay already applied to the executors.  Default no-op;
        GC-tracking protocols fold them into the committed clock so the
        rejoin horizon covers them (protocol/commit_gc.py)."""

    def snapshot(self) -> bytes:
        """Durable image of the full protocol state (commit info, per-dot
        synod state, dedup tables, GC clocks).  The tracer is excluded —
        an open span log is not durable state — and reattached by the
        restorer via :meth:`set_tracer`.  Restart = ``restore(snapshot)``
        [+ WAL tail replay in the run layer] + :meth:`rejoin` catch-up."""
        import pickle

        bp = getattr(self, "bp", None)
        saved = None
        if bp is not None and bp.tracer is not NOOP_TRACER:
            saved, bp.tracer = bp.tracer, NOOP_TRACER
        try:
            return pickle.dumps(self)
        finally:
            if saved is not None:
                bp.tracer = saved

    @classmethod
    def restore(cls, blob: bytes) -> "Protocol":
        """Rebuild a protocol instance from :meth:`snapshot` output."""
        import pickle

        process = pickle.loads(blob)
        assert isinstance(process, cls), (
            f"snapshot holds {type(process).__name__}, not {cls.__name__}"
        )
        return process

    def audit_commit_log(self) -> Optional[Dict[Any, Tuple[Any, Any]]]:
        """The commit log the consistency auditor consumes
        (``Config.audit_log_commits``): ident -> (rifl, value).  Default
        reads the shared BaseProcess log; None when auditing is off."""
        bp = getattr(self, "bp", None)
        return bp.audit_commits if bp is not None else None

    def nudge_recovery(self, dots, time: SysTime) -> None:
        """Executor-watchdog hint: these dots are missing dependencies of
        committed commands.  Default no-op; recovery-capable protocols
        start per-dot recovery consensus for them — including dots whose
        payload never reached any live process (recovered as noops)."""

    def set_tracer(self, tracer) -> None:
        """Runner hook: install the lifecycle tracer
        (fantoch_tpu/observability).  Default wires it into the shared
        BaseProcess plumbing when present; protocols without a ``bp``
        simply stay untraced."""
        bp = getattr(self, "bp", None)
        if bp is not None:
            bp.tracer = tracer

    @abstractmethod
    def to_processes(self) -> Optional[Action]: ...

    def to_processes_iter(self) -> Iterator[Action]:
        while True:
            action = self.to_processes()
            if action is None:
                return
            yield action

    @abstractmethod
    def to_executors(self) -> Optional[Any]: ...

    def to_executors_iter(self) -> Iterator[Any]:
        while True:
            info = self.to_executors()
            if info is None:
                return
            yield info

    @classmethod
    def parallel(cls) -> bool: ...

    @classmethod
    def leaderless(cls) -> bool: ...

    @abstractmethod
    def metrics(self) -> ProtocolMetrics: ...

    # --- worker routing (MessageIndex trait, fantoch/src/protocol/mod.rs:163) ---

    @staticmethod
    def message_index(msg: Any):
        """Worker index for a message; None broadcasts to all workers."""
        return getattr(msg, "INDEX", None)

    @staticmethod
    def event_index(event: Any):
        return getattr(event, "INDEX", None)


class BaseProcess:
    """Shared protocol plumbing (fantoch/src/protocol/base.rs:10-199)."""

    def __init__(
        self,
        process_id: ProcessId,
        shard_id: ShardId,
        config: Config,
        fast_quorum_size: int,
        write_quorum_size: int,
    ):
        # ballots lead with `id` on the slow path and accepted-ballot 0 means
        # "never been through phase-2", so ids must be non-zero
        assert process_id != 0
        self.process_id = process_id
        self.shard_id = shard_id
        self.config = config
        self.fast_quorum_size = fast_quorum_size
        self.write_quorum_size = write_quorum_size
        self._all: Optional[List[ProcessId]] = None
        self._all_but_me: Optional[List[ProcessId]] = None
        self._fast_quorum: Optional[List[ProcessId]] = None
        self._write_quorum: Optional[List[ProcessId]] = None
        self._closest_shard_process: Dict[ShardId, ProcessId] = {}
        self._dot_gen = IdGen(process_id)
        self._metrics: Metrics = Metrics()
        # lifecycle tracer (observability plane); runners swap in a real
        # Tracer via Protocol.set_tracer when Config.trace_sample_rate > 0
        self.tracer = NOOP_TRACER
        # consistency-audit commit log (core/audit.py): every commit
        # decision as ident -> (rifl, value), surviving GC so the
        # post-run auditor can check commit-value agreement across
        # replicas.  None unless Config.audit_log_commits (audit/fuzz
        # instrumentation — the log grows with the run)
        self.audit_commits: Optional[Dict[Any, Tuple[Any, Any]]] = (
            {} if config.audit_log_commits else None
        )

    def discover(self, all_processes: List[Tuple[ProcessId, ShardId]]) -> bool:
        """Learn the (distance-sorted) process list; quorums are the closest
        `fast_quorum_size` / `write_quorum_size` same-shard processes.

        Reference: fantoch/src/protocol/base.rs:59-131.
        """
        self._closest_shard_process = {}
        processes: List[ProcessId] = []
        for process_id, shard_id in all_processes:
            if shard_id == self.shard_id:
                processes.append(process_id)
            else:
                # must be the closest process of that shard
                assert shard_id not in self._closest_shard_process, (
                    "process should only connect to the closest process of each shard"
                )
                self._closest_shard_process[shard_id] = process_id

        fast = processes[: self.fast_quorum_size]
        write = processes[: self.write_quorum_size]
        self._all = list(processes)
        self._all_but_me = [p for p in processes if p != self.process_id]
        self._fast_quorum = fast if len(fast) == self.fast_quorum_size else None
        self._write_quorum = write if len(write) == self.write_quorum_size else None
        return self._fast_quorum is not None and self._write_quorum is not None

    def next_dot(self) -> Dot:
        return self._dot_gen.next_id()

    def all(self) -> Set[ProcessId]:
        assert self._all is not None, "the set of all processes should be known"
        return set(self._all)

    def all_but_me(self) -> Set[ProcessId]:
        assert self._all_but_me is not None
        return set(self._all_but_me)

    def fast_quorum(self) -> Set[ProcessId]:
        assert self._fast_quorum is not None, "the fast quorum should be known"
        return set(self._fast_quorum)

    def write_quorum(self) -> Set[ProcessId]:
        assert self._write_quorum is not None, "the write quorum should be known"
        return set(self._write_quorum)

    def closest_process(self, shard_id: ShardId) -> ProcessId:
        return self._closest_shard_process[shard_id]

    def closest_shard_process(self) -> Dict[ShardId, ProcessId]:
        return self._closest_shard_process

    def metrics(self) -> Metrics:
        return self._metrics

    def fast_path(self, dot: Optional[Dot] = None, cmd=None) -> None:
        self._metrics.aggregate(ProtocolMetricsKind.FAST_PATH, 1)
        if self.tracer.enabled and cmd is not None:
            self.trace_span("path", cmd.rifl, dot=dot, meta={"path": "fast"})

    def slow_path(self, dot: Optional[Dot] = None, cmd=None) -> None:
        self._metrics.aggregate(ProtocolMetricsKind.SLOW_PATH, 1)
        if self.tracer.enabled and cmd is not None:
            self.trace_span("path", cmd.rifl, dot=dot, meta={"path": "slow"})

    def stable(self, count: int) -> None:
        self._metrics.aggregate(ProtocolMetricsKind.STABLE, count)

    def audit_commit(self, ident, rifl, value) -> None:
        """Record one commit decision for the consistency auditor:
        ``ident`` is the dot (leaderless) or slot (FPaxos), ``rifl`` the
        committed command's id (None for recovered noops), ``value`` the
        protocol's agreed payload (Newt clock, graph deps, Caesar
        (clock, deps), None where the ident alone carries the order).
        No-op unless ``Config.audit_log_commits``."""
        if self.audit_commits is not None:
            self.audit_commits[ident] = (rifl, value)

    def trace_span(self, stage: str, rifl, dot: Optional[Dot] = None,
                   meta=None) -> None:
        """Emit one lifecycle span event at this process (no-op unless a
        tracer is installed and the command is sampled)."""
        if self.tracer.enabled:
            self.tracer.span(stage, rifl, dot=dot, pid=self.process_id,
                             meta=meta)
