"""Per-dot command info stores.

Reference: fantoch/src/protocol/info/{mod,sequential,locked}.rs.  Each
in-flight dot has an ``Info`` record (protocol-specific) created on first
access and garbage-collected once stable.  The reference's Locked variant
(Arc<SharedMap<Dot, RwLock<I>>>) exists for intra-process worker
parallelism; in this rebuild workers are asyncio tasks in one interpreter, so
a plain dict with the same interface serves both roles (the "parallel"
distinction lives at the batching layer instead).
"""

from __future__ import annotations

from typing import Callable, Dict, Generic, List, Tuple, TypeVar

from fantoch_tpu.core.config import Config
from fantoch_tpu.core.ids import Dot, ProcessId, ShardId

I = TypeVar("I")


class CommandsInfo(Generic[I]):
    """dot -> protocol info store with GC (sequential.rs:7-80)."""

    def __init__(
        self,
        process_id: ProcessId,
        shard_id: ShardId,
        config: Config,
        fast_quorum_size: int,
        write_quorum_size: int,
        info_factory: Callable[[ProcessId, ShardId, Config, int, int], I],
    ):
        self._process_id = process_id
        self._shard_id = shard_id
        self._config = config
        self._fast_quorum_size = fast_quorum_size
        self._write_quorum_size = write_quorum_size
        self._factory = info_factory
        self._infos: Dict[Dot, I] = {}

    def get(self, dot: Dot) -> I:
        info = self._infos.get(dot)
        if info is None:
            info = self._factory(
                self._process_id,
                self._shard_id,
                self._config,
                self._fast_quorum_size,
                self._write_quorum_size,
            )
            self._infos[dot] = info
        return info

    def get_existing(self, dot: Dot):
        """Info for `dot` if present, without creating it (the Locked
        variant's `get`, locked.rs:34-44)."""
        return self._infos.get(dot)

    def contains(self, dot: Dot) -> bool:
        return dot in self._infos

    def gc(self, stable: List[Tuple[ProcessId, int, int]]) -> int:
        """Remove all dots in the stable ranges; returns removed count
        (sequential.rs:52-77)."""
        removed = 0
        for process_id, start, end in stable:
            for seq in range(start, end + 1):
                if self._infos.pop(Dot(process_id, seq), None) is not None:
                    removed += 1
        return removed

    def gc_single(self, dot: Dot):
        """Remove and return the info for `dot` (None if absent)."""
        return self._infos.pop(dot, None)

    def items(self):
        """Live (dot, info) pairs (insertion order) — the sync plane's
        scan surface (protocol/sync.py)."""
        return self._infos.items()

    def __len__(self) -> int:
        return len(self._infos)


# Alias used by protocols that declare themselves parallel; see module
# docstring for why this is the same class.
SequentialCommandsInfo = CommandsInfo
LockedCommandsInfo = CommandsInfo
