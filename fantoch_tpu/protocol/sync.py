"""Rejoin catch-up: committed-command sync for restarted replicas.

A replica that crashes and restarts from its WAL + snapshot (run/wal.py,
sim crash-restart) knows everything it committed before the crash but
nothing the mesh decided while it was down.  Peers dropped its frames the
moment they declared it dead, so the network never replays that history —
the returning replica must *pull* it.  This mixin is the pull:

1. **MSync** — on :meth:`rejoin` the restarted process broadcasts its
   committed-dot horizon: the GC tracker's own AEClock (contiguous
   frontier + above-exceptions), which survives in the snapshot and —
   because GC only trims ``_cmds``, never the clock — also covers commits
   whose info was already garbage-collected locally.
2. **MSyncReply** — each live peer scans its commit-info store for
   committed dots outside that horizon and streams protocol-specific
   commit records back, chunked (:data:`SYNC_CHUNK` per message) so one
   reply never balloons.  Retention is guaranteed by the
   executed-everywhere GC clock: while the requester was down its
   executed frontier froze, so the mesh's stability meet — and therefore
   GC — stalled at its last notification; everything it missed is still
   in some live peer's ``_cmds``.
3. **Apply** — the requester applies each record through the protocol's
   normal commit machinery (payload adoption + MCommit handler), which is
   idempotent per dot (``Status.COMMIT`` short-circuit), so the same
   record arriving from several peers — or racing a recovery-decided
   commit — is exactly-once.

Protocols plug in two hooks (:meth:`SyncMixin._sync_record` /
:meth:`SyncMixin._apply_sync_record`) plus an optional
:meth:`SyncMixin._sync_backfill_actions` used by Newt: vote-frontier gaps
cannot be reconstructed from commit records alone, but every process's
issued votes on a key are exactly the contiguous range ``[1, its key
clock]``, so peers (and the rejoiner) re-state that range wholesale as
detached votes — ranges dedup in the vote tables, and the restarted
replica's stability frontier heals instead of stalling below a
permanent gap.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, List, Tuple

from fantoch_tpu.core.ids import ProcessId
from fantoch_tpu.core.timing import SysTime
from fantoch_tpu.protocol.base import ToSend

# commit records per MSyncReply message: bounds per-message work at the
# requester and keeps the sim's per-delivery cost flat
SYNC_CHUNK = 128


@dataclass
class MSync:
    """Restarted replica -> everyone: my committed horizon (an
    ``AEClock[ProcessId]``); send me what I missed."""

    committed: Any


@dataclass
class MSyncReply:
    """One chunk of protocol-specific commit records past the
    requester's horizon."""

    records: List[Tuple]


class SyncMixin:
    """Requires from the host protocol: ``self.bp`` (BaseProcess),
    ``self._cmds`` (CommandsInfo with ``items()``), ``self._gc_track``
    (GCTrack), ``self._to_processes`` (deque), and a ``Status`` whose
    committed state is ``"commit"``.  Single-shard only, like the
    recovery plane (cross-shard commit aggregation state dies with the
    dot owner)."""

    _SYNC_STATUS_COMMIT = "commit"

    def _sync_enabled(self) -> bool:
        return self.bp.config.shard_count == 1

    # --- the restarted side ---

    def rejoin(self, time: SysTime) -> None:
        if not self._sync_enabled():
            return
        targets = self.bp.all_but_me()
        if not targets:
            return
        self._to_processes.append(
            ToSend(targets, MSync(self._gc_track.my_clock()))
        )
        self._sync_backfill_actions(targets)

    # --- wire handlers ---

    def handle_sync_message(self, from_: ProcessId, msg: Any, time: SysTime) -> bool:
        """Dispatch a sync message; returns False if ``msg`` is not one."""
        if isinstance(msg, MSync):
            self._handle_msync(from_, msg.committed, time)
        elif isinstance(msg, MSyncReply):
            for record in msg.records:
                self._apply_sync_record(from_, record, time)
        else:
            return False
        return True

    def _handle_msync(self, from_: ProcessId, committed, time: SysTime) -> None:
        if not self._sync_enabled():
            return
        records = []
        # sorted: chunk contents are a pure function of protocol state,
        # not dict insertion history — same-seed traces stay identical
        for dot, info in sorted(self._cmds.items()):
            if info.status != self._SYNC_STATUS_COMMIT:
                continue
            if committed.contains(dot.source, dot.sequence):
                continue
            records.append(self._sync_record(dot, info))
        for start in range(0, len(records), SYNC_CHUNK):
            self._to_processes.append(
                ToSend({from_}, MSyncReply(records[start : start + SYNC_CHUNK]))
            )
        # even with no missing commits the requester may have vote gaps
        self._sync_backfill_actions({from_})

    # --- hooks for the host protocol ---

    def _sync_backfill_actions(self, targets) -> None:
        """Optional: queue frontier-backfill actions toward ``targets``
        (Newt's detached-vote re-statement).  Default no-op."""

    def _sync_record(self, dot, info):
        """One commit record for ``dot`` (committed here, unknown to the
        requester)."""
        raise NotImplementedError

    def _apply_sync_record(self, from_: ProcessId, record, time: SysTime) -> None:
        """Apply one peer commit record; must be idempotent per dot."""
        raise NotImplementedError
